"""Incremental streaming execution (spark_rapids_tpu/streaming/).

The central invariant: EVERY micro-batch result is bit-identical to a
cold full recompute of the same cumulative input — under growing
sources, fault injection, a hygiene sweep racing a live stream, and a
SIGKILL between micro-batches resumed in a fresh process.  Streaming
only ever saves work (merged exchange checkpoints + resume), never
changes an answer:

* a tick over grown sources merges each eligible exchange's delta
  frames onto its committed base (``stream_incremental_merge``) and
  the cumulative query resumes it — ``recompute_fraction`` < 1.0;
* the source ledger commit AFTER the result is the exactly-once
  marker: a batch error (deadline, injection past retries) leaves the
  ledger untouched and the next tick retries the same cumulative set;
* a committed file being rewritten breaks the append-only contract and
  degrades that tick to a full recompute — still the right answer;
* the stream's checkpoint state is PINNED: TTL/maxBytes sweeps skip it
  while the stream lives, and reclaim it after ``stop()``.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.benchmarks import tpch, tpch_datagen
from spark_rapids_tpu.io.arrow_convert import host_batch_to_arrow

FAST = {
    "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
    "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
}


def _conf(root, **extra):
    conf = dict(FAST)
    conf.update({
        "spark.rapids.tpu.recovery.enabled": True,
        "spark.rapids.tpu.recovery.dir": str(root),
        "spark.rapids.tpu.streaming.enabled": True,
        "spark.rapids.tpu.telemetry.enabled": True,
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
    })
    conf.update(extra)
    return conf


@pytest.fixture(scope="module")
def li_table():
    """The full sf=0.001 lineitem as ONE arrow table — sliced into
    parquet chunks that "arrive" over the course of a stream."""
    sess = srt.Session(dict(FAST))
    li = tpch_datagen.dataframes(sess, sf=0.001)["lineitem"]
    return pa.concat_tables(
        [host_batch_to_arrow(b) for b in li.plan.batches])


def _cuts(tbl, k):
    return [i * tbl.num_rows // k for i in range(k + 1)]


def _write_chunk(data_dir, tbl, cuts, i):
    os.makedirs(data_dir, exist_ok=True)
    pq.write_table(tbl.slice(cuts[i], cuts[i + 1] - cuts[i]),
                   os.path.join(data_dir, f"part-{i:03d}.parquet"))


def _tpch_query(sess, qnum, data_dir):
    tables = tpch_datagen.dataframes(sess, sf=0.001)
    tables["lineitem"] = sess.read_parquet(str(data_dir))
    return tpch.QUERIES[qnum](tables)


def _norm(rows):
    return sorted(
        (tuple((None if v is None else
                (round(v, 9) if isinstance(v, float) else v))
               for v in r) for r in rows),
        key=repr)


def _batch_rows(hb):
    return _norm(zip(*[c.to_pylist() for c in hb.columns]))


def _oracle(qnum, data_dir):
    """Cold full recompute of the current cumulative input: fresh
    session, no recovery, no streaming."""
    sess = srt.Session(dict(FAST, **{
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0}))
    return _norm(_tpch_query(sess, qnum, data_dir).collect())


def _stream_events(handle, etype):
    return [e for e in handle.events() if e["event"] == etype]


# ==========================================================================
# Bit-identity over growing sources
# ==========================================================================
def test_q1_growing_fact_table_bit_identical(li_table, tmp_path):
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 4)
    _write_chunk(data, li_table, cuts, 0)
    _write_chunk(data, li_table, cuts, 1)
    sess = srt.Session(_conf(tmp_path / "rec"))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        h.process_available()
        p1 = h.progress()
        assert p1["streaming.batchId"] == 1
        assert p1["streaming.recomputeFraction"] == 1.0  # cold start

        _write_chunk(data, li_table, cuts, 2)
        out2 = h.process_available()
        p2 = h.progress()
        assert _batch_rows(out2) == _oracle(1, data)
        assert p2["streaming.mergedExchanges"] >= 1, p2
        assert p2["streaming.stagesResumed"] >= 1, p2
        assert p2["streaming.recomputeFraction"] < 1.0, p2
        assert _stream_events(h, "stream_incremental_merge")

        _write_chunk(data, li_table, cuts, 3)
        out3 = h.process_available()
        p3 = h.progress()
        assert _batch_rows(out3) == _oracle(1, data)
        assert p3["streaming.recomputeFraction"] < 1.0, p3
        assert len(_stream_events(h, "stream_batch_commit")) == 3
    finally:
        h.stop()
    assert _stream_events(h, "stream_stop")


@pytest.mark.slow
def test_q3_join_pipeline_bit_identical(li_table, tmp_path):
    """q3 joins the growing fact table with two static in-memory
    dimensions: the lineitem-side join exchange merges incrementally,
    the static-side exchanges resume UNCHANGED (same fingerprint), the
    post-join aggregate recomputes — and the result stays
    bit-identical."""
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 3)
    _write_chunk(data, li_table, cuts, 0)
    _write_chunk(data, li_table, cuts, 1)
    sess = srt.Session(_conf(tmp_path / "rec"))
    h = sess.stream(_tpch_query(sess, 3, data), trigger=0)
    try:
        h.process_available()
        _write_chunk(data, li_table, cuts, 2)
        out2 = h.process_available()
        p2 = h.progress()
        assert _batch_rows(out2) == _oracle(3, data)
        assert p2["streaming.stagesResumed"] >= 1, p2
        assert p2["streaming.recomputeFraction"] < 1.0, p2
    finally:
        h.stop()


# ==========================================================================
# Bit-identity under fault injection
# ==========================================================================
def _query_events(sess, etype):
    prof = sess.last_profile
    return [e for e in (prof.events.snapshot() if prof else [])
            if e["event"] == etype]


@pytest.mark.fault_injection
def test_corrupt_injection_on_exchange_write_stays_bit_identical(
        li_table, tmp_path):
    """Corruption on the exchange WRITE path (the only site a
    ``corrupt`` injector can fire — read-side CRC catches it at the
    checkpoint read-back) disables checkpointing for the batch; the
    stream degrades to full recompute but the committed answer must
    not change."""
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 3)
    _write_chunk(data, li_table, cuts, 0)
    _write_chunk(data, li_table, cuts, 1)
    sess = srt.Session(_conf(tmp_path / "rec", **{
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "corrupt",
        "spark.rapids.tpu.fault.injection.site": "exchange.write",
        "spark.rapids.tpu.fault.injection.skipCount": 2,
        "spark.rapids.tpu.sql.taskRetries": 3,
    }))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        h.process_available()
        fired = len(_query_events(sess, "fault_injected"))
        _write_chunk(data, li_table, cuts, 2)
        out2 = h.process_available()
        fired += len(_query_events(sess, "fault_injected"))
        assert fired, "the corruption drill never fired — vacuous test"
        assert _batch_rows(out2) == _oracle(1, data)
    finally:
        h.stop()


@pytest.mark.fault_injection
def test_stage_crash_injection_mid_stream_stays_bit_identical(
        li_table, tmp_path):
    """A stage crash during a micro-batch retries through the normal
    recovery ladder (resuming checkpointed stages, merged ones
    included) and commits the same answer."""
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 3)
    _write_chunk(data, li_table, cuts, 0)
    _write_chunk(data, li_table, cuts, 1)
    sess = srt.Session(_conf(tmp_path / "rec", **{
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "stage_crash",
        "spark.rapids.tpu.fault.injection.site": "exchange.read",
        "spark.rapids.tpu.fault.injection.skipCount": 2,
        "spark.rapids.tpu.sql.taskRetries": 3,
    }))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        h.process_available()
        fired = len(_query_events(sess, "fault_injected"))
        _write_chunk(data, li_table, cuts, 2)
        out2 = h.process_available()
        fired += len(_query_events(sess, "fault_injected"))
        assert fired, "the crash drill never fired — vacuous test"
        assert _batch_rows(out2) == _oracle(1, data)
    finally:
        h.stop()


# ==========================================================================
# Ledger semantics
# ==========================================================================
@pytest.mark.slow
def test_no_new_files_skips_tick(li_table, tmp_path):
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 2)
    _write_chunk(data, li_table, cuts, 0)
    sess = srt.Session(_conf(tmp_path / "rec"))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        assert h.process_available() is not None
        assert h.process_available() is None  # nothing new arrived
        skips = _stream_events(h, "stream_tick_skip")
        assert skips and skips[-1]["reason"] == "no_new_files"
        assert len(_stream_events(h, "stream_batch_commit")) == 1
    finally:
        h.stop()


@pytest.mark.slow
def test_rewritten_source_degrades_to_full_recompute(li_table, tmp_path):
    """Rewriting a COMMITTED file breaks the append-only contract: the
    tick must flag it, drop the incremental path, and still produce
    exactly the cold answer over the files as they now are."""
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 3)
    _write_chunk(data, li_table, cuts, 0)
    sess = srt.Session(_conf(tmp_path / "rec"))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        h.process_available()
        # rewrite the committed chunk with DIFFERENT rows (and size)
        pq.write_table(
            li_table.slice(cuts[0], cuts[2] - cuts[0]),
            os.path.join(str(data), "part-000.parquet"))
        out2 = h.process_available()
        assert _batch_rows(out2) == _oracle(1, data)
        skips = _stream_events(h, "stream_incremental_skip")
        assert any(e["reason"] == "source_rewritten" for e in skips)
    finally:
        h.stop()


@pytest.mark.slow
def test_max_batch_files_caps_and_drains_backlog(li_table, tmp_path):
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 4)
    _write_chunk(data, li_table, cuts, 0)
    sess = srt.Session(_conf(tmp_path / "rec", **{
        "spark.rapids.tpu.streaming.maxBatchFiles": 1}))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        h.process_available()
        # three files arrive at once; the cap admits one per tick
        _write_chunk(data, li_table, cuts, 1)
        _write_chunk(data, li_table, cuts, 2)
        _write_chunk(data, li_table, cuts, 3)
        h.process_available()
        p2 = h.progress()
        assert p2["streaming.filesTotal"] == 2, p2
        assert p2["streaming.backlogFiles"] == 2, p2
        caps = _stream_events(h, "stream_batch_capped")
        assert caps and caps[-1]["deferred_files"] == 2
        h.process_available()
        out4 = h.process_available()
        p4 = h.progress()
        assert p4["streaming.filesTotal"] == 4, p4
        assert p4["streaming.backlogFiles"] == 0, p4
        assert _batch_rows(out4) == _oracle(1, data)
    finally:
        h.stop()


def test_batch_deadline_miss_leaves_ledger_unadvanced(li_table, tmp_path):
    """``streaming.batchDeadlineMs`` rides the scheduler's cooperative
    deadline: a missed batch raises, emits ``stream_batch_error``, and
    does NOT commit — the next stream over the same state starts from
    batch 0 and serves the full, correct answer."""
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 2)
    _write_chunk(data, li_table, cuts, 0)
    sess = srt.Session(_conf(tmp_path / "rec", **{
        "spark.rapids.tpu.streaming.batchDeadlineMs": 1}))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        with pytest.raises(Exception):
            h.process_available()
        errs = _stream_events(h, "stream_batch_error")
        assert errs and errs[-1]["batch_id"] == 1
        assert not _stream_events(h, "stream_batch_commit")
    finally:
        h.stop()

    sess2 = srt.Session(_conf(tmp_path / "rec"))
    h2 = sess2.stream(_tpch_query(sess2, 1, data), trigger=0)
    try:
        assert not h2.resumed  # nothing was ever committed
        out = h2.process_available()
        assert _batch_rows(out) == _oracle(1, data)
    finally:
        h2.stop()


def test_stream_requires_conf_and_file_sources(li_table, tmp_path):
    data = tmp_path / "lineitem"
    _write_chunk(data, li_table, _cuts(li_table, 2), 0)
    sess = srt.Session(dict(FAST))
    with pytest.raises(RuntimeError, match="streaming.enabled"):
        sess.stream(_tpch_query(sess, 1, data))

    sess2 = srt.Session(_conf(tmp_path / "rec"))
    tables = tpch_datagen.dataframes(sess2, sf=0.001)
    with pytest.raises(ValueError, match="file source"):
        sess2.stream(tpch.QUERIES[1](tables))  # all in-memory

    hive = tmp_path / "hive" / "k=1"
    _write_chunk(hive, li_table, _cuts(li_table, 2), 0)
    with pytest.raises(ValueError, match="Hive-partitioned"):
        sess2.stream(
            _tpch_query(sess2, 1, tmp_path / "hive"), trigger=0)


@pytest.mark.slow
def test_trigger_loop_commits_batches(li_table, tmp_path):
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 2)
    _write_chunk(data, li_table, cuts, 0)
    sess = srt.Session(_conf(tmp_path / "rec"))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=50)
    try:
        out = h.await_batch(timeout=120)
        assert _batch_rows(out) == _oracle(1, data)
        _write_chunk(data, li_table, cuts, 1)
        out2 = h.await_batch(timeout=120)
        assert _batch_rows(out2) == _oracle(1, data)
    finally:
        h.stop()
    with pytest.raises(RuntimeError):
        h.process_available()


# ==========================================================================
# Pinned state vs the hygiene sweep (regression: a TTL/maxBytes sweep
# racing a live stream must never evict its aggregate state)
# ==========================================================================
@pytest.mark.slow
def test_sweep_during_live_stream_spares_pinned_state(li_table, tmp_path):
    from spark_rapids_tpu.recovery.store import CheckpointStore

    root = tmp_path / "rec"
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 3)
    _write_chunk(data, li_table, cuts, 0)
    _write_chunk(data, li_table, cuts, 1)
    sess = srt.Session(_conf(root))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    store = CheckpointStore(str(root))
    try:
        h.process_available()
        qdir = store.query_dir(h.stream_fp)
        assert os.path.isdir(qdir)
        # an aggressive sweep (everything expired AND over budget)
        # must spare the live stream's pinned state
        res = store.sweep(ttl_seconds=1e-9, max_bytes=1)
        assert os.path.isdir(qdir), res
        _write_chunk(data, li_table, cuts, 2)
        out2 = h.process_available()
        p2 = h.progress()
        assert _batch_rows(out2) == _oracle(1, data)
        assert p2["streaming.stagesResumed"] >= 1, p2  # state survived
    finally:
        h.stop()
    # stop() unpins: now the same sweep may reclaim the state
    store.sweep(ttl_seconds=1e-9, max_bytes=1)
    assert not os.path.isdir(store.query_dir(h.stream_fp))


# ==========================================================================
# SIGKILL between micro-batches, resume in a fresh process
# ==========================================================================
_CHILD = textwrap.dedent("""\
    import json, os, signal, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    sys.path.insert(0, {repo!r})
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.benchmarks import tpch, tpch_datagen

    mode = sys.argv[1]       # "crash" | "resume" | "oracle"
    root = sys.argv[2]
    data = sys.argv[3]
    conf = {{
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        "spark.rapids.tpu.recovery.enabled": mode != "oracle",
        "spark.rapids.tpu.recovery.dir": root,
        "spark.rapids.tpu.streaming.enabled": True,
        "spark.rapids.tpu.telemetry.enabled": True,
    }}
    sess = srt.Session(conf)
    tables = tpch_datagen.dataframes(sess, sf=0.001)
    tables["lineitem"] = sess.read_parquet(data)
    df = tpch.QUERIES[1](tables)

    def norm(rows):
        return sorted((tuple(round(v, 9) if isinstance(v, float) else v
                             for v in r) for r in rows), key=repr)

    if mode == "oracle":
        print("RESULT:" + json.dumps({{"rows": repr(norm(df.collect()))}}))
        sys.exit(0)
    h = sess.stream(df, trigger=0)
    if mode == "crash":
        h.process_available()   # batch 1 commits (ledger + checkpoints)
        os.kill(os.getpid(), signal.SIGKILL)   # die between batches
    out = h.process_available()
    rows = norm(zip(*[c.to_pylist() for c in out.columns]))
    print("RESULT:" + json.dumps({{
        "rows": repr(rows), "resumed": bool(h.resumed),
        "progress": h.progress()}}))
""")


def _run_child(mode, root, data):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=repo),
         mode, str(root), str(data)],
        capture_output=True, text=True, timeout=300)


def _child_result(proc):
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT:"):
            return json.loads(line[len("RESULT:"):])
    raise AssertionError(
        f"child produced no RESULT\nstdout: {proc.stdout}\n"
        f"stderr: {proc.stderr}")


@pytest.mark.slow
def test_sigkill_between_batches_resumes_in_fresh_process(
        li_table, tmp_path):
    root, data = tmp_path / "rec", tmp_path / "lineitem"
    cuts = _cuts(li_table, 3)
    _write_chunk(data, li_table, cuts, 0)
    _write_chunk(data, li_table, cuts, 1)
    crashed = _run_child("crash", root, data)
    assert crashed.returncode == -signal.SIGKILL, crashed.stderr

    _write_chunk(data, li_table, cuts, 2)  # arrives while "down"
    got = _child_result(_run_child("resume", root, data))
    assert got["resumed"] is True  # the durable ledger was found
    prog = got["progress"]
    assert prog["streaming.batchId"] == 2, prog  # continued, not restarted
    assert prog["streaming.stagesResumed"] > 0, prog
    assert prog["streaming.recomputeFraction"] < 1.0, prog
    oracle = _child_result(_run_child("oracle", root, data))
    assert got["rows"] == oracle["rows"]


# ==========================================================================
# Unit coverage: ledger + plan-shape normalization (no engine)
# ==========================================================================
def test_split_new_files_prefix_contract():
    from spark_rapids_tpu.streaming.ledger import split_new_files

    a = {"path": "a", "size": 1, "mtime_ns": 10}
    b = {"path": "b", "size": 2, "mtime_ns": 20}
    c = {"path": "c", "size": 3, "mtime_ns": 30}
    assert split_new_files([], [a, b]) == (True, [a, b])
    assert split_new_files([a], [a, b, c]) == (True, [b, c])
    assert split_new_files([a, b], [a, b]) == (True, [])
    # rewritten / truncated committed prefix breaks the contract
    assert split_new_files([a, b], [a]) == (False, [])
    a2 = dict(a, mtime_ns=11)
    assert split_new_files([a], [a2, b]) == (False, [])


def test_normalize_plan_text_erases_growing_counts():
    from spark_rapids_tpu.streaming.incremental import normalize_plan_text

    t1 = ("ShuffleExchange[HashPartitioning([k1, k2], 3)]\n"
          "  ShuffleExchange[RangePartitioning(3)]\n"
          "    FileScan[parquet](3 files)")
    t2 = ("ShuffleExchange[HashPartitioning([k1, k2], 8)]\n"
          "  ShuffleExchange[RangePartitioning(8)]\n"
          "    FileScan[parquet](17 files)")
    assert normalize_plan_text(t1) == normalize_plan_text(t2)
    # but keys and operators still distinguish shapes
    t3 = t1.replace("k2", "k9")
    assert normalize_plan_text(t1) != normalize_plan_text(t3)


# ==========================================================================
# Batch-latency histogram in the export surface (ISSUE 13)
# ==========================================================================
def test_batch_latency_histogram_in_progress_and_prometheus(
        li_table, tmp_path):
    data = tmp_path / "lineitem"
    cuts = _cuts(li_table, 3)
    _write_chunk(data, li_table, cuts, 0)
    sess = srt.Session(_conf(tmp_path / "rec"))
    h = sess.stream(_tpch_query(sess, 1, data), trigger=0)
    try:
        h.process_available()
        _write_chunk(data, li_table, cuts, 1)
        h.process_available()
        prog = h.progress()
        for p in ("P50", "P95", "P99"):
            assert f"streaming.batchLatency{p}Ms" in prog, sorted(prog)
        assert prog["streaming.batchLatencyP50Ms"] <= \
            prog["streaming.batchLatencyP99Ms"]
        assert prog["streaming.batchLatencyP50Ms"] > 0
        # live streams surface through the session's export/prometheus
        # aggregation, one labeled histogram series per stream
        em = sess.export_metrics()
        assert any(k.startswith("streaming.batchLatency") for k in em)
        text = sess.metrics_text()
        assert ("# TYPE spark_rapids_tpu_stream_batch_latency_ms "
                "histogram") in text
        assert f'le="+Inf"}} 2' in text
        assert f'stream="{h.stream_id}"' in text
    finally:
        h.stop()
    # a stopped stream drops out of the aggregation
    assert not any(k.startswith("streaming.")
                   for k in sess.export_metrics())
