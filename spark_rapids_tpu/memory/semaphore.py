"""Device admission semaphore.

Reference analogue: GpuSemaphore.scala — limits concurrent tasks holding
the device (default small), acquired just before device work (e.g. right
before upload/decode, GpuParquetScan.scala:554) and released while tasks do
host/IO work, so host-side decode overlaps device compute.

Discipline (reference: GpuSemaphore.scala:58-160 — task-scoped acquire +
a task-completion listener that always releases):

* acquire happens lazily inside device-entry iterators (H2D upload);
* every task-runner thread releases its full hold in a ``finally``
  (``collect_batches`` in plan/physical.py, ``_run_leaf`` drain workers
  in parallel/runner.py);
* a thread must NEVER block on another thread's progress while holding
  a permit — call :meth:`release_all` first (see
  exec/exchange.py ``materialized``);
* acquire carries a watchdog: a blocked acquire past the deadline raises
  ``DeviceSemaphoreTimeout`` instead of hanging the process, so a future
  permit leak fails loudly with a diagnostic."""
from __future__ import annotations

import threading


class DeviceSemaphoreTimeout(RuntimeError):
    """A device-semaphore acquire blocked past the watchdog deadline —
    almost always a leaked permit (a task thread that exited without
    ``release_all``) or a hold-while-blocked cycle."""


class DeviceSemaphore:
    #: watchdog for a single blocked acquire; long enough for any real
    #: device program (first XLA compile included), short enough that CI
    #: fails instead of eating its whole budget
    ACQUIRE_TIMEOUT_SECONDS = 180.0

    def __init__(self, permits: int,
                 acquire_timeout: float | None = None):
        import time

        self.permits = permits
        self._sem = threading.Semaphore(permits)
        self._held = threading.local()
        self.acquire_timeout = (acquire_timeout
                                if acquire_timeout is not None
                                else self.ACQUIRE_TIMEOUT_SECONDS)
        #: monotonic stamp of the most recent release — the watchdog
        #: measures STALL (no release anywhere), not queueing time, so
        #: a long fair queue behind slow-but-progressing tasks never
        #: trips it
        self._last_release = time.monotonic()

    def acquire_if_necessary(self) -> None:
        """Idempotent per-thread acquire (a task re-entering device code
        does not double-count — reference GpuSemaphore.acquireIfNecessary).

        Raises :class:`DeviceSemaphoreTimeout` only when NO permit has
        been released anywhere for ``acquire_timeout`` seconds while
        this thread waited — i.e. the pool has genuinely stopped making
        progress (leaked permit / hold-while-blocked cycle)."""
        import time

        if getattr(self._held, "count", 0) == 0:
            start = time.monotonic()
            while not self._sem.acquire(
                    timeout=min(self.acquire_timeout / 4, 10.0)):
                progress = max(self._last_release, start)
                if time.monotonic() - progress > self.acquire_timeout:
                    raise DeviceSemaphoreTimeout(
                        f"device semaphore made no progress for > "
                        f"{self.acquire_timeout}s ({self.permits} "
                        f"permits, thread "
                        f"{threading.current_thread().name}); a task "
                        "thread likely leaked its permit (missing "
                        "release_all) or blocked while holding one")
        self._held.count = getattr(self._held, "count", 0) + 1

    def release_if_necessary(self) -> None:
        import time

        count = getattr(self._held, "count", 0)
        if count > 0:
            self._held.count = count - 1
            if self._held.count == 0:
                self._last_release = time.monotonic()
                self._sem.release()

    def release_all(self) -> None:
        """Drop this thread's entire hold — the task-completion release
        (reference: GpuSemaphore's task-completion listener,
        GpuSemaphore.scala:101-160).  The underlying permit is held once
        per thread regardless of the reentrancy count."""
        import time

        count = getattr(self._held, "count", 0)
        if count > 0:
            self._held.count = 0
            self._last_release = time.monotonic()
            self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
