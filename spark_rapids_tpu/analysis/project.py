"""Project file discovery and cached AST parsing.

A :class:`Project` roots at the repository directory (the parent of the
``spark_rapids_tpu`` package) and discovers every analyzable source
file once: the whole package tree plus the top-level bench drivers
(``bench.py``, ``bench_streaming.py``, ``bench_serving.py``) — the
drift rules cross-check artifact schema constants there.  Parses are
cached per file, so the N rules that walk overlapping scopes cost one
``ast.parse`` per file, which is what keeps the full engine run well
under its 10s budget.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional

PACKAGE = "spark_rapids_tpu"

#: top-level driver scripts included in discovery (drift rules)
TOP_LEVEL_FILES = ("bench.py", "bench_streaming.py", "bench_serving.py")


def default_root() -> str:
    """The repo root: parent of the installed package directory."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


class Project:
    """Discovered source files + cached parses under ``root``."""

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(root or default_root())
        self._files: Optional[List[str]] = None
        self._trees: Dict[str, ast.Module] = {}
        self._sources: Dict[str, str] = {}
        #: files that failed to parse: relpath -> error string
        self.parse_errors: Dict[str, str] = {}

    # ---------------- discovery ----------------------------------------
    def files(self) -> List[str]:
        """Every analyzable source file, as sorted repo-root-relative
        posix paths."""
        if self._files is not None:
            return self._files
        out: List[str] = []
        pkg = os.path.join(self.root, PACKAGE)
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.root)
                    out.append(rel.replace(os.sep, "/"))
        for fn in TOP_LEVEL_FILES:
            if os.path.isfile(os.path.join(self.root, fn)):
                out.append(fn)
        self._files = sorted(out)
        return self._files

    def select(self, prefixes: Iterable[str] = (),
               files: Iterable[str] = (),
               exclude: Iterable[str] = ()) -> List[str]:
        """Scope helper: files under any of ``prefixes`` plus the named
        ``files`` (when they exist), minus exact ``exclude`` paths."""
        prefixes = tuple(prefixes)
        wanted = set(files)
        excluded = set(exclude)
        out = []
        for rel in self.files():
            if rel in excluded:
                continue
            if rel in wanted or any(rel.startswith(p) for p in prefixes):
                out.append(rel)
        return out

    # ---------------- parsing ------------------------------------------
    def path(self, rel: str) -> str:
        return os.path.join(self.root, rel.replace("/", os.sep))

    def source(self, rel: str) -> str:
        src = self._sources.get(rel)
        if src is None:
            with open(self.path(rel), encoding="utf-8") as f:
                src = f.read()
            self._sources[rel] = src
        return src

    def tree(self, rel: str) -> Optional[ast.Module]:
        """Parsed AST for ``rel``, or None on a syntax error (recorded
        in :attr:`parse_errors` — the engine reports those as findings
        so a broken file can never silently drop out of every scope)."""
        if rel in self._trees:
            return self._trees[rel]
        if rel in self.parse_errors:
            return None
        if not os.path.isfile(self.path(rel)):
            # rules may probe well-known paths (custodian modules,
            # bench drivers) that a partial tree simply lacks
            return None
        try:
            tree = ast.parse(self.source(rel), filename=rel)
        except SyntaxError as e:
            self.parse_errors[rel] = f"{type(e).__name__}: {e}"
            return None
        self._trees[rel] = tree
        return tree

    def read_text(self, rel: str) -> Optional[str]:
        """Raw text of an arbitrary repo-relative file (docs etc.), or
        None when it does not exist."""
        p = os.path.join(self.root, rel.replace("/", os.sep))
        if not os.path.isfile(p):
            return None
        with open(p, encoding="utf-8") as f:
            return f.read()
