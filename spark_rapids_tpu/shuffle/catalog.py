"""Shuffle buffer catalog.

Reference analogue: ShuffleBufferCatalog.scala — a
shuffle-id -> map-id -> buffers index layered over the spill-buffer
catalog, with per-shuffle cleanup so a query's shuffle data is freed
even when a reader abandons early (a ``limit(1)`` over a shuffled
join), and RapidsShuffleInternalManager.scala:230-250's
unregister-on-shuffle-end.  Buffer payloads live in the spill
framework; this index owns only ids and their grouping.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional


class ShuffleCatalog:
    def __init__(self, fw):
        self._fw = fw
        self._lock = threading.Lock()
        self._next = itertools.count()
        #: shuffle id -> map id -> [spill buffer ids]
        self._shuffles: Dict[int, Dict[int, List[int]]] = {}

    # ----- write side -------------------------------------------------
    def register_shuffle(self) -> int:
        with self._lock:
            sid = next(self._next)
            self._shuffles[sid] = {}
            return sid

    def add_buffer(self, shuffle_id: int, map_id: int,
                   buf_id: int) -> None:
        with self._lock:
            maps = self._shuffles.get(shuffle_id)
            if maps is None:  # already unregistered: free immediately
                self._fw.remove_batch(buf_id)
                return
            maps.setdefault(map_id, []).append(buf_id)

    # ----- read side --------------------------------------------------
    def buffers_of(self, shuffle_id: int,
                   map_id: Optional[int] = None) -> List[int]:
        with self._lock:
            maps = self._shuffles.get(shuffle_id, {})
            if map_id is not None:
                return list(maps.get(map_id, ()))
            return [b for bs in maps.values() for b in bs]

    def active_shuffles(self) -> List[int]:
        with self._lock:
            return list(self._shuffles)

    def slot_count(self, shuffle_id: Optional[int] = None) -> int:
        """Registered buffer slots (one shuffle, or all) — the leak
        metric the stage-retry regression tests watch."""
        with self._lock:
            if shuffle_id is not None:
                maps = self._shuffles.get(shuffle_id, {})
                return sum(len(bs) for bs in maps.values())
            return sum(len(bs) for maps in self._shuffles.values()
                       for bs in maps.values())

    # ----- cleanup ----------------------------------------------------
    def drop_buffers(self, shuffle_id: int, buf_ids) -> None:
        """Release SPECIFIC spill entries of one shuffle without
        unregistering the shuffle id — the cleanup of a failed or
        re-executed write attempt (stage retry): the retry re-registers
        a fresh set under the same shuffle id, and without this the
        dead attempt's ids would hold catalog slots until query end."""
        drop = set(buf_ids)
        if not drop:
            return
        with self._lock:
            maps = self._shuffles.get(shuffle_id)
            if maps is not None:
                for mid in list(maps):
                    kept = [b for b in maps[mid] if b not in drop]
                    if kept:
                        maps[mid] = kept
                    else:
                        del maps[mid]
        for b in drop:
            self._fw.remove_batch(b)  # idempotent

    def unregister_shuffle(self, shuffle_id: int) -> None:
        """Free every buffer of one shuffle (idempotent)."""
        with self._lock:
            maps = self._shuffles.pop(shuffle_id, None)
        if maps:
            for bufs in maps.values():
                for b in bufs:
                    self._fw.remove_batch(b)

    def clear(self) -> None:
        for sid in self.active_shuffles():
            self.unregister_shuffle(sid)
