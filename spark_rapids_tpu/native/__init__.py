"""ctypes bindings for the native runtime library.

The reference ships its runtime natively (RMM pool allocator, cudf's
JCudfSerialization, HashedPriorityQueue on the hot spill path — SURVEY
§2.5/§2.9); here the host-runtime equivalents live in
``native/src/srt_native.cc`` and are loaded through ctypes (no pybind11
in the image).  The library is compiled on first use via the checked-in
Makefile and cached; every consumer has a pure-Python fallback, so the
framework still works where no C++ toolchain exists.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libsrt_native.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-s", "-C", _NATIVE_DIR],
                       check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # noqa: BLE001
        # quiet only when an up-to-date .so exists (shipped-.so
        # deployments without a toolchain); a missing or stale library
        # is a real problem worth surfacing
        src = os.path.join(_NATIVE_DIR, "src", "srt_native.cc")
        # a shipped .so without sources counts as current
        fresh = (os.path.exists(_SO_PATH)
                 and (not os.path.exists(src)
                      or os.path.getmtime(_SO_PATH)
                      >= os.path.getmtime(src)))
        if fresh:
            log.debug("native build failed (%s); existing library is "
                      "current", e)
        elif os.path.exists(_SO_PATH):
            log.warning("native build failed (%s); loading STALE library "
                        "older than its source", e)
        else:
            log.warning("native build failed (%s); using Python "
                        "fallbacks", e)
        return False


def _declare(lib) -> None:
    c = ctypes
    u64, i64, u32, i32 = c.c_uint64, c.c_int64, c.c_uint32, c.c_int32
    p = c.c_void_p
    u8p = c.POINTER(c.c_uint8)
    sigs = {
        "srt_arena_create": (p, [u64, i32]),
        "srt_arena_destroy": (None, [p]),
        "srt_arena_alloc": (i64, [p, u64]),
        "srt_arena_free": (i32, [p, i64]),
        "srt_arena_allocated": (u64, [p]),
        "srt_arena_available": (u64, [p]),
        "srt_arena_largest_free": (u64, [p]),
        "srt_arena_base": (u8p, [p]),
        "srt_hpq_create": (p, []),
        "srt_hpq_destroy": (None, [p]),
        "srt_hpq_push": (None, [p, i64, c.c_double]),
        "srt_hpq_pop": (i64, [p]),
        "srt_hpq_peek": (i64, [p]),
        "srt_hpq_remove": (i32, [p, i64]),
        "srt_hpq_contains": (i32, [p, i64]),
        "srt_hpq_size": (u64, [p]),
        "srt_frame_size": (u64, [u32, c.POINTER(u64), c.POINTER(u64)]),
        "srt_frame_write": (u64, [u8p, u32, u64, c.POINTER(u8p),
                                  c.POINTER(u64), c.POINTER(u8p),
                                  c.POINTER(u64), c.POINTER(i32)]),
        "srt_frame_header": (i32, [u8p, c.POINTER(u32), c.POINTER(u64),
                                   c.POINTER(u64)]),
        "srt_frame_columns": (None, [u8p, u32, c.POINTER(i32),
                                     c.POINTER(u64), c.POINTER(u64),
                                     c.POINTER(u64), c.POINTER(u64)]),
    }
    for name, (res, args) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = args


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        # always invoke make: the Makefile's source dependency makes it a
        # no-op when fresh and rebuilds when srt_native.cc changed (a
        # stale .so would silently diverge from the numpy fallback)
        if not _build() and not os.path.exists(_SO_PATH):
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            _declare(lib)
            _lib = lib
        except OSError as e:
            log.warning("native load failed (%s); using Python fallbacks", e)
            _load_failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None
