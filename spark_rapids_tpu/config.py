"""Typed configuration registry.

Capability parity with the reference's ``RapidsConf.scala`` (832 LoC): a
typed builder with defaults and validators, a global registry, markdown doc
generation, and *auto-derived per-operator enable/disable keys* from the
plan-rewrite rule framework (reference: GpuOverrides.scala:118-123 derives
``spark.rapids.sql.<kind>.<ClassName>``).

Keys here live under ``spark.rapids.tpu.*`` and mirror the reference's
grouping: memory, scheduling, batch sizing, feature gates, test hooks,
shuffle/exchange, explain.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional

_REGISTRY: Dict[str, "ConfEntry"] = {}
_REG_LOCK = threading.Lock()


class ConfEntry:
    def __init__(self, key: str, converter: Callable[[str], Any],
                 doc: str, default: Any, is_internal: bool = False,
                 checker: Optional[Callable[[Any], Optional[str]]] = None):
        self.key = key
        self.converter = converter
        self.doc = doc
        self.default = default
        self.is_internal = is_internal
        self.checker = checker
        with _REG_LOCK:
            if key in _REGISTRY:
                raise ValueError(f"duplicate conf key {key}")
            _REGISTRY[key] = self

    def get(self, conf: Dict[str, Any]) -> Any:
        if self.key in conf:
            raw = conf[self.key]
            val = self.converter(raw) if isinstance(raw, str) else raw
        else:
            env_key = self.key.upper().replace(".", "_")
            if env_key in os.environ:
                val = self.converter(os.environ[env_key])
            else:
                return self.default
        if self.checker is not None:
            err = self.checker(val)
            if err:
                raise ValueError(f"{self.key}: {err}")
        return val

    def help(self) -> str:
        return f"{self.key} — {self.doc} (default: {self.default})"


def _to_bool(s: str) -> bool:
    return s.strip().lower() in ("true", "1", "yes", "on")


class ConfBuilder:
    """``conf("key").doc(...).boolean_conf(default)`` builder, mirroring the
    reference's ``ConfBuilder``/``TypedConfBuilder`` (RapidsConf.scala:128-206)."""

    def __init__(self, key: str):
        self.key = key
        self._doc = ""
        self._internal = False
        self._checker = None

    def doc(self, text: str) -> "ConfBuilder":
        self._doc = text
        return self

    def internal(self) -> "ConfBuilder":
        self._internal = True
        return self

    def check(self, fn: Callable[[Any], Optional[str]]) -> "ConfBuilder":
        self._checker = fn
        return self

    def _mk(self, conv, default):
        return ConfEntry(self.key, conv, self._doc, default,
                         self._internal, self._checker)

    def boolean_conf(self, default: bool) -> ConfEntry:
        return self._mk(_to_bool, default)

    def int_conf(self, default: int) -> ConfEntry:
        return self._mk(int, default)

    def long_conf(self, default: int) -> ConfEntry:
        return self._mk(int, default)

    def double_conf(self, default: float) -> ConfEntry:
        return self._mk(float, default)

    def string_conf(self, default: Optional[str]) -> ConfEntry:
        return self._mk(str, default)


def conf(key: str) -> ConfBuilder:
    return ConfBuilder(key)


def lookup(key: str) -> Optional[ConfEntry]:
    return _REGISTRY.get(key)


def register_op_enable_key(kind: str, name: str, doc: str,
                           default: bool = True) -> ConfEntry:
    """Auto-derived per-operator key, e.g.
    ``spark.rapids.tpu.sql.exec.SortExec`` (reference GpuOverrides.scala:118-123).

    Idempotent per key."""
    key = f"spark.rapids.tpu.sql.{kind}.{name}"
    existing = lookup(key)
    if existing is not None:
        return existing
    return conf(key).doc(doc).boolean_conf(default)


def dump_markdown() -> str:
    """Generate the configs doc table (reference: docs/configs.md is generated
    from the registry, RapidsConf.scala help/makeConfAnchor)."""
    lines = ["# Configuration", "",
             "| Key | Default | Description |", "|---|---|---|"]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.is_internal:
            continue
        lines.append(f"| `{key}` | {e.default} | {e.doc} |")
    lines += ["", _MEMORY_ROBUSTNESS_DOC, "", _FAULT_TOLERANCE_DOC,
              "", _SCHEDULING_DOC, "", _QOS_DOC, "",
              _OBSERVABILITY_DOC, "", _PERF_TUNING_DOC, "",
              _SHUFFLE_DOC, "", _ADAPTIVE_DOC, "", _RECOVERY_DOC, "",
              _STREAMING_DOC, "", _SERVING_CACHE_DOC]
    return "\n".join(lines)


_QOS_DOC = """\
## Multi-tenant QoS: fair admission, aging, preemption, shedding

The `scheduler.tenant.*` / `scheduler.overload.*` /
`scheduler.priorityAgingMs` / `scheduler.preemption.*` confs (table
above) configure the multi-tenant QoS layer
(`spark_rapids_tpu/scheduler/qos.py`, docs/qos.md):

* **Tenants** — `Session.submit(plan, priority, tenant="name")` routes
  through per-tenant queues drained by deficit-weighted fair share.
  Tenant names need no pre-registration: `scheduler.tenant.<name>.
  {weight,maxConcurrent,hbmFraction}` are read as dynamic keys, falling
  back to the registered `scheduler.tenant.default.*` entries.
* **Priority aging** — a queued query's effective priority grows by one
  per `scheduler.priorityAgingMs` of queue wait, so fixed priorities
  order dispatch but can never starve a queued query forever.
* **Checkpoint-backed preemption** — `scheduler.preemption.enabled`
  lets a strictly higher-priority queued query evict the
  lowest-priority running victim through the cooperative-cancel
  zero-leak unwind; the victim is requeued (keeping its aging credit)
  and, with `recovery.enabled`, resumes from its completed exchange
  checkpoints (`recovery.numStagesResumed` in the victim's metrics).
  Each preemption is charged against the victim's
  `fault.maxTotalAttempts` budget.
* **Overload detection + load shedding** — the OverloadMonitor tracks
  queue-wait p95 and arena pressure against
  `scheduler.overload.{queueWaitMs,hbmFraction}`; while overloaded,
  submissions below `scheduler.overload.shedBelowPriority` are shed
  with `TpuOverloaded(retry_after_ms=...)`, and
  `overload_{enter,exit,shed}` / `preempt_{victim,resume}` telemetry
  events plus `scheduler.tenant.*` counters make the behavior
  observable (`QueryScheduler.qos_metrics()`)."""


_RECOVERY_DOC = """\
## Stage-level checkpointing & crash recovery

The `recovery.*` confs (table above) configure durable stage
checkpoints (`spark_rapids_tpu/recovery/`, docs/recovery.md):

* **Checkpoint writes** — with `recovery.enabled`, every exchange the
  engine finishes materializing is persisted under
  `recovery.dir/<query_fingerprint>/<exchange_fingerprint>/` as
  CRC32C-stamped partition frames (the spill frame format, host
  bytes — readable by the device, host-shuffle and CPU ladder rungs
  alike) plus an atomically written JSON manifest carrying the plan
  fingerprint, schema signatures, the partition histogram and a
  snapshot of the result-affecting conf keys.
* **Resume** — stage retries, degradation-ladder rungs and (with
  `recovery.autoResume`, or explicitly via `Session.resume(plan)`) a
  fresh process after a crash fingerprint-match the plan, verify every
  manifest and frame CRC eagerly, skip completed exchanges by feeding
  the checkpointed blocks through the exchange read path, and
  re-execute only the unexecuted suffix
  (`recovery.numStagesResumed` in `Session.last_metrics`).
* **Quarantine, never a wrong answer** — a checkpoint failing ANY
  validity check (frame CRC, plan fingerprint, schema signature,
  result-affecting conf snapshot, malformed manifest) is renamed aside
  and a `checkpoint_quarantine` event emitted; the exchange re-executes
  from scratch.
* **Hygiene** — `Session.close()` and scheduler shutdown sweep
  crash-orphaned temp files, expired checkpoints
  (`recovery.ttlSeconds`) and evict least-recently-touched query
  directories over `recovery.maxBytes`; ENOSPC/OSError during a
  checkpoint write disables checkpointing for the query
  (`checkpoint_disabled` event) instead of failing it.
* **Unified retry budget** — `fault.maxTotalAttempts` is the single
  per-query ceiling across task retries, stage retries, shuffle
  fallbacks and ladder rungs; crossing it emits ONE terminal
  `attempt_budget_exhausted` event with the full attempt ledger."""


_STREAMING_DOC = """\
## Incremental streaming execution

The `streaming.*` confs (table above) configure micro-batch
continuous queries (`spark_rapids_tpu/streaming/`, docs/streaming.md):

* **Micro-batch triggers** — `session.stream(plan)` returns a
  `StreamHandle`; every `streaming.triggerIntervalMs` a tick discovers
  newly arrived files (at most `streaming.maxBatchFiles` per batch),
  pins the cumulative file list into the plan and executes it through
  the PR-11 scheduler path under a per-batch
  `streaming.batchDeadlineMs` deadline SLA.
* **Incremental state on the recovery substrate** — each growing
  exchange's partial-aggregate frames persist via the CheckpointStore;
  the next tick executes only the delta files and MERGES their frames
  after the checkpointed ones, so untouched partitions resume from
  CRC-verified checkpoints instead of recomputing
  (`streaming.recomputeFraction` < 1 in batch progress).
* **Exactly-once ledger** — the source ledger under
  `<recovery.dir>/streams/<stream-fingerprint>/` (relocatable via
  `streaming.stateDir`) commits atomically AFTER each batch; a crash
  between batches replays the tick idempotently
  (`Session.resume_stream` in a fresh process, bit-identical results,
  `recovery.numStagesResumed > 0`).
* Every decision emits a `stream_*` telemetry event; results are
  bit-identical to a cold recompute of the same cumulative input,
  including under fault injection and ladder degradation."""


_SERVING_CACHE_DOC = """\
## Sub-second serving: prepared statements & the serving caches

The `serving.cache.*` confs (table above) configure the serving
subsystem (`spark_rapids_tpu/serving/`, docs/serving_cache.md):

* **Prepared statements** — `Session.prepare(plan)` extracts literal
  parameters from the logical plan into a parameterized skeleton;
  `prepared.execute(params)` / `prepared.submit(params)` re-bind
  literals at dispatch without re-planning, re-fingerprinting or
  re-fusing the plan.
* **Plan-template cache** — keyed by the skeleton fingerprint (the
  KernelCache fingerprint discipline applied to optimized-plan
  skeletons): ad-hoc `submit()` calls that normalize to an
  already-seen template reuse the cached optimized physical plan and
  fused segments instead of planning from scratch
  (`serving.cache.templates.maxEntries` bounds the LRU).
* **Result cache** — keyed by the recovery subsystem's rung-invariant
  query+data fingerprint (plan fingerprint x per-file leaf material
  from the discovery stat pass) and stored in the CheckpointStore
  frame format under the reserved `serving/` directory of the
  recovery root.  A `submit()` whose fingerprint matches a cached
  result completes BEFORE admission — a hit never queues, never
  holds an HBM reservation and reports `exec_path == "cache"`.
* **Invalidation, never a stale answer** — every read re-stats the
  scanned files (the same per-file fingerprints the streaming ledger
  commits) and re-validates plan fingerprint, schema signature,
  result-affecting conf snapshot and frame CRCs; ANY doubt
  quarantines the entry (`cache_quarantine`) and the query executes
  cold.  Changed inputs invalidate eagerly (`cache_invalidate`).
* **Eviction** — `serving.cache.results.maxBytes` caps the on-disk
  result bytes; least-recently-used entries are evicted
  (`cache_evict`).  `cache_hit`/`cache_miss`/`cache_store` events and
  `serving.cache.*` metrics (plus the per-tenant `cacheHits` counter)
  make every decision observable.
* **Streaming composition** — a maintained incremental streaming
  aggregate registers its materialized per-tick result in the result
  cache, so a `submit()` of the stream's own query between ticks is a
  cache hit instead of a recompute."""


_ADAPTIVE_DOC = """\
## Adaptive query execution

The `adaptive.*` confs (table above) configure the AQE subsystem
(`spark_rapids_tpu/adaptive/`, docs/adaptive.md):

* **Runtime stage statistics** — the device shuffle's write drain
  already pulls per-partition count vectors to the host in its one
  gated batch readback; `StageStats` aggregates them (plus block byte
  sizes from the arena accounting) into exact per-exchange partition
  histograms with ZERO extra device syncs (lint-enforced), surfaced as
  `shuffle.exchange<N>.partRows{Min,P50,Max}`/`skewPct` in
  `Session.last_metrics`, `profile_report()` and the Prometheus export
  even with `adaptive.enabled=false`.
* **Partition coalescing** — adjacent small post-shuffle partitions
  are merged up to `adaptive.targetPartitionBytes`, shrinking reader
  fan-in; both sides of a co-partitioned join get the identical
  grouping.
* **Skew-join splitting** — a partition exceeding
  `adaptive.skewedPartitionFactor` x the median rows (and
  `adaptive.skewedPartitionThresholdBytes`) is cut into contiguous
  row-balanced sub-slices, each joined against a replica of the full
  build-side partition — the straggler that used to eat the whole
  stage wall (and trip the stage watchdogs) becomes parallel work.
* **Dynamic broadcast conversion** — a planned shuffled-hash join
  whose MATERIALIZED build side lands under
  `adaptive.autoBroadcastJoinThreshold` is demoted to a broadcast
  join, skipping the stream-side exchange entirely.
* Every decision emits a structured `aqe_*` telemetry event, the
  final plan renders AdaptiveSparkPlan-style in EXPLAIN ANALYZE, and
  the scheduler's per-query HBM reservation is re-based from observed
  stage output.  All rewrites are bit-identical to the non-adaptive
  plan — same values, same row placement after the re-partitioning
  rules — including under fault injection and concurrent submit."""


_SHUFFLE_DOC = """\
## Device-resident shuffle

The `shuffle.*` confs (table above) configure the exchange data path
(`exec/exchange.py`, `shuffle/device_shuffle.py`, docs/shuffle.md):

* **Device path** (`shuffle.mode=device`, or `auto` with HBM headroom)
  — hash/round-robin/single-partitioned shuffle blocks stay resident in
  HBM: one jitted partition-build kernel (shared through the kernel
  cache) sorts each input batch by destination partition and records
  per-partition start/count vectors, and readers slice their partition
  out with one gather kernel.  No per-partition d2h -> CRC -> h2d round
  trip; CRC32C stamping happens only if a block crosses the spill/host
  boundary.  Mesh-distributed plans move the same packed form between
  participants via one fused `lax.all_to_all` collective
  (`parallel/exchange.py`).
* **Host path** (`shuffle.mode=host`) — every block is staged to host
  memory immediately and CRC32C-stamped, the fully-verified pre-device
  behavior; `auto` degrades to it under HBM pressure, and blocks the
  spill framework demotes off-device are verified on re-read either
  way.
* **Fallback ladder** — a device-shuffle query that exhausts fault
  recovery re-executes on the host shuffle path (a `shuffle_fallback` +
  `degrade` event, counted in `fault.numShuffleFallbacks`) before the
  CPU rung.
* **Observability** — `shuffle.deviceBytes` / `shuffle.hostBytes` /
  `shuffle.collectiveTime` land in `Session.last_metrics`; bench.py
  reports device vs host `shuffle_write` GB/s and a `q3_exchange`
  wall breakdown."""


_SCHEDULING_DOC = """\
## Concurrent query scheduling

The `scheduler.*` confs (table above) configure the concurrent query
scheduler (`spark_rapids_tpu/scheduler/`, docs/scheduling.md):

* **Admission control** — `Session.submit(plan)` returns a
  `QueryHandle` (`result()` / `cancel()` / `status()`); at most
  `scheduler.maxConcurrent` queries run at once, each holding an HBM
  reservation of `scheduler.reservationFraction` x the DeviceManager
  arena for its lifetime, and at most `scheduler.maxQueued` queries
  wait in the bounded priority queue.  A submit beyond that bound — or
  a queued query not dispatched within `scheduler.queueTimeoutMs` — is
  shed with `QueryRejected` and an `admission_reject` event.
* **Cooperative cancellation** — `handle.cancel()` and
  `scheduler.queryTimeoutMs` deadlines trip the query's `CancelToken`;
  every operator checkpoint the OOM/fault injectors reach polls it, so
  the query unwinds with `TpuQueryCancelled` at its next allocation,
  upload, drain or stage boundary: semaphore permits released,
  spill/upload-cache buffers dropped, shuffle-catalog slots freed, a
  terminal `query_cancelled` event emitted.
* **Per-query failure isolation** — scheduled queries bind private
  (thread-local) fault/OOM injectors instead of the process-wide
  slots, and a query that exhausts its retry/ladder budget trips a
  per-query circuit breaker to the CPU-exec plan without disarming or
  degrading concurrent queries.
* **Deterministic cancellation testing** — `fault.injection.type=
  cancel` cancels the running query's token at any injector checkpoint
  site, so mid-stage unwind is testable everywhere the injector
  reaches."""


_MEMORY_ROBUSTNESS_DOC = """\
## Memory-pressure robustness

On a fixed-HBM TPU, memory pressure is the steady state, not the
exception.  Device operators route every allocation-heavy attempt
through the OOM retry framework (`spark_rapids_tpu/memory/retry.py`):

* **retry** (`TpuRetryOOM`): the allocation failed but may succeed once
  memory is freed.  The task releases its device-semaphore permits,
  forces a synchronous spill through the spill framework, backs off
  with a bounded exponential delay plus seeded jitter
  (`retry.backoffBaseMs` / `retry.backoffMaxMs` / `retry.backoffSeed`),
  and re-executes the attempt from its checkpointed input — up to
  `retry.maxRetries` times.
* **split-and-retry** (`TpuSplitAndRetryOOM`): retrying the same input
  cannot succeed; the input batch is halved by rows — recursively, down
  to the `retry.minSplitRows` floor — and each piece is processed
  independently (upload, stream-side joins, aggregate and sort compose
  per-piece results back into the unsplit answer).  An OOM at the floor
  is genuine and surfaces with a diagnostic naming the operator.

Recovery is observable: per-query counters `retry.numRetries`,
`retry.numSplitRetries`, `retry.retryBlockTimeMs` and
`retry.spillBytesOnRetry` land in `Session.last_metrics`, and a
degraded query logs a summary when `spark.rapids.tpu.sql.trace.enabled`
is on.

The `oomInjection.*` confs (table above) drive any operator path
through its OOM-recovery path deterministically in CI on CPU-only JAX —
no real memory exhaustion required."""


_FAULT_TOLERANCE_DOC = """\
## Distributed fault tolerance

The `fault.*` confs (table above) configure the query-level
fault-tolerance layer (`spark_rapids_tpu/fault/`, docs/fault_tolerance.md):

* **Payload integrity** — spill frames and exchange host round-trips
  carry CRC32C checksums computed on write and verified on read
  (`fault.checksum.enabled`); a mismatch raises `TpuPayloadCorruption`
  and the producing stage is recomputed from lineage.
* **Stage watchdogs** — `fault.stageTimeoutMs` bounds every distributed
  stage and leaf drain; a tripped watchdog abandons the hung attempt
  with `TpuStageTimeout` and re-executes it, bounded by
  `fault.maxStageRetries`.  `fault.semaphoreTimeoutMs` bounds a blocked
  device-semaphore acquire, and `fault.queuePutTimeoutMs` bounds a
  producer blocked on a full prefetch queue.
* **Graceful degradation** — after `fault.maxStageRetries` the runner
  falls back distributed -> single-process -> CPU-exec plan
  (`fault.degrade.enabled`) instead of failing the query; the final
  rung is reported as `fault.degradeLevel`.
* **Elastic multi-host execution** — the `fault.peer.*` confs arm peer
  failure detection (`parallel/elastic.py`): a heartbeat ledger
  (`fault.peer.heartbeatMs` / `missedHeartbeats` / `heartbeatDir`)
  detects dead worker processes, and `fault.peer.collectiveTimeoutMs`
  bounds every guarded collective so a dead peer aborts the dispatch
  with `TpuPeerLost` instead of wedging the mesh.  The ladder then
  re-forms the mesh on the surviving devices (the "shrunken mesh" rung
  above single-process) and re-executes from the recovery substrate's
  checkpoints rather than from scratch.
* **Straggler speculation** — `speculation.*` arms duplicate attempts
  for leaf-drain shards whose latency exceeds
  `speculation.multiplier` x the rolling `speculation.quantile`
  percentile; the first result wins and the loser is cancelled through
  its CancelToken with the zero-leak unwind discipline.
* **Deterministic injection** — `fault.injection.*` drives every
  recovery path (`oom|corrupt|delay|stage_crash|cancel|peer_crash|`
  `peer_stall`, site-filtered, `nth`/`random`/`always` modes) in CI on
  CPU-only JAX; every injected run must produce results bit-identical
  to an injection-free run.

Recovery is observable: `fault.numStageRetries`,
`fault.numChecksumFailures`, `fault.numWatchdogTrips`,
`fault.degradeLevel`, `fault.numPeerLost`, `fault.numMeshShrinks` and
`fault.numSpeculativeWins` land in `Session.last_metrics`, and a
degraded query logs a DEGRADED summary."""


_PERF_TUNING_DOC = """\
## Whole-stage fusion & kernel cache

The `fusion.*` and `kernelCache.*` confs (table above) configure the
compute hot path (`plan/fusion.py`, `exec/kernel_cache.py`,
docs/perf_tuning.md):

* **Whole-stage fusion** — maximal chains of row-local device operators
  (Project, Filter, Expand, Generate) are collapsed into one
  `TpuFusedSegmentExec` whose single jitted kernel composes the member
  compute bodies, so a Project -> Filter -> Project chain issues one
  XLA dispatch per batch instead of three and materializes no
  intermediate batch in HBM.  Filters fuse by threading their keep mask
  through the segment and compacting once at segment exit — results
  stay bit-identical to the unfused plan.  Fusion stops at exchanges,
  aggregates, sorts, joins, transitions and nondeterministic
  expressions; `fusion.maxSegmentExecs` bounds segment size.
* **Shared kernel cache** — every device exec routes jit compilation
  through the process-wide `KernelCache`, keyed by kernel fingerprint
  and schema signature (the row-bucket dimension rides the underlying
  jax shape cache), so identical operators across plans share one
  compiled executable.  `donate_argnums` buffer donation is applied on
  non-CPU backends for segments whose input batches are provably
  single-consumer.  Hit/miss/compile-wall counters land in
  `Session.last_metrics` under `kernelCache.*`, a per-exec
  `compileTime` metric attributes compile wall to operators in
  EXPLAIN ANALYZE, and `bench.py` reports cold (compile-inclusive) vs
  warm timings plus the per-query hit rate."""


_OBSERVABILITY_DOC = """\
## Query telemetry

The `telemetry.*` confs (table above) configure the query-scoped
observability subsystem (`spark_rapids_tpu/telemetry/`,
docs/observability.md):

* **Hierarchical spans** — query -> stage -> exec -> attempt, with wall
  time, device-sync time and rows/batches per physical exec, propagated
  to worker threads via an explicitly captured thread-local binding.
* **Structured event log** — query begin/end, spill, retry, split,
  checksum failure, watchdog trip, degrade-rung change, admission
  verdict and injected faults, in a bounded in-memory ring plus an
  optional append-only JSONL sink (`telemetry.eventLog.dir`);
  multi-controller runs ship worker events back to every controller.
* **EXPLAIN ANALYZE** — `Session.profile_report()` renders the physical
  plan annotated with per-exec metrics plus a top-N hot-operator
  summary; `Session.last_profile` / `Session.profiles` keep the last
  `telemetry.maxQueryProfiles` profiles.
* **Exporters** — Prometheus-text and JSON snapshots over the query
  metrics, plus an HBM-watermark timeline sampled from the
  DeviceManager every `telemetry.sampleHbmMs` milliseconds.
  Dimensional keys (`scheduler.tenant.<name>.*`,
  `shuffle.exchange<N>.*`) export with proper `tenant=`/`exchange=`
  labels; the scheduler's queue-wait, per-tenant query-latency and
  streaming batch-latency distributions export as real
  `# TYPE histogram` families (`Session.metrics_text()`).
* **Per-kernel profiler** — `telemetry.profiler.enabled` attributes
  every jitted-kernel dispatch to a stable kernel fingerprint
  (dispatches, wall, rows/bytes, padding waste) and renders a roofline
  table against the measured host->device ceiling in
  `Session.profile_report()` and the BENCH `kernels` section; the
  disabled cost is one attribute read per dispatch (docs/profiling.md).
* **Trace timelines** — `telemetry.trace.dir` exports one
  Chrome-trace/Perfetto JSON per query (span tree as duration tracks,
  HBM watermark as a counter track, ring events as instants), written
  atomically.
* **Latency histograms** — fixed log-scale bucket histograms
  (`telemetry.histogram.windowS` sliding window for p50/p95/p99
  readouts, cumulative buckets for prometheus) back the scheduler
  queue-wait p95, per-tenant latency and streaming batch latency.

With `telemetry.enabled=false` (the default) every emitter is a no-op
and the metrics snapshot is byte-identical to the un-instrumented
engine."""


# ==========================================================================
# Global entries (grouping mirrors RapidsConf.scala:221-584)
# ==========================================================================

# --- memory (spark.rapids.memory.* :221-269) ------------------------------
DEVICE_MEMORY_FRACTION = conf("spark.rapids.tpu.memory.allocFraction").doc(
    "Fraction of device HBM the engine treats as its working arena; "
    "admission control and spill thresholds derive from it").double_conf(0.9)
HOST_SPILL_STORAGE_SIZE = conf("spark.rapids.tpu.memory.host.spillStorageSize").doc(
    "Bytes of host memory used to spill device batches before disk").long_conf(
    1024 * 1024 * 1024)
DEVICE_MEMORY_DEBUG = conf("spark.rapids.tpu.memory.debug").doc(
    "Log device allocations/frees").boolean_conf(False)

# --- OOM retry / split-and-retry (memory/retry.py; reference:
# RmmRapidsRetryIterator + the RMM OOM-injection test mode) ----------------
RETRY_MAX_RETRIES = conf("spark.rapids.tpu.memory.retry.maxRetries").doc(
    "OOM retries of one attempt (spill + backoff + re-execute) before a "
    "split-capable operator halves its input instead; non-splittable "
    "operators surface the OOM after this many retries").int_conf(8)
RETRY_MIN_SPLIT_ROWS = conf("spark.rapids.tpu.memory.retry.minSplitRows").doc(
    "Split-and-retry floor: an input batch is never split below this "
    "many rows — an OOM at the floor is genuine and surfaces with a "
    "diagnostic naming the operator").int_conf(1)
RETRY_BACKOFF_BASE_MS = conf("spark.rapids.tpu.memory.retry.backoffBaseMs").doc(
    "Base delay of the bounded exponential backoff between OOM retries, "
    "milliseconds (delay = min(base * 2^attempt, backoffMaxMs) with "
    "seeded jitter)").double_conf(2.0)
RETRY_BACKOFF_MAX_MS = conf("spark.rapids.tpu.memory.retry.backoffMaxMs").doc(
    "Upper bound on the exponential backoff delay between OOM retries, "
    "milliseconds").double_conf(200.0)
RETRY_BACKOFF_SEED = conf("spark.rapids.tpu.memory.retry.backoffSeed").doc(
    "Seed for the backoff jitter (decorrelates tasks that OOMed "
    "together without making test timings nondeterministic)").int_conf(0)

# --- deterministic OOM injection (test mode; reference: RMM's
# oomInjection / RmmSpark.forceRetryOOM) -----------------------------------
OOM_INJECTION_MODE = conf("spark.rapids.tpu.memory.oomInjection.mode").doc(
    "Fault-injection mode driving operators through their OOM-recovery "
    "paths without real memory exhaustion: none (off), nth (fire once "
    "at allocation checkpoint #skipCount), random (seeded probabilistic "
    "firing, suppressed during recovery so progress is guaranteed), "
    "always (fire at every checkpoint — proves split-retry bottoms out "
    "at retry.minSplitRows)").string_conf("none")
OOM_INJECTION_SKIP_COUNT = conf(
    "spark.rapids.tpu.memory.oomInjection.skipCount").doc(
    "mode=nth: 0-based allocation checkpoint at which the single "
    "injected OOM fires; sweeping 0..N drives every checkpoint of a "
    "pipeline through recovery, one run at a time").int_conf(0)
OOM_INJECTION_SEED = conf("spark.rapids.tpu.memory.oomInjection.seed").doc(
    "Seed for mode=random's injection decisions (deterministic given "
    "a fixed checkpoint order)").int_conf(0)
OOM_INJECTION_TYPE = conf("spark.rapids.tpu.memory.oomInjection.oomType").doc(
    "Type of injected OOM: retry (TpuRetryOOM — spill+backoff+retry) or "
    "split (TpuSplitAndRetryOOM — the input batch must be halved)"
).string_conf("retry")

# --- distributed fault tolerance (fault/; reference: the transparent
# recovery promise of SURVEY §L0 extended to the distributed path) ---------
FAULT_INJECTION_MODE = conf("spark.rapids.tpu.fault.injection.mode").doc(
    "Generalized fault-injection mode (fault/injector.py) driving every "
    "recovery path deterministically in CI: none (off), nth (fire once "
    "at matching checkpoint #skipCount), random (seeded, suppressed "
    "during recovery), always (every matching checkpoint — proves "
    "bounded retries exhaust into the degradation ladder)"
).string_conf("none")
FAULT_INJECTION_TYPE = conf("spark.rapids.tpu.fault.injection.type").doc(
    "Injected fault type: oom (typed retry OOM), corrupt (flip a byte "
    "in the next checksummed payload write so the read-side CRC32C "
    "verify must catch it), delay (sleep delayMs at the checkpoint — a "
    "straggler), stage_crash (raise TpuStageCrash — a died stage), "
    "cancel (cancel the running query's CancelToken at the checkpoint "
    "— deterministic mid-stage cancellation for unwind testing), "
    "peer_crash (raise TpuPeerLost — a died peer worker; drives the "
    "shrunken-mesh rung), peer_stall (sleep delayMs like delay — a "
    "stalled peer shard; drives straggler speculation)"
).string_conf("oom")
FAULT_INJECTION_SKIP_COUNT = conf(
    "spark.rapids.tpu.fault.injection.skipCount").doc(
    "mode=nth: 0-based matching checkpoint at which the single "
    "injected fault fires; sweeping 0..N drives every checkpoint of a "
    "site class through recovery, one run at a time").int_conf(0)
FAULT_INJECTION_SEED = conf("spark.rapids.tpu.fault.injection.seed").doc(
    "Seed for mode=random's injection decisions").int_conf(0)
FAULT_INJECTION_SITE = conf("spark.rapids.tpu.fault.injection.site").doc(
    "Substring filter on checkpoint sites (spill.write, spill.read, "
    "exchange.write, exchange.write.device, exchange.read, stage.run, "
    "leaf.drain, host.stack, shuffle.collective); empty matches every "
    "site.  Only matching checkpoints advance the skipCount counter"
).string_conf("")
FAULT_INJECTION_DELAY_MS = conf(
    "spark.rapids.tpu.fault.injection.delayMs").doc(
    "type=delay: milliseconds the injected straggler sleeps at the "
    "checkpoint").double_conf(50.0)
FAULT_STAGE_TIMEOUT_MS = conf("spark.rapids.tpu.fault.stageTimeoutMs").doc(
    "Stage watchdog: a distributed stage (or leaf drain) that has not "
    "completed after this many milliseconds is abandoned with "
    "TpuStageTimeout and re-executed from lineage (0 disables; leave "
    "disabled on multi-controller deployments unless every controller "
    "shares the conf — recovery control flow must stay replicated)"
).int_conf(0)
FAULT_MAX_STAGE_RETRIES = conf("spark.rapids.tpu.fault.maxStageRetries").doc(
    "Bounded re-executions of a failed distributed stage/leaf before "
    "the query walks down the degradation ladder (distributed -> "
    "single-process -> CPU-exec plan)").int_conf(2)
FAULT_CHECKSUM_ENABLED = conf("spark.rapids.tpu.fault.checksum.enabled").doc(
    "Compute CRC32C checksums on spill-frame writes and exchange host "
    "round-trips and verify them on read; a mismatch raises "
    "TpuPayloadCorruption and triggers recompute-from-lineage of the "
    "producing stage instead of consuming garbage").boolean_conf(True)
FAULT_HOST_ROUNDTRIP_CHECKSUM = conf(
    "spark.rapids.tpu.fault.checksum.hostRoundtrip").doc(
    "Also stamp+verify the distributed runner's exchange host staging "
    "(per-shard batches between drain and mesh placement).  Costs a "
    "full CRC pass over the staged data per leaf, so it is off by "
    "default in production; it arms automatically while a corrupt "
    "fault injector is installed, and can be forced on to chase "
    "suspected host-memory corruption").boolean_conf(False)
FAULT_DEGRADE_ENABLED = conf("spark.rapids.tpu.fault.degrade.enabled").doc(
    "Graceful degradation: a query that exhausts its fault recovery "
    "(stage retries, task retries) re-executes on the next ladder rung "
    "(single-process, then the CPU-exec plan) instead of failing; the "
    "final rung is reported as fault.degradeLevel in "
    "Session.last_metrics").boolean_conf(True)
FAULT_SEMAPHORE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.fault.semaphoreTimeoutMs").doc(
    "Device-semaphore acquire watchdog: a blocked acquire that sees no "
    "progress for this long raises DeviceSemaphoreTimeout — a "
    "retryable fault the degradation ladder can recover/degrade on — "
    "instead of hanging the process (0 uses the built-in default of "
    "180s)").int_conf(0)
FAULT_QUEUE_PUT_TIMEOUT_MS = conf(
    "spark.rapids.tpu.fault.queuePutTimeoutMs").doc(
    "Producer-side watchdog on bounded prefetch queues: a put() into a "
    "persistently full queue past this deadline raises TpuStageTimeout "
    "(the consumer has died or wedged) instead of busy-looping "
    "silently (0 disables)").int_conf(180000)
FAULT_MAX_TOTAL_ATTEMPTS = conf(
    "spark.rapids.tpu.fault.maxTotalAttempts").doc(
    "Per-query ceiling on the TOTAL number of recovery re-executions "
    "across every mechanism — task retries, adaptive stage retries, "
    "shuffle host fallbacks and degradation-ladder rungs — so stacked "
    "recovery paths cannot multiply into unbounded re-execution.  "
    "Crossing the ceiling emits one terminal attempt_budget_exhausted "
    "event carrying the full attempt ledger and fails the query with "
    "AttemptBudgetExhausted (0 disables the ceiling)").int_conf(64)
FAULT_PEER_HEARTBEAT_MS = conf(
    "spark.rapids.tpu.fault.peer.heartbeatMs").doc(
    "Interval at which each multi-controller worker process touches "
    "its heartbeat file in fault.peer.heartbeatDir so peers can detect "
    "its death without waiting out a wedged collective (0 disables the "
    "heartbeat ledger)").int_conf(0)
FAULT_PEER_MISSED_HEARTBEATS = conf(
    "spark.rapids.tpu.fault.peer.missedHeartbeats").doc(
    "Consecutive missed heartbeat intervals after which a peer is "
    "declared lost: a peer whose heartbeat file is staler than "
    "heartbeatMs * missedHeartbeats aborts in-flight guarded "
    "collectives with TpuPeerLost and triggers the shrunken-mesh "
    "rung").int_conf(3)
FAULT_PEER_COLLECTIVE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.fault.peer.collectiveTimeoutMs").doc(
    "Deadline on every guarded collective dispatch "
    "(parallel/elastic.py): a process_allgather / compiled-collective "
    "call that makes no progress past this deadline is abandoned with "
    "TpuPeerLost instead of wedging every surviving peer forever (0 "
    "disables the deadline; dead peers are then only detectable via "
    "the heartbeat ledger)").int_conf(0)
FAULT_PEER_HEARTBEAT_DIR = conf(
    "spark.rapids.tpu.fault.peer.heartbeatDir").doc(
    "Shared directory for the peer heartbeat ledger (one file per "
    "process id, mtime = last heartbeat).  Must be visible to every "
    "worker process — a shared filesystem or a local dir when all "
    "workers are colocated; empty uses <system tempdir>/"
    "srt-heartbeats").string_conf("")
SPECULATION_ENABLED = conf("spark.rapids.tpu.speculation.enabled").doc(
    "Straggler speculation on leaf drains: when a shard's drain "
    "latency exceeds speculation.multiplier x the rolling "
    "speculation.quantile percentile, a duplicate attempt is launched; "
    "the first result wins and the loser is cancelled through its "
    "CancelToken with the zero-leak unwind discipline").boolean_conf(False)
SPECULATION_MULTIPLIER = conf("spark.rapids.tpu.speculation.multiplier").doc(
    "A shard speculates once its elapsed drain time exceeds this "
    "multiple of the rolling percentile "
    "(speculation.quantile)").double_conf(2.0)
SPECULATION_QUANTILE = conf("spark.rapids.tpu.speculation.quantile").doc(
    "Percentile of the per-shard drain-latency histogram used as the "
    "speculation baseline (e.g. 95.0 = p95)").double_conf(95.0)
SPECULATION_MIN_SAMPLES = conf(
    "spark.rapids.tpu.speculation.minSamples").doc(
    "Minimum completed drains in the rolling latency window before "
    "speculation arms — prevents duplicating shards off a cold, "
    "unrepresentative baseline").int_conf(4)
SPECULATION_MIN_LATENCY_MS = conf(
    "spark.rapids.tpu.speculation.minLatencyMs").doc(
    "Floor below which a shard never speculates regardless of the "
    "percentile baseline, so uniformly fast drains do not duplicate "
    "work over scheduling jitter").double_conf(25.0)

# --- stage-level checkpointing & crash recovery (recovery/;
# reference: Theseus-style resumable exchange artifacts) -------------------
RECOVERY_ENABLED = conf("spark.rapids.tpu.recovery.enabled").doc(
    "Persist every completed exchange materialization as a durable "
    "stage checkpoint (CRC32C-stamped partition frames + an atomically "
    "written JSON manifest under recovery.dir/<query_fingerprint>/).  "
    "Stage retries, degradation-ladder rungs and — with "
    "recovery.autoResume — a fresh process after a crash resume from "
    "the last completed checkpoint instead of re-running the whole "
    "query").boolean_conf(False)
RECOVERY_DIR = conf("spark.rapids.tpu.recovery.dir").doc(
    "Directory holding durable stage checkpoints; empty uses "
    "<system tempdir>/srt-recovery.  Must survive process restarts to "
    "be useful for crash recovery (i.e. point it at a real disk, not a "
    "per-process tmpdir)").string_conf("")
RECOVERY_AUTO_RESUME = conf("spark.rapids.tpu.recovery.autoResume").doc(
    "When recovery.enabled is on, Session.execute() transparently "
    "fingerprint-matches the plan against existing checkpoints and "
    "skips completed exchanges (Session.resume() does this "
    "unconditionally).  Disable to only WRITE checkpoints, e.g. while "
    "validating a new deployment").boolean_conf(True)
RECOVERY_TTL_SECONDS = conf("spark.rapids.tpu.recovery.ttlSeconds").doc(
    "Checkpoint expiry: query directories older than this are removed "
    "by the Session.close()/scheduler-shutdown hygiene sweep (0 "
    "disables age-based expiry)").long_conf(86400)
RECOVERY_MAX_BYTES = conf("spark.rapids.tpu.recovery.maxBytes").doc(
    "Cap on total checkpoint bytes under recovery.dir: the hygiene "
    "sweep evicts least-recently-touched query directories until under "
    "the cap (0 disables the cap)").long_conf(4 * 1024 * 1024 * 1024)
RECOVERY_KILL_AFTER_CHECKPOINTS = conf(
    "spark.rapids.tpu.recovery.killAfterCheckpoints").doc(
    "Test hook: SIGKILL the process immediately after the Nth "
    "successful checkpoint write (0 disables).  Drives the "
    "crash-and-resume integration tests").internal().int_conf(0)

# --- incremental streaming execution (streaming/; reference: Structured
# Streaming micro-batches over the Theseus-style checkpoint substrate) -----
STREAMING_ENABLED = conf("spark.rapids.tpu.streaming.enabled").doc(
    "Allow Session.stream(plan): micro-batch continuous queries over "
    "arriving files, with incremental aggregate state persisted "
    "through the recovery checkpoint store so each tick recomputes "
    "only the partitions the new files touch (requires "
    "recovery.enabled for incremental reuse; without it every batch "
    "is a full recompute)").boolean_conf(False)
STREAMING_TRIGGER_INTERVAL_MS = conf(
    "spark.rapids.tpu.streaming.triggerIntervalMs").doc(
    "Micro-batch trigger period, milliseconds: the stream's tick loop "
    "polls the source directories this often; a tick that finds no "
    "new or changed files emits stream_tick_skip and goes back to "
    "sleep (0 means ticks run only via "
    "StreamHandle.process_available())").int_conf(500)
STREAMING_MAX_BATCH_FILES = conf(
    "spark.rapids.tpu.streaming.maxBatchFiles").doc(
    "Cap on NEW files admitted into one micro-batch; a backlog beyond "
    "it is carried to later ticks (oldest first, stable discovery "
    "order) with a stream_batch_capped event per capped tick (0 "
    "disables the cap)").int_conf(0)
STREAMING_BATCH_DEADLINE_MS = conf(
    "spark.rapids.tpu.streaming.batchDeadlineMs").doc(
    "Per-batch deadline SLA, milliseconds from dispatch, enforced "
    "through the scheduler's cooperative CancelToken: a batch past it "
    "unwinds with TpuQueryCancelled, the tick reports the miss "
    "(stream_batch_error) and the ledger stays at the previous batch "
    "— the next tick retries the same cumulative input (0 falls back "
    "to scheduler.queryTimeoutMs)").int_conf(0)
STREAMING_STATE_DIR = conf("spark.rapids.tpu.streaming.stateDir").doc(
    "Directory holding stream ledgers (source fingerprints + batch "
    "commit markers) under <stateDir>/<stream-fingerprint>/; empty "
    "uses <recovery.dir>/streams/ (the ledger then lives beside the "
    "checkpoints it references, which is what crash recovery wants, "
    "in a subtree hygiene sweeps never touch)"
).string_conf("")

# --- serving caches (serving/; reference: parameterized prepared
# statements + plan-template caching per "Accelerating Presto with
# GPUs" — one compile serves millions of distinct literals) ---------------
SERVING_CACHE_ENABLED = conf("spark.rapids.tpu.serving.cache.enabled").doc(
    "Master enable for the serving caches: Session.submit() consults "
    "the plan-template cache (skip planning/fusion for plans that "
    "normalize to a seen skeleton) and the fingerprint-keyed result "
    "cache (a validated hit completes before admission and never "
    "queues).  Session.prepare() works regardless; this gates the "
    "caching of ad-hoc submissions").boolean_conf(False)
SERVING_CACHE_TEMPLATE_MAX_ENTRIES = conf(
    "spark.rapids.tpu.serving.cache.templates.maxEntries").doc(
    "LRU capacity of the in-memory plan-template cache (entries hold "
    "one optimized+fused physical plan per (skeleton fingerprint, "
    "literal binding); eviction drops the planned tree, not any "
    "compiled kernel — those live in the kernel cache)").int_conf(128)
SERVING_CACHE_RESULTS_ENABLED = conf(
    "spark.rapids.tpu.serving.cache.results.enabled").doc(
    "Result-cache tier of the serving subsystem: completed query "
    "results persist as CRC32C-stamped frames keyed by the recovery "
    "query+data fingerprint, and a later submit of the same query "
    "over unchanged inputs is served from the cache without "
    "executing (requires serving.cache.enabled)").boolean_conf(True)
SERVING_CACHE_RESULTS_MAX_BYTES = conf(
    "spark.rapids.tpu.serving.cache.results.maxBytes").doc(
    "Byte budget of the on-disk result cache: storing a new result "
    "evicts least-recently-used entries until the total fits (0 "
    "disables the cap)").long_conf(1024 * 1024 * 1024)
SERVING_CACHE_RESULTS_MAX_ENTRY_BYTES = conf(
    "spark.rapids.tpu.serving.cache.results.maxEntryBytes").doc(
    "Largest single result the cache will store; bigger results "
    "execute normally and are simply not cached (0 disables the "
    "per-entry cap)").long_conf(256 * 1024 * 1024)
SERVING_CACHE_DIR = conf("spark.rapids.tpu.serving.cache.dir").doc(
    "Directory holding cached result frames; empty uses the reserved "
    "serving/ directory under the recovery root, which the recovery "
    "hygiene sweep skips by name (the serving cache runs its own "
    "byte-budget eviction)").string_conf("")

# --- concurrent query scheduler (scheduler/; reference: Theseus-style
# admission + memory arbitration across concurrent queries) ----------------
SCHEDULER_MAX_CONCURRENT = conf(
    "spark.rapids.tpu.scheduler.maxConcurrent").doc(
    "Queries the scheduler runs concurrently; further admitted queries "
    "wait in the bounded priority queue until a slot AND an HBM "
    "reservation are available").int_conf(2)
SCHEDULER_MAX_QUEUED = conf("spark.rapids.tpu.scheduler.maxQueued").doc(
    "Bound on queries waiting for a run slot; a submit beyond "
    "maxConcurrent+maxQueued in-flight queries is shed immediately "
    "(QueryRejected + an admission_reject event) — reject-or-queue "
    "backpressure, never unbounded buffering").int_conf(16)
SCHEDULER_QUEUE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.scheduler.queueTimeoutMs").doc(
    "A queued query not dispatched within this many milliseconds is "
    "shed with QueryRejected + an admission_reject event (0 waits "
    "forever)").int_conf(30000)
SCHEDULER_RESERVATION_FRACTION = conf(
    "spark.rapids.tpu.scheduler.reservationFraction").doc(
    "Fraction of the DeviceManager arena reserved per admitted query "
    "for its lifetime; dispatch requires a free reservation, so the "
    "sum of running reservations never exceeds the arena — the "
    "admission-side HBM budget that keeps concurrent queries from "
    "thrashing the spill path (0 disables reservations)"
).double_conf(0.25)
SCHEDULER_QUERY_TIMEOUT_MS = conf(
    "spark.rapids.tpu.scheduler.queryTimeoutMs").doc(
    "Deadline on a running query, milliseconds, measured from "
    "dispatch: past it the query's CancelToken trips and the query "
    "unwinds cooperatively at its next operator checkpoint with "
    "TpuQueryCancelled (0 disables)").int_conf(0)

# --- multi-tenant QoS: fair admission, aging, preemption, shedding
# (scheduler/qos.py; reference: admission tiers + fair arbitration in
# "Accelerating Presto with GPUs") --------------------------------------
SCHEDULER_PRIORITY_AGING_MS = conf(
    "spark.rapids.tpu.scheduler.priorityAgingMs").doc(
    "Priority aging: for every this-many milliseconds a query waits "
    "in the admission queue its EFFECTIVE priority grows by one, so a "
    "steady stream of high-priority submissions can delay — but never "
    "indefinitely starve — an already-queued low-priority query (0 "
    "disables aging and restores fixed priorities)").int_conf(5000)
SCHEDULER_PREEMPTION_ENABLED = conf(
    "spark.rapids.tpu.scheduler.preemption.enabled").doc(
    "Checkpoint-backed preemption: a strictly higher-priority queued "
    "query blocked on a run slot or its HBM reservation cooperatively "
    "cancels the lowest-priority running query (the zero-leak "
    "CancelToken unwind), requeues it, and on re-admission the "
    "recovery store (recovery.enabled) resumes the victim from its "
    "completed exchange checkpoints — bit-identical results, each "
    "preemption charged against the victim's fault.maxTotalAttempts "
    "budget").boolean_conf(True)
SCHEDULER_TENANT_DEFAULT_WEIGHT = conf(
    "spark.rapids.tpu.scheduler.tenant.default.weight").doc(
    "Fair-share weight of the default tenant; any "
    "scheduler.tenant.<name>.weight key (read dynamically, no "
    "pre-registration) sets another tenant's weight and falls back to "
    "this one.  Dispatch drains per-tenant queues by deficit-weighted "
    "fair share: under contention a tenant with twice the weight "
    "receives twice the dispatch share").double_conf(1.0)
SCHEDULER_TENANT_DEFAULT_MAX_CONCURRENT = conf(
    "spark.rapids.tpu.scheduler.tenant.default.maxConcurrent").doc(
    "Per-tenant cap on concurrently RUNNING queries, 0 = bounded only "
    "by scheduler.maxConcurrent; scheduler.tenant.<name>.maxConcurrent "
    "(dynamic key) overrides it per tenant").int_conf(0)
SCHEDULER_TENANT_DEFAULT_HBM_FRACTION = conf(
    "spark.rapids.tpu.scheduler.tenant.default.hbmFraction").doc(
    "Per-tenant HBM reservation fraction charged per dispatched query, "
    "0 = use scheduler.reservationFraction; "
    "scheduler.tenant.<name>.hbmFraction (dynamic key) overrides it "
    "per tenant").double_conf(0.0)
SCHEDULER_OVERLOAD_QUEUE_WAIT_MS = conf(
    "spark.rapids.tpu.scheduler.overload.queueWaitMs").doc(
    "Overload threshold on the p95 queue wait (recent dispatches plus "
    "queries still waiting): past it the OverloadMonitor declares "
    "overload and new submissions below "
    "scheduler.overload.shedBelowPriority are shed with TpuOverloaded "
    "carrying a retry_after_ms backoff hint (0 disables queue-wait "
    "overload detection)").int_conf(0)
SCHEDULER_OVERLOAD_HBM_FRACTION = conf(
    "spark.rapids.tpu.scheduler.overload.hbmFraction").doc(
    "Overload threshold on arena pressure (DeviceManager allocated / "
    "arena bytes): past it the OverloadMonitor declares overload and "
    "sheds low-tier submissions (0 disables arena-pressure overload "
    "detection)").double_conf(0.0)
SCHEDULER_OVERLOAD_SHED_BELOW_PRIORITY = conf(
    "spark.rapids.tpu.scheduler.overload.shedBelowPriority").doc(
    "While overloaded, a submit with priority below this value is shed "
    "with TpuOverloaded (a typed retryable QueryRejected carrying "
    "retry_after_ms); submissions at or above it are still admitted "
    "under the normal queue bounds").int_conf(1)
SCHEDULER_OVERLOAD_RETRY_AFTER_MS = conf(
    "spark.rapids.tpu.scheduler.overload.retryAfterMs").doc(
    "Base backoff hint carried by TpuOverloaded.retry_after_ms, scaled "
    "up with current queue depth — a shed client should not retry "
    "sooner").int_conf(1000)
SCHEDULER_OVERLOAD_SAMPLE_MS = conf(
    "spark.rapids.tpu.scheduler.overload.sampleMs").doc(
    "OverloadMonitor sampling period, milliseconds: the monitor thread "
    "re-evaluates queue-wait p95 and arena pressure this often (the "
    "state is also re-evaluated inline at every submit), emitting "
    "overload_enter/overload_exit transition events").int_conf(100)

# --- scheduling -----------------------------------------------------------
CONCURRENT_TPU_TASKS = conf("spark.rapids.tpu.sql.concurrentTpuTasks").doc(
    "Number of tasks that may hold the device semaphore concurrently "
    "(reference: spark.rapids.sql.concurrentGpuTasks)").int_conf(2)
TASK_THREADS = conf("spark.rapids.tpu.sql.taskThreads").doc(
    "Host task-runner threads per process (partition-level data "
    "parallelism)").int_conf(8)
TASK_RETRIES = conf("spark.rapids.tpu.sql.taskRetries").doc(
    "Times a failed partition task is re-executed from its lineage "
    "before the query fails (the engine's analogue of Spark task "
    "rescheduling; 0 disables)").int_conf(1)

# --- batch sizing (:289-309) ---------------------------------------------
BATCH_SIZE_BYTES = conf("spark.rapids.tpu.sql.batchSizeBytes").doc(
    "Target byte size for device batches; coalescing aims for this").long_conf(
    512 * 1024 * 1024)
BATCH_SIZE_ROWS = conf("spark.rapids.tpu.sql.batchSizeRows").doc(
    "Soft cap on rows per device batch").int_conf(1 << 22)
READER_BATCH_SIZE_ROWS = conf("spark.rapids.tpu.sql.reader.batchSizeRows").doc(
    "Soft cap on rows per reader batch (reference: "
    "spark.rapids.sql.reader.batchSizeRows)").int_conf(1 << 21)
READER_BATCH_SIZE_BYTES = conf("spark.rapids.tpu.sql.reader.batchSizeBytes").doc(
    "Soft cap on bytes per reader batch").long_conf(512 * 1024 * 1024)
READER_PREFETCH_BATCHES = conf(
    "spark.rapids.tpu.sql.reader.prefetchBatches").doc(
    "Host batches decoded ahead of the device upload per partition "
    "(decode/upload pipelining; 0 disables the prefetch thread)"
).int_conf(2)
BUCKET_MIN_ROWS = conf("spark.rapids.tpu.sql.bucketMinRows").doc(
    "Device batches are padded to power-of-two row buckets >= this, so XLA "
    "compile caches hit across batches (TPU-specific: static shapes)").int_conf(128)

# --- feature gates (:328-449) --------------------------------------------
SQL_ENABLED = conf("spark.rapids.tpu.sql.enabled").doc(
    "Master enable for the plan-rewrite engine").boolean_conf(True)
INCOMPATIBLE_OPS = conf("spark.rapids.tpu.sql.incompatibleOps.enabled").doc(
    "Allow ops whose results may diverge from the host engine in corner "
    "cases (reference: spark.rapids.sql.incompatibleOps.enabled)").boolean_conf(False)
ALLOW_FLOAT_AGG = conf("spark.rapids.tpu.sql.variableFloatAgg.enabled").doc(
    "Allow floating-point aggregation on device.  Device partial sums "
    "reduce in segment order, which differs from the host oracle's "
    "order, so extreme values (±max, ±inf) can produce different — "
    "equally valid — float results (reference: "
    "spark.rapids.sql.variableFloatAgg.enabled; default true here "
    "because the device order is deterministic for a fixed plan)"
).boolean_conf(True)

STRING_COLUMN_BYTES_GUARD = conf(
    "spark.rapids.tpu.sql.stringColumnBytesGuard").doc(
    "Fail a device upload whose string byte-matrix would exceed this "
    "many bytes per column.  Byte-matrix HBM is rows x max_len, so one "
    "pathological long string in a wide batch silently multiplies the "
    "footprint (e.g. a 10KB string in a 10M-row column costs ~100GB); "
    "this turns that OOM into a diagnosable error naming the column.  "
    "Shrink reader.batchSizeRows, filter/substring the column, or "
    "raise this limit").int_conf(2 << 30)

# --- string cast gates (reference: RapidsConf.scala:373-403) --------------
CAST_STRING_TO_INTEGER = conf(
    "spark.rapids.tpu.sql.castStringToInteger.enabled").doc(
    "Cast string->integral on device.  Exact for [+-]?digits[.digits] "
    "(fractions truncate); exponent forms ('1e2') become NULL on device "
    "where the host parses them.  Off by default like the reference "
    "(RapidsConf.scala:397) — enable to keep string-cast pipelines on "
    "device").boolean_conf(False)
CAST_STRING_TO_FLOAT = conf(
    "spark.rapids.tpu.sql.castStringToFloat.enabled").doc(
    "Cast string->float on device.  Horner digit accumulation can be a "
    "few ULPs off the host's correctly-rounded parse on long mantissas "
    "(reference: castStringToFloat, same default)").boolean_conf(False)
CAST_STRING_TO_TIMESTAMP = conf(
    "spark.rapids.tpu.sql.castStringToTimestamp.enabled").doc(
    "Cast string->date/timestamp on device: ISO 'YYYY[-MM[-DD]]"
    "[ T]HH[:MM[:SS[.ffffff]]]' in UTC, malformed -> NULL.  Exotic "
    "host-accepted forms (timezone suffixes, >6 fraction digits, "
    "compact dates) become NULL on device.  Off by default like the "
    "reference (RapidsConf.scala:373-403)").boolean_conf(False)
# (no castFloatToString key: float->string stays host-side by design —
# Spark's shortest-repr formatting has no faithful device analogue, see
# ops/cast.py; the reference gates the same divergence behind its
# castFloatToString conf)

# --- whole-stage fusion / kernel cache (plan/fusion.py,
# exec/kernel_cache.py; reference: the per-operator dispatch overhead
# called out by "Data Path Fusion in GPU for Analytical Query
# Processing" — see docs/perf_tuning.md) ----------------------------------
FUSION_ENABLED = conf("spark.rapids.tpu.sql.fusion.enabled").doc(
    "Collapse maximal chains of row-local device execs (Project, "
    "Filter, Expand, Generate) into one fused segment whose single "
    "jitted kernel composes the member compute bodies — one XLA "
    "dispatch per batch per segment, no intermediate HBM "
    "materialization; results are bit-identical to the unfused plan"
).boolean_conf(True)
FUSION_MAX_SEGMENT_EXECS = conf(
    "spark.rapids.tpu.sql.fusion.maxSegmentExecs").doc(
    "Upper bound on member execs per fused segment; a longer row-local "
    "chain is split into several segments (guards XLA compile time on "
    "pathological plans)").int_conf(16)
KERNEL_CACHE_ENABLED = conf("spark.rapids.tpu.sql.kernelCache.enabled").doc(
    "Share jit-compiled kernels across exec instances through the "
    "process-wide KernelCache, keyed by kernel fingerprint and schema "
    "signature (the row-bucket dimension rides the jax shape cache). "
    "Disabled, each exec instance compiles privately; cache counters "
    "still report").boolean_conf(True)
KERNEL_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.tpu.sql.kernelCache.maxEntries").doc(
    "LRU capacity of the shared kernel cache (entries hold compiled "
    "XLA executables; eviction frees them)").int_conf(256)
KERNEL_CACHE_DONATION = conf(
    "spark.rapids.tpu.sql.kernelCache.donation.enabled").doc(
    "Donate input batch buffers (jax donate_argnums) to kernels whose "
    "input is provably single-consumer — fused segments fed by fresh "
    "file-scan uploads — so XLA reuses the HBM in place.  No-op on the "
    "CPU backend, which ignores donation").boolean_conf(True)

# --- test hooks (:456-463) ------------------------------------------------
TEST_ENABLED = conf("spark.rapids.tpu.sql.test.enabled").doc(
    "Test mode: fail if any operator unexpectedly stays on the host engine "
    "(reference: spark.rapids.sql.test.enabled)").internal().boolean_conf(False)
TEST_ALLOWED_NON_TPU = conf("spark.rapids.tpu.sql.test.allowedNonTpu").doc(
    "Comma-separated operator class names permitted to fall back when test "
    "mode is on").internal().string_conf("")

# --- debug ----------------------------------------------------------------
EXPLAIN = conf("spark.rapids.tpu.sql.explain").doc(
    "Plan-rewrite explain mode: NONE, ALL, or NOT_ON_TPU").string_conf("NONE")

# --- aggregation modes (:483-493) ----------------------------------------
HASH_AGG_REPLACE_MODE = conf("spark.rapids.tpu.sql.hashAgg.replaceMode").doc(
    "Which aggregation modes to replace: all, partial, final").string_conf("all")

# --- shuffle / exchange (spark.rapids.shuffle.* :500-576) -----------------
SHUFFLE_TRANSPORT_CLASS = conf("spark.rapids.tpu.shuffle.transport.class").doc(
    "Transport used for device-to-device exchange, instantiated by "
    "reflection like the reference's makeTransport "
    "(RapidsConf.scala:505); the default rides ICI collectives"
).string_conf("spark_rapids_tpu.parallel.collective.IciCollectiveTransport")
SHUFFLE_PARTITIONS = conf("spark.rapids.tpu.sql.shuffle.partitions").doc(
    "Default number of exchange output partitions").int_conf(8)
BROADCAST_THRESHOLD = conf(
    "spark.rapids.tpu.sql.broadcastSizeThreshold").doc(
    "Max estimated build-side bytes for a broadcast hash join (reference: "
    "spark.sql.autoBroadcastJoinThreshold feeding GpuBroadcastMeta); "
    "set to 0 to force shuffled joins").long_conf(10 * 1024 * 1024)
SHUFFLE_MODE = conf("spark.rapids.tpu.shuffle.mode").doc(
    "Exchange data path: device (shuffle blocks stay resident in HBM as "
    "packed blocks built by one jitted partition-build kernel — no "
    "d2h/h2d round-trip per partition), host (every block is staged to "
    "host memory and CRC32C-stamped immediately, the pre-device "
    "behavior), or auto (device while the HBM arena has headroom, host "
    "under memory pressure).  Range partitioning always uses the host "
    "path (bounds need a full host-side drain); the degradation ladder "
    "re-executes a failed device-shuffle query on the host path before "
    "falling to the CPU rung").string_conf("auto")
SHUFFLE_TARGET_BATCH_ROWS = conf(
    "spark.rapids.tpu.shuffle.targetBatchRows").doc(
    "Exchange writes coalesce sub-target input batches up to this many "
    "rows before the partition-build kernel runs, so a stream of tiny "
    "batches costs one build dispatch instead of N").int_conf(32768)

# --- adaptive query execution (adaptive/; reference: Spark 3.0 AQE —
# AdaptiveSparkPlanExec + ShufflePartitionsUtil + OptimizeSkewedJoin +
# DynamicJoinSelection, re-planned from exact shuffle stats) ---------------
ADAPTIVE_ENABLED = conf("spark.rapids.tpu.sql.adaptive.enabled").doc(
    "Adaptive query execution: re-optimize the unexecuted plan suffix "
    "between stages from exact materialized shuffle statistics — "
    "partition coalescing, skew-join splitting and dynamic broadcast "
    "conversion.  Rewrites are bit-identical to the static plan; "
    "decisions are recorded as aqe_* telemetry events and rendered in "
    "EXPLAIN ANALYZE").boolean_conf(True)
ADAPTIVE_TARGET_PARTITION_BYTES = conf(
    "spark.rapids.tpu.sql.adaptive.targetPartitionBytes").doc(
    "Post-shuffle partition coalescing target: adjacent partitions "
    "whose combined estimated bytes stay under this are merged into "
    "one reader partition (reference: "
    "spark.sql.adaptive.advisoryPartitionSizeInBytes)").long_conf(
    64 * 1024 * 1024)
ADAPTIVE_AUTO_BROADCAST_THRESHOLD = conf(
    "spark.rapids.tpu.sql.adaptive.autoBroadcastJoinThreshold").doc(
    "Max OBSERVED build-side bytes for demoting a planned "
    "shuffled-hash join to a broadcast join at runtime, skipping the "
    "stream-side exchange (reference: the runtime re-check of "
    "spark.sql.autoBroadcastJoinThreshold inside AQE; 0 disables "
    "dynamic conversion)").long_conf(10 * 1024 * 1024)
ADAPTIVE_SKEW_FACTOR = conf(
    "spark.rapids.tpu.sql.adaptive.skewedPartitionFactor").doc(
    "A join partition is skewed when its row count exceeds this factor "
    "x the median partition rows (reference: "
    "spark.sql.adaptive.skewJoin.skewedPartitionFactor)").double_conf(4.0)
ADAPTIVE_SKEW_THRESHOLD_BYTES = conf(
    "spark.rapids.tpu.sql.adaptive.skewedPartitionThresholdBytes").doc(
    "Skew splitting additionally requires the skewed partition's "
    "estimated bytes to exceed this floor, so tiny-but-lopsided "
    "partitions are not split for nothing (reference: "
    "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes)"
).long_conf(64 * 1024 * 1024)
ADAPTIVE_MAX_SKEW_SLICES = conf(
    "spark.rapids.tpu.sql.adaptive.maxSkewSlices").doc(
    "Upper bound on the contiguous sub-slices one skewed partition is "
    "cut into (each slice replicates the build-side partition, so this "
    "bounds the replication cost)").int_conf(8)

# --- ML interop -----------------------------------------------------------
EXPORT_COLUMNAR_RDD = conf("spark.rapids.tpu.sql.exportColumnarRdd").doc(
    "Allow zero-copy export of device batches to user code (JAX arrays); "
    "reference: spark.rapids.sql.exportColumnarRdd").boolean_conf(False)

# --- metrics / tracing ----------------------------------------------------
TRACE_ENABLED = conf("spark.rapids.tpu.sql.trace.enabled").doc(
    "Wrap hot-path sections in jax.profiler trace annotations (reference: "
    "NVTX ranges)").boolean_conf(False)

# --- telemetry (telemetry/; reference: the per-exec SQLMetrics surfaced
# in the SQL UI + the Spark event log / history server) --------------------
TELEMETRY_ENABLED = conf("spark.rapids.tpu.telemetry.enabled").doc(
    "Query telemetry: hierarchical spans (query -> stage -> exec -> "
    "attempt), the structured event log, EXPLAIN-ANALYZE profiles "
    "(Session.profile_report()) and the metrics exporters "
    "(telemetry/export.py).  Off by default: every emitter is a no-op "
    "and the metrics snapshot is unchanged").boolean_conf(False)
TELEMETRY_EVENT_LOG_DIR = conf(
    "spark.rapids.tpu.telemetry.eventLog.dir").doc(
    "Directory for the append-only JSONL event log (one "
    "events-<queryId>.jsonl per query — the history-server analogue); "
    "empty keeps events only in the bounded in-memory ring").string_conf("")
TELEMETRY_MAX_QUERY_PROFILES = conf(
    "spark.rapids.tpu.telemetry.maxQueryProfiles").doc(
    "Completed query profiles retained on the Session "
    "(Session.profiles / Session.last_profile); the oldest profile is "
    "dropped first").int_conf(8)
TELEMETRY_SAMPLE_HBM_MS = conf(
    "spark.rapids.tpu.telemetry.sampleHbmMs").doc(
    "HBM-watermark sampling period, milliseconds: a per-query sampler "
    "thread records the DeviceManager's allocated/peak-bytes timeline "
    "into the profile and exporters (0 disables the sampler)").int_conf(0)
TELEMETRY_MAX_EVENTS = conf("spark.rapids.tpu.telemetry.maxEvents").doc(
    "Capacity of the per-query in-memory event ring (oldest events are "
    "dropped first and counted); the JSONL file sink is append-only "
    "and unbounded").int_conf(4096)
TELEMETRY_PROFILER_ENABLED = conf(
    "spark.rapids.tpu.telemetry.profiler.enabled").doc(
    "Per-kernel dispatch profiler: accumulates dispatch count, wall "
    "time, rows/bytes and shape-bucketing padding waste per kernel "
    "fingerprint (telemetry/profiler.py), rendered as a roofline table "
    "in Session.profile_report() and the BENCH JSON kernels section.  "
    "Independent of telemetry.enabled; the disabled hot-path cost is "
    "one attribute read per dispatch").boolean_conf(False)
TELEMETRY_TRACE_DIR = conf("spark.rapids.tpu.telemetry.trace.dir").doc(
    "Directory for Chrome-trace/Perfetto JSON timelines (one "
    "trace-<queryId>.json per query, written atomically at query "
    "finish): span tree as duration events, HBM sampler timeline as a "
    "counter track, scheduler/streaming events as instants.  Empty "
    "disables trace export; requires telemetry.enabled").string_conf("")
TELEMETRY_HISTOGRAM_WINDOW_S = conf(
    "spark.rapids.tpu.telemetry.histogram.windowS").doc(
    "Sliding-window span, seconds, for latency-histogram percentile "
    "readouts (scheduler queue-wait, per-tenant query latency, "
    "streaming batch latency).  Cumulative bucket counts exported to "
    "prometheus are unaffected (they are monotonic by "
    "definition)").int_conf(300)


class TpuConf:
    """Immutable view over a key->value dict with typed accessors.

    ``TpuConf({...})`` or ``TpuConf()`` for defaults."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings = dict(settings or {})

    def get(self, entry: ConfEntry):
        return entry.get(self._settings)

    def get_key(self, key: str):
        e = lookup(key)
        if e is None:
            return self._settings.get(key)
        return e.get(self._settings)

    def is_operator_enabled(self, kind: str, name: str) -> bool:
        e = lookup(f"spark.rapids.tpu.sql.{kind}.{name}")
        if e is None:
            return True
        return e.get(self._settings)

    def with_settings(self, **kv) -> "TpuConf":
        s = dict(self._settings)
        s.update(kv)
        return TpuConf(s)

    def set(self, key: str, value) -> "TpuConf":
        s = dict(self._settings)
        s[key] = value
        return TpuConf(s)

    # Convenience typed properties used on hot paths
    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def batch_size_rows(self) -> int:
        return self.get(BATCH_SIZE_ROWS)

    @property
    def is_sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def is_test_enabled(self) -> bool:
        return self.get(TEST_ENABLED)

    @property
    def allowed_non_tpu(self) -> List[str]:
        raw = self.get(TEST_ALLOWED_NON_TPU)
        return [s.strip() for s in raw.split(",") if s.strip()]

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()

    @property
    def concurrent_tpu_tasks(self) -> int:
        return self.get(CONCURRENT_TPU_TASKS)

    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    def items(self):
        return self._settings.items()
