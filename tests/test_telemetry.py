"""Query telemetry subsystem (spark_rapids_tpu/telemetry/).

Contract under test (ISSUE 4 acceptance): with ``telemetry.enabled``
a query — including one under deterministic fault injection — yields a
``Session.profile_report()`` with one span per physical exec (wall +
device-sync, rows/batches) and a JSONL event log containing the
injected retry/fault/degrade events; with it off, every emitter is a
no-op and the metrics snapshot is unchanged.
"""
import glob
import json
import os
import re

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.plan import functions as F
from spark_rapids_tpu.telemetry import spans as tspans
from spark_rapids_tpu.telemetry.events import (EventLog, emit_event,
                                               read_event_log,
                                               replay_summary)
from spark_rapids_tpu.telemetry.export import (json_snapshot,
                                               prometheus_text)

TEL = {"spark.rapids.tpu.telemetry.enabled": True}
FAST = {
    "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
    "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
}


def _agg_df(sess, n=64):
    rng = np.random.RandomState(3)
    df = sess.create_dataframe({
        "g": rng.randint(0, 5, n),
        "v": (rng.rand(n) * 10).round(6)})
    return df.group_by("g").agg(F.sum("v").alias("s"),
                                F.count("v").alias("n"))


# ==========================================================================
# Span tree shape
# ==========================================================================
def test_span_tree_one_span_per_exec():
    sess = srt.Session(dict(TEL))
    _agg_df(sess).collect()
    prof = sess.last_profile
    assert prof is not None
    execs = prof.exec_spans()
    # one exec-kind span per physical exec name of the plan
    for name in ("HostToDeviceExec", "DeviceToHostExec",
                 "TpuHashAggregateExec", "TpuShuffleExchangeExec"):
        assert name in execs, sorted(execs)
    # transitions carry rows/batches and device-sync wall
    h2d = execs["HostToDeviceExec"]
    assert h2d["rows"] > 0 and h2d["batches"] > 0
    assert h2d["device_sync_ns"] > 0
    assert h2d["wall_ns"] > 0
    # root is the query span and parents every exec span
    tree = prof.span_tree()
    assert tree["kind"] == "query"
    assert prof.wall_ns > 0
    kids = {c["name"] for c in tree["children"]}
    assert "HostToDeviceExec" in kids


def test_profile_report_renders_explain_analyze():
    sess = srt.Session(dict(TEL))
    _agg_df(sess).collect()
    report = sess.profile_report()
    assert "Query profile" in report
    assert "Physical plan (annotated)" in report
    assert "HostToDevice" in report and "wall=" in report
    assert "operators by wall" in report
    assert "Span tree" in report
    assert "query_begin: 1" in report


def test_profiles_ring_is_bounded():
    sess = srt.Session(dict(TEL, **{
        "spark.rapids.tpu.telemetry.maxQueryProfiles": 2}))
    df = _agg_df(sess)
    for _ in range(3):
        df.collect()
    assert len(sess.profiles) == 2
    assert sess.profiles[-1] is sess.last_profile


# ==========================================================================
# Event log: round-trip + emitters under fault injection
# ==========================================================================
def test_event_log_roundtrip_and_retry_events(tmp_path):
    conf = dict(TEL, **FAST)
    conf.update({
        "spark.rapids.tpu.telemetry.eventLog.dir": str(tmp_path),
        # one injected OOM at the first upload checkpoint drives the
        # retry recovery path
        "spark.rapids.tpu.memory.oomInjection.mode": "nth",
        "spark.rapids.tpu.memory.oomInjection.skipCount": 0,
    })
    sess = srt.Session(conf)
    _agg_df(sess).collect()
    assert sess.last_metrics.get("retry.numRetries", 0) >= 1

    files = glob.glob(str(tmp_path / "events-*.jsonl"))
    assert len(files) == 1
    events = read_event_log(files[0])
    kinds = {e["event"] for e in events}
    assert {"query_begin", "query_end", "fault_injected",
            "retry"} <= kinds, kinds
    # write -> parse -> replay: the file round-trips to the same
    # stream the in-memory ring holds
    summary = replay_summary(events)
    ring = replay_summary(sess.last_profile.events.snapshot())
    assert summary["counts"] == ring["counts"]
    assert summary["queries"] == ring["queries"]
    # every record is one flat JSON object with the core fields
    for e in events:
        assert e["query"] == summary["queries"][0]
        assert isinstance(e["ts"], float)


@pytest.mark.fault_injection
def test_degrade_and_fault_events_reach_the_profile():
    """A query that exhausts fault recovery and degrades to the CPU
    rung must leave the injected fault AND the degrade decision in the
    event log of its profile (late events land in the same ring)."""
    conf = dict(TEL, **FAST)
    conf.update({
        "spark.rapids.tpu.fault.injection.mode": "always",
        "spark.rapids.tpu.fault.injection.type": "stage_crash",
        "spark.rapids.tpu.fault.injection.site": "exchange.write",
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        "spark.rapids.tpu.sql.taskRetries": 0,
    })
    sess = srt.Session(conf)
    _agg_df(sess).collect()
    assert sess.last_metrics.get("fault.degradeLevel") == 2
    prof = sess.last_profile
    assert prof is not None
    kinds = {e["event"] for e in prof.events.snapshot()}
    assert "fault_injected" in kinds, kinds
    assert "degrade" in kinds, kinds
    degrade = [e for e in prof.events.snapshot()
               if e["event"] == "degrade"][-1]
    assert degrade["level"] == 2 and degrade["rung"] == "cpu"
    # the profile's metrics reflect the final merged counters
    assert prof.metrics.get("fault.degradeLevel") == 2


def test_event_ring_is_bounded_and_counts_drops():
    log = EventLog("qtest", max_events=4)
    for i in range(10):
        log.emit("spill", i=i)
    assert len(log) == 4
    assert log.dropped == 6
    assert [e["i"] for e in log.snapshot()] == [6, 7, 8, 9]


def test_sink_serializes_numpy_scalars(tmp_path):
    """Emitter fields are unvalidated kwargs from ~15 engine call
    sites; numpy scalars (spill sizes, byte counts from array math)
    must land in the JSONL sink, not silently vanish from it."""
    log = EventLog("qnp", max_events=8, sink_dir=str(tmp_path))
    log.emit("spill", bytes=np.int64(5), frac=np.float32(0.5))
    events = read_event_log(str(tmp_path / "events-qnp.jsonl"))
    assert len(events) == 1 and events[0]["event"] == "spill"
    assert log.sink_path is not None  # sink still healthy


def test_emit_event_is_noop_and_safe_without_binding():
    tspans.deactivate()
    emit_event("spill", bytes=1)  # must not raise, must not bind
    assert tspans.current() is None


@pytest.mark.fault_injection
def test_tpch_under_injection_profiles_every_exec(tmp_path):
    """The acceptance shape: a TPC-H query under fault injection
    yields a profile with one span per physical exec (wall +
    device-sync, rows/batches) AND a JSONL event log containing the
    injected retry events."""
    from spark_rapids_tpu.benchmarks import tpch, tpch_datagen
    from spark_rapids_tpu.session import Session

    conf = dict(TEL, **FAST)
    conf.update({
        "spark.rapids.tpu.telemetry.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.memory.oomInjection.mode": "nth",
        "spark.rapids.tpu.memory.oomInjection.skipCount": 1,
    })
    sess = Session(conf)
    tables = tpch_datagen.dataframes(sess, sf=0.0007, seed=7)
    tpch.QUERIES[1](tables).collect()
    prof = sess.last_profile
    assert prof is not None
    execs = prof.exec_spans()
    # every exec of q1's physical plan that registered metrics has a
    # span with its measured wall; the transitions carry rows + sync
    assert {"HostToDeviceExec", "DeviceToHostExec",
            "TpuHashAggregateExec"} <= set(execs), sorted(execs)
    assert execs["HostToDeviceExec"]["rows"] > 0
    assert execs["HostToDeviceExec"]["device_sync_ns"] > 0
    report = sess.profile_report()
    assert "TpuHashAggregate" in report
    files = glob.glob(str(tmp_path / "events-*.jsonl"))
    assert len(files) == 1
    kinds = {e["event"] for e in read_event_log(files[0])}
    assert "fault_injected" in kinds and "retry" in kinds, kinds


# ==========================================================================
# Disabled mode: no-ops, snapshot unchanged
# ==========================================================================
def test_disabled_mode_keeps_metrics_snapshot_identical():
    on = srt.Session(dict(TEL))
    _agg_df(on).collect()
    on_keys = set(on.last_metrics)

    off = srt.Session()
    _agg_df(off).collect()
    off_keys = set(off.last_metrics)

    assert off.last_profile is None and off.profiles == []
    assert off.profile_report() == ""
    # the telemetry-only deviceSyncTime metrics exist ONLY under
    # telemetry; everything else is the identical key set
    sync = {k for k in on_keys if k.endswith(".deviceSyncTime")}
    assert sync, on_keys
    assert not any(k.endswith(".deviceSyncTime") for k in off_keys)
    assert on_keys - sync == off_keys
    # two disabled runs produce the identical key set (stability)
    off2 = srt.Session()
    _agg_df(off2).collect()
    assert set(off2.last_metrics) == off_keys


# ==========================================================================
# _finalize_metrics: no double counting across consecutive queries
# ==========================================================================
def test_counters_not_double_counted_across_queries():
    conf = dict(TEL, **FAST)
    conf.update({
        "spark.rapids.tpu.memory.oomInjection.mode": "nth",
        "spark.rapids.tpu.memory.oomInjection.skipCount": 0,
    })
    sess = srt.Session(conf)
    df = _agg_df(sess)
    df.collect()
    first = sess.last_metrics.get("retry.numRetries", 0)
    assert first >= 1
    # the injector re-arms per query (nth fires once per run): the
    # second run must report ITS OWN counters, not accumulate
    df.collect()
    assert sess.last_metrics.get("retry.numRetries", 0) == first
    # and a clean session reports zeros, not inherited counters
    clean = srt.Session(dict(TEL))
    _agg_df(clean).collect()
    assert clean.last_metrics.get("retry.numRetries", 0) == 0
    assert clean.last_metrics.get("fault.numStageRetries") == 0


# ==========================================================================
# trace_range (satellite): one exception-safe path + span coupling
# ==========================================================================
def test_trace_range_metric_coupling_survives_exceptions():
    from spark_rapids_tpu.utils.metrics import Metric
    from spark_rapids_tpu.utils.tracing import trace_range

    m = Metric("t", "ns")
    with pytest.raises(ValueError):
        with trace_range("boom", m):
            raise ValueError("x")
    assert m.value > 0


def test_trace_range_aggregates_into_current_span():
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.utils.tracing import trace_range

    tele = tspans.QueryTelemetry(TpuConf(dict(TEL)))
    tspans.activate(tele)
    try:
        with tspans.span("work", kind="stage") as sp:
            with trace_range("inner"):
                with trace_range("inner"):  # re-entrant: counted once
                    pass
            with trace_range("other"):
                pass
        assert set(sp.range_ns) == {"inner", "other"}
        assert sp.range_ns["inner"] > 0
    finally:
        tspans.deactivate()


def test_capture_attached_propagates_binding_to_worker():
    import threading

    from spark_rapids_tpu.config import TpuConf

    tele = tspans.QueryTelemetry(TpuConf(dict(TEL)))
    tspans.activate(tele)
    seen = {}

    def work():
        seen["tele"] = tspans.current()

    try:
        cap = tspans.capture()
        t = threading.Thread(target=tspans.bound(cap, work))
        t.start()
        t.join()
        assert seen["tele"] is tele
    finally:
        tspans.deactivate()


# ==========================================================================
# Regression: profiles never back-fill from a previous query
# ==========================================================================
def test_distributed_profile_uses_own_query_metrics():
    """A distributed run after a (bigger) native run must back-fill
    its exec spans from ITS OWN ctx snapshot, not the session's
    previous last_metrics."""
    from spark_rapids_tpu.parallel.runner import run_distributed

    sess = srt.Session(dict(TEL))
    a = sess.create_dataframe({"k": [1, 2] * 32, "v": [1.0] * 64})
    a.group_by("k").agg(f_sum_s()).collect()  # query A: 64 rows
    b = sess.create_dataframe({"k": [1, 2] * 16, "v": [2.0] * 32})
    run_distributed(sess, b.group_by("k").agg(f_sum_s()), n_devices=8)
    prof = sess.last_profile
    h2d = prof.exec_spans().get("HostToDeviceExec")
    if h2d is not None:  # leaf execs registered on this mesh layout
        assert h2d["rows"] == 32, h2d
    # none of query A's per-exec families may leak into B's profile
    assert not [k for k in prof.metrics
                if k.startswith("TpuShuffleExchangeExec")]


def f_sum_s():
    return F.sum("v").alias("s")


def test_bad_event_log_dir_degrades_to_ring():
    """A misconfigured eventLog.dir must never fail the query — the
    log degrades to the in-memory ring."""
    sess = srt.Session(dict(TEL, **{
        "spark.rapids.tpu.telemetry.eventLog.dir": "/proc/nope/x"}))
    d = sess.create_dataframe({"x": [1.0, 2.0]})
    rows = d.select((d["x"] * 2).alias("y")).collect()
    assert sorted(rows) == [(2.0,), (4.0,)]
    prof = sess.last_profile
    assert prof is not None
    assert prof.events.sink_path is None
    assert {e["event"] for e in prof.events.snapshot()} >= {
        "query_begin", "query_end"}


@pytest.mark.fault_injection
def test_ladder_degrade_event_lands_in_reported_profile():
    """rung 0 -> 1: the degrade decision must be visible in the
    profile the user reads (last_profile = the rung-1 query's), with
    the cross-rung merged counters."""
    from spark_rapids_tpu.fault.ladder import run_with_fault_tolerance

    conf = dict(TEL, **FAST)
    conf.update({
        "spark.rapids.tpu.fault.injection.mode": "always",
        "spark.rapids.tpu.fault.injection.type": "stage_crash",
        "spark.rapids.tpu.fault.injection.site": "stage.run",
        "spark.rapids.tpu.fault.maxStageRetries": 0,
    })
    sess = srt.Session(conf)
    df = sess.create_dataframe({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
    run_with_fault_tolerance(
        sess, df.group_by("k").agg(F.sum("v").alias("s")), n_devices=8)
    assert sess.last_metrics.get("fault.degradeLevel") == 1
    kinds = {e["event"] for e in sess.last_profile.events.snapshot()}
    assert "degrade" in kinds, kinds
    assert sess.last_profile.metrics.get("fault.degradeLevel") == 1


def test_disabled_query_clears_stale_last_profile():
    """After a telemetry-enabled query, a later disabled query on the
    same session must not leave the old profile posing as 'the most
    recent execution' (history stays in session.profiles)."""
    sess = srt.Session(dict(TEL))
    d = sess.create_dataframe({"x": [1.0, 2.0]})
    d.select((d["x"] * 2).alias("y")).collect()
    assert sess.last_profile is not None
    kept = sess.last_profile
    sess.conf = sess.conf.set(
        "spark.rapids.tpu.telemetry.enabled", False)
    d2 = sess.create_dataframe({"x": [3.0]})
    d2.select((d2["x"] * 2).alias("y")).collect()
    assert sess.last_profile is None
    assert sess.profile_report() == ""
    assert kept in sess.profiles  # history survives


def test_columnar_export_finishes_telemetry():
    """The ML export path owns its ExecContext, so it must finish the
    query telemetry too — stopping the HbmSampler thread and emitting
    query_end (a leaked sampler polls the DeviceManager forever)."""
    import threading

    sess = srt.Session(dict(TEL, **{
        "spark.rapids.tpu.sql.exportColumnarRdd": True,
        "spark.rapids.tpu.telemetry.sampleHbmMs": 5}))
    d = sess.create_dataframe({"x": [1.0, 2.0, 3.0]})
    batches = sess.execute_columnar(
        d.select((d["x"] * 2).alias("y")).plan)
    assert batches
    prof = sess.last_profile
    assert prof is not None
    kinds = [e["event"] for e in prof.events.snapshot()]
    assert kinds.count("query_end") == 1, kinds
    assert not [t for t in threading.enumerate()
                if t.name == "hbm-sampler" and t.is_alive()]


def test_hbm_watermark_uses_peak_column():
    from spark_rapids_tpu.config import TpuConf

    tele = tspans.QueryTelemetry(TpuConf(dict(TEL)))
    # a spike freed between samples: allocated back at 10, peak at 99
    tele.hbm_timeline = [(1.0, 10, 10), (2.0, 10, 99)]
    tele.finished = True
    from spark_rapids_tpu.telemetry.profile import QueryProfile

    prof = QueryProfile(tele, metrics={})
    assert "peak=99B" in prof.render()
    text = prometheus_text({}, hbm_timeline=prof.hbm_timeline)
    assert "hbm_watermark_bytes 99" in text


# ==========================================================================
# Exporters
# ==========================================================================
_PROM_LINE = re.compile(
    r'^spark_rapids_tpu_metric\{exec="[A-Za-z0-9_]*",'
    r'name="[A-Za-z0-9_]+"(,(tenant|exchange)="[^"]+")?'
    r'(,query="[^"]+")?\} -?[0-9.e+-]+$')


def test_prometheus_export_format_and_stability():
    sess = srt.Session(dict(TEL))
    _agg_df(sess).collect()
    snap = sess.last_metrics
    text1 = prometheus_text(snap, query_id=sess.last_profile.query_id)
    text2 = prometheus_text(snap, query_id=sess.last_profile.query_id)
    assert text1 == text2  # deterministic ordering
    lines = [ln for ln in text1.splitlines()
             if ln and not ln.startswith("#")]
    assert lines
    for ln in lines:
        assert _PROM_LINE.match(ln), ln
    # per-exec metrics carry the exec label
    assert any('exec="HostToDeviceExec"' in ln for ln in lines)
    # counter families export with an empty exec label
    assert any('exec="",name="fault_degradeLevel"' in ln
               for ln in lines)


def test_prometheus_tenant_and_exchange_labels():
    metrics = {
        "scheduler.tenant.alpha.finished": 3,
        "scheduler.tenant.alpha.latencyP95Ms": 12.5,
        "scheduler.tenant.beta-2.shed": 1,
        "shuffle.exchange2.spillBytes": 4096,
        "fault.degradeLevel": 0,
    }
    text = prometheus_text(metrics)
    assert ('spark_rapids_tpu_metric{exec="",'
            'name="scheduler_tenant_finished",tenant="alpha"} 3') in text
    assert ('spark_rapids_tpu_metric{exec="",'
            'name="scheduler_tenant_shed",tenant="beta-2"} 1') in text
    assert ('spark_rapids_tpu_metric{exec="",'
            'name="shuffle_exchange_spillBytes",exchange="2"} 4096') \
        in text
    # every line still matches the canonical grammar, and unlabeled
    # families render exactly as before
    lines = [ln for ln in text.splitlines()
             if ln and not ln.startswith("#")]
    for ln in lines:
        assert _PROM_LINE.match(ln), ln
    assert 'exec="",name="fault_degradeLevel"} 0' in text


def test_prometheus_histogram_exposition():
    from spark_rapids_tpu.telemetry.histogram import LatencyHistogram
    h = LatencyHistogram(window_s=60.0)
    for v in (0.5, 1.0, 2.0, 1000.0):
        h.observe(v, now=100.0)
    text = prometheus_text({}, histograms=[
        ("queue_wait_ms", {}, h),
        ("query_latency_ms", {"tenant": "alpha"}, h),
    ])
    assert "# TYPE spark_rapids_tpu_queue_wait_ms histogram" in text
    assert "# TYPE spark_rapids_tpu_query_latency_ms histogram" in text
    # cumulative buckets are monotone and the +Inf bucket equals _count
    buckets = re.findall(
        r'spark_rapids_tpu_queue_wait_ms_bucket\{le="([^"]+)"\} (\d+)',
        text)
    counts = [int(c) for _le, c in buckets]
    assert counts == sorted(counts)
    assert buckets[-1][0] == "+Inf" and counts[-1] == 4
    assert "spark_rapids_tpu_queue_wait_ms_count 4" in text
    assert "spark_rapids_tpu_queue_wait_ms_sum 1003.5" in text
    # labeled series put the labels before le=
    assert ('spark_rapids_tpu_query_latency_ms_bucket{tenant="alpha",'
            'le="+Inf"} 4') in text
    assert ('spark_rapids_tpu_query_latency_ms_count{tenant="alpha"} 4'
            ) in text


def test_json_snapshot_round_trips():
    sess = srt.Session(dict(TEL))
    _agg_df(sess).collect()
    prof = sess.last_profile
    doc = json.loads(json_snapshot(
        sess.last_metrics, query_id=prof.query_id,
        events=prof.events.snapshot(),
        hbm_timeline=prof.hbm_timeline))
    assert doc["query"] == prof.query_id
    assert doc["metrics"] == {k: v for k, v in
                              sess.last_metrics.items()}
    assert doc["events"]["counts"]["query_begin"] == 1
    assert json_snapshot(sess.last_metrics) == \
        json_snapshot(dict(sess.last_metrics))  # stable


# ==========================================================================
# HBM watermark sampler
# ==========================================================================
@pytest.mark.slow
def test_hbm_watermark_timeline_sampled():
    sess = srt.Session(dict(TEL, **{
        "spark.rapids.tpu.telemetry.sampleHbmMs": 5}))
    _agg_df(sess, n=4096).collect()
    prof = sess.last_profile
    # at least the t0 + closing samples; ts monotone; peak >= allocated
    assert len(prof.hbm_timeline) >= 2
    ts = [t[0] for t in prof.hbm_timeline]
    assert ts == sorted(ts)
    for _t, allocated, peak in prof.hbm_timeline:
        assert peak >= 0 and allocated >= 0
    assert "HBM watermark" in prof.render()


# ==========================================================================
# Multiprocess event ship-back
# ==========================================================================
def test_extend_shipped_merges_peer_events():
    log = EventLog("qtest", max_events=8)
    log.emit("query_begin")
    log.extend_shipped([{"ts": 1.0, "event": "spill", "query": "qpeer",
                         "proc": 1}])
    events = log.snapshot()
    assert len(events) == 2
    assert events[-1]["proc"] == 1


def test_gather_events_single_process_returns_no_peers():
    from spark_rapids_tpu.telemetry.events import (
        gather_multiprocess_events)

    # single controller: the collective degenerates to "no peers" —
    # the local ring must stay untouched
    assert gather_multiprocess_events(
        [{"ts": 1.0, "event": "query_begin", "query": "q"}]) == []


@pytest.mark.slow
def test_two_process_event_shipback():
    import socket
    import subprocess
    import sys

    def _free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    coordinator = f"127.0.0.1:{_free_port()}"
    script = os.path.join(os.path.dirname(__file__),
                          "mp_telemetry_worker.py")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, script, coordinator, "2", str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("mp telemetry workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    if any("Multiprocess computations aren't implemented" in (o or "")
           for o in outs):
        pytest.skip("this jax build cannot run multi-process "
                    "collectives on the CPU backend")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"worker {pid} rc={p.returncode}:\n{out[-4000:]}"
        assert f"MP TELEMETRY OK pid={pid}" in out, out[-4000:]


# ==========================================================================
# Doc drift: every registered conf key is documented
# ==========================================================================
def test_every_conf_key_documented_in_configs_md():
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.plan.overrides import _ensure_registry

    _ensure_registry()  # auto-derived per-operator keys register lazily
    doc_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "configs.md")
    with open(doc_path) as f:
        doc = f.read()
    missing = [key for key, e in C._REGISTRY.items()
               if not e.is_internal and f"`{key}`" not in doc]
    assert not missing, \
        f"conf keys missing from docs/configs.md: {missing} — " \
        "regenerate with config.dump_markdown()"


# ==========================================================================
# bench.py satellite: atomic artifact persistence
# ==========================================================================
def test_bench_artifact_written_atomically(tmp_path):
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    target = str(tmp_path / "BENCH_TPU_LAST.json")
    bench._persist_tpu_artifact({"metric": "x", "value": 1.0},
                                path=target)
    first = json.load(open(target))
    assert first["value"] == 1.0 and "captured_at" in first
    # overwrite leaves a complete new file and no temp litter
    bench._persist_tpu_artifact({"metric": "x", "value": 2.0},
                                path=target)
    assert json.load(open(target))["value"] == 2.0
    assert [f for f in os.listdir(tmp_path)
            if f.endswith(".tmp")] == []
    # a failed serialization keeps the previous artifact intact
    with pytest.raises(TypeError):
        bench._atomic_write_json(target, {"bad": object()})
    assert json.load(open(target))["value"] == 2.0
    assert [f for f in os.listdir(tmp_path)
            if f.endswith(".tmp")] == []
