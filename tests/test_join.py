"""Device join vs CPU oracle (reference test analogue: join_test.py +
HashAggregatesSuite-style dual-session equality)."""
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import f
from spark_rapids_tpu import types as T


def _norm(rows):
    return sorted(
        (tuple((None if v is None else
                (round(v, 9) if isinstance(v, float) else v))
               for v in r) for r in rows),
        key=repr)


def _run_both(build, how_assert_on_tpu=True):
    tpu = srt.Session()
    cpu = srt.Session(tpu_enabled=False)
    tq = build(tpu)
    cq = build(cpu)
    if how_assert_on_tpu:
        ex = tq.explain()
        assert "Join" in ex and "will run on TPU" in ex, ex
    got = _norm(tq.collect())
    want = _norm(cq.collect())
    assert got == want, f"\nTPU: {got}\nCPU: {want}"


LEFT = {"k": [1, 2, 2, 3, None, 5, 6],
        "a": [10.0, 20.0, 21.0, 30.0, 40.0, 50.0, 60.0]}
RIGHT = {"k": [2, 2, 3, 4, None, 6],
         "b": ["x", "y", "z", "w", "n", "q"]}


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "semi", "anti"])
def test_join_types_match_oracle(how):
    def build(sess):
        l = sess.create_dataframe(LEFT)
        r = sess.create_dataframe(RIGHT)
        return l.join(r, on="k", how=how)

    _run_both(build)


def test_join_duplicate_heavy_keys():
    rng = np.random.RandomState(11)
    lk = rng.randint(0, 8, 300).tolist()
    rk = rng.randint(0, 8, 200).tolist()

    def build(sess):
        l = sess.create_dataframe({"k": lk,
                                   "a": list(range(300))})
        r = sess.create_dataframe({"k": rk,
                                   "b": list(range(200))})
        return l.join(r, on="k", how="inner")

    _run_both(build)


def test_join_string_keys():
    def build(sess):
        l = sess.create_dataframe({"k": ["aa", "bb", None, "cc", "aa"],
                                   "a": [1, 2, 3, 4, 5]})
        r = sess.create_dataframe({"k": ["aa", "cc", "dd", None],
                                   "b": [9.0, 8.0, 7.0, 6.0]})
        return l.join(r, on="k", how="left")

    _run_both(build)


def test_join_mixed_dtype_keys():
    s_int = T.Schema([T.Field("k", T.INT32), T.Field("a", T.INT64)])
    s_dbl = T.Schema([T.Field("k", T.FLOAT64), T.Field("b", T.INT64)])

    def build(sess):
        l = sess.create_dataframe({"k": [1, 2, 3], "a": [1, 2, 3]}, s_int)
        r = sess.create_dataframe({"k": [1.0, 3.0, 4.5],
                                   "b": [10, 30, 45]}, s_dbl)
        return l.join(r, on="k", how="inner")

    _run_both(build)


def test_inner_join_with_condition():
    def build(sess):
        l = sess.create_dataframe(LEFT)
        r = sess.create_dataframe(RIGHT)
        return l.join(r, on="k", how="inner",
                      condition=f.col("a") > f.lit(15.0))

    _run_both(build)


def test_outer_join_with_condition_falls_back():
    sess = srt.Session()
    l = sess.create_dataframe(LEFT)
    r = sess.create_dataframe(RIGHT)
    # a residual condition on an outer join must fall back
    j = l.join(r, on="k", how="left", condition=f.col("a") > f.lit(15.0))
    ex = j.explain()
    assert "cannot run on TPU" in ex
    cpu = srt.Session(tpu_enabled=False)
    lc = cpu.create_dataframe(LEFT)
    rc = cpu.create_dataframe(RIGHT)
    jc = lc.join(rc, on="k", how="left",
                 condition=f.col("a") > f.lit(15.0))
    assert _norm(j.collect()) == _norm(jc.collect())


def test_empty_sides():
    for lrows, rrows in [(0, 4), (4, 0), (0, 0)]:
        def build(sess, lrows=lrows, rrows=rrows):
            s1 = T.Schema([T.Field("k", T.INT64), T.Field("a", T.INT64)])
            s2 = T.Schema([T.Field("k", T.INT64), T.Field("b", T.INT64)])
            l = sess.create_dataframe(
                {"k": list(range(lrows)), "a": list(range(lrows))}, s1)
            r = sess.create_dataframe(
                {"k": list(range(rrows)), "b": list(range(rrows))}, s2)
            return l.join(r, on="k", how="left")

        _run_both(build, how_assert_on_tpu=False)


def test_broadcast_artifact_reused_across_collects():
    """The broadcast build side is materialized ONCE and shared across
    repeated collects of the same plan; the artifact dies with the plan
    (reference: GpuBroadcastExchangeExec.scala:215-247 builds the
    broadcast relation once and Spark caches it)."""
    import gc

    from spark_rapids_tpu.exec.joins import TpuBroadcastHashJoinExec

    sess = srt.Session()
    l = sess.create_dataframe(
        {"k": list(range(100)), "v": list(range(100))})
    r = sess.create_dataframe(
        {"rk": list(range(0, 100, 2)), "w": list(range(50))})
    j = l.join(r, on=(["k"], ["rk"]), how="inner")

    phys, _ctx = sess.prepare_execution(j.plan)
    phys._exec_lock.release()
    found = []

    def walk(n):
        if isinstance(n, TpuBroadcastHashJoinExec):
            found.append(n)
        for c in getattr(n, "children", []):
            walk(c)

    walk(phys)
    assert found, "small build side must plan as a broadcast join"

    reg = sess.broadcast_registry
    base = reg.builds
    a = _norm(j.collect())
    b = _norm(j.collect())
    assert a == b and len(a) == 50
    assert reg.builds == base + 1, \
        "build side must materialize exactly once across collects"
    assert len(reg) >= 1

    # plan dropped -> artifact purged (no session-lifetime leak)
    del j, phys, found
    gc.collect()
    reg._purge_dead()
    assert len(reg) == 0
