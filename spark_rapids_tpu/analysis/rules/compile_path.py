"""Compile/dispatch-path rules: jit-direct, stopwatch, profiler-guard.

All three guard the KernelCache contract: every compile goes through
``jit_kernel`` (one cache, one profiler hook, one place to account
compile time), timing around dispatches belongs to the profiler (an ad
hoc stopwatch around a ``jit_kernel`` call measures async dispatch,
not kernel time), and the profiler hook inside ``_CachedKernel`` must
stay a single attribute read when disabled.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..engine import AnalysisContext, Rule
from ..findings import Finding
from ..resolver import own_body_nodes, terminal_name
from . import common

KERNEL_CACHE = "exec/kernel_cache.py"


class JitDirectRule(Rule):
    id = "jit-direct"
    title = "exec/ compiles only through jit_kernel (KernelCache)"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rels = common.scoped(ctx, prefixes=("exec/",),
                             exclude=(KERNEL_CACHE,))
        jit_kernel_sites = 0
        for fi in ctx.resolver.functions(rels):
            for call in fi.own_calls:
                name = terminal_name(call.func)
                if name == "jit":
                    out.append(self.finding(
                        "direct-jit", fi.module, call.lineno,
                        f"{fi.qualname}() calls jit() directly — "
                        f"compile through jit_kernel so the cache "
                        f"and compile-time accounting see it",
                        detail=f"{fi.qualname}:jit"))
                elif name == "jit_kernel":
                    jit_kernel_sites += 1
        out.extend(self.health(
            jit_kernel_sites >= 10, common.PKG + KERNEL_CACHE,
            f"expected >=10 jit_kernel call sites in exec/, "
            f"saw {jit_kernel_sites}"))
        return out


class StopwatchRule(Rule):
    id = "stopwatch"
    title = "no ad-hoc perf_counter timing around jit_kernel dispatches"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rels = common.scoped(ctx, prefixes=("exec/",),
                             exclude=(KERNEL_CACHE,))
        for fi in ctx.resolver.functions(rels):
            names = fi.own_call_names
            timed = names & {"perf_counter", "perf_counter_ns"}
            if timed and "jit_kernel" in names:
                out.append(self.finding(
                    "adhoc-timing", fi.module, fi.lineno,
                    f"{fi.qualname}() wraps a jit_kernel dispatch in "
                    f"{sorted(timed)} — dispatch is async; kernel "
                    f"timing belongs to the KernelProfiler",
                    detail=f"{fi.qualname}:stopwatch"))
        return out


class ProfilerGuardRule(Rule):
    id = "profiler-guard"
    title = "profiler hook in the dispatch path is one attribute read"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rel = common.PKG + KERNEL_CACHE
        mi = ctx.resolver.module(rel)
        if mi is None:
            return [self.finding("health", rel, 0,
                                 "kernel_cache.py missing/unparseable")]
        calls = [fi for fi in mi.functions
                 if fi.class_name == "_CachedKernel" and
                 fi.name == "__call__"]
        if not calls:
            out.append(self.finding(
                "guard", rel, 0,
                "_CachedKernel.__call__ not found — the dispatch-path "
                "profiler guard cannot be verified"))
            return out
        fi = calls[0]
        # the guard: prof = PROFILER if PROFILER.enabled else None
        guard_ok = any(
            isinstance(n, ast.IfExp) and
            isinstance(n.test, ast.Attribute) and
            n.test.attr == "enabled" and
            isinstance(n.orelse, ast.Constant) and
            n.orelse.value is None
            for n in own_body_nodes(fi.node))
        if not guard_ok:
            out.append(self.finding(
                "guard", rel, fi.lineno,
                "_CachedKernel.__call__ must bind the profiler via "
                "`prof = PROFILER if PROFILER.enabled else None` — "
                "one attribute read on the disabled path",
                detail="guard-shape"))
        # every record_dispatch stays behind an `... is not None` If
        guarded_ids = set()
        for n in own_body_nodes(fi.node):
            if isinstance(n, ast.If) and \
                    isinstance(n.test, ast.Compare) and \
                    any(isinstance(op, ast.IsNot)
                        for op in n.test.ops):
                for stmt in n.body:
                    for sub in ast.walk(stmt):
                        guarded_ids.add(id(sub))
        dispatches = [c for c in fi.own_calls
                      if terminal_name(c.func) == "record_dispatch"]
        for c in dispatches:
            if id(c) not in guarded_ids:
                out.append(self.finding(
                    "guard", rel, c.lineno,
                    "record_dispatch call not under an "
                    "`if prof is not None:` guard",
                    detail="record_dispatch-unguarded"))
        out.extend(self.health(
            len(dispatches) >= 1, rel,
            "no record_dispatch site in _CachedKernel.__call__"))
        # the h2d ceiling is recorded at the upload boundary
        trans = ctx.resolver.module(common.PKG + "exec/transitions.py")
        h2d = trans is not None and any(
            "record_h2d" in fi2.own_call_names
            for fi2 in trans.functions)
        out.extend(self.health(
            h2d, common.PKG + "exec/transitions.py",
            "no record_h2d site in exec/transitions.py"))
        prof = ctx.resolver.module(common.PKG + "telemetry/profiler.py")
        have = set(prof.by_name) if prof is not None else set()
        need = {"record_dispatch", "record_h2d", "mark", "since"}
        out.extend(self.health(
            need <= have, common.PKG + "telemetry/profiler.py",
            f"KernelProfiler API incomplete: missing {sorted(need - have)}"))
        return out
