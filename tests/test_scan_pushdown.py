"""Scan pushdown: column pruning + parquet row-group stats pruning.

Reference analogue: ParquetScanSuite predicate-pushdown coverage
(GpuParquetScan.scala:316 footer row-group filtering).
"""
import datetime

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import f
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.optimizer import optimize


@pytest.fixture
def pq_file(tmp_path):
    """One parquet file with 10 row groups of 100 ordered rows each."""
    n = 1000
    tbl = pa.table({
        "k": pa.array(np.arange(n, dtype=np.int64)),
        "v": pa.array(np.arange(n, dtype=np.float64) * 0.5),
        "s": pa.array([f"s{i % 13}" for i in range(n)]),
        "d": pa.array([datetime.date(2020, 1, 1)
                       + datetime.timedelta(days=int(i // 10))
                       for i in range(n)]),
    })
    path = str(tmp_path / "data.parquet")
    pq.write_table(tbl, path, row_group_size=100)
    return path


def _scan_exec(df, phys=None):
    """Dig the FileScanExec out of the (executed) physical plan."""
    from spark_rapids_tpu.io.scans import FileScanExec

    if phys is None:
        phys = df.session.physical_plan(df.plan)
    stack = [phys]
    while stack:
        p = stack.pop()
        if isinstance(p, FileScanExec):
            return p
        stack.extend(p.children)
    raise AssertionError("no FileScanExec in plan")


def test_column_pruning_narrows_scan(pq_file):
    sess = srt.Session(tpu_enabled=False)
    df = sess.read_parquet(pq_file).select("k").filter(
        f.col("k") < f.lit(10))
    scan = _scan_exec(df)
    assert scan.schema.names == ["k"]
    assert [r[0] for r in df.collect()] == list(range(10))


def test_pruning_keeps_filter_only_columns(pq_file):
    sess = srt.Session(tpu_enabled=False)
    df = (sess.read_parquet(pq_file)
          .filter(f.col("v") < f.lit(5.0)).select("k"))
    scan = _scan_exec(df)
    assert set(scan.schema.names) == {"k", "v"}
    assert sorted(r[0] for r in df.collect()) == list(range(10))


def test_row_group_pruning_skips_groups(pq_file):
    sess = srt.Session(tpu_enabled=False)
    df = sess.read_parquet(pq_file).filter(
        (f.col("k") >= f.lit(250)) & (f.col("k") < f.lit(450)))
    sess.start_capture()
    rows = df.collect()
    scan = _scan_exec(df, phys=sess.captured_plans()[-1])
    preds = scan.options.get("_scan_predicates")
    assert preds and ("k", ">=", 250) in preds and ("k", "<", 450) in preds
    assert len(rows) == 200
    # groups [0,100),[100,200),[500,600)... must have been skipped
    assert scan.metrics_skipped_groups == 7


def test_row_group_pruning_on_dates(pq_file):
    sess = srt.Session(tpu_enabled=False)
    df = sess.read_parquet(pq_file).filter(
        f.col("d") >= f.lit(datetime.date(2020, 4, 1)))
    sess.start_capture()
    rows = df.collect()
    scan = _scan_exec(df, phys=sess.captured_plans()[-1])
    # day index >= 91 -> k >= 910 -> only the last row group survives
    assert len(rows) == 90
    assert scan.metrics_skipped_groups == 9


def test_row_group_pruning_on_timestamps(tmp_path):
    """Timestamp stats must normalize to engine micros, not days —
    regression for pruning silently dropping all matching groups."""
    from spark_rapids_tpu import types as T

    n = 1000
    us = (np.arange(n, dtype=np.int64) * 86_400_000_000)
    tbl = pa.table({"ts": pa.array(us, type=pa.timestamp("us")),
                    "v": pa.array(np.arange(n, dtype=np.float64))})
    path = str(tmp_path / "ts.parquet")
    pq.write_table(tbl, path, row_group_size=100)
    sess = srt.Session(tpu_enabled=False)
    cutoff = int(us[n // 2])
    df = sess.read_parquet(path).filter(
        f.col("ts") >= f.lit(cutoff, T.TIMESTAMP))
    sess.start_capture()
    rows = df.collect()
    assert len(rows) == n // 2
    scan = _scan_exec(df, phys=sess.captured_plans()[-1])
    assert scan.metrics_skipped_groups == 5


def test_pushdown_equality_cpu_vs_tpu(pq_file):
    outs = []
    for tpu in (True, False):
        sess = srt.Session(tpu_enabled=tpu)
        df = (sess.read_parquet(pq_file)
              .filter((f.col("k") >= f.lit(100)) & (f.col("k") < f.lit(300))
                      & (f.col("s") == f.lit("s5")))
              .select("k", "v", "s"))
        outs.append(sorted(df.collect()))
    assert outs[0] == outs[1] and len(outs[0]) > 0


def test_optimizer_prunes_through_join():
    sess = srt.Session(tpu_enabled=False)
    # two in-memory relations can't prune (no FileScan), but the rewrite
    # must at least preserve semantics through joins/aggregates
    a = sess.create_dataframe({"x": np.arange(10), "y": np.arange(10.0)})
    b = sess.create_dataframe({"x": np.arange(5), "z": np.arange(5.0)})
    q = (a.join(b, on="x").group_by("x")
         .agg(f.sum("z").alias("sz")).sort("x"))
    plan2 = optimize(q.plan)
    assert isinstance(plan2, L.Sort)
    assert q.collect() == [(i, float(i)) for i in range(5)]
