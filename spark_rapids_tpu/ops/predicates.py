"""Predicate expressions.

Capability parity with the reference's predicates.scala: comparisons,
And/Or/Not with Spark's three-valued (Kleene) logic, null tests, IsNaN,
In/InSet, AtLeastNNonNulls, EqualNullSafe.
"""
from __future__ import annotations

import math
from typing import List

import numpy as np

from .. import types as T
from ..data.column import DeviceColumn, HostColumn
from .expression import (
    BinaryExpression,
    Expression,
    Scalar,
    UnaryExpression,
    _and_validity_jnp,
    _and_validity_np,
    as_device_column,
    as_host_column,
)
from .kernels import stringkernels as sk


# --------------------------------------------------------------------------
# Comparisons
# --------------------------------------------------------------------------
class _Comparison(BinaryExpression):
    op = ""  # "<", "<=", ">", ">=", "=="

    def result_dtype(self, lt, rt):
        return T.BOOL

    def _cast_inputs_np(self, l, r):
        lt, rt = self.left.dtype, self.right.dtype
        if lt.is_numeric and rt.is_numeric and lt != rt:
            p = T.promote(lt, rt)
            return (l.astype(p.np_dtype, copy=False),
                    r.astype(p.np_dtype, copy=False))
        return l, r

    def _cast_inputs_jnp(self, l, r):
        lt, rt = self.left.dtype, self.right.dtype
        if lt.is_numeric and rt.is_numeric and lt != rt:
            p = T.promote(lt, rt)
            return l.astype(p.jnp_dtype), r.astype(p.jnp_dtype)
        return l, r

    def do_cpu(self, l, r):
        if self.left.dtype.is_string or self.right.dtype.is_string:
            # object ndarrays compare elementwise; nulls are masked anyway
            l = np.asarray([x if isinstance(x, str) else "" for x in l],
                           dtype=object)
            r = np.asarray([x if isinstance(x, str) else "" for x in r],
                           dtype=object)
        return _NP_CMP[self.op](l, r)

    def eval_tpu(self, batch):
        if not (self.left.dtype.is_string or self.right.dtype.is_string):
            return super().eval_tpu(batch)
        import jax.numpy as jnp

        n = batch.padded_rows
        lc = self.left.eval_tpu(batch)
        rc = self.right.eval_tpu(batch)
        lcol = as_device_column(lc, n)
        rcol = as_device_column(rc, n)
        validity = _and_validity_jnp(n, lc, rc)
        if self.op == "==":
            data = sk.equals(lcol.data, lcol.lengths, rcol.data, rcol.lengths)
        else:
            c = sk.compare(lcol.data, lcol.lengths, rcol.data, rcol.lengths)
            data = {"<": c < 0, "<=": c <= 0, ">": c > 0, ">=": c >= 0}[self.op]
        return DeviceColumn(T.BOOL, data.astype(jnp.bool_), validity)

    def do_tpu(self, l, r):
        return _JNP_CMP[self.op](l, r)

    def sql(self):
        return f"({self.left.sql()} {self.op} {self.right.sql()})"


_NP_CMP = {
    "==": lambda l, r: np.asarray(l == r, dtype=np.bool_),
    "<": lambda l, r: np.asarray(l < r, dtype=np.bool_),
    "<=": lambda l, r: np.asarray(l <= r, dtype=np.bool_),
    ">": lambda l, r: np.asarray(l > r, dtype=np.bool_),
    ">=": lambda l, r: np.asarray(l >= r, dtype=np.bool_),
}


def _jnp_cmp_table():
    return {
        "==": lambda l, r: l == r,
        "<": lambda l, r: l < r,
        "<=": lambda l, r: l <= r,
        ">": lambda l, r: l > r,
        ">=": lambda l, r: l >= r,
    }


class _LazyCmp(dict):
    def __missing__(self, k):
        self.update(_jnp_cmp_table())
        return self[k]


_JNP_CMP = _LazyCmp()


class EqualTo(_Comparison):
    op = "=="


class LessThan(_Comparison):
    op = "<"


class LessThanOrEqual(_Comparison):
    op = "<="


class GreaterThan(_Comparison):
    op = ">"


class GreaterThanOrEqual(_Comparison):
    op = ">="


class EqualNullSafe(Expression):
    """``<=>``: never null; null <=> null is True."""

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return T.BOOL

    @property
    def nullable(self):
        return False

    def eval_cpu(self, batch):
        eq = EqualTo(self.children[0], self.children[1]).eval_cpu(batch)
        n = batch.num_rows
        eqc = as_host_column(eq, n)
        lc = as_host_column(self.children[0].eval_cpu(batch), n)
        rc = as_host_column(self.children[1].eval_cpu(batch), n)
        lv, rv = lc.is_valid(), rc.is_valid()
        data = np.where(lv & rv, eqc.data.astype(np.bool_) & eqc.is_valid(),
                        ~lv & ~rv)
        return HostColumn(T.BOOL, data.astype(np.bool_), None)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        n = batch.padded_rows
        eq = EqualTo(self.children[0], self.children[1]).eval_tpu(batch)
        lc = as_device_column(self.children[0].eval_tpu(batch), n)
        rc = as_device_column(self.children[1].eval_tpu(batch), n)
        lv, rv = lc.validity, rc.validity
        data = jnp.where(lv & rv, eq.data & eq.validity, ~lv & ~rv)
        return DeviceColumn(T.BOOL, data,
                            jnp.ones((n,), dtype=jnp.bool_))


# --------------------------------------------------------------------------
# Boolean logic (Kleene)
# --------------------------------------------------------------------------
class Not(UnaryExpression):
    def result_dtype(self, ct):
        return T.BOOL

    def do_cpu(self, data):
        return ~data.astype(np.bool_)

    def do_tpu(self, data):
        return ~data

    def sql(self):
        return f"(NOT {self.child.sql()})"


class And(Expression):
    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return T.BOOL

    def eval_cpu(self, batch):
        n = batch.num_rows
        lc = as_host_column(self.children[0].eval_cpu(batch), n)
        rc = as_host_column(self.children[1].eval_cpu(batch), n)
        lv, rv = lc.is_valid(), rc.is_valid()
        ld = lc.data.astype(np.bool_) & lv
        rd = rc.data.astype(np.bool_) & rv
        lf = lv & ~ld
        rf = rv & ~rd
        validity = lf | rf | (lv & rv)
        data = ld & rd
        return HostColumn(T.BOOL, data,
                          None if validity.all() else validity)

    def eval_tpu(self, batch):
        n = batch.padded_rows
        lc = as_device_column(self.children[0].eval_tpu(batch), n)
        rc = as_device_column(self.children[1].eval_tpu(batch), n)
        lv, rv = lc.validity, rc.validity
        ld = lc.data & lv
        rd = rc.data & rv
        lf = lv & ~ld
        rf = rv & ~rd
        return DeviceColumn(T.BOOL, ld & rd, lf | rf | (lv & rv))

    def sql(self):
        return f"({self.children[0].sql()} AND {self.children[1].sql()})"


class Or(Expression):
    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return T.BOOL

    def eval_cpu(self, batch):
        n = batch.num_rows
        lc = as_host_column(self.children[0].eval_cpu(batch), n)
        rc = as_host_column(self.children[1].eval_cpu(batch), n)
        lv, rv = lc.is_valid(), rc.is_valid()
        ld = lc.data.astype(np.bool_) & lv
        rd = rc.data.astype(np.bool_) & rv
        validity = ld | rd | (lv & rv)
        data = ld | rd
        return HostColumn(T.BOOL, data,
                          None if validity.all() else validity)

    def eval_tpu(self, batch):
        n = batch.padded_rows
        lc = as_device_column(self.children[0].eval_tpu(batch), n)
        rc = as_device_column(self.children[1].eval_tpu(batch), n)
        lv, rv = lc.validity, rc.validity
        ld = lc.data & lv
        rd = rc.data & rv
        return DeviceColumn(T.BOOL, ld | rd, ld | rd | (lv & rv))

    def sql(self):
        return f"({self.children[0].sql()} OR {self.children[1].sql()})"


# --------------------------------------------------------------------------
# Null tests
# --------------------------------------------------------------------------
class IsNull(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return T.BOOL

    @property
    def nullable(self):
        return False

    def eval_cpu(self, batch):
        c = self.children[0].eval_cpu(batch)
        if isinstance(c, Scalar):
            return Scalar(T.BOOL, c.is_null)
        return HostColumn(T.BOOL, ~c.is_valid(), None)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        n = batch.padded_rows
        c = as_device_column(self.children[0].eval_tpu(batch), n)
        # padding rows are invalid; report them as "null" — they are masked
        # out again downstream, so this is safe and keeps the kernel pure.
        return DeviceColumn(T.BOOL, ~c.validity,
                            jnp.ones((n,), dtype=jnp.bool_))

    def sql(self):
        return f"({self.children[0].sql()} IS NULL)"


class IsNotNull(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return T.BOOL

    @property
    def nullable(self):
        return False

    def eval_cpu(self, batch):
        c = self.children[0].eval_cpu(batch)
        if isinstance(c, Scalar):
            return Scalar(T.BOOL, not c.is_null)
        return HostColumn(T.BOOL, c.is_valid().copy(), None)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        n = batch.padded_rows
        c = as_device_column(self.children[0].eval_tpu(batch), n)
        return DeviceColumn(T.BOOL, c.validity,
                            jnp.ones((n,), dtype=jnp.bool_))

    def sql(self):
        return f"({self.children[0].sql()} IS NOT NULL)"


class IsNaN(Expression):
    """Spark isnan: false for NULL input (never null itself)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return T.BOOL

    @property
    def nullable(self):
        return False

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch),
                           batch.num_rows)
        with np.errstate(all="ignore"):
            data = np.isnan(c.data) & c.is_valid()
        return HostColumn(T.BOOL, data, None)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        n = batch.padded_rows
        c = as_device_column(self.children[0].eval_tpu(batch), n)
        return DeviceColumn(T.BOOL, jnp.isnan(c.data) & c.validity,
                            jnp.ones((n,), dtype=jnp.bool_))


class AtLeastNNonNulls(Expression):
    def __init__(self, n: int, exprs: List[Expression]):
        super().__init__(exprs)
        self.n = n

    @property
    def dtype(self):
        return T.BOOL

    @property
    def nullable(self):
        return False

    def eval_cpu(self, batch):
        rows = batch.num_rows
        count = np.zeros(rows, dtype=np.int32)
        for e in self.children:
            c = e.eval_cpu(batch)
            col = as_host_column(c, rows)
            ok = col.is_valid().copy()
            if col.dtype.is_floating:
                ok &= ~np.isnan(np.where(ok, col.data, 0).astype(
                    col.dtype.np_dtype))
            count += ok.astype(np.int32)
        return HostColumn(T.BOOL, count >= self.n, None)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        rows = batch.padded_rows
        count = jnp.zeros((rows,), dtype=jnp.int32)
        for e in self.children:
            col = as_device_column(e.eval_tpu(batch), rows)
            ok = col.validity
            if col.dtype.is_floating:
                ok = ok & ~jnp.isnan(col.data)
            count = count + ok.astype(jnp.int32)
        return DeviceColumn(T.BOOL, count >= self.n,
                            jnp.ones((rows,), dtype=jnp.bool_))


# --------------------------------------------------------------------------
# In / InSet (reference: GpuInSet.scala)
# --------------------------------------------------------------------------
class In(Expression):
    """``value IN (expr1, expr2, ...)`` with non-literal list members
    (reference: GpuOverrides registers both In and InSet,
    GpuOverrides.scala:454-1449; the optimizer turns all-literal lists
    into InSet, so this node carries the general expression form).

    Spark null semantics: TRUE if any member matches; otherwise NULL if
    the value or any member is null; else FALSE."""

    def __init__(self, child: Expression, list_exprs: List[Expression]):
        super().__init__([child] + list(list_exprs))

    @property
    def dtype(self):
        return T.BOOL

    def sql(self):
        items = ", ".join(e.sql() for e in self.children[1:])
        return f"({self.children[0].sql()} IN ({items}))"

    def eval_cpu(self, batch):
        n = batch.num_rows
        c = as_host_column(self.children[0].eval_cpu(batch), n)
        c_valid = c.is_valid()
        acc = np.zeros(n, dtype=np.bool_)
        saw_null = ~c_valid.copy()
        for e in self.children[1:]:
            v = as_host_column(e.eval_cpu(batch), n)
            v_valid = v.is_valid()
            both = c_valid & v_valid
            if c.dtype.is_string:
                eq = np.fromiter(
                    (a == b for a, b in zip(c.data, v.data)),
                    dtype=np.bool_, count=n)
            else:
                # compare in the promoted type (1 IN (1.5) is FALSE):
                # casting the member to the value's dtype would
                # silently truncate floats
                common = np.promote_types(c.dtype.np_dtype,
                                          v.dtype.np_dtype)
                eq = c.data.astype(common) == \
                    np.asarray(v.data).astype(common)
            acc |= both & eq
            saw_null |= ~v_valid
        validity = acc | ~saw_null
        return HostColumn(T.BOOL, acc,
                          None if bool(validity.all()) else validity)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        n = batch.padded_rows
        c = as_device_column(self.children[0].eval_tpu(batch), n)
        acc = jnp.zeros((n,), dtype=jnp.bool_)
        saw_null = ~c.validity
        for e in self.children[1:]:
            v = as_device_column(e.eval_tpu(batch), n)
            both = c.validity & v.validity
            if c.dtype.is_string:
                eq = sk.equals(c.data, c.lengths, v.data, v.lengths)
            else:
                common = np.promote_types(c.dtype.np_dtype,
                                          v.dtype.np_dtype)
                eq = c.data.astype(common) == v.data.astype(common)
            acc = acc | (both & eq)
            saw_null = saw_null | ~v.validity
        return DeviceColumn(T.BOOL, acc, acc | ~saw_null)


class InSet(Expression):
    def __init__(self, child: Expression, values: List):
        super().__init__([child])
        self.values = [v for v in values if v is not None]
        self.has_null_value = any(v is None for v in values)

    @property
    def dtype(self):
        return T.BOOL

    def eval_cpu(self, batch):
        n = batch.num_rows
        c = as_host_column(self.children[0].eval_cpu(batch), n)
        if c.dtype.is_string:
            vs = set(self.values)
            data = np.fromiter(((x in vs) for x in c.data),
                               dtype=np.bool_, count=n)
        else:
            data = np.isin(c.data, np.asarray(self.values,
                                              dtype=c.dtype.np_dtype))
        validity = c.validity
        if self.has_null_value:
            # value IN (..., NULL): False becomes NULL
            miss = ~data
            extra_null = miss
            base = c.is_valid()
            validity = base & ~extra_null
        return HostColumn(T.BOOL, data, validity)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        n = batch.padded_rows
        c = as_device_column(self.children[0].eval_tpu(batch), n)
        if c.dtype.is_string:
            from ..data import strings as dstrings

            acc = jnp.zeros((n,), dtype=jnp.bool_)
            for v in self.values:
                bm, ln = dstrings.encode(np.array([v], object), None)
                bm_b = jnp.broadcast_to(jnp.asarray(bm), (n, bm.shape[1]))
                ln_b = jnp.broadcast_to(jnp.asarray(ln), (n,))
                acc = acc | sk.equals(c.data, c.lengths, bm_b, ln_b)
            data = acc
        else:
            vals = jnp.asarray(np.asarray(self.values,
                                          dtype=c.dtype.np_dtype))
            data = (c.data[:, None] == vals[None, :]).any(axis=1)
        validity = c.validity
        if self.has_null_value:
            validity = validity & data
        return DeviceColumn(T.BOOL, data, validity)
