"""Arrow <-> engine columnar conversion.

SURVEY §7 architecture mapping: "Row<->columnar transitions -> Arrow
interchange at the host boundary".  pyarrow does host-side file decode
(the reference does host-side footer/stripe assembly then device decode
via cudf — on TPU the decode stays on host, the upload is the device
boundary)."""
from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

from .. import types as T
from ..data.column import HostBatch, HostColumn

_ARROW_TO_DTYPE = {
    pa.bool_(): T.BOOL,
    pa.int8(): T.INT8,
    pa.int16(): T.INT16,
    pa.int32(): T.INT32,
    pa.int64(): T.INT64,
    pa.float32(): T.FLOAT32,
    pa.float64(): T.FLOAT64,
    pa.date32(): T.DATE32,
    pa.string(): T.STRING,
    pa.large_string(): T.STRING,
}


def arrow_type_to_dtype(at: pa.DataType) -> T.DType:
    if at in _ARROW_TO_DTYPE:
        return _ARROW_TO_DTYPE[at]
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_decimal(at):
        raise TypeError("decimal not supported (same gate as reference)")
    if pa.types.is_dictionary(at):
        return arrow_type_to_dtype(at.value_type)
    raise TypeError(f"unsupported arrow type {at}")


def dtype_to_arrow(dt: T.DType) -> pa.DataType:
    for at, d in _ARROW_TO_DTYPE.items():
        if d == dt and at != pa.large_string():
            return at
    if dt.id is T.TypeId.TIMESTAMP:
        return pa.timestamp("us", tz="UTC")
    raise TypeError(f"no arrow type for {dt}")


def arrow_schema_to_schema(s: pa.Schema) -> T.Schema:
    return T.Schema([T.Field(f.name, arrow_type_to_dtype(f.type),
                             f.nullable) for f in s])


def schema_to_arrow(s: T.Schema) -> pa.Schema:
    return pa.schema([pa.field(f.name, dtype_to_arrow(f.dtype),
                               f.nullable) for f in s])


def arrow_to_host_batch(tbl, schema: Optional[T.Schema] = None) -> HostBatch:
    if isinstance(tbl, pa.RecordBatch):
        tbl = pa.Table.from_batches([tbl])
    if schema is None:
        schema = arrow_schema_to_schema(tbl.schema)
    cols = []
    for f in schema:
        arr = tbl.column(f.name).combine_chunks()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.chunk(0) if arr.num_chunks else pa.array(
                [], type=dtype_to_arrow(f.dtype))
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        validity = None
        if arr.null_count:
            validity = np.asarray(arr.is_valid())
        if f.dtype.id is T.TypeId.STRING:
            data = np.asarray(arr.to_pylist(), dtype=object)
        elif f.dtype.id is T.TypeId.TIMESTAMP:
            data = arr.cast(pa.timestamp("us")).to_numpy(
                zero_copy_only=False).astype("datetime64[us]").astype(
                np.int64)
        elif f.dtype.id is T.TypeId.DATE32:
            data = arr.to_numpy(zero_copy_only=False).astype(
                "datetime64[D]").astype(np.int32)
        else:
            data = arr.to_numpy(zero_copy_only=False)
            if validity is not None:
                # arrow uses NaN/masked for nulls; re-zero invalid lanes
                data = np.where(validity, data, 0).astype(f.dtype.np_dtype)
            else:
                data = data.astype(f.dtype.np_dtype)
        cols.append(HostColumn(f.dtype, data, validity))
    return HostBatch(schema, cols)


def host_batch_to_arrow(batch: HostBatch) -> pa.Table:
    arrays = []
    for f, c in zip(batch.schema, batch.columns):
        at = dtype_to_arrow(f.dtype)
        mask = None if c.validity is None else ~c.validity
        if f.dtype.id is T.TypeId.STRING:
            vals = [v if (c.validity is None or c.validity[i]) else None
                    for i, v in enumerate(c.data)]
            arrays.append(pa.array(vals, type=at))
        elif f.dtype.id is T.TypeId.TIMESTAMP:
            arrays.append(pa.array(c.data.astype("datetime64[us]"),
                                   type=at, mask=mask))
        elif f.dtype.id is T.TypeId.DATE32:
            arrays.append(pa.array(c.data.astype("datetime64[D]"),
                                   type=at, mask=mask))
        else:
            arrays.append(pa.array(c.data, type=at, mask=mask))
    return pa.Table.from_arrays(arrays, schema=schema_to_arrow(batch.schema))
