"""Metrics export: Prometheus text exposition + JSON snapshots + the
HBM-watermark sampler.

Reference analogue: the reference plugin surfaces SQLMetrics through
the Spark UI/REST API; a standalone engine needs its own scrape
surface.  Output is deterministic (sorted keys) so repeated exports of
the same snapshot are byte-identical — exporter stability is what lets
a scraper diff two snapshots.
"""
from __future__ import annotations

import json
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: metric-name prefix of every exported sample
PROM_PREFIX = "spark_rapids_tpu"


def _sanitize(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _split_metric(key: str) -> Tuple[str, str]:
    """``ExecName.metric`` -> (exec, metric); counter families
    (``retry.numRetries``) and bare keys export with an empty exec."""
    if "." in key:
        head, tail = key.split(".", 1)
        if head and head[0].isupper():
            return head, tail
    return "", key


_TENANT_RE = re.compile(r"^scheduler\.tenant\.([^.]+)\.(.+)$")
_EXCHANGE_RE = re.compile(r"^shuffle\.exchange(\d+)\.(.+)$")


def _metric_labels(key: str) -> Tuple[str, str]:
    """Dimensional metric keys -> (canonical metric name, extra label).

    ``scheduler.tenant.<name>.<counter>`` and
    ``shuffle.exchange<N>.<metric>`` carry a dimension *inside* the
    key; flattening it into the sanitized metric name (the pre-PR-13
    behavior) made per-tenant/per-exchange series impossible to
    aggregate in PromQL.  They now export one canonical name with a
    proper ``tenant=``/``exchange=`` label; every other key returns an
    empty label and renders byte-identically to before.
    """
    m = _TENANT_RE.match(key)
    if m:
        return ("scheduler_tenant_" + _sanitize(m.group(2)),
                f',tenant="{m.group(1)}"')
    m = _EXCHANGE_RE.match(key)
    if m:
        return ("shuffle_exchange_" + _sanitize(m.group(2)),
                f',exchange="{m.group(1)}"')
    return "", ""


def prometheus_text(metrics: Dict[str, int],
                    query_id: Optional[str] = None,
                    hbm_timeline: Optional[List] = None,
                    histograms: Optional[List] = None) -> str:
    """Render a metric snapshot in the Prometheus text exposition
    format (one gauge family, labeled by exec/metric; stable order).

    ``histograms``: optional ``[(family_suffix, labels, hist), ...]``
    triples (``hist`` a :class:`~.histogram.LatencyHistogram`) rendered
    as proper ``# TYPE <family> histogram`` blocks after the gauges —
    the scheduler's queue-wait / per-tenant latency and the streaming
    batch-latency histograms arrive this way."""
    family = f"{PROM_PREFIX}_metric"
    lines = [f"# HELP {family} spark-rapids-tpu query metric snapshot",
             f"# TYPE {family} gauge"]
    qlabel = f',query="{query_id}"' if query_id else ""
    for key in sorted(metrics):
        val = metrics[key]
        if not isinstance(val, (int, float)):
            continue
        name, extra = _metric_labels(key)
        if name:
            lines.append(
                f'{family}{{exec="",name="{name}"{extra}{qlabel}}} {val}')
            continue
        exec_name, metric = _split_metric(key)
        labels = (f'exec="{_sanitize(exec_name)}",'
                  if exec_name else 'exec="",')
        lines.append(
            f"{family}{{{labels}name=\"{_sanitize(metric)}\"{qlabel}}}"
            f" {val}")
    if histograms:
        from .histogram import prometheus_histogram_lines

        grouped: Dict[str, List] = {}
        for suffix, labels, hist in histograms:
            grouped.setdefault(suffix, []).append((labels, hist))
        for suffix in sorted(grouped):
            lines.extend(prometheus_histogram_lines(
                f"{PROM_PREFIX}_{_sanitize(suffix)}", grouped[suffix]))
    if hbm_timeline:
        # column 2 is the DeviceManager's tracked high-watermark — it
        # catches spikes that rise and free BETWEEN samples, which the
        # allocated column (1) misses
        peak = max(t[2] for t in hbm_timeline)
        hbm = f"{PROM_PREFIX}_hbm_watermark_bytes"
        lines.append(f"# HELP {hbm} peak sampled device-arena bytes")
        lines.append(f"# TYPE {hbm} gauge")
        lines.append(f"{hbm}{{{qlabel[1:] if qlabel else ''}}} {peak}"
                     if qlabel else f"{hbm} {peak}")
    return "\n".join(lines) + "\n"


def json_snapshot(metrics: Dict[str, int],
                  query_id: Optional[str] = None,
                  events: Optional[List[Dict]] = None,
                  hbm_timeline: Optional[List] = None) -> str:
    """One JSON document of the same snapshot (stable key order)."""
    doc = {
        "query": query_id,
        "metrics": {k: metrics[k] for k in sorted(metrics)},
    }
    if events is not None:
        from .events import replay_summary

        doc["events"] = replay_summary(events)
    if hbm_timeline is not None:
        doc["hbm_timeline"] = [list(t) for t in hbm_timeline]
    return json.dumps(doc, sort_keys=True, indent=1)


class HbmSampler:
    """Samples the DeviceManager's logical-arena usage on a daemon
    thread every ``telemetry.sampleHbmMs`` ms into a bounded timeline
    of ``(ts, allocated_bytes, peak_bytes)`` — the HBM-watermark trace
    the profile and exporters surface."""

    MAX_SAMPLES = 4096

    def __init__(self, device_manager, interval_ms: int):
        self._dm = device_manager
        self._interval_s = max(1, int(interval_ms)) / 1000.0
        self._samples: List[Tuple[float, int, int]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _sample_once(self) -> None:
        rec = (time.time(), self._dm.allocated_bytes, self._dm.peak_bytes)
        with self._lock:
            if len(self._samples) < self.MAX_SAMPLES:
                self._samples.append(rec)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            self._sample_once()

    def start(self) -> None:
        from . import spans as _spans

        if self._thread is not None:
            return
        self._sample_once()  # t0 sample even for very short queries
        cap = _spans.capture()
        self._thread = threading.Thread(
            target=_spans.bound(cap, self._loop), daemon=True,
            name="hbm-sampler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._sample_once()  # closing sample

    def timeline(self) -> List[Tuple[float, int, int]]:
        with self._lock:
            return list(self._samples)
