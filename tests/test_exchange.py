"""Device shuffle exchange vs CPU oracle (reference analogue:
repart_test.py)."""
import numpy as np

import spark_rapids_tpu as srt
from spark_rapids_tpu import f


def _norm(rows):
    return sorted(rows, key=repr)


def test_grouped_agg_uses_device_exchange():
    sess = srt.Session()
    rng = np.random.RandomState(5)
    data = {"k": rng.randint(0, 20, 500).tolist(),
            "v": rng.rand(500).tolist()}
    df = sess.create_dataframe(data, n_partitions=4)
    q = df.group_by("k").agg(f.sum("v").alias("s"))
    ex = q.explain()
    assert "ShuffleExchangeExec -> will run on TPU" in ex, ex
    cpu = srt.Session(tpu_enabled=False)
    cq = cpu.create_dataframe(data, n_partitions=4) \
        .group_by("k").agg(f.sum("v").alias("s"))
    got, want = _norm(q.collect()), _norm(cq.collect())
    for g, w in zip(got, want):
        assert g[0] == w[0] and abs(g[1] - w[1]) < 1e-9


def test_join_shuffles_on_device():
    sess = srt.Session()
    rng = np.random.RandomState(6)
    l = {"k": rng.randint(0, 30, 400).tolist(),
         "a": list(range(400))}
    r = {"k": rng.randint(0, 30, 300).tolist(),
         "b": list(range(300))}
    ldf = sess.create_dataframe(l, n_partitions=4)
    rdf = sess.create_dataframe(r, n_partitions=3)
    q = ldf.join(rdf, on="k", how="inner")
    ex = q.explain()
    assert "cannot run on TPU" not in ex.replace(
        "LocalScanExec -> cannot run on TPU", ""), ex
    cpu = srt.Session(tpu_enabled=False)
    cq = cpu.create_dataframe(l, n_partitions=4).join(
        cpu.create_dataframe(r, n_partitions=3), on="k", how="inner")
    assert _norm(q.collect()) == _norm(cq.collect())


def test_repartition_round_robin_preserves_rows():
    sess = srt.Session()
    data = {"x": list(range(57))}
    df = sess.create_dataframe(data, n_partitions=2).repartition(5)
    assert sorted(r[0] for r in df.collect()) == list(range(57))


def test_hash_partition_placement_matches_host():
    """Row placement must be bit-identical to the host murmur3 —
    the reference's cudf spark-murmur3 parity property."""
    import jax.numpy as jnp

    from spark_rapids_tpu.data.column import HostBatch, host_to_device
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.utils import hashing

    rng = np.random.RandomState(9)
    schema = T.Schema([T.Field("k", T.INT64)])
    hb = HostBatch.from_pydict(
        {"k": rng.randint(-10**12, 10**12, 257).tolist()}, schema)
    host_ids = hashing.pmod(
        hashing.hash_batch_np([hb.columns[0]]), 8)
    db = host_to_device(hb)
    dev_h = hashing.hash_device_batch([db.columns[0]])
    dev_ids = np.asarray(hashing.pmod(dev_h, 8))[:hb.num_rows]
    np.testing.assert_array_equal(host_ids, dev_ids)


def test_per_shuffle_cleanup_on_abandoned_reader():
    """limit(1) over a shuffled join abandons the exchange readers
    early; query-end per-shuffle cleanup must still free every shuffle
    buffer (reference: ShuffleBufferCatalog per-shuffle cleanup +
    RapidsShuffleInternalManager.scala:230-250 unregister)."""
    import gc

    import spark_rapids_tpu as srt
    from spark_rapids_tpu.memory.spill import SpillFramework

    sess = srt.Session(
        {"spark.rapids.tpu.sql.broadcastSizeThreshold": 0})
    fw = SpillFramework.get()
    base_ids = set(fw.catalog.ids())
    l = sess.create_dataframe(
        {"k": list(range(300)), "v": list(range(300))})
    r = sess.create_dataframe(
        {"rk": list(range(300)), "w": list(range(300))})
    rows = l.join(r, on=(["k"], ["rk"]), how="inner").limit(1).collect()
    assert len(rows) == 1
    # the query-end unregister ran (not just the GC backstop)
    assert sess.shuffle_catalog.active_shuffles() == []
    gc.collect()
    leftover = set(fw.catalog.ids()) - base_ids
    assert not leftover, f"orphaned spill buffers: {leftover}"
