"""The rule catalog.

Importing this package registers every rule with the engine (see
``engine.all_rules``).  Rule ids, scopes and semantics are documented
in ``docs/static_analysis.md``; each module groups the rules of one
invariant family.
"""
from . import (  # noqa: F401  (imported for registration side effect)
    cache_rules,
    cancellation,
    compile_path,
    drift,
    durability,
    host_sync,
    imports_rule,
    locks,
    resources,
    telemetry_rules,
)
