"""QueryScheduler — bounded admission, dispatch, deadlines and
per-query failure isolation for concurrent queries.

Reference analogue: the admission/memory-arbitration layer Theseus-
style accelerator engines put in front of scarce device memory (see
PAPERS.md) — here built on the existing DeviceManager budget, retry
framework, degradation ladder and telemetry events.

Model:

* ``Session.submit(plan)`` -> :class:`QueryHandle` — at most
  ``scheduler.maxConcurrent`` queries run concurrently (one daemon
  worker thread each), at most ``scheduler.maxQueued`` wait in the
  bounded priority queue; a submit past the bound — or a queued query
  not dispatched within ``scheduler.queueTimeoutMs`` — is shed with
  :class:`QueryRejected` plus an ``admission_reject`` event.
* Each dispatched query holds an HBM *reservation* of
  ``scheduler.reservationFraction`` x the DeviceManager arena for its
  lifetime (``DeviceManager.try_reserve``): dispatch waits until the
  reservation fits, so the sum of running reservations never exceeds
  the arena.  When nothing is running the head query dispatches even
  if its reservation cannot be charged — forward progress is never
  reservation-deadlocked.
* Cancellation is cooperative: ``handle.cancel()`` (or the
  ``scheduler.queryTimeoutMs`` deadline, or an injected ``cancel``
  fault) trips the query's :class:`~.cancel.CancelToken`; every
  operator checkpoint polls it, and the worker unwinds — semaphore
  permits released, upload caches dropped, shuffle slots freed by the
  normal query-end path, a terminal ``query_cancelled`` event emitted.
* Per-query failure isolation: scheduled queries run with PRIVATE
  fault/OOM injectors (thread-local, see ``ExecContext``), and a query
  that exhausts its retry/ladder budget trips a per-query circuit
  breaker onto the CPU-exec plan — without disarming the process-wide
  injector slots or writing the global fault counters, so concurrent
  queries stay on the TPU path unpoisoned.
"""
from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
import weakref
from typing import Dict, List, Optional

from .cancel import CancelToken, TpuQueryCancelled

log = logging.getLogger(__name__)

#: all live schedulers in the process — the test harness shuts them
#: down between tests (conftest) so no scheduler thread outlives its
#: test
_LIVE: "weakref.WeakSet[QueryScheduler]" = weakref.WeakSet()


def shutdown_all() -> None:
    """Shut down every live scheduler (test-harness hook)."""
    for sched in list(_LIVE):
        try:
            sched.shutdown()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass


class QueryRejected(RuntimeError):
    """The scheduler shed this query (queue full or queue timeout)."""


class QueryStatus:
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    FAILED = "failed"
    CANCELLED = "cancelled"
    REJECTED = "rejected"


class QueryHandle:
    """Caller-side handle of one submitted query."""

    def __init__(self, scheduler: "QueryScheduler", query_id: int,
                 plan, priority: int):
        self._scheduler = scheduler
        self.query_id = query_id
        self.plan = plan
        self.priority = priority
        self.token = CancelToken(query_id)
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._status = QueryStatus.QUEUED
        self._result = None
        self._error: Optional[BaseException] = None
        self._queued_at = time.monotonic()
        #: per-query attribution (the session's last_metrics /
        #: last_profile are last-writer-wins under concurrency)
        self.metrics: Dict = {}
        self.profile = None
        #: "tpu" or "cpu" — which path produced the result (the
        #: circuit-breaker rung)
        self.exec_path: Optional[str] = None
        self._ctx = None  # the native attempt's ExecContext

    # ----- caller API ------------------------------------------------------
    def result(self, timeout: Optional[float] = None):
        """Block for the result; raises the query's terminal error
        (``TpuQueryCancelled`` / ``QueryRejected`` / the failure)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} not done after {timeout}s "
                f"(status={self.status()})")
        if self._error is not None:
            raise self._error
        return self._result

    def cancel(self, reason: str = "cancelled by caller") -> bool:
        """Trip the query's cancel token; a queued query is removed
        immediately, a running one unwinds at its next checkpoint.
        Returns True on the first effective cancel."""
        first = self.token.cancel(reason)
        self._scheduler._on_cancel(self, reason)
        return first

    def status(self) -> str:
        with self._lock:
            return self._status

    def done(self) -> bool:
        return self._done.is_set()

    def events(self) -> List[Dict]:
        """This query's telemetry event ring (empty when telemetry was
        disabled)."""
        tele = getattr(self._ctx, "telemetry", None)
        if tele is None or tele.events is None:
            return []
        return tele.events.snapshot()

    # ----- scheduler-side transitions --------------------------------------
    def _mark_running(self) -> None:
        with self._lock:
            if not self._done.is_set():
                self._status = QueryStatus.RUNNING

    def _finish(self, status: str, result=None,
                error: Optional[BaseException] = None) -> bool:
        with self._lock:
            if self._done.is_set():
                return False
            self._status = status
            self._result = result
            self._error = error
            self._done.set()
            return True


class QueryScheduler:
    """One per Session (created lazily by ``Session.submit``); owns a
    dispatcher thread plus one daemon worker thread per running
    query."""

    def __init__(self, session):
        from ..config import (FAULT_DEGRADE_ENABLED,
                              SCHEDULER_MAX_CONCURRENT,
                              SCHEDULER_MAX_QUEUED,
                              SCHEDULER_QUERY_TIMEOUT_MS,
                              SCHEDULER_QUEUE_TIMEOUT_MS,
                              SCHEDULER_RESERVATION_FRACTION)
        from ..telemetry import spans as tspans

        self.session = session
        conf = session.conf
        self.max_concurrent = max(1, conf.get(SCHEDULER_MAX_CONCURRENT))
        self.max_queued = max(0, conf.get(SCHEDULER_MAX_QUEUED))
        self.queue_timeout_ms = conf.get(SCHEDULER_QUEUE_TIMEOUT_MS)
        self.query_timeout_ms = conf.get(SCHEDULER_QUERY_TIMEOUT_MS)
        self._dm = session.device_manager
        frac = conf.get(SCHEDULER_RESERVATION_FRACTION)
        self.reservation_bytes = 0
        if self._dm is not None and frac > 0:
            self.reservation_bytes = min(
                int(frac * self._dm.arena_bytes), self._dm.arena_bytes)
        self._degrade_enabled = (self._dm is not None
                                 and conf.get(FAULT_DEGRADE_ENABLED))
        self._cv = threading.Condition()
        self._heap: List = []  # (-priority, seq, handle)
        self._seq = itertools.count()
        self._next_qid = itertools.count(1)
        self._n_active = 0
        self._running: set = set()  # running QueryHandles
        #: worker-thread ident -> [currently held reservation bytes];
        #: the mutable cell lets AQE shrink a running query's charge
        #: (rebase_reservation) while the worker's finally still
        #: releases exactly what remains held
        self._reservations: Dict[int, List[int]] = {}
        self._workers: set = set()  # live worker threads
        self._shutdown = False
        _LIVE.add(self)
        # the dispatcher inherits the creator's (usually empty)
        # execution binding via the telemetry capture() discipline
        self._dispatcher = threading.Thread(
            target=tspans.bound(tspans.capture(), self._dispatch_loop),
            daemon=True, name="query-scheduler")
        self._dispatcher.start()

    # ----- submission ------------------------------------------------------
    def submit(self, plan, priority: int = 0) -> QueryHandle:
        from ..telemetry.events import emit_event

        with self._cv:
            if self._shutdown:
                raise RuntimeError("QueryScheduler is shut down")
            if len(self._heap) >= self.max_queued \
                    and self._n_active >= self.max_concurrent:
                queued, running = len(self._heap), self._n_active
                emit_event("admission_reject", source="scheduler",
                           reason="queue_full", queued=queued,
                           running=running,
                           max_queued=self.max_queued,
                           max_concurrent=self.max_concurrent)
                raise QueryRejected(
                    f"scheduler queue full ({running} running / "
                    f"{queued} queued; maxConcurrent="
                    f"{self.max_concurrent}, maxQueued="
                    f"{self.max_queued})")
            handle = QueryHandle(self, next(self._next_qid), plan,
                                 priority)
            heapq.heappush(self._heap,
                           (-priority, next(self._seq), handle))
            self._cv.notify_all()
        return handle

    # ----- caller-side cancel hook -----------------------------------------
    def _on_cancel(self, handle: QueryHandle, reason: str) -> None:
        """Remove a still-queued handle immediately; a running one
        unwinds cooperatively at its next checkpoint."""
        with self._cv:
            before = len(self._heap)
            self._heap = [e for e in self._heap if e[2] is not handle]
            removed = len(self._heap) != before
            if removed:
                heapq.heapify(self._heap)
                self._cv.notify_all()
        if removed:
            handle._finish(QueryStatus.CANCELLED,
                           error=TpuQueryCancelled(reason))

    # ----- dispatcher ------------------------------------------------------
    def _dispatch_loop(self) -> None:
        from ..telemetry import spans as tspans

        while True:
            with self._cv:
                handle = reservation = None
                while handle is None:
                    if self._shutdown:
                        return
                    self._shed_expired_locked(time.monotonic())
                    if self._heap \
                            and self._n_active < self.max_concurrent:
                        entry = heapq.heappop(self._heap)
                        cand = entry[2]
                        if cand._done.is_set():
                            continue  # cancelled while queued
                        reservation = self.reservation_bytes
                        if reservation and not self._dm.try_reserve(
                                reservation):
                            if self._n_active == 0:
                                # forward-progress guarantee: an empty
                                # machine always runs the head query
                                reservation = 0
                            else:
                                heapq.heappush(self._heap, entry)
                                self._cv.wait(timeout=0.05)
                                continue
                        handle = cand
                        continue
                    self._cv.wait(timeout=self._wait_timeout_locked())
                self._n_active += 1
                self._running.add(handle)
                handle._mark_running()
                worker = threading.Thread(
                    target=tspans.bound(tspans.capture(),
                                        self._worker_main),
                    args=(handle, reservation), daemon=True,
                    name=f"query-worker-{handle.query_id}")
                self._workers.add(worker)
            worker.start()
            # drop the frame locals before sleeping on the condition:
            # a dispatcher idling between queries must not pin the last
            # handle (and through it the query's result/context) after
            # every caller reference is gone
            del worker, handle, cand, entry

    def _wait_timeout_locked(self) -> Optional[float]:
        """How long the dispatcher may sleep: until the earliest
        queued entry would exceed its queue timeout (None = until
        notified)."""
        if self.queue_timeout_ms <= 0 or not self._heap:
            return None
        now = time.monotonic()
        horizon = self.queue_timeout_ms / 1000.0
        earliest = min(e[2]._queued_at for e in self._heap)
        return max(0.01, earliest + horizon - now)

    def _shed_expired_locked(self, now: float) -> None:
        if not self._heap:
            return
        horizon = (self.queue_timeout_ms / 1000.0
                   if self.queue_timeout_ms > 0 else None)
        keep = []
        shed = []
        for entry in self._heap:
            h = entry[2]
            if h._done.is_set():
                continue  # cancelled while queued, already finished
            if horizon is not None and now - h._queued_at >= horizon:
                shed.append(h)
            else:
                keep.append(entry)
        if len(keep) != len(self._heap):
            self._heap = keep
            heapq.heapify(self._heap)
        for h in shed:
            self._reject_queued(h, "queue_timeout")

    def _reject_queued(self, handle: QueryHandle, why: str) -> None:
        from ..telemetry.events import emit_event

        emit_event("admission_reject", source="scheduler", reason=why,
                   query_id=handle.query_id,
                   queue_timeout_ms=self.queue_timeout_ms)
        log.warning("query %d shed from the scheduler queue (%s)",
                    handle.query_id, why)
        handle._finish(QueryStatus.REJECTED, error=QueryRejected(
            f"query {handle.query_id} shed: {why} (queueTimeoutMs="
            f"{self.queue_timeout_ms})"))

    # ----- worker ----------------------------------------------------------
    def _worker_main(self, handle: QueryHandle,
                     reservation: int) -> None:
        from ..fault.errors import TpuFaultError
        from ..fault.injector import bind_scoped_fault_injector
        from ..memory.retry import bind_scoped_injector
        from ..telemetry import spans as tspans
        from . import cancel as _cancel

        token = handle.token
        if self.query_timeout_ms and self.query_timeout_ms > 0:
            token.deadline = (time.monotonic()
                              + self.query_timeout_ms / 1000.0)
        _cancel.activate(token)
        holder = [reservation]
        with self._cv:
            self._reservations[threading.get_ident()] = holder
        sink: Dict = {}
        try:
            try:
                out = self.session._execute_native(
                    handle.plan, scheduled=True, cancel_token=token,
                    ctx_sink=sink)
                handle.exec_path = "tpu"
                self._attribute(handle, sink)
                handle._finish(QueryStatus.FINISHED, result=out)
            except TpuQueryCancelled as e:
                self._unwind_cancelled(handle, sink, e)
            except TpuFaultError as e:
                if not self._degrade_enabled:
                    self._attribute(handle, sink)
                    handle._finish(QueryStatus.FAILED, error=e)
                else:
                    try:
                        self._run_cpu_fallback(handle, e, sink)
                    except TpuQueryCancelled as e2:
                        self._unwind_cancelled(handle, sink, e2)
        except BaseException as e:  # noqa: BLE001 — worker must not die silent
            self._attribute(handle, sink)
            handle._finish(QueryStatus.FAILED, error=e)
        finally:
            # the worker thread dies with the query, but unbinding
            # keeps the thread-local discipline explicit
            _cancel.deactivate()
            bind_scoped_injector(None)
            bind_scoped_fault_injector(None)
            tspans.deactivate()
            if self._dm is not None:
                # any device hold still on this thread dies with it —
                # the semaphore can never get a dead thread's permit
                # back, so the worker's last act is to drop its own
                self._dm.semaphore.release_task()
            with self._cv:
                held = holder[0]
                holder[0] = 0
                self._reservations.pop(threading.get_ident(), None)
            if held and self._dm is not None:
                self._dm.release_reservation(held)
            with self._cv:
                self._n_active -= 1
                self._running.discard(handle)
                self._workers.discard(threading.current_thread())
                self._cv.notify_all()

    # ----- adaptive reservation rebase --------------------------------------
    def rebase_reservation(self, observed_bytes: int) -> int:
        """SHRINK the calling worker thread's HBM reservation to
        ``observed_bytes`` (never grows — growing mid-flight could
        over-commit the arena) and wake the dispatcher so a queued
        query can use the freed headroom.  Called by the adaptive
        executor once real stage-output sizes replace the admission
        estimate.  Returns the bytes freed (0 when not a worker
        thread, or nothing to free)."""
        if self._dm is None:
            return 0
        target = max(0, int(observed_bytes))
        with self._cv:
            holder = self._reservations.get(threading.get_ident())
            if holder is None or holder[0] <= target:
                return 0
            freed = holder[0] - target
            holder[0] = target
        self._dm.release_reservation(freed)
        with self._cv:
            self._cv.notify_all()
        return freed

    def _attribute(self, handle: QueryHandle, sink: Dict) -> None:
        """Per-query metric/profile attribution from the attempt's own
        ExecContext (stowed by ``Session._finalize_metrics``)."""
        ctx = sink.get("ctx")
        if ctx is None:
            return
        handle._ctx = ctx
        handle.metrics = dict(getattr(ctx, "final_metrics", None)
                              or ctx.metrics.snapshot())
        handle.profile = getattr(ctx, "profile", None)

    def _unwind_cancelled(self, handle: QueryHandle, sink: Dict,
                          exc: TpuQueryCancelled) -> None:
        """Terminal cancellation unwind.  The normal query-end path
        (``_execute_native``'s finally) already finalized metrics,
        released the plan's exec lock and freed this query's shuffle
        slots; what remains query-scoped is the worker's own semaphore
        permits and the plan's cached uploads."""
        from ..telemetry.events import emit_event

        # the query's telemetry binding is still on this thread, so
        # the terminal event lands in ITS event ring
        emit_event("query_cancelled", query_id=handle.query_id,
                   reason=str(exc))
        if self._dm is not None:
            try:
                self._dm.semaphore.release_task()
            except Exception:  # noqa: BLE001 — unwind must not raise
                pass
        phys = sink.get("phys")
        if phys is not None:
            self._drop_upload_caches(phys)
        self._attribute(handle, sink)
        log.warning("query %d cancelled: %s", handle.query_id, exc)
        # drop the traceback/context chain before stowing the error on
        # the handle: cancellation is cooperative (the frames carry no
        # diagnosis) and their locals would pin device batches past the
        # zero-leak unwind contract
        exc.__cause__ = None
        exc.__context__ = None
        handle._finish(QueryStatus.CANCELLED,
                       error=exc.with_traceback(None))

    def _drop_upload_caches(self, phys) -> None:
        """Walk the physical tree dropping cached uploads — the one
        device artifact designed to outlive its query must not outlive
        a CANCELLED query (zero-leak unwind contract)."""
        seen = set()
        stack = [phys]
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            drop = getattr(node, "drop_cached_uploads", None)
            if drop is not None:
                try:
                    drop()
                except Exception:  # noqa: BLE001 — unwind must not raise
                    pass
            stack.extend(getattr(node, "children", ()) or ())

    def _run_cpu_fallback(self, handle: QueryHandle, cause,
                          sink: Dict) -> None:
        """Per-query circuit breaker: re-execute THIS query on the
        CPU-exec plan.  Unlike the direct-execute ladder rung this
        must NOT disarm the process-wide injectors or write the global
        fault counters — concurrent queries keep their TPU path and
        their own failure budgets."""
        from ..fault.stats import DEGRADE_CPU
        from ..plan.overrides import cpu_exec_plan
        from ..plan.physical import ExecContext, collect_batches
        from ..telemetry.events import emit_event

        # Same zero-leak discipline as the cancellation unwind: the
        # failed attempt's frames (held by cause.__traceback__ and its
        # context chain) pin the attempt's exec tree — and with it any
        # upload cache the attempt already published — so strip them
        # BEFORE the cause reaches a log record that may retain it,
        # and drop the dead attempt's caches deterministically.
        cause.__cause__ = None
        cause.__context__ = None
        cause = cause.with_traceback(None)
        failed_phys = sink.get("phys")
        if failed_phys is not None:
            self._drop_upload_caches(failed_phys)

        emit_event("degrade", level=DEGRADE_CPU, rung="cpu",
                   cause=type(cause).__name__, scheduled=True,
                   query_id=handle.query_id)
        log.warning(
            "scheduled query %d exhausted fault recovery (%s: %s) — "
            "circuit breaker tripped to the CPU-exec plan",
            handle.query_id, type(cause).__name__, cause)
        self._attribute(handle, sink)  # failed attempt's counters
        prior = {k: v for k, v in (handle.metrics or {}).items()
                 if k.startswith(("fault.", "retry."))}
        sess = self.session
        phys = cpu_exec_plan(sess.conf, handle.plan)
        # session=None: a bare host context — no telemetry re-begin,
        # no injector (re)install, no global stats writes
        ctx = ExecContext(sess.conf, None)
        data = phys.execute(ctx)
        schema = phys.schema if len(phys.schema) else handle.plan.schema
        out = collect_batches(data, schema, ctx)
        merged = dict(ctx.metrics.snapshot())
        merged.update(prior)
        merged["fault.degradeLevel"] = DEGRADE_CPU
        handle.metrics = merged
        handle.exec_path = "cpu"
        handle._finish(QueryStatus.FINISHED, result=out)

    # ----- lifecycle -------------------------------------------------------
    def shutdown(self, timeout: float = 10.0) -> None:
        """Cancel queued + running queries, stop the dispatcher, and
        join every scheduler thread."""
        with self._cv:
            already = self._shutdown
            self._shutdown = True
            queued = [e[2] for e in self._heap]
            self._heap = []
            running = list(self._running)
            workers = list(self._workers)
            self._cv.notify_all()
        for h in queued:
            h.token.cancel("scheduler shutdown")
            h._finish(QueryStatus.CANCELLED,
                      error=TpuQueryCancelled("scheduler shutdown"))
        for h in running:
            h.token.cancel("scheduler shutdown")
        if not already:
            self._dispatcher.join(timeout)
        for t in workers:
            t.join(timeout)
        if not already:
            # end-of-life storage hygiene (shared with Session.close):
            # orphaned spill files + expired/over-cap checkpoint dirs
            try:
                self.session.sweep_storage()
            except Exception:  # noqa: BLE001 — shutdown must not raise
                log.warning("shutdown storage sweep failed",
                            exc_info=True)

    @property
    def active_count(self) -> int:
        return self._n_active

    @property
    def queued_count(self) -> int:
        with self._cv:
            return len(self._heap)
