"""Cooperative cancellation primitives.

A :class:`CancelToken` is created per submitted query by the
:class:`~spark_rapids_tpu.scheduler.query_scheduler.QueryScheduler` and
threaded through ``ExecContext``.  It is *cooperative*: nothing is ever
killed; instead every operator checkpoint the OOM/fault injectors
already reach (``maybe_inject_oom`` / ``maybe_inject_fault``) first
polls :func:`check_cancel`, so a cancelled or past-deadline query
unwinds at the next allocation, upload, drain or stage boundary with an
ordinary exception — :class:`TpuQueryCancelled` — that the retry
machinery deliberately does **not** retry and the degradation ladder
deliberately does **not** degrade.

The token binding is thread-local (like the telemetry binding) and is
propagated to worker threads through the extended
``telemetry.spans.capture()`` tuple, so every existing pool / watchdog /
prefetch spawn site carries it for free.

Design note: cancellation is suppressed while the current thread is
inside a retry *shield* (``fault.injector._shield_depth() > 0``) — the
recovery machinery (suspend/spill/resume) must never be unwound halfway
or permits and spill registrations would leak; the poll fires again at
the next checkpoint outside the shield.
"""
from __future__ import annotations

import threading
import time
from typing import Optional


class TpuQueryCancelled(Exception):
    """Raised at a cooperative checkpoint once the query's token is
    cancelled (explicitly, by deadline, or by the ``cancel`` fault
    type).

    Deliberately **not** a ``TpuFaultError``: the fault-tolerance
    ladder catches ``TpuFaultError`` to degrade a query to a lower
    rung, but a cancelled query must terminate, not degrade.
    """

    def __init__(self, reason: str = "query cancelled"):
        super().__init__(reason)
        self.reason = reason


class CancelToken:
    """Shared cancellation flag + optional monotonic deadline."""

    def __init__(self, query_id: int = 0,
                 deadline: Optional[float] = None):
        self.query_id = query_id
        #: absolute ``time.monotonic()`` deadline, or None
        self.deadline = deadline
        self._lock = threading.Lock()
        self._cancelled = threading.Event()
        self._reason: Optional[str] = None

    # ----- state -----------------------------------------------------------
    def cancel(self, reason: str = "query cancelled") -> bool:
        """Mark the token cancelled; returns True on the first call."""
        with self._lock:
            if self._cancelled.is_set():
                return False
            self._reason = reason
            self._cancelled.set()
            return True

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    def expired(self) -> bool:
        return (self.deadline is not None
                and time.monotonic() >= self.deadline)

    # ----- cooperative checkpoint -----------------------------------------
    def check(self, site: str = "") -> None:
        """Raise :class:`TpuQueryCancelled` if cancelled or past the
        deadline.  A deadline trip cancels the token first so every
        sibling task thread of the query stops at its own next
        checkpoint."""
        if not self._cancelled.is_set():
            if self.deadline is None or time.monotonic() < self.deadline:
                return
            self.cancel("query deadline exceeded")
        reason = self._reason or "query cancelled"
        if site:
            raise TpuQueryCancelled(f"{reason} (at {site})")
        raise TpuQueryCancelled(reason)


# ---------------------------------------------------------------------------
# thread-local binding (mirrors telemetry.spans activate/deactivate)
# ---------------------------------------------------------------------------
_tl = threading.local()


def activate(token: Optional[CancelToken]) -> None:
    """Bind *token* to the current thread (None unbinds)."""
    _tl.token = token


def deactivate() -> None:
    _tl.token = None


def current() -> Optional[CancelToken]:
    return getattr(_tl, "token", None)


class activated:
    """Scope that binds *token* to the current thread and restores the
    previous binding on exit — for worker threads (e.g. speculative
    drain attempts) that need a private token without clobbering the
    query token bound by their spawner."""

    def __init__(self, token: Optional[CancelToken]):
        self._token = token
        self._prev: Optional[CancelToken] = None

    def __enter__(self):
        self._prev = current()
        activate(self._token)
        return self._token

    def __exit__(self, *exc):
        activate(self._prev)
        return False


def check_cancel(site: str = "") -> None:
    """Poll the current thread's cancel token; no-op when unbound.

    Called first thing by ``memory.retry.maybe_inject_oom`` and
    ``fault.injector.maybe_inject_fault`` — i.e. at every operator
    checkpoint — plus explicitly in the runner's stage loop and the
    transition prefetch loops.  Suppressed inside a retry shield (see
    module docstring)."""
    token = getattr(_tl, "token", None)
    if token is None:
        return
    if not token.cancelled() and not token.expired():
        return
    # Lazy import: fault.injector imports this module at top level.
    from ..fault.injector import _shield_depth

    if _shield_depth() > 0:
        return
    token.check(site)
