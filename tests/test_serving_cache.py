"""Sub-second serving caches (spark_rapids_tpu/serving/ — ISSUE 19).

The serving subsystem's standing promise: a cached answer is
BIT-IDENTICAL to a cold recompute or it is not served at all.

* plan-template cache — re-planning an already-seen query shape reuses
  the cached optimized physical tree, and the result stays identical
  to the cold plan on real TPC-H shapes, including under the
  corrupt/OOM/stage-crash injection suite;
* result cache — a repeated ``submit()`` of the same query over
  unchanged inputs is served from disk (``exec_path == "cache"``);
  appending or rewriting a source file makes the entry unreachable
  (fresh stat pass -> new query fingerprint) and sweeps the stale
  sibling — never a stale answer;
* eviction — the on-disk byte budget holds via LRU eviction;
* attribution — concurrent mixed-tenant submits count their hits on
  the right tenant (``scheduler.tenant.<t>.cacheHits``);
* fingerprints — recovery and serving derive identity from the SAME
  helper (``recovery.manager.plan_fingerprints``) and can never drift;
* streaming — a maintained stream registers each committed cumulative
  result, so an ad-hoc ``submit()`` of the same query between ticks is
  a cache hit, and the ledger commit invalidates entries whose source
  files were rewritten.
"""
import os
import threading

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.benchmarks import tpch, tpch_datagen

FAST = {
    "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
    "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
    "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
}


def _conf(tmp_path, **extra):
    conf = dict(FAST)
    conf.update({
        "spark.rapids.tpu.serving.cache.enabled": True,
        "spark.rapids.tpu.serving.cache.dir": str(tmp_path / "serving"),
        "spark.rapids.tpu.recovery.dir": str(tmp_path / "rec"),
        "spark.rapids.tpu.telemetry.enabled": True,
    })
    conf.update(extra)
    return conf


def _norm(rows):
    return sorted(
        (tuple((None if v is None else
                (round(v, 9) if isinstance(v, float) else v))
               for v in r) for r in rows),
        key=repr)


def _batch_rows(hb):
    return _norm(zip(*[c.to_pylist() for c in hb.columns]))


def _write_part(data_dir, name, a_vals, b_vals):
    os.makedirs(data_dir, exist_ok=True)
    pq.write_table(
        pa.table({"a": pa.array(a_vals, type=pa.int64()),
                  "b": pa.array(b_vals, type=pa.float64())}),
        os.path.join(data_dir, name))


def _serving_metric(sess, name):
    return sess.export_metrics().get(name, 0)


# ==========================================================================
# Plan-template cache: bit-identity to the cold plan on TPC-H shapes
# ==========================================================================
@pytest.mark.parametrize("qnum", [1, 3, 5, 6])
def test_template_cache_hit_bit_identical_tpch(qnum, tmp_path):
    """Rebuilding the same TPC-H query from scratch normalizes to the
    cached template — planning is skipped and the answer is identical
    to the cold plan's."""
    sess = srt.Session(_conf(tmp_path))
    try:
        tables = tpch_datagen.dataframes(sess, sf=0.001)
        cold = _norm(tpch.QUERIES[qnum](tables).collect())
        hits0 = _serving_metric(sess, "serving.template.hits")
        # a brand-new logical tree of the same shape: the per-plan
        # cache cannot help, only the template cache can
        warm = _norm(tpch.QUERIES[qnum](tables).collect())
        assert warm == cold
        assert _serving_metric(sess, "serving.template.hits") > hits0
    finally:
        sess.close()


@pytest.mark.fault_injection
@pytest.mark.parametrize("fault", ["corrupt", "oom", "stage_crash"])
def test_cached_results_bit_identical_under_injection(fault, tmp_path):
    """Under each injection mode: the first submit survives the fault
    (retries / checkpoint recovery), its STORED result is the correct
    one, and the replay is served from cache bit-identical to a clean
    oracle."""
    site = "exchange.read" if fault == "stage_crash" else "exchange.write"
    oracle_sess = srt.Session(dict(FAST))
    oracle = _norm(tpch.QUERIES[3](
        tpch_datagen.dataframes(oracle_sess, sf=0.001)).collect())
    oracle_sess.close()

    sess = srt.Session(_conf(tmp_path, **{
        "spark.rapids.tpu.recovery.enabled": True,
        "spark.rapids.tpu.sql.taskRetries": 3,
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": fault,
        "spark.rapids.tpu.fault.injection.site": site,
        "spark.rapids.tpu.fault.injection.skipCount": 1,
    }))
    try:
        tables = tpch_datagen.dataframes(sess, sf=0.001)
        h1 = sess.submit(tpch.QUERIES[3](tables))
        out1 = h1.result(timeout=120)
        assert _batch_rows(out1) == oracle
        h2 = sess.submit(tpch.QUERIES[3](tables))
        out2 = h2.result(timeout=120)
        assert h2.exec_path == "cache", h2.exec_path
        assert _batch_rows(out2) == oracle
    finally:
        sess.close()


# ==========================================================================
# Result cache: invalidation on source-file append and rewrite
# ==========================================================================
def test_result_cache_never_stale_after_append_or_rewrite(tmp_path):
    data = tmp_path / "data"
    _write_part(data, "part-0.parquet", [1, 2, 3, 4], [1.0, 2.0, 3.0, 4.0])
    sess = srt.Session(_conf(tmp_path))
    try:
        def q():
            df = sess.read_parquet(str(data))
            return df.filter(srt.f.col("a") < 100).group_by().agg(
                srt.f.sum("b").alias("s"))

        h1 = sess.submit(q())
        assert _batch_rows(h1.result(timeout=60)) == [(10.0,)]
        assert h1.exec_path != "cache"
        h2 = sess.submit(q())
        assert h2.exec_path == "cache"
        assert _batch_rows(h2.result(timeout=60)) == [(10.0,)]

        # append: fresh stat pass -> new query_fp -> the old entry is
        # unreachable; the correct new answer is computed and stored
        _write_part(data, "part-1.parquet", [5], [5.0])
        h3 = sess.submit(q())
        assert h3.exec_path != "cache"
        assert _batch_rows(h3.result(timeout=60)) == [(15.0,)]
        h4 = sess.submit(q())
        assert h4.exec_path == "cache"
        assert _batch_rows(h4.result(timeout=60)) == [(15.0,)]

        # rewrite in place: same file COUNT (same plan_fp), different
        # content — the fresh stat pass proves the sibling entry stale
        # and sweeps it on sight, and the answer is never the old one
        _write_part(data, "part-0.parquet", [1], [1.0])
        h5 = sess.submit(q())
        assert h5.exec_path != "cache"
        assert _batch_rows(h5.result(timeout=60)) == [(6.0,)]
        assert _serving_metric(sess, "serving.result.invalidated") >= 1
    finally:
        sess.close()


# ==========================================================================
# Eviction under the byte budget
# ==========================================================================
def test_result_cache_eviction_under_byte_budget(tmp_path):
    data = tmp_path / "data"
    _write_part(data, "part-0.parquet", list(range(20)),
                [float(i) for i in range(20)])
    sess = srt.Session(_conf(tmp_path, **{
        "spark.rapids.tpu.serving.cache.results.maxBytes": 2500,
    }))
    try:
        def q(n):
            df = sess.read_parquet(str(data))
            return df.filter(srt.f.col("a") < n).group_by().agg(
                srt.f.sum("b").alias("s"))

        for n in (5, 6, 7, 8, 9, 10):
            sess.submit(q(n)).result(timeout=60)
        m = sess.export_metrics()
        assert m["serving.result.stores"] >= 4
        assert m["serving.result.evicted"] >= 1
        # the on-disk footprint respects the budget
        total = 0
        root = str(tmp_path / "serving")
        for dirpath, _dirs, files in os.walk(root):
            total += sum(os.path.getsize(os.path.join(dirpath, f))
                         for f in files)
        assert total <= 2500, total
        # the most recent entry survived and still hits
        h = sess.submit(q(10))
        assert h.exec_path == "cache"
        assert _batch_rows(h.result(timeout=60)) == [(45.0,)]
    finally:
        sess.close()


# ==========================================================================
# Concurrent mixed-tenant submits: per-tenant hit attribution
# ==========================================================================
def test_concurrent_mixed_tenant_hits_attributed(tmp_path):
    data = tmp_path / "data"
    _write_part(data, "part-0.parquet", [1, 2, 3], [1.0, 2.0, 3.0])
    sess = srt.Session(_conf(tmp_path, **{
        "spark.rapids.tpu.scheduler.tenant.gold.weight": 4.0,
        "spark.rapids.tpu.scheduler.tenant.bronze.weight": 1.0,
    }))
    try:
        def q():
            df = sess.read_parquet(str(data))
            return df.group_by().agg(srt.f.sum("b").alias("s"))

        sess.submit(q()).result(timeout=60)  # prime

        per_tenant = {"gold": 7, "bronze": 3}
        results = {t: [] for t in per_tenant}
        errors = []

        def drive(tenant, n):
            try:
                for _ in range(n):
                    h = sess.submit(q(), tenant=tenant)
                    results[tenant].append(
                        (h.exec_path, _batch_rows(h.result(timeout=60))))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=drive, args=(t, n))
                   for t, n in per_tenant.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        for tenant, n in per_tenant.items():
            assert len(results[tenant]) == n
            assert all(rows == [(6.0,)] for _p, rows in results[tenant])
            assert all(p == "cache" for p, _r in results[tenant])
        qos = sess.scheduler.qos_metrics()
        for tenant, n in per_tenant.items():
            assert qos[f"scheduler.tenant.{tenant}.cacheHits"] == n
    finally:
        sess.close()


# ==========================================================================
# Fingerprint parity: recovery and serving share ONE identity
# ==========================================================================
def test_recovery_and_serving_fingerprints_identical(tmp_path):
    """Regression pin for the shared helper: ``RecoveryManager
    .attach_query`` and ``ResultCache.fingerprint`` must agree on the
    query fingerprint of the same plan — a drift here would make the
    result cache key results recovery can't find (or vice versa)."""
    from spark_rapids_tpu.recovery.manager import RecoveryManager
    from spark_rapids_tpu.serving.result_cache import ResultCache

    sess = srt.Session(_conf(tmp_path, **{
        "spark.rapids.tpu.recovery.enabled": True,
    }))
    try:
        tables = tpch_datagen.dataframes(sess, sf=0.001)
        for qnum in (1, 6):
            plan = tpch.QUERIES[qnum](tables).plan
            mgr = RecoveryManager(sess.conf)
            mgr.attach_query(plan)
            key = ResultCache(sess.conf).fingerprint(plan)
            assert mgr.query_fp is not None
            assert key is not None
            assert key.query_fp == mgr.query_fp
            # and the computation is stable call-to-call
            again = ResultCache(sess.conf).fingerprint(plan)
            assert (again.plan_fp, again.query_fp) == \
                (key.plan_fp, key.query_fp)
    finally:
        sess.close()


# ==========================================================================
# Prepared statements
# ==========================================================================
def test_prepared_statement_extracts_and_rebinds(tmp_path):
    sess = srt.Session(_conf(tmp_path))
    try:
        tables = tpch_datagen.dataframes(sess, sf=0.001)
        nation = tables["nation"]
        ps = sess.prepare(nation.filter(srt.f.col("n_nationkey") < 10))
        assert ps.num_params >= 1
        assert 10 in ps.defaults
        idx = ps.defaults.index(10)

        base = ps.execute()
        assert base.num_rows == 10
        rebound = list(ps.defaults)
        rebound[idx] = 5
        assert ps.execute(rebound).num_rows == 5
        # a re-bound synchronous execute equals the plain DataFrame run
        assert _batch_rows(ps.execute(rebound)) == _norm(
            nation.filter(srt.f.col("n_nationkey") < 5).collect())

        with pytest.raises(ValueError):
            ps.execute(list(ps.defaults) + [1])  # arity
        with pytest.raises(ValueError):
            bad = list(ps.defaults)
            bad[idx] = "not-a-number"            # dtype
            ps.execute(bad)

        # submit path: the second identical binding is a result-cache hit
        h1 = ps.submit()
        h1.result(timeout=60)
        h2 = ps.submit()
        assert h2.exec_path == "cache"
        assert _batch_rows(h2.result(timeout=60)) == _batch_rows(base)
    finally:
        sess.close()


# ==========================================================================
# Streaming composition: ticks feed the result cache
# ==========================================================================
def test_stream_result_served_to_adhoc_submit_between_ticks(tmp_path):
    data = tmp_path / "data"
    _write_part(data, "part-0.parquet", [1, 2, 3], [1.0, 2.0, 3.0])
    sess = srt.Session(_conf(tmp_path, **{
        "spark.rapids.tpu.recovery.enabled": True,
        "spark.rapids.tpu.streaming.enabled": True,
    }))

    def q():
        df = sess.read_parquet(str(data))
        return df.group_by().agg(srt.f.sum("b").alias("s"))

    h = sess.stream(q(), trigger=0)
    try:
        out1 = h.process_available()
        assert _batch_rows(out1) == [(6.0,)]
        # the committed cumulative result was registered: an ad-hoc
        # submit of the same query between ticks never executes
        a1 = sess.submit(q())
        assert a1.exec_path == "cache", a1.exec_path
        assert _batch_rows(a1.result(timeout=60)) == [(6.0,)]

        # a new file lands BEFORE the next tick: the ad-hoc submit must
        # see the grown input (new fingerprint -> miss), never stale
        _write_part(data, "part-1.parquet", [4], [4.0])
        a2 = sess.submit(q())
        assert a2.exec_path != "cache"
        assert _batch_rows(a2.result(timeout=60)) == [(10.0,)]

        out2 = h.process_available()
        assert _batch_rows(out2) == [(10.0,)]
        a3 = sess.submit(q())
        assert a3.exec_path == "cache"
        assert _batch_rows(a3.result(timeout=60)) == [(10.0,)]
    finally:
        h.stop()
        sess.close()


def test_stream_ledger_commit_invalidates_rewritten_sources(tmp_path):
    """Rewriting a committed file breaks the append-only contract: the
    tick degrades to a full recompute (still correct) and the ledger
    commit eagerly drops every serving entry derived from the
    rewritten source's files."""
    data = tmp_path / "data"
    _write_part(data, "part-0.parquet", [1, 2], [1.0, 2.0])
    sess = srt.Session(_conf(tmp_path, **{
        "spark.rapids.tpu.recovery.enabled": True,
        "spark.rapids.tpu.streaming.enabled": True,
    }))

    def q():
        df = sess.read_parquet(str(data))
        return df.group_by().agg(srt.f.sum("b").alias("s"))

    h = sess.stream(q(), trigger=0)
    try:
        assert _batch_rows(h.process_available()) == [(3.0,)]
        assert sess.submit(q()).exec_path == "cache"

        _write_part(data, "part-0.parquet", [7, 8, 9],
                    [7.0, 8.0, 9.0])
        out2 = h.process_available()
        assert _batch_rows(out2) == [(24.0,)]
        assert _serving_metric(sess, "serving.result.invalidated") >= 1
        a = sess.submit(q())
        assert _batch_rows(a.result(timeout=60)) == [(24.0,)]
    finally:
        h.stop()
        sess.close()
