"""Adaptive query execution (AQE).

Reference analogue: Spark 3.0's AdaptiveSparkPlanExec +
ShufflePartitionsUtil/OptimizeSkewedJoin/DynamicJoinSelection — the
not-yet-executed remainder of a physical plan is re-optimized from
EXACT statistics materialized at shuffle boundaries, instead of the
static estimates the planner had at plan time (SURVEY §1:
GpuShuffleExchangeExec participates in AQE stage re-planning; Theseus
makes the same argument for accelerator SQL: data-movement decisions
must come from observed, not estimated, sizes).

Three pieces:

* :mod:`.stats` — ``StageStats``: per-exchange partition histograms
  aggregated from the count vectors the device shuffle's write drain
  already pulls to the host in its ONE gated readback
  (``exec/exchange.py``'s ``flush``).  Zero extra device syncs — this
  module never imports jax (the ``jax-import`` analysis rule enforces
  it mechanically).
* :mod:`.planner` — ``AdaptivePlanner``: the three rewrites applied to
  the unexecuted plan suffix between stages — partition coalescing,
  skew-join splitting, dynamic broadcast conversion — each recorded as
  a structured ``aqe_*`` telemetry event.
* :mod:`.executor` — ``maybe_execute_adaptive``: the stage-at-a-time
  driver hooked into ``Session._execute_native``.  It materializes the
  deepest exchanges eagerly (build side of a shuffled join first, so a
  conversion can still skip the stream-side exchange entirely),
  replaces each with a ``MaterializedStageExec`` over the resident
  shuffle output, re-plans, and repeats; the final plan is annotated
  AdaptiveSparkPlan-style in EXPLAIN ANALYZE.

Every rewrite is bit-identical to the non-adaptive plan: same values,
same row placement after the re-partitioning rules — pinned on TPC-H
including under fault injection and concurrent ``session.submit``.
"""
from .stats import StageStats  # noqa: F401
