"""Device equi-join kernels: sort-merge with static-shape expansion.

Reference analogue: GpuHashJoin.scala:71-140 (cudf hash-join calls) —
but where cudf scatters into hash tables, the TPU-friendly frontier is
sort-based (SURVEY §7 "Hard parts": hash join on TPU → sort + merge;
the reference replaces SortMergeJoin with hash join, here the
replacement is reversed).  Three stages, all static shapes:

  1. group ids: concat both sides' key columns, one lexsort, segment
     ids at key-change boundaries → per-row int32 ids where equal keys
     (with Spark null/NaN/-0.0 semantics) share an id across sides.
  2. probe: sort right ids once; per left row, searchsorted gives the
     contiguous run [lo, lo+cnt) of its matches.  Match counts are
     exact before any expansion — the same "size before materialize"
     contract cudf's join APIs give the reference.
  3. expand: with an output capacity chosen from the exact count, a
     searchsorted over the emit-prefix-sum turns slot t into its
     (left row, k-th match) pair; gathers materialize the output.

The only host sync is reading the match count to pick the output's
power-of-two bucket (the same sync point the reference has when cudf
returns the join output size).
"""
from __future__ import annotations

from typing import List, NamedTuple

from ...data.column import DeviceColumn
from . import segment as seg


def _concat_key_cols(lc: DeviceColumn, rc: DeviceColumn) -> DeviceColumn:
    """Row-concat one key column from each side (strings pad to the
    wider byte matrix)."""
    import jax.numpy as jnp

    if lc.dtype.is_string:
        w = max(lc.data.shape[1], rc.data.shape[1])

        def widen(d):
            return jnp.pad(d, ((0, 0), (0, w - d.shape[1]))) \
                if d.shape[1] < w else d

        data = jnp.concatenate([widen(lc.data), widen(rc.data)], axis=0)
        lengths = jnp.concatenate([lc.lengths, rc.lengths])
    else:
        data = jnp.concatenate([lc.data, rc.data])
        lengths = None
    validity = jnp.concatenate([lc.validity, rc.validity])
    return DeviceColumn(lc.dtype, data, validity, lengths)


def group_ids(l_keys: List[DeviceColumn], r_keys: List[DeviceColumn],
              l_ok, r_ok):
    """Per-row join-key group ids: rows (on either side) with equal,
    fully-non-null keys share an id.  Left rows with null keys/padding
    get -1, right ones -2 — sentinels that never match anything."""
    import jax.numpy as jnp

    nl, nr = l_ok.shape[0], r_ok.shape[0]
    combined = [_concat_key_cols(a, b) for a, b in zip(l_keys, r_keys)]
    ok = jnp.concatenate([l_ok, r_ok])
    # null keys never join: fold key validity into row eligibility
    for c in combined:
        ok = ok & c.validity
    order = seg.lexsort_device(combined, pad_valid=ok)
    sorted_cols = [DeviceColumn(c.dtype, c.data[order],
                                c.validity[order] & ok[order],
                                c.lengths[order]
                                if c.lengths is not None else None)
                   for c in combined]
    ids_sorted = seg.segment_ids_device(sorted_cols, pad_valid=ok[order])
    n = nl + nr
    ids = jnp.zeros((n,), dtype=jnp.int32).at[order].set(ids_sorted)
    gl = jnp.where(ok[:nl], ids[:nl], -1)
    gr = jnp.where(ok[nl:], ids[nl:], -2)
    return gl, gr


class Probe(NamedTuple):
    gl: object       # int32[Nl] left group ids (-1 = never matches)
    gr: object       # int32[Nr]
    order_r: object  # int32[Nr] right rows sorted by group id
    lo: object       # int32[Nl] first match position in order_r
    cnt: object      # int32[Nl] number of right matches per left row
    has_r: object    # bool[Nr] right row has a left match


def probe(l_keys, r_keys, l_ok, r_ok) -> Probe:
    import jax.numpy as jnp

    gl, gr = group_ids(l_keys, r_keys, l_ok, r_ok)
    order_r = jnp.argsort(gr, stable=True).astype(jnp.int32)
    sorted_gr = gr[order_r]
    lo = jnp.searchsorted(sorted_gr, gl, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sorted_gr, gl, side="right").astype(jnp.int32)
    cnt = hi - lo
    sorted_gl = jnp.sort(gl)
    rlo = jnp.searchsorted(sorted_gl, gr, side="left")
    rhi = jnp.searchsorted(sorted_gl, gr, side="right")
    has_r = (rhi > rlo) & (gr >= 0)
    return Probe(gl, gr, order_r, lo, cnt, has_r)


def emit_counts(p: Probe, how: str, l_rm, r_rm):
    """Per-left-row emit counts + unmatched-right mask + total rows.

    l_rm/r_rm: logical-row masks (padding excluded).  Emit semantics
    match the host oracle: inner = cnt; left/full = max(cnt, 1);
    right/full additionally emit each unmatched right row once."""
    import jax.numpy as jnp

    cnt = jnp.where(l_rm, p.cnt, 0)
    if how in ("left", "full"):
        emit = jnp.where(l_rm, jnp.maximum(cnt, 1), 0)
    else:
        emit = cnt
    if how in ("right", "full"):
        r_extra = r_rm & ~p.has_r
    else:
        r_extra = jnp.zeros_like(r_rm)
    total = emit.sum(dtype=jnp.int64) + r_extra.sum(dtype=jnp.int64)
    return emit, r_extra, total


def expand_pairs(p: Probe, emit, r_extra, c_out: int):
    """Turn slot t in [0, c_out) into its (lidx, ridx) pair; -1 marks
    the null-extended side.  Returns (lidx, ridx, slot_valid)."""
    import jax.numpy as jnp

    nl = emit.shape[0]
    nr = p.gr.shape[0]
    offs = jnp.cumsum(emit)                      # inclusive prefix sum
    m_left = offs[-1]
    t = jnp.arange(c_out, dtype=jnp.int64)
    li = jnp.searchsorted(offs, t, side="right").astype(jnp.int32)
    li_safe = jnp.clip(li, 0, nl - 1)
    prev = offs[li_safe] - emit[li_safe]         # exclusive prefix
    k = (t - prev).astype(jnp.int32)
    in_left = t < m_left
    matched = p.cnt[li_safe] > 0
    ri_pos = jnp.clip(p.lo[li_safe] + k, 0, nr - 1)
    ridx = jnp.where(matched, p.order_r[ri_pos], -1)
    lidx = jnp.where(in_left, li_safe, -1)
    ridx = jnp.where(in_left, ridx, -1)

    # unmatched right rows fill slots [m_left, m_left + n_extra)
    n_extra = r_extra.sum(dtype=jnp.int64)
    unmatched_order = jnp.argsort(~r_extra, stable=True).astype(jnp.int32)
    s = jnp.clip(t - m_left, 0, nr - 1)
    ridx = jnp.where(~in_left, unmatched_order[s], ridx)
    slot_valid = t < (m_left + n_extra)
    ridx = jnp.where(slot_valid, ridx, -1)
    lidx = jnp.where(slot_valid, lidx, -1)
    return lidx, ridx, slot_valid


def gather_side(columns: List[DeviceColumn], idx, slot_valid
                ) -> List[DeviceColumn]:
    """Gather one side's columns by row index; idx -1 → null."""
    import jax.numpy as jnp

    out = []
    for c in columns:
        safe = jnp.clip(idx, 0, c.data.shape[0] - 1)
        data = c.data[safe]
        validity = c.validity[safe] & (idx >= 0) & slot_valid
        lengths = c.lengths[safe] if c.lengths is not None else None
        out.append(DeviceColumn(c.dtype, data, validity, lengths))
    return out
