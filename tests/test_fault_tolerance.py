"""Distributed fault-tolerance layer (spark_rapids_tpu/fault/).

The central invariant, extending PR-1's OOM contract to the full fault
model: with the generalized deterministic injector driving faults
(``corrupt`` / ``delay`` / ``stage_crash``) through the engine's
checkpoints — spill writes/reads, exchange steps, stage boundaries —
every injected run must complete with results bit-identical to an
injection-free run, the ``fault.*`` counters must make the recovery
visible, and a query that exhausts its bounded retries must return
correct results through the degradation ladder (single-process / CPU
rung) instead of raising.
"""
import threading
import time

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.fault import (FaultInjector, fault_stats,
                                    install_fault_injector)
from spark_rapids_tpu.fault.errors import (TpuFaultError,
                                           TpuPayloadCorruption,
                                           TpuStageCrash, TpuStageTimeout)
from spark_rapids_tpu.plan import functions as F

#: fast-recovery confs shared by injection tests (CI must not sleep
#: through its budget; the backoff code is real either way)
FAST = {
    "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
    "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
}


def _inject(mode, fault_type, site="", skip=0, seed=0, delay_ms=50.0,
            **extra):
    conf = dict(FAST)
    conf.update({
        "spark.rapids.tpu.fault.injection.mode": mode,
        "spark.rapids.tpu.fault.injection.type": fault_type,
        "spark.rapids.tpu.fault.injection.site": site,
        "spark.rapids.tpu.fault.injection.skipCount": skip,
        "spark.rapids.tpu.fault.injection.seed": seed,
        "spark.rapids.tpu.fault.injection.delayMs": delay_ms,
    })
    conf.update(extra)
    return conf


def _norm(rows):
    return sorted(
        (tuple((None if v is None else
                (round(v, 9) if isinstance(v, float) else v))
               for v in r) for r in rows),
        key=repr)


# ==========================================================================
# FaultInjector unit tests
# ==========================================================================
def test_fault_injector_site_filter_counts_only_matches():
    inj = FaultInjector(mode="nth", skip_count=1,
                        fault_type="stage_crash", site="stage.run")
    inj.check("spill.write")   # filtered out: no count
    inj.check("stage.run")     # matching checkpoint #0
    with pytest.raises(TpuStageCrash) as ei:
        inj.check("stage.run")  # matching checkpoint #1 -> fire
    assert ei.value.injected and ei.value.site == "stage.run"
    inj.check("stage.run")      # one-shot: disarmed
    assert inj.injections_fired == 1
    assert inj.checkpoints_seen == 3  # only matching sites counted


def test_fault_injector_corrupt_only_fires_on_write_hook():
    inj = FaultInjector(mode="always", fault_type="corrupt")
    inj.check("spill.write")  # corrupt never raises from check()
    assert inj.injections_fired == 0
    assert inj.should_corrupt("spill.write")
    assert inj.injections_fired == 1
    # and the raising types never fire through the corrupt hook
    crash = FaultInjector(mode="always", fault_type="stage_crash")
    assert not crash.should_corrupt("spill.write")


def test_fault_injector_delay_sleeps_instead_of_raising():
    inj = FaultInjector(mode="nth", skip_count=0, fault_type="delay",
                        delay_ms=80.0)
    t0 = time.monotonic()
    inj.check("stage.run")
    assert time.monotonic() - t0 >= 0.05
    assert inj.injections_fired == 1


def test_fault_injector_validates_inputs():
    with pytest.raises(ValueError):
        FaultInjector(mode="bogus")
    with pytest.raises(ValueError):
        FaultInjector(fault_type="bogus")


def test_oom_injector_is_a_fault_injector_specialization():
    """The PR-1 OomInjector surface is preserved as the ``oom``
    specialization of the generalized injector."""
    from spark_rapids_tpu.memory.retry import (OomInjector, TpuRetryOOM,
                                               TpuSplitAndRetryOOM)

    inj = OomInjector(mode="nth", skip_count=0, oom_type="split")
    assert isinstance(inj, FaultInjector)
    with pytest.raises(TpuSplitAndRetryOOM):
        inj.check("x")
    inj2 = OomInjector(mode="always")
    with pytest.raises(TpuRetryOOM) as ei:
        inj2.check("y")
    assert ei.value.injected


# ==========================================================================
# Spill-frame CRC32C integrity
# ==========================================================================
def _device_batch(n=64):
    from spark_rapids_tpu.data.column import HostBatch, host_to_device

    return host_to_device(HostBatch.from_pydict(
        {"x": list(range(n)), "s": [f"v{i}" for i in range(n)]}))


def test_spill_frame_checksum_roundtrip_clean():
    from spark_rapids_tpu.data.column import device_to_host
    from spark_rapids_tpu.memory.spill import SpillFramework

    fw = SpillFramework()
    bid = fw.add_batch(_device_batch())
    fw.spill_device_to_target(0)
    buf = fw.catalog.get(bid)
    assert buf.crc is not None
    hb = device_to_host(fw.acquire_batch(bid))
    assert hb.column("x").to_pylist() == list(range(64))
    fw.release_batch(bid)
    fw.remove_batch(bid)


def test_spill_frame_corruption_detected_on_read():
    from spark_rapids_tpu.memory.spill import SpillFramework

    fw = SpillFramework()
    bid = fw.add_batch(_device_batch())
    fw.spill_device_to_target(0)
    fw.catalog.get(bid).corrupt_payload()
    before = fault_stats.get("numChecksumFailures")
    with pytest.raises(TpuPayloadCorruption) as ei:
        fw.acquire_batch(bid)
    assert "crc32c" in str(ei.value)
    assert fault_stats.get("numChecksumFailures") == before + 1
    fw.remove_batch(bid)


def test_injected_corruption_on_spill_write_is_detected():
    """An armed ``corrupt`` injector damages the next spill-catalog
    write; the read must detect it — never consume garbage."""
    from spark_rapids_tpu.memory.spill import SpillFramework, StorageTier

    fw = SpillFramework()
    install_fault_injector(FaultInjector(
        mode="nth", skip_count=0, fault_type="corrupt",
        site="spill.write"))
    try:
        bid = fw.add_batch(_device_batch())
        buf = fw.catalog.get(bid)
        assert buf.tier == StorageTier.HOST  # demoted by the injection
        with pytest.raises(TpuPayloadCorruption):
            fw.acquire_batch(bid)
    finally:
        install_fault_injector(None)
        fw.remove_batch(bid)


def test_disk_spill_keeps_checksum_verification():
    from spark_rapids_tpu.memory.spill import SpillFramework, StorageTier

    fw = SpillFramework(host_limit_bytes=1)  # everything -> disk
    bid = fw.add_batch(_device_batch())
    fw.spill_device_to_target(0)
    buf = fw.catalog.get(bid)
    assert buf.tier == StorageTier.DISK
    # flip a byte in the disk file: the read path must catch it
    with open(buf._disk_path, "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(TpuPayloadCorruption) as ei:
        fw.acquire_batch(bid)
    assert "spill.read.disk" in str(ei.value)
    fw.remove_batch(bid)


# ==========================================================================
# ShuffleCatalog slot release (stage re-execution / abort regression)
# ==========================================================================
def test_shuffle_catalog_releases_slots_of_failed_attempt():
    from spark_rapids_tpu.memory.spill import SpillFramework
    from spark_rapids_tpu.shuffle.catalog import ShuffleCatalog

    fw = SpillFramework()
    cat = ShuffleCatalog(fw)
    sid = cat.register_shuffle()
    ids = [fw.add_batch(_device_batch(8)) for _ in range(3)]
    for mid, bid in enumerate(ids):
        cat.add_buffer(sid, mid, bid)
    assert cat.slot_count(sid) == 3
    # a failed write attempt releases its entries WITHOUT unregistering
    cat.drop_buffers(sid, ids[:2])
    assert cat.slot_count(sid) == 1
    assert all(fw.catalog.get(b) is None for b in ids[:2])
    # the retry re-registers fresh buffers under the same shuffle id
    nid = fw.add_batch(_device_batch(8))
    cat.add_buffer(sid, 0, nid)
    assert cat.slot_count(sid) == 2
    cat.unregister_shuffle(sid)
    assert cat.slot_count() == 0
    assert fw.catalog.get(ids[2]) is None and fw.catalog.get(nid) is None


@pytest.mark.fault_injection
def test_shuffle_retry_does_not_leak_catalog_slots():
    """End-to-end: a crashed shuffle write re-executes from lineage and
    the dead attempt's catalog slots are released (regression: retries
    used to leak the failed attempt's ids in the shuffle index)."""
    sess = srt.Session(_inject(
        "nth", "stage_crash", site="exchange.write", skip=1, **{
            "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
            "spark.rapids.tpu.sql.taskRetries": 3,
        }))
    df = sess.create_dataframe({
        "k": [i % 7 for i in range(96)],
        "v": [float(i) for i in range(96)]})
    got = df.group_by("k").agg(F.sum("v").alias("s")).collect()
    exp = srt.Session(tpu_enabled=False).create_dataframe({
        "k": [i % 7 for i in range(96)],
        "v": [float(i) for i in range(96)]}).group_by("k").agg(
        F.sum("v").alias("s")).collect()
    assert _norm(got) == _norm(exp)
    # query-end cleanup + per-attempt release: no slots survive
    assert sess.shuffle_catalog.slot_count() == 0


# ==========================================================================
# Local-engine recovery: bit-identical under injection
# ==========================================================================
def _join_agg_query(sess):
    rng = np.random.RandomState(3)
    orders = {"o_custkey": rng.randint(0, 40, 300).tolist(),
              "o_total": [round(float(v), 6)
                          for v in rng.rand(300) * 1000]}
    cust = {"c_custkey": list(range(40)),
            "c_nation": rng.randint(0, 5, 40).tolist()}
    o = sess.create_dataframe(orders)
    c = sess.create_dataframe(cust)
    j = o.join(c, on=(["o_custkey"], ["c_custkey"]), how="inner")
    return j.group_by("c_nation").agg(
        F.sum("o_total").alias("rev"), F.count("o_total").alias("n"))


SHUFFLED = {"spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
            "spark.rapids.tpu.sql.taskRetries": 3}


@pytest.mark.fault_injection
def test_local_corrupt_exchange_payload_bit_identical():
    """A corrupted shuffle map-output payload is detected by the CRC on
    read, the producing write re-executes from lineage, and the result
    is bit-identical to the injection-free device run."""
    clean = _join_agg_query(srt.Session(dict(SHUFFLED))).collect()
    sess = srt.Session(_inject("nth", "corrupt", site="exchange.write",
                               **SHUFFLED))
    got = _join_agg_query(sess).collect()
    assert _norm(got) == _norm(clean)
    m = sess.last_metrics
    assert m.get("fault.numChecksumFailures", 0) >= 1, m
    oracle = _join_agg_query(srt.Session(tpu_enabled=False)).collect()
    assert _norm(got) == _norm(oracle)


@pytest.mark.fault_injection
@pytest.mark.parametrize("site", ["exchange.write", "exchange.read",
                                  "spill.read"])
def test_local_stage_crash_sites_bit_identical(site):
    clean = _join_agg_query(srt.Session(dict(SHUFFLED))).collect()
    sess = srt.Session(_inject("nth", "stage_crash", site=site,
                               **SHUFFLED))
    got = _join_agg_query(sess).collect()
    assert _norm(got) == _norm(clean), site
    assert "fault.degradeLevel" in sess.last_metrics


@pytest.mark.fault_injection
def test_local_delay_injection_bit_identical():
    clean = _join_agg_query(srt.Session(dict(SHUFFLED))).collect()
    sess = srt.Session(_inject("nth", "delay", site="exchange.write",
                               delay_ms=30.0, **SHUFFLED))
    got = _join_agg_query(sess).collect()
    assert _norm(got) == _norm(clean)


@pytest.mark.fault_injection
def test_session_ladder_degrades_to_cpu_rung():
    """mode=always stage crashes with task retries exhausted: the query
    must still return correct results via the CPU-exec rung (the bottom
    of the ladder), with the degradation visible in the metrics."""
    conf = _inject("always", "stage_crash", site="exchange.write", **{
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        "spark.rapids.tpu.sql.taskRetries": 0,
    })
    sess = srt.Session(conf)
    got = _join_agg_query(sess).collect()
    oracle = _join_agg_query(srt.Session(tpu_enabled=False)).collect()
    assert _norm(got) == _norm(oracle)
    assert sess.last_metrics.get("fault.degradeLevel") == 2, \
        sess.last_metrics


@pytest.mark.fault_injection
def test_degrade_disabled_surfaces_the_fault():
    conf = _inject("always", "stage_crash", site="exchange.write", **{
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        "spark.rapids.tpu.sql.taskRetries": 0,
        "spark.rapids.tpu.fault.degrade.enabled": False,
    })
    with pytest.raises(TpuFaultError):
        _join_agg_query(srt.Session(conf)).collect()


def test_clean_run_reports_zero_fault_counters():
    sess = srt.Session()
    df = sess.create_dataframe({"x": [1.0, 2.0, 3.0]})
    df.select((df["x"] * 2.0).alias("y")).collect()
    m = sess.last_metrics
    assert m.get("fault.degradeLevel") == 0
    assert m.get("fault.numStageRetries") == 0
    assert m.get("fault.numChecksumFailures") == 0
    assert m.get("fault.numWatchdogTrips") == 0


# ==========================================================================
# Stage watchdog + bounded stage re-execution (unit, no jax)
# ==========================================================================
def _runner(n=2):
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.runner import DistributedRunner

    return DistributedRunner(make_mesh(n))


class _Ctx:
    def __init__(self, **kv):
        from spark_rapids_tpu.config import TpuConf

        self.conf = TpuConf(dict(FAST, **kv))


def test_watchdog_trips_on_hung_stage():
    r = _runner()
    ctx = _Ctx(**{"spark.rapids.tpu.fault.stageTimeoutMs": 100,
                  "spark.rapids.tpu.fault.maxStageRetries": 0})
    before = fault_stats.get("numWatchdogTrips")
    with pytest.raises(TpuStageTimeout):
        r._recover(lambda: time.sleep(2.0), ctx, "stage[test]")
    assert fault_stats.get("numWatchdogTrips") == before + 1


def test_recover_bounded_reexecution_then_success():
    r = _runner()
    ctx = _Ctx(**{"spark.rapids.tpu.fault.maxStageRetries": 3})
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise TpuStageCrash("boom", injected=True)
        return "ok"

    before = fault_stats.get("numStageRetries")
    assert r._recover(fn, ctx, "stage[test]") == "ok"
    assert len(calls) == 3
    assert fault_stats.get("numStageRetries") == before + 2


def test_recover_exhaustion_reraises_for_the_ladder():
    r = _runner()
    ctx = _Ctx(**{"spark.rapids.tpu.fault.maxStageRetries": 1})

    def fn():
        raise TpuStageCrash("persistent")

    with pytest.raises(TpuStageCrash):
        r._recover(fn, ctx, "stage[test]")


def test_recover_does_not_catch_non_fault_errors():
    r = _runner()
    ctx = _Ctx(**{"spark.rapids.tpu.fault.maxStageRetries": 5})
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("a genuine bug")

    with pytest.raises(ValueError):
        r._recover(fn, ctx, "stage[test]")
    assert len(calls) == 1, "non-fault errors must not re-execute"


# ==========================================================================
# Distributed runner under injection (virtual 8-device CPU mesh)
# ==========================================================================
def _dist_query(sess):
    rng = np.random.RandomState(5)
    df = sess.create_dataframe({
        "k": rng.randint(0, 20, 240).tolist(),
        "v": [round(float(x), 6) for x in rng.rand(240) * 100]})
    return df.filter(df["v"] > 10).group_by("k").agg(
        F.sum("v").alias("s"), F.count("v").alias("c"))


def _dist_run(conf=None):
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.runner import run_distributed

    sess = srt.Session(dict(conf or {}))
    out = run_distributed(sess, _dist_query(sess), mesh=make_mesh(8))
    return sess, _norm(out.to_rows())


@pytest.mark.fault_injection
@pytest.mark.parametrize("fault_type,site,skips", [
    ("stage_crash", "stage.run", (0, 1)),
    ("stage_crash", "leaf.drain", (0, 1)),
    ("corrupt", "host.stack", (0,)),
])
def test_distributed_injection_sweep_bit_identical(fault_type, site,
                                                   skips):
    """Injected stage crashes and host round-trip corruption recover
    via bounded stage re-execution with bit-identical results."""
    _, clean = _dist_run(dict(FAST))
    for skip in skips:
        sess, got = _dist_run(_inject("nth", fault_type, site=site,
                                      skip=skip))
        assert got == clean, (fault_type, site, skip)
        m = sess.last_metrics
        assert m.get("fault.numStageRetries", 0) >= 1, (site, skip, m)
        if fault_type == "corrupt":
            assert m.get("fault.numChecksumFailures", 0) >= 1, m


@pytest.mark.fault_injection
def test_distributed_delay_trips_watchdog_and_recovers():
    """An injected straggler at the stage boundary trips the
    ``fault.stageTimeoutMs`` watchdog; the abandoned attempt re-executes
    and results stay bit-identical."""
    _, clean = _dist_run(dict(FAST))
    sess, got = _dist_run(_inject(
        "nth", "delay", site="stage.run", delay_ms=30000.0, **{
            "spark.rapids.tpu.fault.stageTimeoutMs": 3000,
        }))
    assert got == clean
    m = sess.last_metrics
    assert m.get("fault.numWatchdogTrips", 0) >= 1, m
    assert m.get("fault.numStageRetries", 0) >= 1, m


@pytest.mark.fault_injection
def test_distributed_ladder_degrades_to_single_process():
    """Persistent stage crashes exhaust fault.maxStageRetries: the
    ladder falls back to the single-process rung and still returns
    correct results, with degradeLevel=1 in the metrics."""
    from spark_rapids_tpu.fault.ladder import run_with_fault_tolerance
    from spark_rapids_tpu.parallel.mesh import make_mesh

    sess = srt.Session(_inject("always", "stage_crash", site="stage.run",
                               **{
        "spark.rapids.tpu.fault.maxStageRetries": 1,
    }))
    out = run_with_fault_tolerance(sess, _dist_query(sess),
                                   mesh=make_mesh(8))
    oracle = _dist_query(srt.Session(tpu_enabled=False)).collect()
    assert _norm(out.to_rows()) == _norm(oracle)
    m = sess.last_metrics
    assert m.get("fault.degradeLevel") == 1, m
    assert m.get("fault.numStageRetries", 0) >= 1, m


# ==========================================================================
# Prefetch-queue watchdog (exec/transitions.py satellite)
# ==========================================================================
def test_bounded_put_honors_stop_flag():
    import queue

    from spark_rapids_tpu.exec.transitions import _bounded_put

    q = queue.Queue(maxsize=1)
    q.put("full")
    stop = threading.Event()
    stop.set()
    assert _bounded_put(q, "x", stop, timeout_s=60.0) is False


def test_bounded_put_surfaces_watchdog_on_dead_consumer():
    import queue

    from spark_rapids_tpu.exec.transitions import _bounded_put

    q = queue.Queue(maxsize=1)
    q.put("full")  # nobody ever drains: the consumer is dead
    stop = threading.Event()
    t0 = time.monotonic()
    with pytest.raises(TpuStageTimeout):
        _bounded_put(q, "x", stop, timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0, "must not busy-loop forever"


def test_next_prefetched_detects_dead_producer():
    import queue

    from spark_rapids_tpu.exec.transitions import _next_prefetched

    q = queue.Queue(maxsize=1)
    err = [None]
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    with pytest.raises(TpuStageTimeout):
        _next_prefetched(q, dead, err)
    # and a recorded producer error is surfaced verbatim
    err[0] = RuntimeError("decode failed")
    with pytest.raises(RuntimeError, match="decode failed"):
        _next_prefetched(q, dead, err)


# ==========================================================================
# Semaphore watchdog as a retryable/degradable fault (satellite)
# ==========================================================================
def test_semaphore_timeout_is_a_typed_fault():
    from spark_rapids_tpu.memory.semaphore import (DeviceSemaphore,
                                                   DeviceSemaphoreTimeout)

    assert issubclass(DeviceSemaphoreTimeout, TpuFaultError)
    sem = DeviceSemaphore(1, acquire_timeout=0.3)
    holding = threading.Event()
    release = threading.Event()

    def holder():
        sem.acquire_if_necessary()
        holding.set()
        release.wait(timeout=30)
        sem.release_task()

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert holding.wait(timeout=30)
    with pytest.raises(DeviceSemaphoreTimeout):
        sem.acquire_if_necessary()
    release.set()
    t.join(timeout=30)


def test_semaphore_timeout_conf_is_wired():
    """fault.semaphoreTimeoutMs is a documented conf and reaches the
    DeviceSemaphore the DeviceManager builds."""
    from spark_rapids_tpu.config import FAULT_SEMAPHORE_TIMEOUT_MS, lookup

    assert lookup("spark.rapids.tpu.fault.semaphoreTimeoutMs") \
        is FAULT_SEMAPHORE_TIMEOUT_MS
    assert not FAULT_SEMAPHORE_TIMEOUT_MS.is_internal
    # 0 = built-in default; the stage-recovery protocol treats the
    # timeout as recoverable
    from spark_rapids_tpu.memory.semaphore import DeviceSemaphoreTimeout
    from spark_rapids_tpu.parallel.runner import RECOVERABLE_FAULTS

    assert DeviceSemaphoreTimeout in RECOVERABLE_FAULTS \
        or issubclass(DeviceSemaphoreTimeout, RECOVERABLE_FAULTS)


# ==========================================================================
# 2-process multi-controller crash/straggler (slow tier)
# ==========================================================================
@pytest.mark.slow
@pytest.mark.fault_injection
@pytest.mark.parametrize("fault", ["crash", "straggler"])
def test_two_process_fault_recovery(fault):
    """A 2-process CPU multi-controller run survives (a) a replicated
    stage crash re-executed in lockstep on every controller, and (b) a
    one-sided straggler delaying one controller's leaf drain — results
    stay oracle-equal on every controller."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coordinator = f"127.0.0.1:{port}"
    script = os.path.join(os.path.dirname(__file__),
                          "mp_fault_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    procs = [subprocess.Popen(
        [sys.executable, script, coordinator, "2", str(pid), fault],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("fault-injected multi-process workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    if any("Multiprocess computations aren't implemented" in (o or "")
           for o in outs):
        pytest.skip("this jax build's CPU backend lacks multi-process "
                    "collectives (same limitation as "
                    "test_multiprocess) — nothing to recover over")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"worker {pid} rc={p.returncode}:\n{out[-4000:]}"
        assert f"MPF RESULT OK pid={pid} fault={fault}" in out, \
            out[-4000:]
        if fault == "crash":
            assert f"MPF RETRIES pid={pid} n=" in out, out[-4000:]
