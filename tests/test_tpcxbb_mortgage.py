"""TPCx-BB-like q1..q30 + Mortgage ETL: CPU-oracle vs TPU equality.

Reference analogue: TpcxbbLikeSpark query suite + MortgageSparkSuite.
"""
import pytest

from spark_rapids_tpu.benchmarks import (mortgage, tpcxbb, tpcxbb_datagen)
from spark_rapids_tpu.session import Session
from spark_rapids_tpu.testing.asserts import assert_rows_equal

SF = 0.001
SEED = 99


def _run_bb(qnum: int, tpu: bool):
    sess = Session(tpu_enabled=tpu)
    tables = tpcxbb_datagen.dataframes(sess, sf=SF, seed=SEED)
    return tpcxbb.QUERIES[qnum](tables).collect()


# queries whose trailing sort totally orders the output rows
_ORDERED = {3, 5, 12, 15, 17, 22, 24, 28, 30}


@pytest.mark.parametrize("qnum", sorted(tpcxbb.QUERIES))
def test_tpcxbb_query_cpu_vs_tpu(qnum):
    cpu_rows = _run_bb(qnum, tpu=False)
    tpu_rows = _run_bb(qnum, tpu=True)
    assert_rows_equal(cpu_rows, tpu_rows,
                      ignore_order=qnum not in _ORDERED,
                      approximate_float=1e-6)


def test_tpcxbb_nonempty_coverage():
    nonempty = sum(bool(_run_bb(q, tpu=False))
                   for q in sorted(tpcxbb.QUERIES))
    assert nonempty >= 27, f"only {nonempty}/30 queries returned rows"


# ===========================================================================
def _run_mortgage(fn, tpu: bool):
    sess = Session(tpu_enabled=tpu)
    tables = mortgage.dataframes(sess, sf=0.005, seed=31)
    return fn(tables).collect()


def test_mortgage_etl_cpu_vs_tpu():
    cpu_rows = _run_mortgage(mortgage.etl, tpu=False)
    tpu_rows = _run_mortgage(mortgage.etl, tpu=True)
    assert len(cpu_rows) > 0
    assert_rows_equal(cpu_rows, tpu_rows, approximate_float=1e-6)


def test_mortgage_summary_cpu_vs_tpu():
    cpu_rows = _run_mortgage(mortgage.summary, tpu=False)
    tpu_rows = _run_mortgage(mortgage.summary, tpu=True)
    assert len(cpu_rows) > 0
    assert_rows_equal(cpu_rows, tpu_rows, approximate_float=1e-6)
