"""Incremental micro-batch planning over the recovery substrate.

The core trick: a streaming query's cumulative plan at tick N and at
tick N+1 differ ONLY in the file lists of their scan leaves.  The
recovery substrate already fingerprints every exchange from its host
subtree + leaf data identity, so the tick-over-tick delta is visible as
a fingerprint delta per exchange occurrence.  This module

1. normalizes exchange keys so the same occurrence matches across
   ticks despite differing file counts (``FileScan[parquet](N files)``
   → ``FileScan[parquet](* files)``),
2. derives a :class:`StreamRecoveryManager` whose query fingerprint is
   the STREAM fingerprint (stable across ticks — checkpoints of every
   tick share one pinned query directory), and
3. merges growing exchanges: for an exchange whose inputs only GREW,
   executes the delta subtree over just the new files on the host path
   and appends its frames to the previous tick's committed frames,
   writing the result under the new exchange fingerprint.  The
   cumulative query then resumes that exchange from the merged
   checkpoint instead of rescanning history.

Correctness of the merge (why append == recompute, bit for bit): merges
are attempted only for HashPartitioning exchanges over per-row
content-addressed partition ids, with nothing between scan and exchange
except row-local operators (filter/project/expand/generate) and at most
a PARTIAL hash aggregate.  Per output partition, old frames hold
exactly the rows (or ≤1 partial-agg row per group per file) of the
committed file prefix, delta frames those of the new suffix, in file
order — which is exactly the order the cold cumulative execution
produces, because discovery is sorted and the prefix is
fingerprint-stable.  The FINAL aggregate above the exchange merges
partials with order-insensitive buffers per group, so the cumulative
query over the merged checkpoint is bit-identical to a cold full
recompute.  Anything outside this shape (range/round-robin
partitioning, final/complete aggregates below the exchange, joins in
the subtree) is skipped with a ``stream_incremental_skip`` event and
recomputes from scratch — correct, just not incremental.

No jax here: delta subtrees run on the HOST operator path (the frames
are mode-independent; the cumulative query resumes them on any rung).
"""
from __future__ import annotations

import logging
import re
from typing import Dict, List, Optional, Tuple

from ..recovery.manager import (RecoveryManager, _digest, _exchange_key,
                                _leaf_material, schema_signature,
                                RESULT_CONF_KEYS)
from ..scheduler.cancel import TpuQueryCancelled, check_cancel
from ..telemetry.events import emit_event

log = logging.getLogger(__name__)

#: host execs that are row-local (each output row is a function of one
#: input row of one file) — safe between a scan and a merged exchange
_INCREMENTAL_SAFE_EXECS = frozenset({
    "FileScanExec", "ProjectExec", "FilterExec", "ExpandExec",
    "GenerateExec",
})

_FILE_COUNT_RE = re.compile(r"(FileScan\[\w+\])\(\d+ files\)")
#: ``HashPartitioning([k1, k2], 8)`` / ``RangePartitioning(8)`` — the
#: trailing fan-out tracks the input partition count, which grows with
#: the file set; occurrence matching must see through it
_PART_N_RE = re.compile(r"(\w+Partitioning\()((?:\[[^\]]*\], )?)\d+\)")


def normalize_plan_text(text: str) -> str:
    """Erase scan file counts AND partitioning fan-outs so the same
    plan shape matches across ticks with different cumulative file
    sets (the planner scales both with the input partition count)."""
    return _PART_N_RE.sub(r"\1\2*)",
                          _FILE_COUNT_RE.sub(r"\1(* files)", text))


def occurrence_key(norm_key: str, idx: int) -> str:
    """Stable ledger key of one exchange occurrence: digest of the
    normalized subtree string + preorder occurrence index."""
    return f"{_digest(norm_key)}#{idx}"


def stream_fingerprint(conf, plan) -> str:
    """Identity of a continuous query: normalized logical template tree
    + result-affecting conf.  Deliberately EXCLUDES leaf data — the
    whole point is that every tick, over a growing file set, shares one
    checkpoint namespace (one pinned query dir, one ledger)."""
    conf_part = "\n".join(
        f"{k}={conf.get_key(k)!r}" for k in RESULT_CONF_KEYS)
    return _digest("stream\n" + normalize_plan_text(plan.tree_string())
                   + "\n" + conf_part)


def _exchange_occurrences(phys) -> Dict[Tuple[str, int], object]:
    """Preorder map of ``(normalized key, occurrence idx) -> node`` for
    every exchange in a host physical tree."""
    out: Dict[Tuple[str, int], object] = {}
    seen: Dict[str, int] = {}

    def visit(node):
        key = _exchange_key(node)
        if key is not None:
            norm = normalize_plan_text(key)
            idx = seen.get(norm, 0)
            seen[norm] = idx + 1
            out[(norm, idx)] = node
        for c in getattr(node, "children", ()):
            visit(c)

    visit(phys)
    return out


def compute_exchange_fingerprints(host_phys) -> Dict[Tuple[str, int], str]:
    """Per-occurrence exchange fingerprints for one tick's cumulative
    plan: normalized subtree shape + occurrence index + the subtree's
    leaf DATA identity (file fingerprints).  Two ticks agree on an
    occurrence's fingerprint exactly when its input files are
    unchanged — that is what lets untouched exchanges resume."""
    fps: Dict[Tuple[str, int], str] = {}
    for (norm, idx), node in _exchange_occurrences(host_phys).items():
        material: List[str] = []
        _leaf_material(node, material)
        fps[(norm, idx)] = _digest(
            f"{norm}#{idx}@{_digest(chr(10).join(material))}")
    return fps


class StreamRecoveryManager(RecoveryManager):
    """RecoveryManager variant for one micro-batch of a stream.

    Differs from the per-query base in exactly two ways: the query
    fingerprint is the STREAM fingerprint (all ticks share one pinned
    checkpoint namespace), and exchange stamps fold in per-occurrence
    leaf data identity (so a grown scan changes the stamp and a merged
    checkpoint written under the new stamp is picked up by resume).
    Resume is forced on — a stream that checkpoints but never resumes
    would be pure overhead."""

    def __init__(self, conf, stream_fp: str):
        super().__init__(conf, force_resume=True)
        self.stream_fp = stream_fp
        #: (normalized key, occurrence idx) -> exchange fingerprint
        self.occ_fps: Dict[Tuple[str, int], str] = {}
        #: ledger form of the same map (occurrence_key -> fingerprint)
        self.exchange_fps: Dict[str, str] = {}
        self.host_phys = None
        #: exchanges stamped on the widest rung — the denominator of
        #: the batch's recompute fraction
        self.stamped_total = 0

    def attach_query(self, plan) -> None:
        if not (self.write_enabled or self.resume_enabled):
            return
        try:
            from ..recovery.manager import plan_fingerprints

            host_phys, _, query_fp, _ = plan_fingerprints(self.conf, plan)
            if query_fp is None:
                log.debug("stream recovery declined: nondeterministic "
                          "plan")
                self.write_enabled = self.resume_enabled = False
                return
            self.query_fp = self.stream_fp
            self.host_phys = host_phys
            self.occ_fps = compute_exchange_fingerprints(host_phys)
            self.exchange_fps = {
                occurrence_key(norm, idx): fp
                for (norm, idx), fp in self.occ_fps.items()}
        except Exception:  # noqa: BLE001 - recovery must never fail a query
            log.warning("stream recovery disabled: fingerprint failed",
                        exc_info=True)
            self.write_enabled = self.resume_enabled = False

    def stamp_plan(self, phys) -> int:
        """Stamp every exchange with its data-aware occurrence
        fingerprint.  Falls back to the base shape-only stamp for an
        occurrence the attach pass did not see (defensive: a rung that
        planned extra exchanges simply won't resume them)."""
        if self.query_fp is None:
            return 0
        seen: Dict[str, int] = {}
        stamped = 0

        def visit(node):
            nonlocal stamped
            key = _exchange_key(node)
            if key is not None:
                norm = normalize_plan_text(key)
                idx = seen.get(norm, 0)
                seen[norm] = idx + 1
                node._recovery_fp = self.occ_fps.get(
                    (norm, idx), _digest(f"{key}#{idx}"))
                stamped += 1
            for c in getattr(node, "children", ()):
                visit(c)

        visit(phys)
        self.stamped_total = max(self.stamped_total, stamped)
        return stamped


def incremental_safe(exchange_node) -> Optional[str]:
    """None when a host exchange's subtree is merge-eligible, else the
    human-readable reason it is not (emitted on the skip event)."""
    from ..shuffle.partitioning import HashPartitioning

    if not isinstance(exchange_node.partitioning, HashPartitioning):
        return ("partitioning "
                f"{type(exchange_node.partitioning).__name__} is not "
                "content-addressed")
    scans = 0
    stack = [exchange_node.children[0]]
    while stack:
        check_cancel("streaming.plan")
        node = stack.pop()
        name = type(node).__name__
        if name == "HashAggregateExec":
            if node.mode != "partial":
                return f"{node.mode} aggregate below exchange"
        elif name == "FileScanExec":
            scans += 1
        elif name not in _INCREMENTAL_SAFE_EXECS:
            return f"{name} below exchange is not row-local"
        stack.extend(getattr(node, "children", ()))
    if scans != 1:
        return f"subtree has {scans} file scans (need exactly 1)"
    return None


def _clone_with_delta_scan(node, new_by_cum: Dict[tuple, List[str]]):
    """Shallow-clone a cumulative exchange's child subtree with its
    (single, row-local) scan leaf swapped to the DELTA files — the
    delta executes under the cumulative plan's exact shape and
    partitioning, so its frames drop straight into the merged
    checkpoint.  ``new_by_cum`` maps a source's cumulative file tuple
    (how the tick pinned it) to that source's new-file suffix."""
    import copy

    from ..io.scans import FileScanExec, file_fingerprint

    if isinstance(node, FileScanExec):
        delta = new_by_cum.get(tuple(node.files))
        if delta is None:
            raise ValueError(
                "scan file list does not match a stream source")
        clone = copy.copy(node)
        clone.files = list(delta)
        clone.file_fingerprints = [file_fingerprint(p) for p in delta]
        clone.n_partitions = max(1, len(delta))
        clone.part_values = [{} for _ in delta]
        return clone
    clone = copy.copy(node)
    clone.children = [_clone_with_delta_scan(c, new_by_cum)
                      for c in node.children]
    return clone


def execute_delta_frames(conf, exchange_node,
                         new_by_cum: Dict[tuple, List[str]]):
    """Run a merge-eligible exchange subtree over the DELTA files on
    the host operator path and return its serialized partition frames
    ``frames[p] = [(uint8 frame, rows)]`` — the exact shape
    ``CheckpointStore.write_exchange`` persists.  Mirrors the host
    ``ShuffleExchangeExec`` store loop (and uses the CUMULATIVE plan's
    bound partitioning) so merged and cold checkpoints are
    indistinguishable."""
    import numpy as np

    from ..native import serializer
    from ..plan.physical import ExecContext

    ctx = ExecContext(conf, None)
    child = _clone_with_delta_scan(exchange_node.children[0], new_by_cum)
    data = child.execute(ctx)
    part = exchange_node.partitioning  # bound at planning time
    part.prepare(data, child.schema)
    n_out = exchange_node.n_out
    store: List[List[object]] = [[] for _ in range(n_out)]
    for pid in range(data.n_partitions):
        check_cancel("streaming.delta")
        for batch in data.iterator(pid):
            if batch.num_rows == 0:
                continue
            pids = part.partition_ids(batch)
            for out_pid in range(n_out):
                sel = np.nonzero(pids == out_pid)[0]
                if len(sel):
                    store[out_pid].append(batch.take(sel))
    frames = [[(serializer.serialize(b), b.num_rows) for b in plist]
              for plist in store]
    return frames


def _repartition_frames(base, schema, partitioning, new_n: int):
    """Re-split a committed base's frames across a GROWN fan-out using
    the cumulative plan's (content-addressed) partitioning.  Only
    called for partial-aggregate exchanges: there every group's rows —
    ≤1 per input file — live in exactly one old partition (hashed by
    group key) and stay in file order through the stable re-split, so
    per-group merge order matches a cold recompute bit for bit."""
    import numpy as np

    from ..native import serializer

    out: List[List[object]] = [[] for _ in range(new_n)]
    for plist in base:
        check_cancel("streaming.repartition")
        for frame, _rows in plist:
            batch = serializer.deserialize(frame, schema)
            pids = partitioning.partition_ids(batch)
            for p in range(new_n):
                sel = np.nonzero(pids == p)[0]
                if len(sel):
                    out[p].append(batch.take(sel))
    return [[(serializer.serialize(b), b.num_rows) for b in plist]
            for plist in out]


def load_committed_frames(store, stream_fp: str, old_fp: str, *,
                          schema_sig: List[str],
                          conf_snapshot: Dict[str, str]):
    """Load the previous tick's committed frames for one exchange with
    the SAME paranoid validation as ``RecoveryManager.try_resume``
    (fingerprints, schema, conf snapshot, every frame CRC) — a merge
    built on a doubtful base would poison every later tick.  Raises on
    any invalidity (the caller skips the merge).  Returns
    ``(frames, old_n)`` with ``frames[p] = [(frame, rows)]`` ready to
    append delta frames to."""
    d = store.exchange_dir(stream_fp, old_fp)
    m = store.read_manifest(d)
    if m.get("plan_fingerprint") != old_fp:
        raise ValueError("stale plan fingerprint on committed base")
    if m.get("query_fingerprint") != stream_fp:
        raise ValueError("stream fingerprint mismatch on committed base")
    if m.get("schema") != list(schema_sig):
        raise ValueError("schema signature changed since last tick")
    if m.get("conf") != conf_snapshot:
        raise ValueError("result-affecting conf changed since last tick")
    old_n = int(m.get("n_out", -1))
    if old_n <= 0:
        raise ValueError(f"bad committed fan-out: {old_n}")
    frames = store.load_frames(d, m, old_n)  # CRC-verified eagerly
    rows: List[List[int]] = [[] for _ in range(old_n)]
    for rec in m["frames"]:  # same order load_frames appended in
        rows[int(rec["partition"])].append(int(rec["rows"]))
    return [list(zip(frames[p], rows[p])) for p in range(old_n)], old_n


def merge_growing_exchanges(mgr: StreamRecoveryManager,
                            new_by_cum: Dict[tuple, List[str]],
                            prev_exchanges: Dict[str, str]) -> int:
    """The incremental core of one tick: for every exchange occurrence
    whose fingerprint moved since the last committed batch, append the
    delta subtree's frames to the committed base and checkpoint the
    merge under the NEW fingerprint — the cumulative query then resumes
    it instead of recomputing history.  Returns how many exchanges were
    merged; every non-merge emits ``stream_incremental_skip`` with its
    reason.  Never fails the tick: a skipped merge just recomputes."""
    if mgr.query_fp is None or not (mgr.write_enabled
                                    and mgr.resume_enabled):
        return 0
    cum_occ = _exchange_occurrences(mgr.host_phys)
    merged = 0
    for (norm, idx), node in cum_occ.items():
        check_cancel("streaming.merge")
        cur_fp = mgr.occ_fps.get((norm, idx))
        old_fp = prev_exchanges.get(occurrence_key(norm, idx))
        if cur_fp is None or old_fp is None or cur_fp == old_fp:
            continue  # unseen / brand new / untouched — nothing to merge
        if mgr.store.has_manifest(mgr.query_fp, cur_fp):
            continue  # a crashed tick already merged this — idempotent
        reason = incremental_safe(node)
        if reason is not None:
            emit_event("stream_incremental_skip",
                       exchange=occurrence_key(norm, idx), reason=reason)
            continue
        try:
            sig = schema_signature(node.schema)
            n_out = node.partitioning.num_partitions
            base, old_n = load_committed_frames(
                mgr.store, mgr.query_fp, old_fp, schema_sig=sig,
                conf_snapshot=mgr._conf_snapshot)
            if old_n != n_out:
                # the planner grew the fan-out with the file count; a
                # re-split preserves per-group order only when groups
                # are file-unique — i.e. under a partial aggregate
                if type(node.children[0]).__name__ \
                        != "HashAggregateExec":
                    raise ValueError(
                        f"fan-out grew {old_n} -> {n_out} on a "
                        "non-aggregate exchange")
                base = _repartition_frames(
                    base, node.schema, node.partitioning, n_out)
            delta = execute_delta_frames(mgr.conf, node, new_by_cum)
            frames = [base[p] + delta[p] for p in range(n_out)]
            written = mgr.checkpoint_exchange(
                cur_fp, schema_sig=sig, n_out=n_out,
                part_rows=[sum(r for _f, r in plist)
                           for plist in frames],
                total_bytes=sum(int(f.nbytes)
                                for plist in frames for f, _r in plist),
                partitioning=type(node.partitioning).__name__,
                frames=frames)
            if written > 0:
                merged += 1
                emit_event(
                    "stream_incremental_merge",
                    exchange=occurrence_key(norm, idx),
                    partitions=n_out,
                    delta_rows=int(sum(r for plist in delta
                                       for _f, r in plist)),
                    bytes=int(written))
            else:
                emit_event("stream_incremental_skip",
                           exchange=occurrence_key(norm, idx),
                           reason="checkpoint write declined")
        except TpuQueryCancelled:
            raise
        except Exception as e:  # noqa: BLE001 - recompute, never fail
            emit_event("stream_incremental_skip",
                       exchange=occurrence_key(norm, idx),
                       reason=f"{type(e).__name__}: {e}")
            log.warning("incremental merge of exchange %s#%d skipped "
                        "(%s: %s) — recomputing", norm.splitlines()[0],
                        idx, type(e).__name__, e)
    return merged
