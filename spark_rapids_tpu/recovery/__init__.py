"""Stage-level checkpointing & crash recovery.

Durable checkpoints at exchange materialization points — the natural
recovery boundary Theseus-class engines exploit (PAPERS.md): a shuffle
write that finished is a complete, partition-addressed artifact, so a
retry, a lower degradation-ladder rung, or an entirely fresh process
can resume from it instead of re-running the whole query.

* :mod:`spark_rapids_tpu.recovery.store` — the on-disk layout:
  CRC32C-stamped partition frames (the spill frame format) plus an
  atomically written JSON manifest per exchange.  Pure
  filesystem/numpy code — NO jax (lint-enforced), so a crashed device
  process's checkpoints are readable by any rung, CPU included.
* :mod:`spark_rapids_tpu.recovery.manager` — policy: plan/query
  fingerprints, resume validation (manifest + CRC + conf snapshot,
  quarantine on ANY doubt), checkpoint writes, hygiene sweeps.
"""
from .manager import RecoveryManager, sweep_recovery_dir  # noqa: F401
from .store import CheckpointStore  # noqa: F401
