"""Hierarchical query spans — query -> stage -> exec -> attempt.

Reference analogue: NvtxWithMetrics coupling every hot-path range with
a SQLMetric, widened into an explicit span tree so a query profile can
say WHERE wall time went (per exec, per stage, per recovery attempt)
instead of only how much there was in total.

Binding discipline: :meth:`QueryTelemetry.begin` binds the query's
telemetry to the CREATING thread only.  Worker threads (task pools,
prefetch producers, stage watchdogs, multiprocess drains, samplers)
never inherit thread-locals, so every thread-spawn site must
:func:`capture` the binding before spawning and run the worker body
under :func:`attached` (or wrap the target with :func:`bound`) — the
same discipline a query-governor ``activate(current_query())`` binding
uses, and composable with one when a ``governor`` package is present
(capture both, attach both).  The ``thread-capture`` analysis rule
enforces the capture at the AST level for every thread-spawn site in
the package.

Cost model: with ``telemetry.enabled=false`` nothing here is reachable
beyond a thread-local ``getattr`` returning ``None`` — no spans, no
ring, no sink, no sampler.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

_tl = threading.local()

_query_counter = itertools.count(1)


# ==========================================================================
# Span
# ==========================================================================
class Span:
    """One node of the span tree.  Counters are additive and
    thread-safe (pool workers of one exec update concurrently)."""

    __slots__ = ("span_id", "name", "kind", "parent_id", "start_ns",
                 "end_ns", "attrs", "rows", "batches", "bytes",
                 "device_sync_ns", "range_ns", "children", "_lock")

    def __init__(self, span_id: int, name: str, kind: str,
                 parent_id: Optional[int] = None, attrs: Optional[Dict] = None):
        self.span_id = span_id
        self.name = name
        self.kind = kind
        self.parent_id = parent_id
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.attrs = dict(attrs or {})
        self.rows = 0
        self.batches = 0
        self.bytes = 0
        self.device_sync_ns = 0
        #: aggregated trace_range wall per range name (outermost
        #: occurrence only — re-entrant ranges do not double count)
        self.range_ns: Dict[str, int] = {}
        self.children: List["Span"] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def add(self, rows: int = 0, batches: int = 0, nbytes: int = 0,
            device_sync_ns: int = 0) -> None:
        with self._lock:
            self.rows += rows
            self.batches += batches
            self.bytes += nbytes
            self.device_sync_ns += device_sync_ns

    def add_range(self, name: str, elapsed_ns: int) -> None:
        with self._lock:
            self.range_ns[name] = self.range_ns.get(name, 0) + elapsed_ns

    def finish(self) -> None:
        if self.end_ns is None:
            self.end_ns = time.perf_counter_ns()

    @property
    def wall_ns(self) -> int:
        end = self.end_ns if self.end_ns is not None \
            else time.perf_counter_ns()
        return max(0, end - self.start_ns)

    # ------------------------------------------------------------------
    def to_dict(self) -> Dict:
        """Nested plain-dict form (profile rendering / JSON export)."""
        return {
            "name": self.name,
            "kind": self.kind,
            "wall_ns": self.wall_ns,
            "rows": self.rows,
            "batches": self.batches,
            "bytes": self.bytes,
            "device_sync_ns": self.device_sync_ns,
            "ranges": dict(self.range_ns),
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }

    def __repr__(self):  # pragma: no cover
        return f"Span({self.kind}:{self.name}, wall={self.wall_ns}ns)"


# ==========================================================================
# Per-query telemetry
# ==========================================================================
class QueryTelemetry:
    """Everything one query's observability owns: the span tree, the
    event log, and (optionally) the HBM sampler.  Created per query by
    ``ExecContext`` when ``telemetry.enabled`` is on; finished exactly
    once by ``Session._finalize_metrics``."""

    def __init__(self, conf, session=None, query_id: Optional[str] = None):
        from ..config import (TELEMETRY_EVENT_LOG_DIR, TELEMETRY_MAX_EVENTS,
                              TELEMETRY_SAMPLE_HBM_MS)
        from .events import EventLog

        self.query_id = query_id or \
            f"q{os.getpid()}-{next(_query_counter):04d}"
        self._lock = threading.Lock()
        self._next_span_id = itertools.count(1)
        self.root = Span(0, self.query_id, "query")
        self.events = EventLog(
            self.query_id,
            max_events=max(1, conf.get(TELEMETRY_MAX_EVENTS)),
            sink_dir=conf.get(TELEMETRY_EVENT_LOG_DIR) or "")
        #: exec-name -> Span (one span per physical exec name; execs of
        #: the same class share a metrics prefix, so they share a span)
        self._exec_spans: Dict[str, Span] = {}
        self.finished = False
        self.hbm_timeline: List[Tuple[float, int, int]] = []
        self._sampler = None
        sample_ms = conf.get(TELEMETRY_SAMPLE_HBM_MS)
        dm = getattr(session, "device_manager", None) \
            if session is not None else None
        if sample_ms and sample_ms > 0 and dm is not None:
            from .export import HbmSampler

            self._sampler = HbmSampler(dm, sample_ms)

    # ------------------------------------------------------------------
    @classmethod
    def begin(cls, conf, session=None) -> Optional["QueryTelemetry"]:
        """Per-query entry point: returns an ACTIVATED telemetry object
        when ``telemetry.enabled`` is on, else clears any stale binding
        left by a previous query and returns None (a disabled query
        must never append late events to a finished predecessor)."""
        from ..config import TELEMETRY_ENABLED

        if not conf.get(TELEMETRY_ENABLED):
            deactivate()
            return None
        tele = cls(conf, session=session)
        activate(tele)
        tele.events.emit("query_begin", query=tele.query_id)
        if tele._sampler is not None:
            tele._sampler.start()
        return tele

    # ------------------------------------------------------------------
    def start_span(self, name: str, kind: str = "span",
                   parent: Optional[Span] = None,
                   attrs: Optional[Dict] = None) -> Span:
        parent = parent or current_span() or self.root
        sp = Span(next(self._next_span_id), name, kind,
                  parent_id=parent.span_id, attrs=attrs)
        with self._lock:
            parent.children.append(sp)
        return sp

    def exec_span(self, name: str) -> Span:
        """The (deduplicated) exec-kind span for one physical exec
        name; wall/rows/batches are back-filled from the exec's metrics
        at :meth:`finish` so the hot path never touches the span."""
        with self._lock:
            sp = self._exec_spans.get(name)
            if sp is None:
                parent = current_span() or self.root
                sp = Span(next(self._next_span_id), name, "exec",
                          parent_id=parent.span_id)
                parent.children.append(sp)
                self._exec_spans[name] = sp
            return sp

    # ------------------------------------------------------------------
    def _fill_exec_spans(self, metrics: Dict[str, int]) -> None:
        """Back-fill exec spans from the query metric snapshot (the
        per-exec registries use a ``<ExecName>.`` prefix)."""
        for name, sp in self._exec_spans.items():
            prefix = name + "."
            sp.rows = int(metrics.get(prefix + "numOutputRows", sp.rows))
            sp.batches = int(
                metrics.get(prefix + "numOutputBatches", sp.batches))
            wall = metrics.get(prefix + "totalTime")
            if wall is not None:
                sp.end_ns = sp.start_ns + int(wall)
            sync = metrics.get(prefix + "deviceSyncTime")
            if sync is not None:
                sp.device_sync_ns = int(sync)
            sp.finish()

    def finish(self, metrics: Optional[Dict[str, int]] = None,
               plan=None):
        """End the query span, stop the sampler, emit ``query_end`` and
        build the :class:`~.profile.QueryProfile`.  Idempotent (the
        first call wins); safe to call with the query binding still
        active — late events (a degrade decision taken above this
        layer) keep landing in the same ring/sink."""
        from .profile import QueryProfile

        if self.finished:
            return None
        self.finished = True
        if self._sampler is not None:
            self._sampler.stop()
            self.hbm_timeline = self._sampler.timeline()
        metrics = dict(metrics or {})
        self._fill_exec_spans(metrics)
        self.root.finish()
        self.events.emit("query_end", query=self.query_id,
                         wall_ms=round(self.root.wall_ns / 1e6, 3))
        return QueryProfile(self, metrics=metrics, plan=plan)


# ==========================================================================
# Thread-local binding
# ==========================================================================
def activate(tele: QueryTelemetry) -> None:
    _tl.telemetry = tele
    _tl.stack = [tele.root]
    _tl.ranges = []


def deactivate() -> None:
    _tl.telemetry = None
    _tl.stack = None
    _tl.ranges = None


def current() -> Optional[QueryTelemetry]:
    return getattr(_tl, "telemetry", None)


def current_span() -> Optional[Span]:
    stack = getattr(_tl, "stack", None)
    return stack[-1] if stack else None


# ----- worker-thread propagation ------------------------------------------
def capture():
    """Capture the caller's per-query execution binding for a worker
    thread: the telemetry binding PLUS the scheduler's cancel token
    and per-query scoped fault/OOM injectors (all thread-local), so
    every pool/watchdog/prefetch spawn site propagates cancellation
    and failure isolation for free.  Returns None when nothing is
    bound — attach is then a no-op.  Every thread-spawn site in the
    package must call this BEFORE spawning and bind the worker body
    with :func:`attached`/:func:`bound`."""
    from ..fault import injector as _finj
    from ..memory import retry as _retry
    from ..scheduler import cancel as _cancel

    tele = current()
    token = _cancel.current()
    oom_inj = _retry.get_scoped_injector()
    fault_inj = _finj.get_scoped_fault_injector()
    if tele is None and token is None and oom_inj is None \
            and fault_inj is None:
        return None
    parent = current_span() if tele is not None else None
    return (tele, parent, token, oom_inj, fault_inj)


@contextmanager
def attached(cap):
    """Bind a captured execution context to the current (worker)
    thread for the duration of the block; restores the previous
    binding on exit (re-entrant)."""
    if cap is None:
        yield
        return
    from ..fault import injector as _finj
    from ..memory import retry as _retry
    from ..scheduler import cancel as _cancel

    tele, parent, token, oom_inj, fault_inj = cap
    prev_t = getattr(_tl, "telemetry", None)
    prev_s = getattr(_tl, "stack", None)
    prev_r = getattr(_tl, "ranges", None)
    prev_tok = _cancel.current()
    prev_oom = _retry.get_scoped_injector()
    prev_flt = _finj.get_scoped_fault_injector()
    if tele is not None:
        _tl.telemetry = tele
        _tl.stack = [parent or tele.root]
        _tl.ranges = []
    _cancel.activate(token)
    _retry.bind_scoped_injector(oom_inj)
    _finj.bind_scoped_fault_injector(fault_inj)
    try:
        yield
    finally:
        if tele is not None:
            _tl.telemetry = prev_t
            _tl.stack = prev_s
            _tl.ranges = prev_r
        _cancel.activate(prev_tok)
        _retry.bind_scoped_injector(prev_oom)
        _finj.bind_scoped_fault_injector(prev_flt)


def bound(cap, fn):
    """Wrap ``fn`` so it runs under :func:`attached` — the convenience
    form for ``Thread(target=...)`` / ``pool.map`` call sites."""
    if cap is None:
        return fn

    def _runner(*args, **kwargs):
        with attached(cap):
            return fn(*args, **kwargs)

    return _runner


# ----- scoped spans --------------------------------------------------------
@contextmanager
def span(name: str, kind: str = "span", **attrs):
    """Exception-safe scoped span under the current thread's binding;
    yields None (and costs one thread-local getattr) when telemetry is
    inactive."""
    tele = current()
    if tele is None:
        yield None
        return
    sp = tele.start_span(name, kind, attrs=attrs or None)
    stack = getattr(_tl, "stack", None)
    if stack is None:
        stack = _tl.stack = [tele.root]
    stack.append(sp)
    try:
        yield sp
    finally:
        if stack and stack[-1] is sp:
            stack.pop()
        sp.finish()


# ----- trace_range coupling ------------------------------------------------
def push_range(name: str):
    """Range-stack push for ``utils.tracing.trace_range`` (re-entrant,
    thread-local): returns an opaque token, or None when inactive."""
    tele = current()
    if tele is None:
        return None
    st = getattr(_tl, "ranges", None)
    if st is None:
        st = _tl.ranges = []
    reentrant = name in st
    st.append(name)
    return (name, reentrant)


def pop_range(token, elapsed_ns: int) -> None:
    """Range-stack pop: attributes the elapsed wall of the OUTERMOST
    occurrence of a range name to the current span (re-entrant ranges
    never double count)."""
    if token is None:
        return
    st = getattr(_tl, "ranges", None)
    if st:
        st.pop()
    name, reentrant = token
    if reentrant:
        return
    sp = current_span()
    if sp is None:
        tele = current()
        sp = tele.root if tele is not None else None
    if sp is not None:
        sp.add_range(name, elapsed_ns)


def register_exec(node) -> None:
    """exec/base.py hook: one exec-kind span per physical exec name
    under the active query (no-op when telemetry is inactive)."""
    tele = current()
    if tele is not None:
        tele.exec_span(node.name)
