"""String expressions.

Capability parity with the reference's stringFunctions.scala: Upper, Lower,
InitCap, StringLocate, Substring, SubstringIndex, StringReplace, Trim
family, StartsWith, EndsWith, Contains, Concat, Like, RegExpReplace,
Length.

Device path: ops with static output width run on the fixed-width byte
matrix (kernels/stringkernels.py).  Regex-class ops (Like, RegExpReplace,
InitCap, SubstringIndex, StringReplace) evaluate on the host engine only —
the same bail-out the reference takes for unsupported regex escapes
(GpuOverrides.scala:326-371).
"""
from __future__ import annotations

import re
from typing import Optional

import numpy as np

from .. import types as T
from ..data.column import DeviceColumn, HostColumn
from .expression import (
    Expression,
    Literal,
    Scalar,
    as_device_column,
    as_host_column,
)
from .kernels import stringkernels as sk


def _host_str_map(col: HostColumn, fn) -> np.ndarray:
    n = col.num_rows
    out = np.empty(n, dtype=object)
    valid = col.is_valid()
    for i in range(n):
        if valid[i] and col.data[i] is not None:
            out[i] = fn(col.data[i])
    return out


class _StrUnary(Expression):
    """String->string unary with host fn + optional device kernel."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return T.STRING

    def host_fn(self, s: str) -> str:
        raise NotImplementedError

    def device_kernel(self, bm, lengths):
        return None

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        return HostColumn(T.STRING, _host_str_map(c, self.host_fn),
                          c.validity)

    def eval_tpu(self, batch):
        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        result = self.device_kernel(c.data, c.lengths)
        if result is None:
            raise NotImplementedError
        bm, ln = result
        return DeviceColumn(T.STRING, bm, c.validity, ln)

    @property
    def tpu_supported(self):
        try:
            import jax.numpy as jnp  # noqa: F401

            probe = self.device_kernel.__func__ is not _StrUnary.device_kernel
        except Exception:  # noqa: BLE001
            probe = False
        return probe


class Upper(_StrUnary):
    """ASCII uppercase on device (documented incompat for non-ASCII,
    mirroring the reference's incompat annotation on cudf upper)."""

    def host_fn(self, s):
        return s.upper()

    def device_kernel(self, bm, lengths):
        return sk.upper(bm, lengths)


class Lower(_StrUnary):
    def host_fn(self, s):
        return s.lower()

    def device_kernel(self, bm, lengths):
        return sk.lower(bm, lengths)


class InitCap(_StrUnary):
    def host_fn(self, s):
        return " ".join(w[:1].upper() + w[1:].lower() if w else w
                        for w in s.split(" "))


class StringTrim(_StrUnary):
    side = "both"

    def host_fn(self, s):
        if self.side == "both":
            return s.strip(" ")
        return s.lstrip(" ") if self.side == "left" else s.rstrip(" ")

    def device_kernel(self, bm, lengths):
        return sk.trim_ws(bm, lengths, bm.shape[1],
                          left=self.side in ("both", "left"),
                          right=self.side in ("both", "right"))


class StringTrimLeft(StringTrim):
    side = "left"


class StringTrimRight(StringTrim):
    side = "right"


class Length(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return T.INT32

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        n = c.num_rows
        out = np.zeros(n, dtype=np.int32)
        valid = c.is_valid()
        for i in range(n):
            if valid[i] and c.data[i] is not None:
                out[i] = len(c.data[i])
        return HostColumn(T.INT32, out, c.validity)

    def eval_tpu(self, batch):
        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        return DeviceColumn(T.INT32, sk.length(c.data, c.lengths),
                            c.validity)


class Substring(Expression):
    """substring(str, pos, len) — pos is 1-based; 0 behaves like 1;
    negative counts from the end (Spark semantics)."""

    def __init__(self, child, pos: int, length: Optional[int] = None):
        super().__init__([child])
        self.pos = int(pos)
        self.length = int(length) if length is not None else None

    @property
    def dtype(self):
        return T.STRING

    def _py(self, s: str) -> str:
        pos, ln = self.pos, self.length
        if pos > 0:
            start = pos - 1
        elif pos == 0:
            start = 0
        else:
            start = max(len(s) + pos, 0)
        end = len(s) if ln is None else start + max(ln, 0)
        return s[start:end]

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        return HostColumn(T.STRING, _host_str_map(c, self._py), c.validity)

    def eval_tpu(self, batch):
        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        start = self.pos - 1 if self.pos > 0 else (0 if self.pos == 0
                                                   else self.pos)
        ln = self.length if self.length is not None else c.data.shape[1]
        out_w = min(max(ln, 1), c.data.shape[1])
        bm, lens = sk.substring(c.data, c.lengths, start, ln, out_w)
        return DeviceColumn(T.STRING, bm, c.validity, lens)

    @property
    def tpu_supported(self):
        # byte==char only for ASCII; multibyte falls back (documented)
        return True


class SubstringIndex(Expression):
    def __init__(self, child, delim: str, count: int):
        super().__init__([child])
        self.delim = delim
        self.count = count

    @property
    def dtype(self):
        return T.STRING

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)

        def fn(s):
            parts = s.split(self.delim)
            if self.count > 0:
                return self.delim.join(parts[: self.count])
            if self.count < 0:
                return self.delim.join(parts[self.count:])
            return ""

        return HostColumn(T.STRING, _host_str_map(c, fn), c.validity)

    def eval_tpu(self, batch):
        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        bm, ln = sk.substring_index(
            c.data, c.lengths, self.delim.encode("utf-8"), self.count)
        return DeviceColumn(T.STRING, bm, c.validity, ln)

    @property
    def tpu_supported(self):
        # single-byte delimiters cannot self-overlap, so the device
        # match-count kernel is exact vs str.split; multi-byte
        # delimiters stay on host
        return len(self.delim.encode("utf-8")) == 1 and \
            self.children[0].tpu_supported


class StringReplace(Expression):
    def __init__(self, child, search: str, replace: str):
        super().__init__([child])
        self.search = search
        self.replace = replace

    @property
    def dtype(self):
        return T.STRING

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        return HostColumn(
            T.STRING,
            _host_str_map(c, lambda s: s.replace(self.search, self.replace)),
            c.validity)

    def eval_tpu(self, batch):
        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        bm, ln = sk.replace_single(c.data, c.lengths,
                                   self.search.encode("utf-8"),
                                   self.replace.encode("utf-8"))
        return DeviceColumn(T.STRING, bm, c.validity, ln)

    @property
    def tpu_supported(self):
        # a single search byte cannot self-overlap -> exact on device;
        # longer patterns stay on host
        return len(self.search.encode("utf-8")) == 1 and \
            self.children[0].tpu_supported


class _NeedlePredicate(Expression):
    """contains/startswith/endswith with literal needle."""

    kernel = None  # set in subclass
    py_fn = None

    def __init__(self, child, needle):
        super().__init__([child, needle if isinstance(needle, Expression)
                          else Literal(needle, T.STRING)])

    @property
    def dtype(self):
        return T.BOOL

    def _needle(self) -> Optional[str]:
        n = self.children[1]
        if isinstance(n, Literal):
            return n.value
        return None

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        needle = self._needle()
        n = c.num_rows
        out = np.zeros(n, dtype=np.bool_)
        valid = c.is_valid()
        for i in range(n):
            if valid[i] and c.data[i] is not None:
                out[i] = type(self).py_fn(c.data[i], needle)
        return HostColumn(T.BOOL, out, c.validity)

    def eval_tpu(self, batch):
        needle = self._needle()
        if needle is None:
            raise NotImplementedError("non-literal needle")
        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        data = type(self).kernel(c.data, c.lengths, needle.encode("utf-8"))
        return DeviceColumn(T.BOOL, data, c.validity)

    @property
    def tpu_supported(self):
        return self._needle() is not None


class Contains(_NeedlePredicate):
    kernel = staticmethod(sk.contains)
    py_fn = staticmethod(lambda s, n: n in s)


class StartsWith(_NeedlePredicate):
    kernel = staticmethod(sk.startswith)
    py_fn = staticmethod(lambda s, n: s.startswith(n))


class EndsWith(_NeedlePredicate):
    kernel = staticmethod(sk.endswith)
    py_fn = staticmethod(lambda s, n: s.endswith(n))


class StringLocate(Expression):
    """locate(substr, str, pos) — 1-based, 0 when absent."""

    def __init__(self, substr: str, child, pos: int = 1):
        super().__init__([child])
        self.substr = substr
        self.pos = pos

    @property
    def dtype(self):
        return T.INT32

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        n = c.num_rows
        out = np.zeros(n, dtype=np.int32)
        valid = c.is_valid()
        for i in range(n):
            if valid[i] and c.data[i] is not None:
                out[i] = c.data[i].find(self.substr, self.pos - 1) + 1
        return HostColumn(T.INT32, out, c.validity)

    def eval_tpu(self, batch):
        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        data = sk.locate(c.data, c.lengths, self.substr.encode("utf-8"),
                         self.pos)
        return DeviceColumn(T.INT32, data, c.validity)


class ConcatStrings(Expression):
    def __init__(self, exprs):
        super().__init__(list(exprs))

    @property
    def dtype(self):
        return T.STRING

    def eval_cpu(self, batch):
        n = batch.num_rows
        cols = [as_host_column(e.eval_cpu(batch), n) for e in self.children]
        out = np.empty(n, dtype=object)
        validity = np.ones(n, dtype=np.bool_)
        for c in cols:
            validity &= c.is_valid()
        for i in range(n):
            if validity[i]:
                out[i] = "".join(str(c.data[i]) for c in cols)
        return HostColumn(T.STRING, out,
                          None if validity.all() else validity)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        n = batch.padded_rows
        cols = [as_device_column(e.eval_tpu(batch), n)
                for e in self.children]
        bm, ln = sk.concat([(c.data, c.lengths) for c in cols])
        validity = jnp.ones((n,), dtype=jnp.bool_)
        for c in cols:
            validity = validity & c.validity
        return DeviceColumn(T.STRING, bm, validity, ln)


class Like(Expression):
    """SQL LIKE with literal pattern.

    Device path (reference: the cudf regex translation with escape
    bail-outs, stringFunctions.scala Like + rules
    GpuOverrides.scala:326-371): patterns built only from literal text
    and ``%`` lower onto the byte-matrix kernels — prefix/suffix/
    contains and the general multi-``%`` shape via greedy leftmost
    segment matching (correct for ``%`` because it matches any length).
    Patterns using ``_`` (single-char, character-based) bail out to the
    exact host regex, mirroring the reference's bail-outs.  Byte-level
    segment matching is exact for valid UTF-8 (self-synchronizing: a
    valid segment cannot match starting mid-character)."""

    def __init__(self, child, pattern: str, escape: str = "\\"):
        super().__init__([child])
        self.pattern = pattern
        self.escape = escape
        self._re = re.compile(self._to_regex(pattern, escape), re.DOTALL)
        self._match = self._re.match  # LIKE regex is ^…$-anchored
        self._segs = self._parse_segments(pattern, escape)

    @staticmethod
    def _parse_segments(pattern: str, escape: str):
        """Split into literal byte segments on unescaped ``%``.
        Returns None when the pattern uses ``_`` — host regex only."""
        segs, cur, i = [], [], 0
        while i < len(pattern):
            ch = pattern[i]
            if ch == escape and i + 1 < len(pattern):
                cur.append(pattern[i + 1])
                i += 2
                continue
            if ch == "%":
                segs.append("".join(cur))
                cur = []
            elif ch == "_":
                return None
            else:
                cur.append(ch)
            i += 1
        segs.append("".join(cur))
        return [s.encode("utf-8") for s in segs]

    @staticmethod
    def _to_regex(pattern: str, escape: str) -> str:
        out, i = ["^"], 0
        while i < len(pattern):
            ch = pattern[i]
            if ch == escape and i + 1 < len(pattern):
                out.append(re.escape(pattern[i + 1]))
                i += 2
                continue
            if ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(re.escape(ch))
            i += 1
        out.append("$")
        return "".join(out)

    @property
    def dtype(self):
        return T.BOOL

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        n = c.num_rows
        out = np.zeros(n, dtype=np.bool_)
        valid = c.is_valid()
        for i in range(n):
            if valid[i] and c.data[i] is not None:
                out[i] = self._match(c.data[i]) is not None
        return HostColumn(T.BOOL, out, c.validity)

    def eval_tpu(self, batch):
        import jax.numpy as jnp

        c = as_device_column(self.children[0].eval_tpu(batch),
                             batch.padded_rows)
        bm, ln = c.data, c.lengths
        segs = self._segs
        n = bm.shape[0]
        if len(segs) == 1:
            # no wildcard at all: exact (length + prefix) equality
            needle = segs[0]
            ok = sk.startswith(bm, ln, needle) & (ln == len(needle))
            return DeviceColumn(T.BOOL, ok, c.validity)
        first, last, mids = segs[0], segs[-1], segs[1:-1]
        ok = (sk.startswith(bm, ln, first) if first
              else jnp.ones((n,), dtype=jnp.bool_))
        cursor = jnp.full((n,), len(first), dtype=jnp.int32)
        for seg in mids:
            if not seg:
                continue
            pos1 = sk.locate_from(bm, ln, seg, cursor)
            ok = ok & (pos1 > 0)
            cursor = jnp.where(pos1 > 0, pos1 - 1 + len(seg), cursor)
        if last:
            ok = ok & sk.endswith(bm, ln, last) & \
                (ln - len(last) >= cursor)
        else:
            ok = ok & (ln >= cursor)
        return DeviceColumn(T.BOOL, ok, c.validity)

    @property
    def tpu_supported(self):
        # %-only patterns lower onto the byte-matrix kernels; `_`
        # (character-based) bails out to the host regex
        return self._segs is not None and self.children[0].tpu_supported


class RegExpReplace(Expression):
    def __init__(self, child, pattern: str, replacement: str):
        super().__init__([child])
        self.pattern = pattern
        self.replacement = replacement
        self._re = re.compile(pattern)

    @property
    def dtype(self):
        return T.STRING

    def eval_cpu(self, batch):
        c = as_host_column(self.children[0].eval_cpu(batch), batch.num_rows)
        repl = re.sub(r"\$(\d)", r"\\\1", self.replacement)
        return HostColumn(
            T.STRING,
            _host_str_map(c, lambda s: self._re.sub(repl, s)),
            c.validity)
