"""ML interop export + API validation + generated config docs.

Reference analogues: ColumnarRdd export tests, ApiValidation, and the
generated docs/configs.md.
"""
import numpy as np
import pytest

from spark_rapids_tpu import Session, ml
from spark_rapids_tpu import types as T
from spark_rapids_tpu.plan import functions as F


def _session(export=True):
    conf = {"spark.rapids.tpu.sql.exportColumnarRdd": export}
    return Session(conf)


def _df(sess, n=500):
    rng = np.random.default_rng(0)
    return sess.create_dataframe({
        "k": (np.arange(n) % 11).astype(np.int64),
        "x": rng.random(n),
        "y": rng.random(n).astype(np.float32),
        "s": np.array([f"r{i}" for i in range(n)], dtype=object),
    })


def test_export_requires_conf():
    sess = _session(export=False)
    with pytest.raises(RuntimeError, match="exportColumnarRdd"):
        ml.columnar_batches(_df(sess))


def test_columnar_batches_stay_on_device():
    from spark_rapids_tpu.data.column import DeviceBatch

    sess = _session()
    df = _df(sess).filter(F.col("x") > 0.5)
    batches = ml.columnar_batches(df)
    assert batches and all(isinstance(b, DeviceBatch) for b in batches)
    total = sum(int(b.num_rows) for b in batches)
    assert total == df.count()


def test_feature_matrix_matches_collect():
    sess = _session()
    df = _df(sess)
    X = ml.feature_matrix(df, ["x", "y"])
    assert X.shape == (500, 2) and str(X.dtype) == "float32"
    rows = _df(Session(tpu_enabled=False)).collect()
    np.testing.assert_allclose(
        np.sort(np.asarray(X[:, 0])),
        np.sort(np.array([r[1] for r in rows], dtype=np.float32)),
        rtol=1e-6)


def test_feature_matrix_default_numeric_columns():
    sess = _session()
    X = ml.feature_matrix(_df(sess))  # k, x, y (string col skipped)
    assert X.shape[1] == 3


def test_feature_matrix_drops_null_rows():
    """Rows with a NULL in any selected feature must be dropped, not
    exported as fabricated 0.0 values."""
    sess = _session()
    x = np.array([1.0, 2.0, 3.0, 4.0])
    df = sess.create_dataframe(
        {"x": x, "g": np.array([0, 1, 0, 1])},
        T.Schema([T.Field("x", T.FLOAT64), T.Field("g", T.INT64)]))
    # NaNvl-style trick: make one row null via a conditional expression
    df = df.with_column(
        "x", F.when(F.col("g") == F.lit(1), F.col("x")).end())
    X = ml.feature_matrix(df, ["x"])
    assert X.shape == (2, 1)
    assert sorted(np.asarray(X[:, 0]).tolist()) == [2.0, 4.0]


def test_round_trip_from_device_batches():
    sess = _session()
    df = _df(sess, n=100)
    batches = ml.columnar_batches(df)
    df2 = ml.from_device_batches(sess, batches)
    assert sorted(df.collect()) == sorted(df2.collect())


def test_aggregated_export():
    """Export after an aggregation — peels the transition off a
    multi-stage device plan."""
    sess = _session()
    g = _df(sess).group_by("k").agg(F.sum("x").alias("sx"))
    batches = ml.columnar_batches(g)
    assert sum(int(b.num_rows) for b in batches) == 11


# ===========================================================================
def test_api_validation_clean():
    from spark_rapids_tpu.testing.api_validation import validate

    assert validate() == []


def test_reference_expression_drift_empty():
    """The registry must cover the reference's expr rule table with no
    undocumented gaps (VERDICT r4 item 8); skips when the reference
    tree is absent (end-user installs)."""
    import pytest

    from spark_rapids_tpu.testing.api_validation import (
        reference_expression_drift,
    )

    drift = reference_expression_drift()
    if drift is None:
        pytest.skip("reference tree not available")
    assert drift["missing"] == [], drift["missing"]


def test_config_docs_up_to_date():
    """docs/configs.md must match the registry (regenerate with
    python -c 'from spark_rapids_tpu.plan.overrides import
    _ensure_registry; _ensure_registry(); from spark_rapids_tpu.config
    import dump_markdown; ...')."""
    import os

    from spark_rapids_tpu.config import dump_markdown
    from spark_rapids_tpu.plan.overrides import _ensure_registry

    _ensure_registry()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "configs.md")
    with open(path) as fh:
        on_disk = fh.read()
    assert on_disk == dump_markdown() + "\n", \
        "docs/configs.md is stale — regenerate from the config registry"
