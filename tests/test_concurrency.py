"""Concurrent partition execution (reference: GpuSemaphore.scala:58-98 —
2-4 concurrent tasks per device; docs/tuning-guide.md:85-100).

Partitions are drained by a task thread pool under device-semaphore
admission; results must be identical to sequential execution and the
semaphore must bound concurrent holders.
"""
import threading

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import f
from spark_rapids_tpu.memory.semaphore import DeviceSemaphore


def _norm(rows):
    return sorted(rows, key=repr)


@pytest.mark.parametrize("threads", [1, 4])
def test_concurrent_collect_matches_sequential(threads):
    rng = np.random.RandomState(5)
    data = {"k": rng.randint(0, 30, 2000).tolist(),
            "v": rng.randint(-100, 100, 2000).tolist()}

    sess = srt.Session({"spark.rapids.tpu.sql.taskThreads": threads})
    df = sess.create_dataframe(data, n_partitions=8)
    got = _norm(df.group_by("k").agg(f.sum(df["v"]).alias("s"),
                                     f.count("*").alias("c")).collect())

    ref = srt.Session({"spark.rapids.tpu.sql.taskThreads": 1})
    rdf = ref.create_dataframe(data, n_partitions=8)
    want = _norm(rdf.group_by("k").agg(f.sum(rdf["v"]).alias("s"),
                                       f.count("*").alias("c")).collect())
    assert got == want


def test_concurrent_join_matches_sequential():
    rng = np.random.RandomState(7)
    left = {"k": rng.randint(0, 50, 1500).tolist(),
            "a": list(range(1500))}
    right = {"k": rng.randint(0, 50, 1000).tolist(),
             "b": list(range(1000))}

    def run(threads):
        s = srt.Session({"spark.rapids.tpu.sql.taskThreads": threads})
        l = s.create_dataframe(left, n_partitions=6)
        r = s.create_dataframe(right, n_partitions=6)
        return _norm(l.join(r, on="k", how="left").collect())

    assert run(4) == run(1)


def test_semaphore_bounds_concurrency():
    sem = DeviceSemaphore(2)
    active = []
    peak = []
    lock = threading.Lock()
    barrier = threading.Barrier(6, timeout=10)

    def task():
        barrier.wait()  # all threads contend at once
        sem.acquire_if_necessary()
        sem.acquire_if_necessary()  # reentrant: still one permit
        try:
            with lock:
                active.append(1)
                peak.append(len(active))
            import time

            time.sleep(0.02)
            with lock:
                active.pop()
        finally:
            sem.release_all()

    threads = [threading.Thread(target=task) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert max(peak) <= 2
    assert len(peak) == 6  # every task eventually admitted


def test_release_all_drops_reentrant_hold():
    sem = DeviceSemaphore(1)
    sem.acquire_if_necessary()
    sem.acquire_if_necessary()
    sem.acquire_if_necessary()
    sem.release_all()
    # permit must be back: a fresh acquire succeeds without blocking
    ok = sem._sem.acquire(timeout=1)
    assert ok
    sem._sem.release()
