"""Randomized dual-engine sweep over the device string ops and window
frames (reference analogue: FuzzerUtils.scala + data_gen.py's seeded
adversarial generators).  Each seed drives LIKE patterns (incl. escaped
%), substring_index counts, single-byte replace, and first/last/min
windows over random frames against the host oracle."""
import random

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import f
from spark_rapids_tpu.ops.windowexprs import over, window
from spark_rapids_tpu.testing.asserts import assert_rows_equal


def _rand_strings(rng, n, alphabet, max_len):
    out = []
    for _ in range(n):
        r = rng.random()
        if r < 0.08:
            out.append(None)
        elif r < 0.18:
            out.append("")
        else:
            k = rng.randrange(0, max_len)
            out.append("".join(rng.choice(alphabet) for _ in range(k)))
    return out


def _rand_pattern(rng):
    chars = []
    for _ in range(rng.randrange(0, 6)):
        r = rng.random()
        if r < 0.35:
            chars.append("%")
        elif r < 0.45:
            chars.append("\\%")
        else:
            chars.append(rng.choice("abc.-"))
    return "".join(chars)


@pytest.mark.parametrize("seed", [2, 11, 23, 31])
def test_fuzz_string_and_window_ops(seed):
    rng = random.Random(seed)
    nprng = np.random.RandomState(seed)
    n = rng.choice([63, 128, 300])
    data = {
        "s": _rand_strings(rng, n, rng.choice(["ab.", "abc.-", "x."]),
                           rng.choice([3, 9, 33])),
        "k": [None if nprng.rand() < 0.1 else int(x)
              for x in nprng.randint(0, 5, n)],
        "t": [None if nprng.rand() < 0.05 else int(x)
              for x in nprng.randint(0, 50, n)],
        "v": [None if nprng.rand() < 0.15 else float(x)
              for x in (nprng.rand(n) * 100).round(3)],
    }
    pat = _rand_pattern(rng)
    delim = rng.choice([".", "-", "a"])
    cnt = rng.choice([-3, -1, 0, 1, 2])
    search = rng.choice([".", "-", "a"])
    repl = rng.choice(["", "::", "Z", "xyz"])
    lo = rng.choice([None, -rng.randrange(0, 400)])
    hi = rng.choice([None, rng.randrange(0, 400)])

    def build(sess):
        df = sess.create_dataframe(dict(data))
        q = df.select(
            "s", "k", "t", "v",
            df["s"].like(pat).alias("lk"),
            f.substring_index(df["s"], delim, cnt).alias("si"),
            f.replace(df["s"], search, repl).alias("rp"))
        w = window().partition_by("k").order_by("t")
        if lo is not None or hi is not None:
            w = w.rows_between(lo, 0 if hi is None else hi)
        q = q.with_window("fst", over(f.first("v"), w))
        q = q.with_window("lst", over(f.last("v", ignore_nulls=True), w))
        q = q.with_window("mn", over(f.min("v"), w))
        return q.sort(f.col("t"), f.col("s"))

    got = build(srt.Session()).collect()
    exp = build(srt.Session(tpu_enabled=False)).collect()
    assert_rows_equal(exp, got, ignore_order=True,
                      approximate_float=1e-9)


@pytest.mark.oom_injection
@pytest.mark.parametrize("seed", [7, 29])
def test_fuzz_random_pipeline_under_random_oom_injection(seed):
    """Seeded fuzz: a random expression pipeline (arithmetic /
    conditional / string ops + group-by + sort) executed while the
    fault injector randomly fails allocation checkpoints — recovery
    must be invisible in the results (memory/retry.py)."""
    rng = random.Random(seed)
    nprng = np.random.RandomState(seed)
    n = rng.choice([96, 200])
    data = {
        "k": [int(x) for x in nprng.randint(0, 6, n)],
        "a": [None if nprng.rand() < 0.1 else float(x)
              for x in (nprng.rand(n) * 50).round(3)],
        "b": [int(x) for x in nprng.randint(-20, 20, n)],
        "s": _rand_strings(rng, n, "abc.-", 9),
    }
    c1 = rng.choice(["a", "b"])
    c2 = rng.choice(["a", "b"])
    thresh = float(rng.randrange(-10, 10))
    pat = _rand_pattern(rng)

    def build(sess):
        df = sess.create_dataframe(dict(data))
        q = df.select(
            "k", "s",
            (df[c1] + df[c2]).alias("add"),
            (df["a"] * 2.0 - df["b"]).alias("mix"),
            f.when(df["b"] > thresh, df["a"]).otherwise(
                f.lit(0.0)).alias("cond"),
            df["s"].like(pat).alias("lk"))
        q = q.group_by("k").agg(
            f.sum("add").alias("sa"),
            f.min("mix").alias("mm"),
            f.count("*").alias("c"))
        return q.sort(f.col("k"))

    inject = {
        "spark.rapids.tpu.memory.oomInjection.mode": "random",
        "spark.rapids.tpu.memory.oomInjection.seed": seed,
        "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
        "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
    }
    got = build(srt.Session(inject)).collect()
    exp = build(srt.Session(tpu_enabled=False)).collect()
    assert_rows_equal(exp, got, ignore_order=True,
                      approximate_float=1e-9)
