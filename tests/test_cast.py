"""Device string-cast matrix vs the host oracle.

Reference analogue: GpuCast.scala:30-77 + CastOpSuite / cast_test.py —
string parses (malformed -> NULL), exact X->string formatting, the
conf-gated divergent directions (RapidsConf.scala:373-403), and
randomized round trips.
"""
import random

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import f
from spark_rapids_tpu import types as T
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
)

INTS = ["0", "42", "-7", "+15", " 99 ", "3.7", "-3.7", ".5", "-", "",
        "abc", "9223372036854775807", "-9223372036854775808",
        "9223372036854775808", "-9223372036854775809", "00123", "1.999",
        "127", "128", "-128", "-129", None, "  -42  ", "4 2", "++1",
        "1.", "1.2.3", "12345678901234567890"]

#: the divergence-gated device directions, enabled for kernel tests
#: (reference keeps them off by default, RapidsConf.scala:373-403)
DEVICE_CAST_CONF = {
    "spark.rapids.tpu.sql.castStringToInteger.enabled": True,
    "spark.rapids.tpu.sql.castStringToTimestamp.enabled": True,
}


@pytest.mark.parametrize("to", ["bigint", "int", "smallint", "tinyint"])
def test_string_to_integral(to):
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["s"].cast(to).alias("x"), df["i"]),
        {"s": INTS, "i": list(range(len(INTS)))},
        conf=DEVICE_CAST_CONF)


def test_string_to_bool():
    vals = ["t", "TRUE", "Yes", "y", "1", "f", "False", "no", "N", "0",
            "x", "", " true ", None, "truthy"]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["s"].cast("boolean").alias("x"),
                             df["i"]),
        {"s": vals, "i": list(range(len(vals)))})


def test_string_to_date():
    vals = ["2021-01-15", "1970-01-01", "2100-12-31", "2021-02-29",
            "2020-02-29", "2021-13-01", "2021-00-10", "2021-1-5",
            "2021", "2021-06", "junk", " 2021-03-04 ", "", None,
            "2021-04-31", "0001-01-01", "9999-12-31"]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["s"].cast("date").alias("x"), df["i"]),
        {"s": vals, "i": list(range(len(vals)))},
        conf=DEVICE_CAST_CONF)


def test_string_to_timestamp():
    vals = ["2021-01-15 10:30:00", "2021-01-15T10:30:00",
            "2021-01-15 10:30:00.123456", "2021-01-15 10:30:00.5",
            "2021-01-15 10:30", "2021-01-15 10", "2021-01-15",
            "1969-12-31 23:59:59.999999", "2021-01-15 24:00:00",
            "2021-01-15 10:61:00", "2021-01-15x10:30:00", "", None,
            "2021", "2021-06", "2021-01-15 10:30:61"]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["s"].cast("timestamp").alias("x"),
                             df["i"]),
        {"s": vals, "i": list(range(len(vals)))},
        conf=DEVICE_CAST_CONF)


def test_int_bool_to_string():
    iv = [0, 1, -1, 42, -999999, 2 ** 62, -(2 ** 63), 2 ** 63 - 1,
          None, 123456789]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["v"].cast("string").alias("x"),
                             df["i"]),
        {"v": iv, "i": list(range(len(iv)))})
    bv = [True, False, None, True]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["v"].cast("string").alias("x"),
                             df["i"]),
        {"v": bv, "i": list(range(len(bv)))})


def test_date_timestamp_to_string():
    schema = T.Schema([T.Field("v", T.DATE32), T.Field("i", T.INT64)])
    dv = [0, 18642, -3650, None, 2932896]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["v"].cast("string").alias("x"),
                             df["i"]),
        {"v": dv, "i": list(range(len(dv)))}, schema=schema)
    schema = T.Schema([T.Field("v", T.TIMESTAMP), T.Field("i", T.INT64)])
    tv = [0, 1611700200123456, -1, -86400000001, None,
          1234567890000000]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["v"].cast("string").alias("x"),
                             df["i"]),
        {"v": tv, "i": list(range(len(tv)))}, schema=schema)


def test_string_to_float_gated():
    """string->float runs on device only under the castStringToFloat
    conf (ULP-divergence gate, like the reference)."""
    vals = ["1.5", "-2.25", "1e3", "2.5E-2", "inf", "-Infinity", "NaN",
            "3", ".5", "1e", "x", "", None, "+0.125"]
    conf = {"spark.rapids.tpu.sql.castStringToFloat.enabled": True}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["s"].cast("double").alias("x"),
                             df["i"]),
        {"s": vals, "i": list(range(len(vals)))}, conf=conf)
    # default off: the expression tags to the host engine
    sess = srt.Session()
    df = sess.create_dataframe({"s": ["1.5"]})
    ex = df.select(df["s"].cast("double").alias("x")).explain()
    assert "castStringToFloat" in ex


def test_cast_pipeline_stays_on_device_strict():
    """scan-shaped pipeline: cast(string)->filter->agg never leaves the
    device under strict test mode (VERDICT r4 item 5's done bar)."""
    strict = srt.Session({
        "spark.rapids.tpu.sql.test.enabled": True,
        "spark.rapids.tpu.sql.test.allowedNonTpu": "ShuffleExchangeExec",
        **DEVICE_CAST_CONF,
    })
    df = strict.create_dataframe(
        {"s": ["10", "20", "30", "bad", "40"], "g": [1, 1, 2, 2, 2]})
    out = (df.select(df["s"].cast("bigint").alias("v"), df["g"])
             .filter(f.col("v") > 15)
             .group_by("g").agg(f.sum("v").alias("sv"))).collect()
    assert sorted(out) == [(1, 20), (2, 70)]


@pytest.mark.parametrize("seed", [5, 17])
def test_fuzz_cast_round_trips(seed):
    """Randomized cast round trips: int -> string -> int is the
    identity; random digit-strings parse identically on both engines;
    date -> string -> date round-trips."""
    rng = random.Random(seed)
    n = 300
    ints = [None if rng.random() < 0.1 else
            rng.randrange(-(2 ** 63), 2 ** 63) for _ in range(n)]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            df["v"].cast("string").cast("bigint").alias("x"), df["i"]),
        {"v": ints, "i": list(range(n))}, conf=DEVICE_CAST_CONF)

    def rand_numeric_string():
        r = rng.random()
        if r < 0.1:
            return None
        if r < 0.2:
            return "".join(rng.choice("0123456789abc .-+")
                           for _ in range(rng.randrange(0, 8)))
        s = rng.choice(["", "-", "+"])
        s += "".join(rng.choice("0123456789")
                     for _ in range(rng.randrange(1, 21)))
        if rng.random() < 0.3:
            s += "." + "".join(rng.choice("0123456789")
                               for _ in range(rng.randrange(0, 4)))
        return s

    strs = [rand_numeric_string() for _ in range(n)]
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["s"].cast("bigint").alias("x"),
                             df["i"]),
        {"s": strs, "i": list(range(n))}, conf=DEVICE_CAST_CONF)

    days = [None if rng.random() < 0.1 else rng.randrange(-30000, 80000)
            for _ in range(n)]
    schema = T.Schema([T.Field("v", T.DATE32), T.Field("i", T.INT64)])
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            df["v"].cast("string").cast("date").alias("x"), df["i"]),
        {"v": days, "i": list(range(n))}, schema=schema,
        conf=DEVICE_CAST_CONF)