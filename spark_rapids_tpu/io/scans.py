"""File scans — Parquet / ORC / CSV.

Capability parity with the reference's L5 scan layer (GpuParquetScan.scala,
GpuOrcScan.scala, GpuBatchScanExec.scala CSV): per-file partitions,
row-group batching to the reader size targets
(spark.rapids.tpu.sql.reader.batchSizeRows/Bytes — reference
RapidsConf.scala:295-309), and predicate pushdown hooks.

Host-side decode is pyarrow (the reference re-assembles raw chunks on the
host then device-decodes with cudf; on TPU the host decodes and the device
upload happens at the columnar transition inserted by the rewrite engine).
"""
from __future__ import annotations

import glob as globmod
import os
from typing import List

from .. import types as T
from ..config import READER_BATCH_SIZE_BYTES, READER_BATCH_SIZE_ROWS
from ..data.column import HostBatch
from ..ops import miscexprs
from ..plan import logical as L
from ..plan import physical as P
from . import arrow_convert as ac


#: Spark's directory name for a null partition value (single source of
#: truth — the writers import it from here)
HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"

#: characters escaped in partition directory names (reference:
#: ExternalCatalogUtils.escapePathName) — without this a value
#: containing '/' would silently nest directories and corrupt readback
_PATH_ESCAPE_CHARS = set('"#%\'*/:=?\\{[]^\x7f') | \
    {chr(c) for c in range(0x20)}


def escape_path_name(value: str) -> str:
    return "".join(f"%{ord(ch):02X}" if ch in _PATH_ESCAPE_CHARS else ch
                   for ch in value)


def partition_dir_name(key: str, value) -> str:
    """The canonical ``key=value`` directory segment — THE single
    naming rule both writers (host io/writers.py and device
    exec/write.py) must share, else the same data writes different
    layouts per engine.  Nulls use the Hive sentinel; -0.0 normalizes
    to 0.0 so the two zeros (numerically equal, differently rendered)
    cannot straddle group and name boundaries."""
    import numpy as np

    if value is None:
        return f"{key}={HIVE_NULL}"
    if isinstance(value, (float, np.floating)) and value == 0.0:
        value = type(value)(0.0)
    return f"{key}={escape_path_name(str(value))}"


def unescape_path_name(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        if value[i] == "%" and i + 3 <= len(value):
            try:
                out.append(chr(int(value[i + 1:i + 3], 16)))
                i += 3
                continue
            except ValueError:
                pass
        out.append(value[i])
        i += 1
    return "".join(out)


def expand_paths(paths: List[str]) -> List[str]:
    return discover_files(paths)[0]


def file_fingerprint(path: str) -> dict:
    """Stable identity record of one leaf file — ``path`` plus the
    ``size``/``mtime_ns`` pair a single ``os.stat`` observes (−1/−1
    when the file vanished between listing and stat).  THE shared
    currency between the streaming source ledger and the recovery
    data-material fingerprint: both consume these records, so a file is
    stat-ed exactly once per discovery."""
    try:
        st = os.stat(path)
        return {"path": path, "size": int(st.st_size),
                "mtime_ns": int(st.st_mtime_ns)}
    except OSError:
        return {"path": path, "size": -1, "mtime_ns": -1}


def discover_files(paths: List[str]):
    """Recursive file listing with Hive-partition discovery: files under
    ``key=value`` directories carry those values (reference:
    PartitioningAwareFileIndex + the per-batch constant append in
    ColumnarPartitionReaderWithPartitionValues.scala:96).

    Returns ``(files, part_values, part_keys, fingerprints)`` — per-file
    dicts of raw (string) partition values, the ordered key list (empty
    for flat layouts), and one :func:`file_fingerprint` record per file
    (stat-ed during the walk — discovery is the only stat pass)."""
    files: List[str] = []
    values: List[dict] = []
    fingerprints: List[dict] = []

    def add(path: str, acc) -> None:
        files.append(path)
        values.append(dict(acc))
        fingerprints.append(file_fingerprint(path))

    def walk(d, acc):
        for f in sorted(os.listdir(d)):
            if f.startswith((".", "_")):
                continue
            full = os.path.join(d, f)
            if os.path.isdir(full):
                k, eq, v = f.partition("=")
                walk(full,
                     acc + [(k, unescape_path_name(v))] if eq else acc)
            else:
                add(full, acc)

    for p in paths:
        if os.path.isdir(p):
            walk(p, [])
        elif any(ch in p for ch in "*?["):
            for g in sorted(globmod.glob(p)):
                add(g, [])
        else:
            add(p, [])
    keys: List[str] = []
    for pv in values:
        for k in pv:
            if k not in keys:
                keys.append(k)
    return files, values, keys, fingerprints


def _infer_partition_fields(values: List[dict],
                            keys: List[str]) -> List[T.Field]:
    """Spark-style partition-value type inference: int64 if every value
    parses as an integer, float64 if numeric, else string; the
    HIVE_NULL sentinel is a null of whatever the others infer."""
    fields = []
    for k in keys:
        raw = [pv.get(k) for pv in values]
        present = [v for v in raw if v is not None and v != HIVE_NULL]
        dtype = T.INT64
        for v in present:
            try:
                if not (-(2 ** 63) <= int(v) < 2 ** 63):
                    dtype = None  # out of int64 range: wider type
                    break
            except ValueError:
                dtype = None
                break
        if dtype is None:
            dtype = T.FLOAT64
            for v in present:
                try:
                    float(v)
                except ValueError:
                    dtype = T.STRING
                    break
        fields.append(T.Field(k, dtype))
    return fields


def _parse_partition_value(raw, dtype):
    if raw is None or raw == HIVE_NULL:
        return None
    if dtype.id is T.TypeId.STRING:
        return raw
    return dtype.np_dtype.type(raw)


def infer_schema(fmt: str, paths: List[str], options: dict) -> T.Schema:
    if fmt == "csv":
        validate_csv_options(options)
    files, values, keys, _fps = discover_files(paths)
    if not files:
        raise FileNotFoundError(f"no files for {paths}")
    f0 = files[0]
    if fmt == "parquet":
        import pyarrow.parquet as pq

        schema = ac.arrow_schema_to_schema(pq.read_schema(f0))
    elif fmt == "orc":
        import pyarrow.orc as orc

        schema = ac.arrow_schema_to_schema(orc.ORCFile(f0).schema)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        tbl = pacsv.read_csv(f0, **_csv_args(options))
        schema = ac.arrow_schema_to_schema(tbl.schema)
    else:
        raise ValueError(fmt)
    # partition columns append after the file columns (Spark layout)
    part_fields = [f for f in _infer_partition_fields(values, keys)
                   if f.name not in schema.names]
    if part_fields:
        schema = T.Schema(list(schema.fields) + part_fields)
    return schema


def _csv_args(options: dict):
    import pyarrow.csv as pacsv

    read_opts = pacsv.ReadOptions(
        autogenerate_column_names=not options.get("header", True))
    parse_opts = pacsv.ParseOptions(
        delimiter=options.get("sep", ","))
    conv = pacsv.ConvertOptions()
    if "schema" in options:
        sch = options["schema"]
        conv = pacsv.ConvertOptions(column_types={
            f.name: ac.dtype_to_arrow(f.dtype) for f in sch})
        if not options.get("header", True):
            read_opts = pacsv.ReadOptions(
                column_names=[f.name for f in sch])
    return {"read_options": read_opts, "parse_options": parse_opts,
            "convert_options": conv}


class FileScanExec(P.PhysicalPlan):
    """One partition per file; within a file, batches split to reader size
    targets (reference: populateCurrentBlockChunk GpuParquetScan.scala:571)."""

    def __init__(self, fmt: str, files: List[str], schema: T.Schema,
                 options: dict, conf, part_values=None, part_keys=None,
                 file_fingerprints=None):
        super().__init__()
        self.fmt = fmt
        self.files = files
        #: per-file identity records captured at discovery time (path,
        #: size, mtime_ns) — the recovery data-material fingerprint and
        #: the streaming source ledger read THESE instead of re-stat-ing
        self.file_fingerprints = (
            file_fingerprints if file_fingerprints is not None
            else [file_fingerprint(p) for p in files])
        self._schema = schema
        self.options = options
        self.max_rows = conf.get(READER_BATCH_SIZE_ROWS)
        self.max_bytes = conf.get(READER_BATCH_SIZE_BYTES)
        self.n_partitions = max(1, len(files))
        self.metrics_skipped_groups = 0
        self.metrics_skipped_stripes = 0
        self.metrics_skipped_files = 0
        # Hive-partition layout: per-file raw values + the derived
        # constant columns appended to every batch
        self.part_values = part_values or [{} for _ in files]
        self.part_fields = [
            schema.fields[schema.index_of(k)] for k in (part_keys or [])
            if k in schema.names]
        part_names = {f.name for f in self.part_fields}
        self._file_schema = T.Schema(
            [f for f in schema.fields if f.name not in part_names])

    @property
    def schema(self):
        return self._schema

    def _read_file(self, fi: int):
        import numpy as np

        path = self.files[fi]
        miscexprs.context.input_file = path
        miscexprs.context.input_file_block_start = 0
        miscexprs.context.input_file_block_length = os.path.getsize(path)
        pv = self.part_values[fi] if fi < len(self.part_values) else {}

        def finish(file_batch):
            return self._append_partitions(file_batch, pv, np)

        if not self._file_schema.fields and self.part_fields:
            # projection kept ONLY partition columns (e.g. count(*) over
            # a filter on the partition key): no file column is read,
            # but the row count still comes from the file metadata
            n = self._count_rows(path)
            for lo in range(0, n, self.max_rows):
                yield self._partition_only_batch(
                    min(self.max_rows, n - lo), pv, np)
            return

        if self.fmt == "parquet":
            import pyarrow.parquet as pq

            pf = pq.ParquetFile(path)
            cols = self._projected_names()
            groups = self._prune_row_groups(pf)
            if not groups:
                return
            for rb in pf.iter_batches(batch_size=self.max_rows,
                                      row_groups=groups, columns=cols):
                yield finish(ac.arrow_to_host_batch(rb,
                                                    self._file_schema))
        elif self.fmt == "orc":
            import pyarrow.orc as orc

            f = orc.ORCFile(path)
            for i in self._prune_stripes(f, path):
                stripe = f.read_stripe(i, columns=self._projected_names())
                batch = ac.arrow_to_host_batch(stripe, self._file_schema)
                for b in _split_to_target(batch, self.max_rows):
                    yield finish(b)
        elif self.fmt == "csv":
            import pyarrow.csv as pacsv

            tbl = pacsv.read_csv(path, **_csv_args(self.options))
            batch = ac.arrow_to_host_batch(tbl, self._file_schema)
            for b in _split_to_target(batch, self.max_rows):
                yield finish(b)
        else:
            raise ValueError(self.fmt)

    def _partition_columns(self, n: int, pv: dict, np) -> dict:
        from ..data.column import HostColumn

        out = {}
        for f in self.part_fields:
            v = _parse_partition_value(pv.get(f.name), f.dtype)
            if v is None:
                out[f.name] = HostColumn.nulls(n, f.dtype)
            elif f.dtype.id is T.TypeId.STRING:
                data = np.empty(n, dtype=object)
                data[:] = v
                out[f.name] = HostColumn(f.dtype, data, None)
            else:
                out[f.name] = HostColumn(
                    f.dtype, np.full(n, v, dtype=f.dtype.np_dtype), None)
        return out

    def _append_partitions(self, batch: HostBatch, pv: dict, np):
        """Append the file's constant partition columns, output columns
        ordered by the scan schema (reference:
        ColumnarPartitionReaderWithPartitionValues.scala:96)."""
        if not self.part_fields:
            return batch
        by_name = dict(zip(self._file_schema.names, batch.columns))
        by_name.update(self._partition_columns(batch.num_rows, pv, np))
        return HostBatch(self._schema,
                         [by_name[name] for name in self._schema.names])

    def _partition_only_batch(self, n: int, pv: dict, np) -> HostBatch:
        cols = self._partition_columns(n, pv, np)
        return HostBatch(self._schema,
                         [cols[name] for name in self._schema.names])

    def _count_rows(self, path: str) -> int:
        if self.fmt == "parquet":
            import pyarrow.parquet as pq

            return pq.ParquetFile(path).metadata.num_rows
        if self.fmt == "orc":
            import pyarrow.orc as orc

            return orc.ORCFile(path).nrows
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path, **_csv_args(self.options)).num_rows

    def _projected_names(self):
        return self._file_schema.names

    def _prune_row_groups(self, pf):
        """Keep row groups whose min-max statistics admit the pushed
        predicates (reference: the footer row-group filtering in
        GpuParquetScan.scala:316 reusing Spark's ParquetFilters)."""
        preds = self.options.get("_scan_predicates") or []
        n_groups = pf.metadata.num_row_groups
        if not preds:
            return list(range(n_groups))
        col_idx = {pf.metadata.schema.column(i).name: i
                   for i in range(pf.metadata.num_columns)}
        kept = []
        for g in range(n_groups):
            rg = pf.metadata.row_group(g)
            admit = True
            for name, op, value in preds:
                i = col_idx.get(name)
                if i is None:
                    continue
                st = rg.column(i).statistics
                if st is None or not st.has_min_max:
                    continue
                dtype = self._schema[self._schema.index_of(name)].dtype \
                    if name in self._schema else None
                lo = _stat_value(st.min, dtype)
                hi = _stat_value(st.max, dtype)
                try:
                    if op == "==" and (value < lo or value > hi):
                        admit = False
                    elif op == "<" and lo >= value:
                        admit = False
                    elif op == "<=" and lo > value:
                        admit = False
                    elif op == ">" and hi <= value:
                        admit = False
                    elif op == ">=" and hi < value:
                        admit = False
                except TypeError:  # incomparable stats type: keep group
                    pass
                if not admit:
                    break
            if admit:
                kept.append(g)
        self.metrics_skipped_groups += n_groups - len(kept)
        return kept

    def _prune_stripes(self, f, path):
        """ORC stripe selection under pushed predicates (reference:
        GpuOrcScan.scala stripe planning + OrcFilters SARG pushdown).
        pyarrow exposes no stripe statistics, so the predicate COLUMNS
        of each stripe are decoded first (cheap when the projection is
        wider) and min/max evaluated on host; excluded stripes never
        decode their remaining columns."""
        import numpy as np

        preds = self.options.get("_scan_predicates") or []
        names = set(self._file_schema.names)
        preds = [p for p in preds if p[0] in names]
        if not preds or f.nstripes <= 1:
            return list(range(f.nstripes))
        pred_cols = sorted({name for name, _op, _v in preds})
        kept = []
        for i in range(f.nstripes):
            tbl = f.read_stripe(i, columns=pred_cols)
            admit = True
            for name, op, value in preds:
                col = tbl.column(name)
                vals = col.to_numpy(zero_copy_only=False)
                mask = ~np.asarray([v is None for v in vals]) \
                    if vals.dtype == object else ~np.isnan(vals) \
                    if np.issubdtype(vals.dtype, np.floating) \
                    else np.ones(len(vals), dtype=bool)
                if not mask.any():
                    continue
                lo, hi = vals[mask].min(), vals[mask].max()
                try:
                    if op == "==" and (value < lo or value > hi):
                        admit = False
                    elif op == "<" and lo >= value:
                        admit = False
                    elif op == "<=" and lo > value:
                        admit = False
                    elif op == ">" and hi <= value:
                        admit = False
                    elif op == ">=" and hi < value:
                        admit = False
                except TypeError:
                    pass
                if not admit:
                    break
            if admit:
                kept.append(i)
        self.metrics_skipped_stripes += f.nstripes - len(kept)
        return kept

    def _partition_pruned_files(self):
        """Whole-file pruning from pushed predicates on partition
        columns (reference: Spark's partition pruning in the file index
        feeding GpuFileSourceScanExec)."""
        preds = self.options.get("_scan_predicates") or []
        part_types = {f.name: f.dtype for f in self.part_fields}
        preds = [p for p in preds if p[0] in part_types]
        if not preds:
            return list(range(len(self.files)))
        kept = []
        for i in range(len(self.files)):
            pv = self.part_values[i] if i < len(self.part_values) else {}
            admit = True
            for name, op, value in preds:
                v = _parse_partition_value(pv.get(name),
                                           part_types[name])
                if v is None:
                    admit = False  # null never satisfies a comparison
                    break
                try:
                    ok = {"==": v == value, "<": v < value,
                          "<=": v <= value, ">": v > value,
                          ">=": v >= value}[op]
                except TypeError:
                    continue
                if not ok:
                    admit = False
                    break
            if admit:
                kept.append(i)
        return kept

    def execute(self, ctx):
        def make(fi):
            return lambda: self._read_file(fi)

        kept = self._partition_pruned_files()
        self.metrics_skipped_files = len(self.files) - len(kept)
        return P.PartitionedData(
            [make(i) for i in kept]
            or [lambda: iter(())])

    def describe(self):
        return f"FileScan[{self.fmt}]({len(self.files)} files)"


def _stat_value(v, dtype=None):
    """Normalize a parquet statistics value to the engine's host
    representation for the scan column's dtype: DATE32 -> int32 days
    since epoch, TIMESTAMP -> int64 microseconds since epoch."""
    import datetime as dt

    if isinstance(v, dt.datetime):
        if dtype is not None and dtype.id is T.TypeId.TIMESTAMP:
            epoch = dt.datetime(1970, 1, 1, tzinfo=v.tzinfo)
            return int((v - epoch).total_seconds() * 1_000_000)
        v = v.date()
    if isinstance(v, dt.date):
        return (v - dt.date(1970, 1, 1)).days
    return v


def _split_to_target(batch: HostBatch, max_rows: int):
    n = batch.num_rows
    if n <= max_rows:
        yield batch
        return
    for lo in range(0, n, max_rows):
        yield batch.slice(lo, min(lo + max_rows, n))


#: CSV reader options the scan supports; anything else is rejected up
#: front (reference: GpuCSVScan.tagSupport's option gates,
#: GpuBatchScanExec.scala:90-237 — unsupported parse modes fall back)
_CSV_SUPPORTED_OPTIONS = {"header", "sep", "schema", "_scan_predicates"}


def validate_csv_options(options: dict) -> None:
    unknown = set(options) - _CSV_SUPPORTED_OPTIONS
    if unknown:
        raise ValueError(
            f"unsupported CSV options {sorted(unknown)}; supported: "
            f"{sorted(_CSV_SUPPORTED_OPTIONS - {'_scan_predicates'})} "
            "(the reference CSV scan likewise gates unsupported parse "
            "options, GpuCSVScan.tagSupport)")
    sep = options.get("sep", ",")
    if not isinstance(sep, str) or len(sep) != 1:
        raise ValueError(f"CSV sep must be a single character, got "
                         f"{sep!r}")


def create_scan_exec(node: L.FileScan, conf) -> FileScanExec:
    if node.fmt == "csv":
        validate_csv_options(node.options)
    files, values, keys, fps = discover_files(node.paths)
    return FileScanExec(node.fmt, files, node.schema, node.options, conf,
                        part_values=values, part_keys=keys,
                        file_fingerprints=fps)
