"""Device admission semaphore.

Reference analogue: GpuSemaphore.scala — limits concurrent tasks holding
the device (default small), acquired just before device work (e.g. right
before upload/decode, GpuParquetScan.scala:554) and released while tasks do
host/IO work, so host-side decode overlaps device compute.

Discipline (reference: GpuSemaphore.scala:58-160 — task-scoped acquire +
a task-completion listener that always releases):

* acquire happens lazily inside device-entry iterators (H2D upload);
* every task-runner thread releases its full hold in a ``finally``
  (``collect_batches`` in plan/physical.py, ``_run_leaf`` drain workers
  in parallel/runner.py);
* a thread must NEVER block on another thread's progress while holding
  a permit — call :meth:`release_all` first (see
  exec/exchange.py ``materialized``);
* acquire carries a watchdog: a blocked acquire past the deadline raises
  ``DeviceSemaphoreTimeout`` instead of hanging the process, so a future
  permit leak fails loudly with a diagnostic."""
from __future__ import annotations

import threading

from ..fault.errors import TpuFaultError


class DeviceSemaphoreTimeout(TpuFaultError):
    """A device-semaphore acquire blocked past the watchdog deadline —
    almost always a leaked permit (a task thread that exited without
    ``release_all``) or a hold-while-blocked cycle.  A
    :class:`~..fault.errors.TpuFaultError`: task-level retry re-executes
    the partition's lineage and the degradation ladder can fall back a
    rung instead of crashing the query.  The deadline is configurable
    via ``spark.rapids.tpu.fault.semaphoreTimeoutMs`` (wired in
    DeviceManager)."""


class DeviceSemaphore:
    #: watchdog for a single blocked acquire; long enough for any real
    #: device program (first XLA compile included), short enough that CI
    #: fails instead of eating its whole budget
    ACQUIRE_TIMEOUT_SECONDS = 180.0

    def __init__(self, permits: int,
                 acquire_timeout: float | None = None):
        import time

        self.permits = permits
        self._sem = threading.Semaphore(permits)
        self._held = threading.local()
        self.acquire_timeout = (acquire_timeout
                                if acquire_timeout is not None
                                else self.ACQUIRE_TIMEOUT_SECONDS)
        #: monotonic stamp of the most recent release — the watchdog
        #: measures STALL (no release anywhere), not queueing time, so
        #: a long fair queue behind slow-but-progressing tasks never
        #: trips it
        self._last_release = time.monotonic()

    def acquire_if_necessary(self) -> None:
        """Idempotent per-thread acquire (a task re-entering device code
        does not double-count — reference GpuSemaphore.acquireIfNecessary).

        Raises :class:`DeviceSemaphoreTimeout` only when NO permit has
        been released anywhere for ``acquire_timeout`` seconds while
        this thread waited — i.e. the pool has genuinely stopped making
        progress (leaked permit / hold-while-blocked cycle)."""
        import time

        if getattr(self._held, "count", 0) == 0:
            from ..scheduler.cancel import check_cancel

            start = time.monotonic()
            while not self._sem.acquire(
                    timeout=min(self.acquire_timeout / 4, 10.0)):
                # admission is a cancellation checkpoint: a cancelled
                # query queued for the device must unwind now, not
                # after winning a permit it will never use
                check_cancel("semaphore.acquire")
                progress = max(self._last_release, start)
                if time.monotonic() - progress > self.acquire_timeout:
                    raise DeviceSemaphoreTimeout(
                        f"device semaphore made no progress for > "
                        f"{self.acquire_timeout}s ({self.permits} "
                        f"permits, thread "
                        f"{threading.current_thread().name}); a task "
                        "thread likely leaked its permit (missing "
                        "release_all) or blocked while holding one")
        self._held.count = getattr(self._held, "count", 0) + 1

    def release_if_necessary(self) -> None:
        import time

        count = getattr(self._held, "count", 0)
        if count > 0:
            self._held.count = count - 1
            if self._held.count == 0:
                self._last_release = time.monotonic()
                self._sem.release()

    def release_task(self) -> None:
        """Release ONLY the calling task's permits — its thread-local
        hold, whatever the reentrancy count (reference: GpuSemaphore's
        task-completion listener releases the completing task's hold,
        GpuSemaphore.scala:101-160).  This is the failure-path release:
        a task that dies or enters OOM recovery drops ITS permits and
        nothing else, so concurrently-running healthy tasks are never
        stranded by a peer's cleanup."""
        import time

        count = getattr(self._held, "count", 0)
        if count > 0:
            self._held.count = 0
            self._last_release = time.monotonic()
            self._sem.release()

    def release_all(self) -> None:
        """Deprecated name for :meth:`release_task` — it never released
        other tasks' permits (the hold is thread-local), but the name
        suggested it did; call sites on failure paths should use
        ``release_task`` so the per-task scope is explicit."""
        self.release_task()

    def held_count(self) -> int:
        """This task's current reentrancy count (0 = no permit held)."""
        return getattr(self._held, "count", 0)

    def suspend_task(self) -> int:
        """Drop this task's permit for a blocking wait and return the
        reentrancy count so :meth:`resume_task` can restore it exactly.
        The count pairs with per-batch acquire/release protocols (H2D
        acquires once per uploaded batch, D2H unwinds one per output
        batch) — collapsing it to 1 across a wait would make a later
        single release drop the permit while device work is still in
        flight."""
        count = getattr(self._held, "count", 0)
        self.release_task()
        return count

    def resume_task(self, count: int) -> None:
        """Re-enter device admission after :meth:`suspend_task`,
        restoring the saved reentrancy count (no-op for count 0: a task
        that held nothing must not gain a hold it never had)."""
        if count > 0:
            self.acquire_if_necessary()
            self._held.count = count

    def rewind_task(self, count: int) -> None:
        """Drop this task's reentrancy count DOWN to ``count``,
        releasing the permit when it reaches 0 — undoes acquires made
        by a failed attempt so its re-execution (which re-acquires)
        doesn't inflate the count."""
        if self.held_count() > count:
            if count <= 0:
                self.release_task()
            else:
                self._held.count = count

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
