"""Logical optimizations ahead of physical planning.

Reference analogue: the scan-pushdown half of GpuParquetScan /
GpuOrcScan — column projection into the reader and predicate pushdown
that prunes parquet row groups / ORC stripes by their min-max statistics
(GpuParquetScan.scala:316 readPartFile's row-group filtering reusing
Spark's ParquetFilters; OrcFilters.scala SARG pushdown).  The host SQL
engine has no Catalyst doing this for us, so the two rewrites live here:

  * prune_scan_columns: narrow every FileScan to the columns its
    ancestors actually reference (the reader then decodes only those).
  * push_scan_predicates: collect conjunctive ``col <op> literal``
    predicates sitting directly above a scan and attach them to the scan
    as advisory row-group filters; the Filter node stays in the plan
    (stats pruning is sound but not complete).
"""
from __future__ import annotations

from typing import List, Optional, Set, Tuple

from ..ops import predicates as pr
from ..ops.expression import Expression, Literal, UnresolvedAttribute
from . import logical as L


def optimize(plan: L.LogicalPlan) -> L.LogicalPlan:
    plan = prune_scan_columns(plan, set(plan.schema.names))
    plan = push_scan_predicates(plan)
    return plan


# ==========================================================================
# column pruning
# ==========================================================================
def _refs(exprs) -> Set[str]:
    out: Set[str] = set()
    for e in exprs:
        out |= e.references()
    return out


def prune_scan_columns(node: L.LogicalPlan,
                       required: Set[str]) -> L.LogicalPlan:
    """Rebuild ``node`` with every reachable FileScan narrowed to the
    columns required above it.  ``required`` is the set of this node's
    output columns the parent needs."""
    if isinstance(node, L.FileScan):
        keep = [f for f in node.schema if f.name in required]
        if 0 < len(keep) < len(node.schema):
            return L.FileScan(node.fmt, node.paths,
                              type(node.schema)(keep), node.options)
        return node

    if isinstance(node, L.Project):
        child_req = _refs(node.exprs)
        child = prune_scan_columns(node.children[0], child_req)
        return L.Project(child, node.exprs)
    if isinstance(node, L.Filter):
        child_req = required | _refs([node.condition])
        child = prune_scan_columns(node.children[0], child_req)
        return L.Filter(child, node.condition)
    if isinstance(node, L.Aggregate):
        child_req = _refs(node.keys) | _refs(node.aggregates)
        child = prune_scan_columns(node.children[0], child_req)
        return L.Aggregate(child, node.keys, node.aggregates)
    if isinstance(node, L.Sort):
        child_req = required | _refs([k.expr for k in node.keys])
        child = prune_scan_columns(node.children[0], child_req)
        return L.Sort(child, node.keys, node.global_sort)
    if isinstance(node, L.Limit):
        child = prune_scan_columns(node.children[0], set(required))
        return L.Limit(child, node.n)
    if isinstance(node, L.Join):
        need = (required | _refs(node.left_keys) | _refs(node.right_keys)
                | (_refs([node.condition]) if node.condition is not None
                   else set()))
        lnames = set(node.children[0].schema.names)
        rnames = set(node.children[1].schema.names)
        left = prune_scan_columns(node.children[0], need & lnames)
        right = prune_scan_columns(node.children[1], need & rnames)
        return L.Join(left, right, node.left_keys, node.right_keys,
                      node.how, node.condition)
    if isinstance(node, L.Union):
        children = [prune_scan_columns(c, set(required))
                    for c in node.children]
        return L.Union(children)
    # conservative default: the child must keep every column
    new_children = [prune_scan_columns(c, set(c.schema.names))
                    for c in node.children]
    if new_children != node.children:
        import copy

        node = copy.copy(node)
        node.children = new_children
    return node


# ==========================================================================
# predicate pushdown (row-group stats pruning)
# ==========================================================================
_CMP_OPS = {
    pr.EqualTo: "==", pr.LessThan: "<", pr.LessThanOrEqual: "<=",
    pr.GreaterThan: ">", pr.GreaterThanOrEqual: ">=",
}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}

ScanPredicate = Tuple[str, str, object]  # (column, op, literal value)


def _conjuncts(e: Expression) -> List[Expression]:
    if isinstance(e, pr.And):
        return _conjuncts(e.children[0]) + _conjuncts(e.children[1])
    return [e]


def _as_scan_predicate(e: Expression) -> Optional[ScanPredicate]:
    op = _CMP_OPS.get(type(e))
    if op is None or len(e.children) != 2:
        return None
    a, b = e.children
    if isinstance(a, UnresolvedAttribute) and isinstance(b, Literal) \
            and b.value is not None:
        return (a.attr_name, op, b.value)
    if isinstance(b, UnresolvedAttribute) and isinstance(a, Literal) \
            and a.value is not None:
        return (b.attr_name, _FLIP[op], a.value)
    return None


def push_scan_predicates(node: L.LogicalPlan) -> L.LogicalPlan:
    if isinstance(node, L.Filter) \
            and isinstance(node.children[0], L.FileScan):
        scan = node.children[0]
        preds = [p for p in (_as_scan_predicate(c)
                             for c in _conjuncts(node.condition))
                 if p is not None and p[0] in scan.schema]
        if preds:
            new_scan = L.FileScan(scan.fmt, scan.paths, scan.schema,
                                  dict(scan.options,
                                       _scan_predicates=preds))
            return L.Filter(new_scan, node.condition)
        return node
    new_children = [push_scan_predicates(c) for c in node.children]
    if new_children != node.children:
        import copy

        node = copy.copy(node)
        node.children = new_children
    return node
