"""Multi-process / multi-host distributed execution.

Reference analogue: the executor model of the RAPIDS shuffle — one JVM
per node, each owning one GPU, with shuffle data moving BETWEEN
processes over UCX (Plugin.scala:219-247 executor bootstrap,
UCX.scala:54-86 worker/endpoint plumbing, RapidsShuffleClient.scala:452
fetch protocol).  The TPU-native form is jax's multi-controller SPMD:

    * every process calls ``jax.distributed.initialize`` (the TCP
      handshake the reference does over its management port,
      UCXConnection.scala:354)
    * the global mesh spans every process's local devices; the SAME
      stage program runs on every controller
    * exchanges stay the SAME compiled ``all_to_all`` — XLA routes
      lanes over ICI within a host and DCN across hosts; the entire
      client/server/bounce-buffer machinery of the reference collapses
      into the runtime (SURVEY §5 "Distributed communication backend")

Host-side control flow (stage loop, capacity retries) is replicated on
every controller, so every decision must derive from replicated values
— the runner pmax-replicates capacity aux outputs for exactly this
reason (see DistributedRunner._run_stage).

Process-local leaf execution: non-distributable subtrees (scans, host
fallbacks) are executed by EVERY process — deterministically identical
— and each process materializes only its addressable shards
(``jax.make_array_from_callback``).  This mirrors Spark recomputing a
partition's lineage on whichever executor owns the task, without a
driver shipping bytes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..data.column import DeviceBatch, HostBatch, device_to_host
from . import exchange as X
from .runner import DistributedRunner


def init_multiprocess(coordinator: str, num_processes: int,
                      process_id: int,
                      local_cpu_devices: Optional[int] = None):
    """Join the multi-controller job and return the global mesh.

    ``local_cpu_devices``: for tests/CI — force this process onto the
    local CPU backend with that many virtual devices BEFORE the backend
    initializes (the 2-process CPU fixture the reference never had for
    its UCX path, SURVEY §4 "TPU-build implication")."""
    import os
    import re

    if local_cpu_devices:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        want = (f"--xla_force_host_platform_device_count="
                f"{local_cpu_devices}")
        if "host_platform_device_count" in flags:
            # an inherited count (e.g. the pytest conftest's 8) must be
            # REPLACED, not kept — otherwise every worker gets the
            # inherited device count and the mesh silently changes size
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want,
                flags)
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
    import jax

    if local_cpu_devices:
        jax.config.update("jax_platforms", "cpu")
        try:
            from jax._src import xla_bridge as _xb

            _xb._backend_factories.pop("axon", None)
        except Exception:  # noqa: BLE001
            pass
    jax.distributed.initialize(coordinator, num_processes=num_processes,
                               process_id=process_id)
    # single-device work (leaf uploads) must land on a device THIS
    # process owns, never a peer's (the executor-local GPU rule,
    # GpuDeviceManager.scala:98-112)
    jax.config.update("jax_default_device", jax.local_devices()[0])
    from jax.sharding import Mesh

    from .mesh import DATA_AXIS

    devs = np.array(sorted(jax.devices(), key=lambda d: d.id))
    return Mesh(devs, (DATA_AXIS,))


class MultiProcessRunner(DistributedRunner):
    """DistributedRunner over a mesh that spans OS processes/hosts.

    Differences from the single-controller base:
      * leaf placement constructs global arrays shard-by-shard so each
        process only touches devices it owns;
      * inter-stage retiling reads row counts through a replicated
        reduction (a sharded array is not host-readable on every
        controller);
      * the final collect gathers every process's shards
        (``multihost_utils.process_allgather`` — the read side of the
        reference's fetch protocol, RapidsShuffleIterator.scala:45)."""

    def _place(self, stacked: DeviceBatch) -> DeviceBatch:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh

        def put(arr):
            arr = np.asarray(arr)
            sh = NamedSharding(mesh, P(*([self.axis]
                                         + [None] * (arr.ndim - 1))))
            return jax.make_array_from_callback(
                arr.shape, sh, lambda idx, a=arr: a[idx])

        cols = []
        from ..data.column import DeviceColumn

        for c in stacked.columns:
            cols.append(DeviceColumn(
                c.dtype, put(c.data), put(c.validity),
                put(c.lengths) if c.lengths is not None else None))
        return DeviceBatch(stacked.schema, cols, put(stacked.num_rows))

    def _retile(self, stacked: DeviceBatch) -> DeviceBatch:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..data.column import bucket_rows as _bucket

        mx = jax.jit(lambda r: r.max(),
                     out_shardings=NamedSharding(self.mesh, P()))(
            stacked.num_rows)
        need = _bucket(max(int(np.asarray(mx)), 1), self.min_bucket)
        if need >= stacked.padded_rows:
            return stacked
        from ..data.column import DeviceColumn

        sharding = NamedSharding(self.mesh, P(self.axis))

        @jax.jit
        def trim(b):
            cols = [DeviceColumn(
                c.dtype, c.data[:, :need], c.validity[:, :need],
                c.lengths[:, :need] if c.lengths is not None else None)
                for c in b.columns]
            return DeviceBatch(b.schema, cols, b.num_rows)

        out = trim(stacked)
        return jax.device_put(out, sharding)

    def _collect_output(self, out: DeviceBatch, stages) -> HostBatch:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(out, tiled=True)
        # gathered leaves are full global numpy arrays [n, ...]
        parts = X.unstack_partitions(gathered)
        host = [device_to_host(p) for p in parts]
        host = [h for h in host if h.num_rows]
        if not host:
            from ..plan.physical import _empty_batch

            return _empty_batch(self._schema_of(stages[-1].root))
        return HostBatch.concat(host)


def run_distributed_mp(session, df, mesh) -> HostBatch:
    """Execute ``df`` SPMD across every controller process of ``mesh``.
    Must be called by ALL processes with an identically-built plan;
    returns the full result on every process."""
    from ..plan.physical import ExecContext
    from .collective import make_transport
    from .mesh import DATA_AXIS as _AX

    phys = session.physical_plan(df.plan)
    ctx = ExecContext(session.conf, session)
    axis = mesh.axis_names[0] if mesh.axis_names else _AX
    return MultiProcessRunner(
        mesh, transport=make_transport(session.conf, axis)).run(phys, ctx)
