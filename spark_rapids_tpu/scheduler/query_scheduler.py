"""QueryScheduler — bounded admission, multi-tenant fair share,
dispatch, deadlines, preemption and per-query failure isolation for
concurrent queries.

Reference analogue: the admission/memory-arbitration layer Theseus-
style accelerator engines put in front of scarce device memory (see
PAPERS.md) — here built on the existing DeviceManager budget, retry
framework, degradation ladder and telemetry events, with the
multi-tenant QoS tier of "Accelerating Presto with GPUs" on top
(:mod:`.qos`).

Model:

* ``Session.submit(plan, priority, tenant=...)`` -> :class:`QueryHandle`
  — at most ``scheduler.maxConcurrent`` queries run concurrently (one
  daemon worker thread each); queued queries wait in per-tenant queues
  drained by deficit-weighted fair share with priority aging
  (:mod:`.qos`).  A submit past ``scheduler.maxQueued`` — or a queued
  query not dispatched within ``scheduler.queueTimeoutMs`` — is shed
  with :class:`QueryRejected` plus an ``admission_reject`` event
  carrying the queue depth and queue wait.
* While the :class:`~.qos.OverloadMonitor` declares overload (queue-wait
  p95 or arena pressure past ``scheduler.overload.*`` thresholds), new
  submissions below ``scheduler.overload.shedBelowPriority`` are shed
  with :class:`~.qos.TpuOverloaded` carrying a ``retry_after_ms``
  backoff hint (``overload_shed`` event).
* Each dispatched query holds an HBM *reservation* of
  ``scheduler.reservationFraction`` (or its tenant's ``hbmFraction``)
  x the DeviceManager arena for its lifetime
  (``DeviceManager.try_reserve``): dispatch waits until the reservation
  fits, so the sum of running reservations never exceeds the arena.
  When nothing is running the head query dispatches even if its
  reservation cannot be charged — forward progress is never
  reservation-deadlocked.
* **Checkpoint-backed preemption** — a strictly higher-priority queued
  query blocked on a slot or its reservation cooperatively cancels the
  lowest-priority running victim (the same zero-leak CancelToken
  unwind as a terminal cancel), requeues it with its aging credit
  intact, and on re-dispatch the recovery store (``recovery.enabled``)
  resumes the victim from its completed exchange checkpoints
  (``preempt_victim`` / ``preempt_resume`` events); every preemption
  is charged against the victim's ``fault.maxTotalAttempts`` budget.
* Cancellation is cooperative: ``handle.cancel()`` (or the
  ``scheduler.queryTimeoutMs`` deadline, or an injected ``cancel``
  fault) trips the query's :class:`~.cancel.CancelToken`; every
  operator checkpoint polls it, and the worker unwinds — semaphore
  permits released, upload caches dropped, shuffle slots freed by the
  normal query-end path, a terminal ``query_cancelled`` event emitted.
* Per-query failure isolation: scheduled queries run with PRIVATE
  fault/OOM injectors (thread-local, see ``ExecContext``), and a query
  that exhausts its retry/ladder budget trips a per-query circuit
  breaker onto the CPU-exec plan — without disarming the process-wide
  injector slots or writing the global fault counters, so concurrent
  queries stay on the TPU path unpoisoned.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
import weakref
from typing import Dict, List, Optional

from .cancel import CancelToken, TpuQueryCancelled
from .qos import (DEFAULT_TENANT, OverloadMonitor,  # noqa: F401
                  QueryRejected, TenantRegistry, TpuOverloaded)

log = logging.getLogger(__name__)

#: all live schedulers in the process — the test harness shuts them
#: down between tests (conftest) so no scheduler thread outlives its
#: test
_LIVE: "weakref.WeakSet[QueryScheduler]" = weakref.WeakSet()


def shutdown_all() -> None:
    """Shut down every live scheduler (test-harness hook)."""
    for sched in list(_LIVE):
        try:
            sched.shutdown()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass


class QueryStatus:
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    FAILED = "failed"
    CANCELLED = "cancelled"
    REJECTED = "rejected"


#: terminal status -> tenant counter (QUEUED = a preemption requeue)
_DONE_COUNTER = {QueryStatus.FINISHED: "finished",
                 QueryStatus.FAILED: "failed",
                 QueryStatus.CANCELLED: "cancelled",
                 QueryStatus.REJECTED: "cancelled",
                 QueryStatus.QUEUED: "preempted"}


class QueryHandle:
    """Caller-side handle of one submitted query."""

    def __init__(self, scheduler: "QueryScheduler", query_id: int,
                 plan, priority: int, tenant: str = DEFAULT_TENANT,
                 recovery=None, deadline_ms: Optional[int] = None):
        self._scheduler = scheduler
        self.query_id = query_id
        self.plan = plan
        self.priority = priority
        self.tenant = tenant
        #: caller-provided RecoveryManager (streaming micro-batches
        #: bring their own stream-scoped manager) — None means the
        #: session builds the default per-query one
        self.recovery = recovery
        #: per-query deadline override (streaming batchDeadlineMs);
        #: None/0 falls back to scheduler.queryTimeoutMs
        self.deadline_ms = deadline_ms
        self.token = CancelToken(query_id)
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._status = QueryStatus.QUEUED
        self._result = None
        self._error: Optional[BaseException] = None
        self._queued_at = time.monotonic()
        #: first enqueue stamp — survives preemption requeues, so a
        #: victim keeps its priority-aging credit
        self._first_queued_at = self._queued_at
        #: times this query was preempted; charged against the
        #: fault.maxTotalAttempts budget
        self.preemptions = 0
        self._user_cancel = False
        #: preemptor's query id while an eviction is in flight
        self._preempted_by: Optional[int] = None
        #: event rings of earlier, preempted attempts (events())
        self._prior_events: List[Dict] = []
        #: per-query attribution (the session's last_metrics /
        #: last_profile are last-writer-wins under concurrency)
        self.metrics: Dict = {}
        self.profile = None
        #: "tpu", "cpu" (the circuit-breaker rung) or "cache" (served
        #: from the serving result cache before admission) — which path
        #: produced the result
        self.exec_path: Optional[str] = None
        #: serving-cache identity captured at submit time (serving/);
        #: the worker stores the result under it at success
        self._serving_key = None
        self._ctx = None  # the native attempt's ExecContext

    # ----- caller API ------------------------------------------------------
    def result(self, timeout: Optional[float] = None):
        """Block for the result; raises the query's terminal error
        (``TpuQueryCancelled`` / ``QueryRejected`` / the failure)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} not done after {timeout}s "
                f"(status={self.status()})")
        if self._error is not None:
            raise self._error
        return self._result

    def cancel(self, reason: str = "cancelled by caller") -> bool:
        """Trip the query's cancel token; a queued query is removed
        immediately, a running one unwinds at its next checkpoint.
        Returns True on the first effective cancel."""
        self._user_cancel = True  # a preemption requeue must not undo it
        first = self.token.cancel(reason)
        self._scheduler._on_cancel(self, reason)
        return first

    def status(self) -> str:
        with self._lock:
            return self._status

    def done(self) -> bool:
        return self._done.is_set()

    def events(self) -> List[Dict]:
        """This query's telemetry event ring (empty when telemetry was
        disabled); for a preempted query the rings of its earlier
        attempts come first, so preempt_victim events stay visible."""
        out = list(self._prior_events)
        tele = getattr(self._ctx, "telemetry", None)
        if tele is not None and tele.events is not None:
            out.extend(tele.events.snapshot())
        return out

    # ----- scheduler-side transitions --------------------------------------
    def _mark_running(self) -> None:
        with self._lock:
            if not self._done.is_set():
                self._status = QueryStatus.RUNNING

    def _finish(self, status: str, result=None,
                error: Optional[BaseException] = None) -> bool:
        with self._lock:
            if self._done.is_set():
                return False
            self._status = status
            self._result = result
            self._error = error
            self._done.set()
            return True

    def _reset_for_requeue(self) -> None:
        """Preemption requeue: back to QUEUED with a FRESH cancel token
        (the tripped one is spent) and a fresh queue-timeout clock —
        but the original first-queued stamp, so the victim keeps its
        aging credit and re-dispatches ahead of equal-priority
        newcomers."""
        with self._lock:
            self._status = QueryStatus.QUEUED
        self.token = CancelToken(self.query_id)
        self._queued_at = time.monotonic()


class QueryScheduler:
    """One per Session (created lazily by ``Session.submit``); owns a
    dispatcher thread, an overload-monitor thread (when the
    ``scheduler.overload.*`` thresholds are set), plus one daemon
    worker thread per running query."""

    def __init__(self, session):
        from ..config import (FAULT_DEGRADE_ENABLED,
                              SCHEDULER_MAX_CONCURRENT,
                              SCHEDULER_MAX_QUEUED,
                              SCHEDULER_OVERLOAD_SHED_BELOW_PRIORITY,
                              SCHEDULER_PREEMPTION_ENABLED,
                              SCHEDULER_PRIORITY_AGING_MS,
                              SCHEDULER_QUERY_TIMEOUT_MS,
                              SCHEDULER_QUEUE_TIMEOUT_MS,
                              SCHEDULER_RESERVATION_FRACTION)
        from ..telemetry import spans as tspans

        self.session = session
        conf = session.conf
        self.max_concurrent = max(1, conf.get(SCHEDULER_MAX_CONCURRENT))
        self.max_queued = max(0, conf.get(SCHEDULER_MAX_QUEUED))
        self.queue_timeout_ms = conf.get(SCHEDULER_QUEUE_TIMEOUT_MS)
        self.query_timeout_ms = conf.get(SCHEDULER_QUERY_TIMEOUT_MS)
        self.aging_ms = conf.get(SCHEDULER_PRIORITY_AGING_MS)
        self.preemption_enabled = conf.get(SCHEDULER_PREEMPTION_ENABLED)
        self.shed_below_priority = conf.get(
            SCHEDULER_OVERLOAD_SHED_BELOW_PRIORITY)
        self._dm = session.device_manager
        frac = conf.get(SCHEDULER_RESERVATION_FRACTION)
        self.reservation_bytes = 0
        if self._dm is not None and frac > 0:
            self.reservation_bytes = min(
                int(frac * self._dm.arena_bytes), self._dm.arena_bytes)
        self._degrade_enabled = (self._dm is not None
                                 and conf.get(FAULT_DEGRADE_ENABLED))
        self._cv = threading.Condition()
        self.qos = TenantRegistry(conf)
        self.overload = OverloadMonitor(conf, self._queue_waits_ms,
                                        self._arena_pressure)
        self._next_qid = itertools.count(1)
        self._n_active = 0
        self._running: set = set()  # running QueryHandles
        #: the victim of an in-flight eviction — one at a time, so a
        #: burst of high-tier arrivals cannot cascade-cancel the world
        self._preempt_inflight: Optional[QueryHandle] = None
        #: worker-thread ident -> [currently held reservation bytes];
        #: the mutable cell lets AQE shrink a running query's charge
        #: (rebase_reservation) while the worker's finally still
        #: releases exactly what remains held
        self._reservations: Dict[int, List[int]] = {}
        self._workers: set = set()  # live worker threads
        self._shutdown = False
        _LIVE.add(self)
        # the dispatcher inherits the creator's (usually empty)
        # execution binding via the telemetry capture() discipline
        self._dispatcher = threading.Thread(
            target=tspans.bound(tspans.capture(), self._dispatch_loop),
            daemon=True, name="query-scheduler")
        self._dispatcher.start()
        self.overload.start()

    # ----- submission ------------------------------------------------------
    def submit(self, plan, priority: int = 0,
               tenant: str = DEFAULT_TENANT, *, recovery=None,
               deadline_ms: Optional[int] = None) -> QueryHandle:
        from ..telemetry.events import emit_event

        # serving result-cache lookup BEFORE admission (serving/):
        # fingerprinting and the validated disk read happen outside the
        # scheduler lock, and a hit completes the handle immediately —
        # it never queues, never occupies a slot and is never shed.
        # Callers that bring their own RecoveryManager (streaming
        # micro-batches) bypass the cache: their execution must write
        # checkpoints for the next incremental tick to merge from.
        cached = None
        serving_key = None
        serving = self.session.serving_if_enabled()
        if serving is not None and recovery is None:
            serving_key = serving.results.fingerprint(plan)
            if serving_key is not None:
                cached = serving.results.lookup(serving_key)
        with self._cv:
            if self._shutdown:
                raise RuntimeError("QueryScheduler is shut down")
            if cached is not None:
                handle = QueryHandle(self, next(self._next_qid), plan,
                                     priority, tenant, recovery=recovery,
                                     deadline_ms=deadline_ms)
                handle.exec_path = "cache"
                self.qos.count_cache_hit_locked(tenant)
                handle._finish(QueryStatus.FINISHED, result=cached)
                return handle
            self._maybe_shed_overload_locked(priority, tenant)
            queued = self.qos.queued_count_locked()
            if queued >= self.max_queued \
                    and self._n_active >= self.max_concurrent:
                now = time.monotonic()
                oldest = self.qos.earliest_queued_at_locked()
                head_wait = round((now - oldest) * 1000.0, 1) \
                    if oldest is not None else 0.0
                emit_event("admission_reject", source="scheduler",
                           reason="queue_full", queued=queued,
                           running=self._n_active,
                           queue_depth=queued,
                           queue_wait_ms=head_wait, tenant=tenant,
                           max_queued=self.max_queued,
                           max_concurrent=self.max_concurrent)
                raise QueryRejected(
                    f"scheduler queue full ({self._n_active} running / "
                    f"{queued} queued; maxConcurrent="
                    f"{self.max_concurrent}, maxQueued="
                    f"{self.max_queued})")
            handle = QueryHandle(self, next(self._next_qid), plan,
                                 priority, tenant, recovery=recovery,
                                 deadline_ms=deadline_ms)
            handle._serving_key = serving_key
            self.qos.enqueue_locked(handle)
            self._cv.notify_all()
        return handle

    def _maybe_shed_overload_locked(self, priority: int,
                                    tenant: str) -> None:
        """Load-shedding decision site: while the OverloadMonitor is
        in overload, a submit below scheduler.overload.shedBelowPriority
        is shed with TpuOverloaded (typed, retryable, carrying the
        retry_after_ms backoff hint) plus an overload_shed event —
        emitted on the submitting thread, where the caller's telemetry
        binding lives."""
        from ..telemetry.events import emit_event

        if not self.overload.enabled:
            return
        if not self.overload.evaluate() \
                or priority >= self.shed_below_priority:
            return
        depth = self.qos.queued_count_locked()
        retry_ms = self.overload.retry_after_ms(depth, self.max_queued)
        self.qos.count_shed_locked(tenant)
        emit_event("overload_shed", source="scheduler", tenant=tenant,
                   priority=priority, queue_depth=depth,
                   retry_after_ms=retry_ms,
                   queue_wait_p95_ms=round(self.overload.wait_p95(), 1))
        raise TpuOverloaded(
            f"scheduler overloaded: priority {priority} submission "
            f"shed (below shedBelowPriority="
            f"{self.shed_below_priority}); retry after {retry_ms}ms",
            retry_after_ms=retry_ms)

    # ----- caller-side cancel hook -----------------------------------------
    def _on_cancel(self, handle: QueryHandle, reason: str) -> None:
        """Remove a still-queued handle immediately; a running one
        unwinds cooperatively at its next checkpoint."""
        with self._cv:
            removed = self.qos.remove_locked(handle)
            if removed:
                self._cv.notify_all()
        if removed:
            handle._finish(QueryStatus.CANCELLED,
                           error=TpuQueryCancelled(reason))

    # ----- overload-monitor inputs ------------------------------------------
    def _queue_waits_ms(self) -> List[float]:
        with self._cv:
            return self.qos.queue_waits_ms_locked(time.monotonic())

    def _arena_pressure(self) -> float:
        dm = self._dm
        if dm is None or dm.arena_bytes <= 0:
            return 0.0
        return dm.allocated_bytes / float(dm.arena_bytes)

    # ----- dispatcher ------------------------------------------------------
    def _dispatch_loop(self) -> None:
        from ..telemetry import spans as tspans

        while True:
            with self._cv:
                handle = cand = None
                reservation = 0
                while handle is None:
                    if self._shutdown:
                        return
                    now = time.monotonic()
                    self._shed_expired_locked(now)
                    if self._n_active < self.max_concurrent:
                        cand = self.qos.pick_locked(now, self.aging_ms)
                        if cand is not None:
                            reservation = \
                                self._reservation_for_locked(cand)
                            if reservation and not self._dm.try_reserve(
                                    reservation):
                                if self._n_active == 0:
                                    # forward-progress guarantee: an
                                    # empty machine always runs the
                                    # head query
                                    reservation = 0
                                else:
                                    self.qos.requeue_front_locked(cand)
                                    self._maybe_preempt_locked(cand)
                                    self._cv.wait(timeout=0.05)
                                    continue
                            handle = cand
                            continue
                    else:
                        # every slot is busy: a strictly higher-tier
                        # queued query may still evict a victim
                        cand = self.qos.peek_locked(now, self.aging_ms)
                        if cand is not None:
                            self._maybe_preempt_locked(cand)
                    self._cv.wait(timeout=self._wait_timeout_locked())
                wait_ms = self.qos.note_dispatch_locked(
                    handle, time.monotonic())
                self.overload.record_wait(wait_ms)
                self._n_active += 1
                self._running.add(handle)
                handle._mark_running()
                worker = threading.Thread(
                    target=tspans.bound(tspans.capture(),
                                        self._worker_main),
                    args=(handle, reservation), daemon=True,
                    name=f"query-worker-{handle.query_id}")
                self._workers.add(worker)
            worker.start()
            # drop the frame locals before sleeping on the condition:
            # a dispatcher idling between queries must not pin the last
            # handle (and through it the query's result/context) after
            # every caller reference is gone
            del worker, handle, cand

    def _reservation_for_locked(self, handle: QueryHandle) -> int:
        """The HBM reservation this query must hold: its tenant's
        hbmFraction of the arena, or the scheduler-wide default."""
        if self._dm is None:
            return 0
        frac = self.qos.get_locked(handle.tenant).hbm_fraction
        if frac <= 0:
            return self.reservation_bytes
        return min(int(frac * self._dm.arena_bytes),
                   self._dm.arena_bytes)

    def _maybe_preempt_locked(self, cand: QueryHandle) -> None:
        """Checkpoint-backed preemption decision: a strictly
        higher-priority candidate blocked on a slot or its HBM
        reservation evicts the lowest-priority running victim by
        tripping its CancelToken — the victim unwinds through the
        normal zero-leak cancellation path and ``_requeue_preempted``
        puts it back in its tenant queue.  The ``preempt_victim``
        event is emitted there, on the victim's own worker thread,
        where its telemetry binding (and event ring) lives — the
        dispatcher thread has no query binding
        (the decision-event analysis rule allowlists this site for
        that reason)."""
        if not self.preemption_enabled:
            return
        if self._preempt_inflight is not None:
            return  # one eviction at a time — no preemption cascades
        victims = [h for h in self._running
                   if h.priority < cand.priority]
        if not victims:
            return
        victim = min(victims, key=lambda h: (h.priority, h.query_id))
        victim._preempted_by = cand.query_id
        if not victim.token.cancel(
                f"preempted by query {cand.query_id} (priority "
                f"{cand.priority} > {victim.priority})"):
            victim._preempted_by = None  # already cancelled elsewhere
            return
        self._preempt_inflight = victim
        log.info("query %d (priority %d) preempting query %d "
                 "(priority %d)", cand.query_id, cand.priority,
                 victim.query_id, victim.priority)

    def _wait_timeout_locked(self) -> Optional[float]:
        """How long the dispatcher may sleep: until the earliest
        queued entry would exceed its queue timeout (None = until
        notified)."""
        earliest = self.qos.earliest_queued_at_locked()
        if self.queue_timeout_ms <= 0 or earliest is None:
            return None
        horizon = self.queue_timeout_ms / 1000.0
        return max(0.01, earliest + horizon - time.monotonic())

    def _shed_expired_locked(self, now: float) -> None:
        if self.queue_timeout_ms <= 0:
            return
        horizon = self.queue_timeout_ms / 1000.0
        for h in self.qos.all_queued_locked():
            if h._done.is_set():
                self.qos.remove_locked(h)
            elif now - h._queued_at >= horizon:
                self.qos.remove_locked(h)
                self._reject_queued(h, "queue_timeout")

    def _reject_queued(self, handle: QueryHandle, why: str) -> None:
        from ..telemetry.events import emit_event

        wait_ms = round(
            (time.monotonic() - handle._queued_at) * 1000.0, 1)
        emit_event("admission_reject", source="scheduler", reason=why,
                   query_id=handle.query_id, tenant=handle.tenant,
                   queue_depth=self.qos.queued_count_locked(),
                   queue_wait_ms=wait_ms,
                   queue_timeout_ms=self.queue_timeout_ms)
        log.warning("query %d shed from the scheduler queue (%s after "
                    "%sms)", handle.query_id, why, wait_ms)
        handle._finish(QueryStatus.REJECTED, error=QueryRejected(
            f"query {handle.query_id} shed: {why} (queueTimeoutMs="
            f"{self.queue_timeout_ms})"))

    # ----- worker ----------------------------------------------------------
    def _worker_main(self, handle: QueryHandle,
                     reservation: int) -> None:
        from ..fault.errors import TpuFaultError
        from ..fault.injector import bind_scoped_fault_injector
        from ..memory.retry import bind_scoped_injector
        from ..telemetry import spans as tspans
        from ..telemetry.events import emit_event
        from . import cancel as _cancel

        token = handle.token
        timeout_ms = handle.deadline_ms \
            if handle.deadline_ms and handle.deadline_ms > 0 \
            else self.query_timeout_ms
        if timeout_ms and timeout_ms > 0:
            token.deadline = time.monotonic() + timeout_ms / 1000.0
        _cancel.activate(token)
        holder = [reservation]
        with self._cv:
            self._reservations[threading.get_ident()] = holder
        sink: Dict = {}
        try:
            try:
                out = self.session._execute_native(
                    handle.plan, scheduled=True, cancel_token=token,
                    ctx_sink=sink, recovery=handle.recovery)
                handle.exec_path = "tpu"
                self._store_serving_result(handle, out)
                self._attribute(handle, sink)
                if handle.preemptions:
                    # work-preserving resume evidence: the recovery
                    # counters say how many stages were skipped
                    emit_event(
                        "preempt_resume", query_id=handle.query_id,
                        tenant=handle.tenant,
                        preemptions=handle.preemptions,
                        stages_resumed=handle.metrics.get(
                            "recovery.numStagesResumed", 0))
                handle._finish(QueryStatus.FINISHED, result=out)
            except TpuQueryCancelled as e:
                if handle._preempted_by is not None \
                        and not handle._user_cancel \
                        and not self._shutdown:
                    self._requeue_preempted(handle, sink, e)
                else:
                    self._unwind_cancelled(handle, sink, e)
            except TpuFaultError as e:
                if not self._degrade_enabled:
                    self._attribute(handle, sink)
                    handle._finish(QueryStatus.FAILED, error=e)
                else:
                    try:
                        self._run_cpu_fallback(handle, e, sink)
                    except TpuQueryCancelled as e2:
                        self._unwind_cancelled(handle, sink, e2)
        except BaseException as e:  # noqa: BLE001 — worker must not die silent
            self._attribute(handle, sink)
            handle._finish(QueryStatus.FAILED, error=e)
        finally:
            # the worker thread dies with the query, but unbinding
            # keeps the thread-local discipline explicit
            _cancel.deactivate()
            bind_scoped_injector(None)
            bind_scoped_fault_injector(None)
            tspans.deactivate()
            if self._dm is not None:
                # any device hold still on this thread dies with it —
                # the semaphore can never get a dead thread's permit
                # back, so the worker's last act is to drop its own
                self._dm.semaphore.release_task()
            with self._cv:
                held = holder[0]
                holder[0] = 0
                self._reservations.pop(threading.get_ident(), None)
            if held and self._dm is not None:
                self._dm.release_reservation(held)
            with self._cv:
                self._n_active -= 1
                self._running.discard(handle)
                self._workers.discard(threading.current_thread())
                if self._preempt_inflight is handle:
                    self._preempt_inflight = None
                self.qos.note_done_locked(
                    handle, _DONE_COUNTER.get(handle.status()))
                self._cv.notify_all()

    def _store_serving_result(self, handle: QueryHandle, out) -> None:
        """Store-at-success hook of the serving result cache: the
        fingerprint captured at submit time is re-validated against a
        FRESH stat of the file material inside ``store_result``, so a
        source rewritten mid-flight is never cached under the stale
        pre-execution identity.  Never raises (the cache fails open)."""
        key = handle._serving_key
        if key is None:
            return
        serving = self.session.serving_if_enabled()
        if serving is not None:
            serving.results.store_result(key, out)

    # ----- preemption (victim side) -----------------------------------------
    def _requeue_preempted(self, handle: QueryHandle, sink: Dict,
                           exc: TpuQueryCancelled) -> None:
        """Victim side of checkpoint-backed preemption: the same
        zero-leak unwind as a terminal cancel (permits, upload caches
        — the normal query-end path already freed shuffle slots and
        finalized metrics), then back into the tenant queue instead of
        a terminal CANCELLED.  Emits ``preempt_victim`` from the
        victim's own telemetry binding, preserves the attempt's event
        ring on the handle, and charges the preemption against the
        victim's ``fault.maxTotalAttempts`` budget."""
        from ..config import FAULT_MAX_TOTAL_ATTEMPTS
        from ..telemetry.events import emit_event

        handle.preemptions += 1
        limit = self.session.conf.get(FAULT_MAX_TOTAL_ATTEMPTS)
        emit_event("preempt_victim", query_id=handle.query_id,
                   by_query=handle._preempted_by, tenant=handle.tenant,
                   preemptions=handle.preemptions, reason=str(exc))
        if self._dm is not None:
            try:
                self._dm.semaphore.release_task()
            except Exception:  # noqa: BLE001 — unwind must not raise
                pass
        phys = sink.get("phys")
        if phys is not None:
            self._drop_upload_caches(phys)
        # cooperative preemption carries no diagnosis: the frames'
        # locals would pin device batches past the zero-leak contract
        exc.__cause__ = None
        exc.__context__ = None
        if limit and handle.preemptions >= limit:
            # terminal — _attribute keeps this attempt's ring on the
            # handle, so no _prior_events copy (it would double up)
            self._fail_preempt_budget(handle, sink, limit)
            return
        # keep the preempted attempt's ring visible on the handle (the
        # resumed attempt begins a fresh one)
        tele = getattr(sink.get("ctx"), "telemetry", None)
        if tele is not None and tele.events is not None:
            handle._prior_events.extend(tele.events.snapshot())
        handle._preempted_by = None
        log.warning("query %d preempted (x%d) — requeued for "
                    "checkpoint-backed resume", handle.query_id,
                    handle.preemptions)
        dead = False
        with self._cv:
            if self._shutdown or handle._user_cancel:
                dead = True
            else:
                handle._reset_for_requeue()
                self.qos.requeue_front_locked(handle)
                self._cv.notify_all()
        if dead:
            handle._finish(QueryStatus.CANCELLED,
                           error=exc.with_traceback(None))

    def _fail_preempt_budget(self, handle: QueryHandle, sink: Dict,
                             limit: int) -> None:
        """Terminal: the victim spent its whole fault.maxTotalAttempts
        budget on preemptions — fail it instead of requeueing forever
        (the same attempt-ceiling contract as stacked retries)."""
        from ..fault.budget import AttemptBudgetExhausted
        from ..telemetry.events import emit_event

        ledger = [{"kind": "preempt", "count": handle.preemptions}]
        emit_event("attempt_budget_exhausted",
                   query_id=handle.query_id, limit=limit,
                   attempts=handle.preemptions, ledger=ledger)
        self._attribute(handle, sink)
        handle._finish(QueryStatus.FAILED, error=AttemptBudgetExhausted(
            f"query {handle.query_id} preempted {handle.preemptions} "
            f"times — fault.maxTotalAttempts ({limit}) exhausted",
            ledger))

    # ----- adaptive reservation rebase --------------------------------------
    def rebase_reservation(self, observed_bytes: int) -> int:
        """SHRINK the calling worker thread's HBM reservation to
        ``observed_bytes`` (never grows — growing mid-flight could
        over-commit the arena) and wake the dispatcher so a queued
        query can use the freed headroom.  Called by the adaptive
        executor once real stage-output sizes replace the admission
        estimate.  Returns the bytes freed (0 when not a worker
        thread, or nothing to free)."""
        if self._dm is None:
            return 0
        target = max(0, int(observed_bytes))
        with self._cv:
            holder = self._reservations.get(threading.get_ident())
            if holder is None or holder[0] <= target:
                return 0
            freed = holder[0] - target
            holder[0] = target
        self._dm.release_reservation(freed)
        with self._cv:
            self._cv.notify_all()
        return freed

    def _attribute(self, handle: QueryHandle, sink: Dict) -> None:
        """Per-query metric/profile attribution from the attempt's own
        ExecContext (stowed by ``Session._finalize_metrics``)."""
        ctx = sink.get("ctx")
        if ctx is None:
            return
        handle._ctx = ctx
        handle.metrics = dict(getattr(ctx, "final_metrics", None)
                              or ctx.metrics.snapshot())
        handle.profile = getattr(ctx, "profile", None)

    def _unwind_cancelled(self, handle: QueryHandle, sink: Dict,
                          exc: TpuQueryCancelled) -> None:
        """Terminal cancellation unwind.  The normal query-end path
        (``_execute_native``'s finally) already finalized metrics,
        released the plan's exec lock and freed this query's shuffle
        slots; what remains query-scoped is the worker's own semaphore
        permits and the plan's cached uploads."""
        from ..telemetry.events import emit_event

        # the query's telemetry binding is still on this thread, so
        # the terminal event lands in ITS event ring
        emit_event("query_cancelled", query_id=handle.query_id,
                   reason=str(exc))
        if self._dm is not None:
            try:
                self._dm.semaphore.release_task()
            except Exception:  # noqa: BLE001 — unwind must not raise
                pass
        phys = sink.get("phys")
        if phys is not None:
            self._drop_upload_caches(phys)
        self._attribute(handle, sink)
        log.warning("query %d cancelled: %s", handle.query_id, exc)
        # drop the traceback/context chain before stowing the error on
        # the handle: cancellation is cooperative (the frames carry no
        # diagnosis) and their locals would pin device batches past the
        # zero-leak unwind contract
        exc.__cause__ = None
        exc.__context__ = None
        handle._finish(QueryStatus.CANCELLED,
                       error=exc.with_traceback(None))

    def _drop_upload_caches(self, phys) -> None:
        """Walk the physical tree dropping cached uploads — the one
        device artifact designed to outlive its query must not outlive
        a CANCELLED query (zero-leak unwind contract)."""
        seen = set()
        stack = [phys]
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            drop = getattr(node, "drop_cached_uploads", None)
            if drop is not None:
                try:
                    drop()
                except Exception:  # noqa: BLE001 — unwind must not raise
                    pass
            stack.extend(getattr(node, "children", ()) or ())

    def _run_cpu_fallback(self, handle: QueryHandle, cause,
                          sink: Dict) -> None:
        """Per-query circuit breaker: re-execute THIS query on the
        CPU-exec plan.  Unlike the direct-execute ladder rung this
        must NOT disarm the process-wide injectors or write the global
        fault counters — concurrent queries keep their TPU path and
        their own failure budgets."""
        from ..fault.stats import DEGRADE_CPU
        from ..plan.overrides import cpu_exec_plan
        from ..plan.physical import ExecContext, collect_batches
        from ..telemetry.events import emit_event

        # Same zero-leak discipline as the cancellation unwind: the
        # failed attempt's frames (held by cause.__traceback__ and its
        # context chain) pin the attempt's exec tree — and with it any
        # upload cache the attempt already published — so strip them
        # BEFORE the cause reaches a log record that may retain it,
        # and drop the dead attempt's caches deterministically.
        cause.__cause__ = None
        cause.__context__ = None
        cause = cause.with_traceback(None)
        failed_phys = sink.get("phys")
        if failed_phys is not None:
            self._drop_upload_caches(failed_phys)

        emit_event("degrade", level=DEGRADE_CPU, rung="cpu",
                   cause=type(cause).__name__, scheduled=True,
                   query_id=handle.query_id)
        log.warning(
            "scheduled query %d exhausted fault recovery (%s: %s) — "
            "circuit breaker tripped to the CPU-exec plan",
            handle.query_id, type(cause).__name__, cause)
        self._attribute(handle, sink)  # failed attempt's counters
        prior = {k: v for k, v in (handle.metrics or {}).items()
                 if k.startswith(("fault.", "retry."))}
        sess = self.session
        phys = cpu_exec_plan(sess.conf, handle.plan)
        # session=None: a bare host context — no telemetry re-begin,
        # no injector (re)install, no global stats writes
        ctx = ExecContext(sess.conf, None)
        data = phys.execute(ctx)
        schema = phys.schema if len(phys.schema) else handle.plan.schema
        out = collect_batches(data, schema, ctx)
        merged = dict(ctx.metrics.snapshot())
        merged.update(prior)
        merged["fault.degradeLevel"] = DEGRADE_CPU
        handle.metrics = merged
        handle.exec_path = "cpu"
        # the CPU rung's result is bit-identical by the oracle contract,
        # so it is just as cacheable as the native one
        self._store_serving_result(handle, out)
        handle._finish(QueryStatus.FINISHED, result=out)

    # ----- lifecycle -------------------------------------------------------
    def shutdown(self, timeout: float = 10.0) -> None:
        """Cancel queued + running queries, stop the dispatcher and
        overload monitor, and join every scheduler thread."""
        with self._cv:
            already = self._shutdown
            self._shutdown = True
            queued = self.qos.drain_all_locked()
            running = list(self._running)
            workers = list(self._workers)
            self._cv.notify_all()
        self.overload.stop()
        for h in queued:
            h.token.cancel("scheduler shutdown")
            h._finish(QueryStatus.CANCELLED,
                      error=TpuQueryCancelled("scheduler shutdown"))
        for h in running:
            h.token.cancel("scheduler shutdown")
        if not already:
            self._dispatcher.join(timeout)
        for t in workers:
            t.join(timeout)
        if not already:
            # end-of-life storage hygiene (shared with Session.close):
            # orphaned spill files + expired/over-cap checkpoint dirs
            try:
                self.session.sweep_storage()
            except Exception:  # noqa: BLE001 — shutdown must not raise
                log.warning("shutdown storage sweep failed",
                            exc_info=True)

    @property
    def active_count(self) -> int:
        return self._n_active

    @property
    def queued_count(self) -> int:
        with self._cv:
            return self.qos.queued_count_locked()

    def qos_metrics(self) -> Dict[str, float]:
        """``scheduler.tenant.<name>.*`` counters (submitted,
        dispatched, finished, shed, preempted, queue waits, live
        depths, latency percentiles) plus the overload state and the
        queue-wait percentiles — the serving-tier observability
        surface (bench_serving.py, docs/qos.md)."""
        with self._cv:
            out = self.qos.metrics_locked()
        out["scheduler.overloaded"] = \
            1.0 if self.overload.overloaded else 0.0
        for p, v in self.overload.wait_hist.percentiles().items():
            out[f"scheduler.queueWait{p.capitalize()}Ms"] = round(v, 3)
        return out

    def histograms(self) -> List:
        """``(family_suffix, labels, LatencyHistogram)`` triples for
        ``telemetry.export.prometheus_text(histograms=...)``: the
        queue-wait histogram plus one end-to-end latency histogram per
        tenant."""
        with self._cv:
            out = self.qos.histograms_locked()
        return [("queue_wait_ms", {}, self.overload.wait_hist)] + out
