"""Source ledger — the durable exactly-once record of one stream.

One JSON document per stream under ``streaming.stateDir`` (default: the
reserved ``streams/`` directory inside the recovery root, which the
CheckpointStore hygiene sweep skips by name):

::

    <state root>/<stream fingerprint>/ledger.json

It records, per committed micro-batch: the batch id, the per-source
file-fingerprint lists the batch covered (the :func:`io.scans
.file_fingerprint` records — path, size, mtime_ns), and the
per-occurrence exchange fingerprints of the batch's plan.  The ledger
is written atomically (utils/fsio temp+fsync+rename) strictly AFTER
the batch result materialized: the ledger advancing IS the commit
point.  A crash after checkpoint writes but before the ledger commit
merely re-runs a tick that is idempotent over the same cumulative
input — the merged checkpoint is found by fingerprint and reused.

Host-only, like recovery/: no jax, no engine imports.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Tuple

from ..config import STREAMING_STATE_DIR
from ..recovery.manager import resolve_root
from ..recovery.store import STREAMS_DIRNAME
from ..utils import fsio

log = logging.getLogger(__name__)

LEDGER_NAME = "ledger.json"
LEDGER_VERSION = 1


def stream_state_root(conf) -> str:
    """Where stream ledgers live: ``streaming.stateDir`` when set, else
    the reserved ``streams/`` dir under the recovery root."""
    d = conf.get(STREAMING_STATE_DIR)
    if d:
        return d
    return os.path.join(resolve_root(conf), STREAMS_DIRNAME)


def fingerprints_match(a: Dict, b: Dict) -> bool:
    return (a.get("path") == b.get("path")
            and int(a.get("size", -1)) == int(b.get("size", -1))
            and int(a.get("mtime_ns", -1)) == int(b.get("mtime_ns", -1)))


def split_new_files(prev: List[Dict],
                    cur: List[Dict]) -> Tuple[bool, List[Dict]]:
    """``(prefix_stable, new_suffix)``: committed files must reappear
    as an UNCHANGED prefix of the current (sorted) discovery — the
    append-only source contract.  A rewritten, resized or removed
    committed file breaks prefix stability and the caller falls back to
    a full-recompute batch (correct, just not incremental)."""
    if len(cur) < len(prev):
        return False, []
    for p, c in zip(prev, cur):
        if not fingerprints_match(p, c):
            return False, []
    return True, cur[len(prev):]


class SourceLedger:
    """Load/commit surface of one stream's ledger document."""

    def __init__(self, conf, stream_fp: str, result_cache=None):
        self._conf = conf  # serving-cache invalidation at commit time
        #: the owning session's serving result cache when available, so
        #: commit-time invalidations land in ITS counters/metrics; None
        #: falls back to a detached policy instance
        self._result_cache = result_cache
        self.dir = os.path.join(stream_state_root(conf), stream_fp)
        self.path = os.path.join(self.dir, LEDGER_NAME)
        self.stream_fp = stream_fp
        self.batch_id = 0
        #: per-source committed fingerprint lists (source order = the
        #: template plan's FileScan preorder position)
        self.files: List[List[Dict]] = []
        #: occurrence key -> exchange fingerprint of the last batch
        self.exchanges: Dict[str, str] = {}

    def load(self) -> bool:
        """True when a committed ledger was loaded (stream resume)."""
        try:
            with open(self.path) as f:
                m = json.load(f)
            if not isinstance(m, dict) or "batch_id" not in m \
                    or not isinstance(m.get("files"), list):
                raise ValueError(f"malformed stream ledger: {self.path}")
            self.batch_id = int(m["batch_id"])
            self.files = [list(fps) for fps in m["files"]]
            self.exchanges = dict(m.get("exchanges") or {})
            return True
        except FileNotFoundError:
            return False
        except Exception:  # noqa: BLE001 — a torn ledger restarts at batch 0
            log.warning("stream ledger %s unreadable — restarting from "
                        "batch 0", self.path, exc_info=True)
            self.batch_id = 0
            self.files = []
            self.exchanges = {}
            return False

    def commit(self, batch_id: int, files: List[List[Dict]],
               exchanges: Dict[str, str]) -> None:
        """Atomically advance the ledger — the exactly-once commit
        marker of one micro-batch.  OSError propagates: a batch whose
        commit cannot land must NOT be reported committed."""
        os.makedirs(self.dir, exist_ok=True)
        fsio.atomic_write_json(self.path, {
            "version": LEDGER_VERSION,
            "stream": self.stream_fp,
            "batch_id": int(batch_id),
            "files": [list(fps) for fps in files],
            "exchanges": dict(exchanges),
        })
        prev_files = self.files
        self.batch_id = int(batch_id)
        self.files = [list(fps) for fps in files]
        self.exchanges = dict(exchanges)
        self._invalidate_serving(prev_files, self.files)

    def _invalidate_serving(self, prev: List[List[Dict]],
                            cur: List[List[Dict]]) -> None:
        """Eagerly drop serving result-cache entries derived from
        files this commit changed or extended — the push half of the
        serving invalidation contract (serving/result_cache.py owns
        the policy and the ``cache_invalidate`` events; this module
        only reports WHICH paths moved).  Never fails the commit."""
        try:
            changed = set()
            for i, fps in enumerate(cur):
                old = prev[i] if i < len(prev) else []
                stable, new_suffix = split_new_files(old, fps)
                if not stable:
                    # rewritten/shrunk prefix: every file of the source
                    # is suspect, old AND new
                    for fp in list(old) + list(fps):
                        changed.add(fp.get("path"))
                else:
                    for fp in new_suffix:
                        changed.add(fp.get("path"))
            changed.discard(None)
            if not changed:
                return
            if self._result_cache is not None:
                self._result_cache.invalidate_paths(changed)
            else:
                from ..serving.result_cache import invalidate_for_files

                invalidate_for_files(self._conf, changed)
        except Exception:  # noqa: BLE001 — the commit already landed
            log.warning("serving-cache invalidation failed",
                        exc_info=True)
