"""Sort / limit equality tests — CPU oracle vs TPU engine.

Reference analogues: SortExecSuite, sort_test.py, LimitExecSuite.
"""
import pytest

from spark_rapids_tpu import f
from spark_rapids_tpu.testing import datagen as dg
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
)


def _data(n=400, seed=0):
    return dg.gen_batch({
        "a": dg.IntGen(dg.T.INT32, min_val=-50, max_val=50),
        "b": dg.IntGen(dg.T.INT64),
        "c": dg.FloatGen(dg.T.FLOAT64),
        "s": dg.StringGen(max_len=6),
    }, n, seed)


@pytest.mark.parametrize("keys_fn", [
    lambda df: [df["a"]],
    lambda df: [df["a"].desc()],
    lambda df: [df["c"]],
    lambda df: [df["c"].desc()],
    lambda df: [df["a"], df["b"].desc()],
    lambda df: [df["s"]],
    lambda df: [df["s"].desc(), df["a"]],
    lambda df: [df["a"].asc().nulls_last_()],
    lambda df: [df["a"].desc().nulls_first_()],
], ids=["asc", "desc", "float", "float_desc", "multi", "str", "str_desc",
        "nulls_last", "desc_nulls_first"])
def test_global_sort(keys_fn):
    # global sort: total order matters, so compare ordered rows; ties are
    # broken by sorting on all remaining columns to make the test
    # deterministic across engines
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(*(keys_fn(df) + [df["b"], df["s"], df["c"]])),
        _data())


def test_sort_within_partitions():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort_within_partitions(df["a"], df["b"], df["s"],
                                             df["c"]),
        _data(300, 5))


def test_sort_nan_ordering():
    data = {
        "x": [1.0, float("nan"), None, -0.0, 0.0, float("inf"),
              -float("inf"), 2.5, None, float("nan")],
        "i": list(range(10)),
    }
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["x"], df["i"]), data)
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["x"].desc(), df["i"]), data)


def test_limit():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["b"], df["a"], df["s"], df["c"]).limit(17),
        _data(200, 9))


def test_global_sort_long_string_prefix_collision():
    # Strings wider than the range partitioner's 32-byte placement
    # prefix, sharing that prefix, with a DIFFERENT secondary-key order
    # than the post-prefix bytes: placement must ignore keys after the
    # truncated string (prefix-only placement is monotone; including the
    # secondary key routes prefix-equal rows against the global order).
    import random

    rng = random.Random(7)
    prefix = "x" * 40  # every string collides on the 32-byte prefix
    rows = []
    for i in range(300):
        tail = "%06d" % rng.randrange(1000)
        rows.append((prefix + tail, rng.randrange(100), i))
    data = {
        "s": [r[0] for r in rows],
        "k": [r[1] for r in rows],
        "i": [r[2] for r in rows],
    }
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["s"], df["k"], df["i"]), data,
        n_partitions=4)
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["s"].desc(), df["k"].desc(), df["i"]),
        data, n_partitions=4)


def test_global_sort_mixed_width_string_batches():
    # Some input partitions hold only SHORT strings (batch byte matrix
    # narrower than the placement prefix) while others hold long ones:
    # the range partitioner's pass layout must be identical for every
    # batch (bounds/samples are shared), i.e. the cut after a string
    # key cannot depend on the batch's own matrix width.
    import random

    rng = random.Random(11)
    short = ["a%02d" % rng.randrange(40) for _ in range(200)]
    long_ = [("z" * 36) + "%04d" % rng.randrange(100)
             for _ in range(200)]
    # first half of the rows (the first input partitions) short, the
    # rest long — chunked row->partition assignment keeps them apart
    data = {
        "s": short + long_,
        "k": [rng.randrange(30) for _ in range(400)],
        "i": list(range(400)),
    }
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["s"], df["k"], df["i"]), data,
        n_partitions=4)


def test_sort_on_device_plan_placement():
    from spark_rapids_tpu import Session

    sess = Session({
        "spark.rapids.tpu.sql.test.enabled": True,
        "spark.rapids.tpu.sql.test.allowedNonTpu": "ShuffleExchangeExec",
    })
    df = sess.create_dataframe({"k": [3, 1, 2], "v": [1.0, 2.0, 3.0]},
                               n_partitions=1)
    out = df.sort("k").collect()
    assert out == [(1, 2.0), (2, 3.0), (3, 1.0)]
