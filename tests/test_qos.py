"""Multi-tenant QoS (spark_rapids_tpu/scheduler/qos.py + the
scheduler's preemption/shedding paths).

The contracts under test:

* **Weighted fair share** — under contention, dispatch counts converge
  to the ``scheduler.tenant.<name>.weight`` ratio regardless of
  arrival order; an idle tenant cannot bank virtual time into a burst.
* **Priority aging** — a queued low-priority query accrues effective
  priority with wait, so a steady high-priority stream can delay but
  never indefinitely starve it (the PR 7 fixed-priority starvation
  edge, pinned by a regression test).
* **Checkpoint-backed preemption** — a strictly higher-priority query
  evicts the lowest-priority running victim through the zero-leak
  cancellation unwind; the victim requeues with its aging credit,
  resumes from completed exchange checkpoints (``recovery.enabled``)
  bit-identical with ``recovery.numStagesResumed > 0``, and every
  preemption is charged against ``fault.maxTotalAttempts``.
* **Overload shedding** — past the ``scheduler.overload.*`` thresholds
  new low-tier submissions shed with the typed retryable
  :class:`TpuOverloaded` carrying ``retry_after_ms``; transitions and
  sheds emit ``overload_{enter,exit,shed}`` events.
* **Admission observability** — every ``admission_reject`` carries the
  queue depth and the victim's queue wait in milliseconds.
"""
import glob
import os
import threading
import time

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.fault.budget import AttemptBudgetExhausted
from spark_rapids_tpu.scheduler import QueryRejected, TpuOverloaded
from spark_rapids_tpu.scheduler.qos import (OverloadMonitor,
                                            TenantRegistry,
                                            effective_priority)
from spark_rapids_tpu.scheduler.query_scheduler import QueryStatus

from test_scheduler import (FAST, SHUFFLED, _assert_unwound, _inject,
                            _join_agg_df, _norm, _select_df,
                            _wait_until)


class _H:
    """Stub QueryHandle for registry-level unit tests."""

    _ids = iter(range(1, 10_000))

    def __init__(self, tenant, priority, first_queued_at=None):
        self.tenant = tenant
        self.priority = priority
        self.query_id = next(_H._ids)
        self._queued_at = time.monotonic()
        self._first_queued_at = (self._queued_at
                                 if first_queued_at is None
                                 else first_queued_at)
        self._done = threading.Event()


# ==========================================================================
# Fair share + aging (registry-level, no session)
# ==========================================================================
def test_fair_share_interleave_matches_weights():
    reg = TenantRegistry(TpuConf({
        "spark.rapids.tpu.scheduler.tenant.gold.weight": 3.0,
        "spark.rapids.tpu.scheduler.tenant.bronze.weight": 1.0,
    }))
    for _ in range(6):
        reg.enqueue_locked(_H("gold", 0))
        reg.enqueue_locked(_H("bronze", 0))
    order = []
    now = time.monotonic()
    for _ in range(8):
        h = reg.pick_locked(now, aging_ms=0)
        reg.note_dispatch_locked(h, now)
        order.append(h.tenant)
    # vtime advances 1/weight per dispatch -> 3:1 service ratio
    assert order.count("gold") == 6 and order.count("bronze") == 2, order
    assert reg.tenants["gold"].vtime == pytest.approx(
        reg.tenants["bronze"].vtime)


def test_idle_tenant_cannot_bank_virtual_time():
    reg = TenantRegistry(TpuConf())
    now = time.monotonic()
    # busy tenant dispatches 10 while "idle" has nothing queued
    for _ in range(10):
        reg.enqueue_locked(_H("busy", 0))
        reg.note_dispatch_locked(reg.pick_locked(now, 0), now)
    reg.enqueue_locked(_H("idle", 0))
    # the floor: idle joins at the busy tenant's clock, not at 0 —
    # otherwise it would win the next 10 dispatches as a burst
    assert reg.tenants["idle"].vtime == pytest.approx(
        reg.tenants["busy"].vtime)


def test_priority_aging_overtakes_within_tenant():
    reg = TenantRegistry(TpuConf())
    now = time.monotonic()
    old_low = _H("t", 0, first_queued_at=now - 1.0)  # waited 1s
    fresh_high = _H("t", 5)
    reg.enqueue_locked(old_low)
    reg.enqueue_locked(fresh_high)
    # aging off: static priority wins
    assert reg.peek_locked(now, aging_ms=0) is fresh_high
    # 100ms/level aging: 1s of wait = +10 effective levels
    assert reg.peek_locked(now, aging_ms=100) is old_low
    assert effective_priority(old_low, now, 100) == pytest.approx(10.0)


# ==========================================================================
# OverloadMonitor (unit, stubbed inputs)
# ==========================================================================
def test_overload_monitor_hysteresis_and_retry_hint():
    conf = TpuConf({
        "spark.rapids.tpu.scheduler.overload.queueWaitMs": 100,
        "spark.rapids.tpu.scheduler.overload.retryAfterMs": 500,
    })
    inputs = {"waits": [], "pressure": 0.0}
    mon = OverloadMonitor(conf, lambda: inputs["waits"],
                          lambda: inputs["pressure"])
    assert mon.enabled and not mon.overloaded
    inputs["waits"] = [250.0] * 8  # p95 well past the threshold
    assert mon.evaluate() is True
    # hysteresis: recovery requires < 0.5x threshold, 60ms is not cool
    inputs["waits"] = [60.0] * 8
    assert mon.evaluate() is True
    inputs["waits"] = [10.0] * 8
    assert mon.evaluate() is False
    assert [h["event"] for h in mon.history] == ["overload_enter",
                                                 "overload_exit"]
    # retry hint scales with queue depth
    assert mon.retry_after_ms(0, 16) == 500
    assert mon.retry_after_ms(16, 16) == 1000


def test_tpu_overloaded_requires_retry_after_ms():
    with pytest.raises(TypeError):
        TpuOverloaded("no hint")  # retry_after_ms is kw-only required
    e = TpuOverloaded("shed", retry_after_ms=750)
    assert e.retry_after_ms == 750


# ==========================================================================
# Starvation regression (satellite: the PR 7 fixed-priority edge)
# ==========================================================================
def test_high_priority_stream_cannot_starve_queued_low():
    """A STEADY stream of freshly-arriving priority-10 queries (always
    >= 2 outstanding, replenished on completion) against one queued
    priority-0 query.  Each new arrival starts with zero age while the
    low query keeps accruing (20ms per effective level), so it
    overtakes the stream instead of waiting for it to drain —
    the PR 7 fixed-priority scheduler starved it indefinitely here.
    Preemption is off: this pins the queue-ORDERING contract (an
    evicted victim is the preemption tests' concern)."""
    sess = srt.Session({
        **FAST, **SHUFFLED,
        "spark.rapids.tpu.scheduler.maxConcurrent": 1,
        "spark.rapids.tpu.scheduler.preemption.enabled": False,
        "spark.rapids.tpu.scheduler.priorityAgingMs": 20,
    })
    try:
        first = sess.submit(_join_agg_df(sess), priority=10)
        low = sess.submit(_join_agg_df(sess), priority=0)
        highs = [first]
        deadline = time.monotonic() + 120
        while not low.done() and time.monotonic() < deadline:
            highs = [h for h in highs if not h.done()]
            while len(highs) < 2:
                highs.append(sess.submit(_join_agg_df(sess),
                                         priority=10))
            time.sleep(0.01)
        assert low.done(), \
            "low-priority query starved by the high-priority stream"
        low.result(timeout=10)
        for h in highs:
            h.result(timeout=180)
    finally:
        sess.shutdown_scheduler()
        sess.close()


# ==========================================================================
# Overload shedding (behavioral)
# ==========================================================================
def test_overload_sheds_low_tier_with_retry_hint():
    from spark_rapids_tpu.telemetry import spans

    sess = srt.Session(_inject(
        "always", "delay", site="exchange.write", delay_ms=250.0,
        **SHUFFLED,
        **{"spark.rapids.tpu.telemetry.enabled": True,
           "spark.rapids.tpu.scheduler.maxConcurrent": 1,
           "spark.rapids.tpu.scheduler.preemption.enabled": False,
           "spark.rapids.tpu.scheduler.overload.queueWaitMs": 60,
           "spark.rapids.tpu.scheduler.overload.shedBelowPriority": 5}))
    tele = spans.QueryTelemetry(sess.conf)
    spans.activate(tele)
    try:
        hs = [sess.submit(_join_agg_df(sess), priority=5,
                          tenant="gold") for _ in range(2)]
        # the queued query's live wait crosses 60ms -> overload
        _wait_until(lambda: sess.scheduler.overload.evaluate(),
                    timeout=30, msg="overload_enter")
        with pytest.raises(TpuOverloaded) as ei:
            sess.submit(_select_df(sess), priority=0, tenant="bronze")
        assert ei.value.retry_after_ms > 0
        # high-tier submissions are never shed
        hs.append(sess.submit(_select_df(sess), priority=5,
                              tenant="gold"))
        for h in hs:
            h.result(timeout=180)
        shed = [e for e in tele.events.snapshot()
                if e["event"] == "overload_shed"]
        assert shed and shed[0]["retry_after_ms"] > 0 \
            and shed[0]["tenant"] == "bronze", shed
        assert [h["event"] for h in
                sess.scheduler.overload.history][:1] == ["overload_enter"]
        m = sess.scheduler.qos_metrics()
        assert m["scheduler.tenant.bronze.shed"] >= 1
    finally:
        spans.deactivate()
        sess.shutdown_scheduler()
        sess.close()


# ==========================================================================
# Checkpoint-backed preemption
# ==========================================================================
def test_preemption_resumes_from_checkpoints_bit_identical(tmp_path):
    """The ISSUE preemption drill: a low-tier shuffling query is
    preempted mid-query by a high-tier one under maxConcurrent=1; both
    finish bit-identical to serial, the victim's metrics show
    ``recovery.numStagesResumed > 0`` (work-preserving resume), and
    the unwind leaks nothing."""
    sess = srt.Session(_inject(
        "always", "delay", site="exchange.read", delay_ms=300.0,
        **SHUFFLED,
        **{"spark.rapids.tpu.telemetry.enabled": True,
           "spark.rapids.tpu.scheduler.maxConcurrent": 1,
           "spark.rapids.tpu.recovery.enabled": True,
           "spark.rapids.tpu.recovery.dir": str(tmp_path)}))
    try:
        serial = _join_agg_df(sess).collect()
        sel_serial = _select_df(sess).collect()
        victim = sess.submit(_join_agg_df(sess), priority=0,
                             tenant="bronze")
        # exchange WRITES complete fast (the injected delay is on the
        # read side), so checkpoints exist before the eviction
        _wait_until(
            lambda: glob.glob(os.path.join(
                str(tmp_path), "*", "*", "manifest.json")),
            timeout=60, msg="first exchange checkpoint")
        pre = sess.submit(_select_df(sess), priority=10, tenant="gold")
        assert _norm(pre.result(timeout=180).to_rows()) \
            == _norm(sel_serial)
        assert _norm(victim.result(timeout=180).to_rows()) \
            == _norm(serial)
        assert victim.preemptions >= 1  # charged to the victim
        assert victim.metrics.get("recovery.numStagesResumed", 0) > 0
        evs = [e["event"] for e in victim.events()]
        assert "preempt_victim" in evs and "preempt_resume" in evs, evs
        resume = [e for e in victim.events()
                  if e["event"] == "preempt_resume"][0]
        assert resume["stages_resumed"] > 0
        del victim, pre
        _assert_unwound(sess)
    finally:
        sess.shutdown_scheduler()
        sess.close()


def test_preemption_without_recovery_reruns_bit_identical():
    """No recovery store: the victim loses its partial work but still
    requeues (aging credit intact) and re-runs to the identical
    result, with zero leaked permits/reservations/slots."""
    sess = srt.Session(_inject(
        "always", "delay", site="exchange.write", delay_ms=150.0,
        **SHUFFLED,
        **{"spark.rapids.tpu.scheduler.maxConcurrent": 1}))
    try:
        serial = _join_agg_df(sess).collect()
        victim = sess.submit(_join_agg_df(sess), priority=0,
                             tenant="bronze")
        _wait_until(lambda: victim.status() == QueryStatus.RUNNING,
                    timeout=60, msg="victim running")
        pre = sess.submit(_select_df(sess), priority=10, tenant="gold")
        pre.result(timeout=180)
        assert _norm(victim.result(timeout=180).to_rows()) \
            == _norm(serial)
        assert victim.preemptions >= 1
        assert victim.status() == QueryStatus.FINISHED
        m = sess.scheduler.qos_metrics()
        assert m["scheduler.tenant.bronze.preempted"] >= 1
        del victim, pre
        _assert_unwound(sess)
    finally:
        sess.shutdown_scheduler()
        sess.close()


def test_preemption_charges_and_exhausts_attempt_budget():
    """fault.maxTotalAttempts=1: the first preemption spends the whole
    attempt budget, so the victim fails terminally with
    AttemptBudgetExhausted instead of requeueing forever."""
    sess = srt.Session(_inject(
        "always", "delay", site="exchange.write", delay_ms=150.0,
        **SHUFFLED,
        **{"spark.rapids.tpu.telemetry.enabled": True,
           "spark.rapids.tpu.scheduler.maxConcurrent": 1,
           "spark.rapids.tpu.fault.maxTotalAttempts": 1}))
    try:
        victim = sess.submit(_join_agg_df(sess), priority=0)
        _wait_until(lambda: victim.status() == QueryStatus.RUNNING,
                    timeout=60, msg="victim running")
        pre = sess.submit(_select_df(sess), priority=10)
        pre.result(timeout=180)
        with pytest.raises(AttemptBudgetExhausted):
            victim.result(timeout=180)
        assert victim.status() == QueryStatus.FAILED
        evs = [e["event"] for e in victim.events()]
        assert "attempt_budget_exhausted" in evs, evs
        del victim, pre
        _assert_unwound(sess)
    finally:
        sess.shutdown_scheduler()
        sess.close()


# ==========================================================================
# admission_reject observability (satellite)
# ==========================================================================
def test_admission_reject_events_carry_depth_and_wait():
    from spark_rapids_tpu.telemetry import spans

    sess = srt.Session(_inject(
        "always", "delay", site="exchange.write", delay_ms=250.0,
        **SHUFFLED,
        **{"spark.rapids.tpu.telemetry.enabled": True,
           "spark.rapids.tpu.scheduler.maxConcurrent": 1,
           "spark.rapids.tpu.scheduler.maxQueued": 1,
           "spark.rapids.tpu.scheduler.queueTimeoutMs": 150}))
    tele = spans.QueryTelemetry(sess.conf)
    spans.activate(tele)
    try:
        # the dispatcher thread captures this binding at creation, so
        # ITS queue_timeout rejections land in this ring too
        sched = sess.scheduler
        running = sess.submit(_join_agg_df(sess))
        _wait_until(lambda: sched.active_count == 1, timeout=60,
                    msg="first query running")
        queued = sess.submit(_join_agg_df(sess))
        with pytest.raises(QueryRejected):
            sess.submit(_select_df(sess))  # queue_full
        # the queued query then exceeds queueTimeoutMs -> queue_timeout
        with pytest.raises(QueryRejected):
            queued.result(timeout=60)
        running.result(timeout=180)
        rejects = {e["reason"]: e for e in tele.events.snapshot()
                   if e["event"] == "admission_reject"}
        assert {"queue_full", "queue_timeout"} <= set(rejects), rejects
        for ev in rejects.values():
            assert "queue_depth" in ev and "queue_wait_ms" in ev, ev
        assert rejects["queue_full"]["queue_depth"] >= 1
        assert rejects["queue_timeout"]["queue_wait_ms"] >= 150
    finally:
        spans.deactivate()
        sess.shutdown_scheduler()
        sess.close()


# ==========================================================================
# Latency histograms (queue-wait + per-tenant) in the export surface
# ==========================================================================
def test_latency_histograms_export_percentiles_and_prometheus():
    sess = srt.Session()
    try:
        handles = [sess.submit(_select_df(sess), tenant=t)
                   for t in ("gold", "bronze", "gold")]
        for h in handles:
            h.result(timeout=120)
        em = sess.export_metrics()
        # sliding-window percentile gauges for queue wait and for each
        # tenant's end-to-end latency
        for p in ("P50", "P95", "P99"):
            assert f"scheduler.queueWait{p}Ms" in em, sorted(
                k for k in em if "queueWait" in k)
            assert f"scheduler.tenant.gold.latency{p}Ms" in em
            assert f"scheduler.tenant.bronze.latency{p}Ms" in em
        assert em["scheduler.tenant.gold.latencyP50Ms"] <= \
            em["scheduler.tenant.gold.latencyP99Ms"]
        # prometheus: proper histogram exposition with tenant labels
        text = sess.metrics_text()
        assert "# TYPE spark_rapids_tpu_queue_wait_ms histogram" in text
        assert ("# TYPE spark_rapids_tpu_query_latency_ms histogram"
                in text)
        assert 'query_latency_ms_bucket{tenant="gold",le="+Inf"} 2' \
            in text
        assert 'query_latency_ms_count{tenant="bronze"} 1' in text
        # the queue-wait histogram counted every dispatched query
        import re as _re
        m = _re.search(
            r'spark_rapids_tpu_queue_wait_ms_count (\d+)', text)
        assert m and int(m.group(1)) >= 3, text[-500:]
    finally:
        sess.shutdown_scheduler()
        sess.close()


def test_overload_monitor_p95_rides_the_histogram():
    mon = OverloadMonitor(TpuConf({}), lambda: [], lambda: 0.0)
    for _ in range(50):
        mon.record_wait(10.0)
    mon.record_wait(2000.0)
    p95 = mon.wait_p95()
    # p95 of 50x10ms + 1x2s sits in the 10ms bucket's neighborhood;
    # bucketing may round up to the bucket bound, never down past it
    assert 8.0 <= p95 <= 32.0, p95
