"""AST lint: QoS shed/preempt telemetry and overload discipline.

Load shedding and preemption are *silent* failure modes when their
telemetry is missing — a client sees a rejection or a slow query and
has no record of why.  Three properties are enforced mechanically:

1. **Decision sites emit** — every function in the scheduler package
   whose name marks a shed or preempt decision (``shed``/``preempt``
   in the name) must call ``emit_event`` itself or via another
   function in the same module, or appear in the allowlist with a
   reason.
2. **TpuOverloaded always carries the backoff hint** — no call site
   anywhere in the package constructs ``TpuOverloaded`` without a
   ``retry_after_ms`` keyword (the class enforces it at runtime; the
   lint catches it before a test ever has to hit the path).
3. **OverloadMonitor threads capture the telemetry binding** — the
   sampler thread spawn must wrap its target with ``capture``/
   ``bound`` (same discipline as test_lint_scheduler.py, pinned here
   specifically so the monitor can never silently lose its ring).
"""
import ast
import os
import re

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "spark_rapids_tpu")

DECISION_RE = re.compile(r"shed|preempt", re.IGNORECASE)

#: "<file>:<function>" -> reason
ALLOWLIST = {
    "query_scheduler.py:_maybe_preempt_locked":
        "dispatcher-side decision; the dispatcher thread has no query "
        "telemetry binding — the victim emits preempt_victim from its "
        "own worker thread in _requeue_preempted",
    "qos.py:count_shed_locked":
        "pure counter bump under _cv; the decision site "
        "(_maybe_shed_overload_locked) emits overload_shed",
}


def _terminal_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _calls_in(node):
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield _terminal_name(n.func)


def _scheduler_sources():
    base = os.path.join(PKG, "scheduler")
    for fn in sorted(os.listdir(base)):
        if fn.endswith(".py"):
            path = os.path.join(base, fn)
            yield fn, ast.parse(open(path).read(), filename=path)


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def test_every_shed_or_preempt_decision_site_emits_telemetry():
    offenders, matched = [], 0
    for fn, tree in _scheduler_sources():
        funcs = {f.name: f for f in _functions(tree)}
        # transitive emit closure WITHIN the module: f emits if it
        # calls emit_event, or calls a module function that does
        emits = {name for name, f in funcs.items()
                 if "emit_event" in set(_calls_in(f))}
        changed = True
        while changed:
            changed = False
            for name, f in funcs.items():
                if name in emits:
                    continue
                if set(_calls_in(f)) & emits:
                    emits.add(name)
                    changed = True
        for name, f in funcs.items():
            if not DECISION_RE.search(name):
                continue
            matched += 1
            if f"{fn}:{name}" in ALLOWLIST:
                continue
            if name not in emits:
                offenders.append(f"{fn}:{name} (line {f.lineno})")
    # _maybe_shed_overload_locked / _shed_expired_locked /
    # _requeue_preempted / _fail_preempt_budget at minimum
    assert matched >= 4, \
        f"decision-site scan matched only {matched} — lint broken?"
    assert not offenders, \
        "shed/preempt decision sites that never emit a telemetry " \
        f"event (emit_event, directly or via this module): {offenders}"


def test_no_tpu_overloaded_without_retry_after_ms():
    sites, offenders = 0, []
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            tree = ast.parse(open(path).read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) \
                        or _terminal_name(node.func) != "TpuOverloaded":
                    continue
                sites += 1
                kw = {k.arg for k in node.keywords}
                if "retry_after_ms" not in kw and None not in kw:
                    offenders.append(
                        f"{os.path.relpath(path, PKG)}:{node.lineno}")
    assert sites >= 1, "no TpuOverloaded construction found — scan broken?"
    assert not offenders, \
        "TpuOverloaded constructed without its retry_after_ms " \
        f"backoff hint: {offenders}"


def test_overload_monitor_thread_captures_binding():
    path = os.path.join(PKG, "scheduler", "qos.py")
    tree = ast.parse(open(path).read(), filename=path)
    monitor = next(n for n in ast.walk(tree)
                   if isinstance(n, ast.ClassDef)
                   and n.name == "OverloadMonitor")
    spawns = [n for n in ast.walk(monitor)
              if isinstance(n, ast.Call)
              and _terminal_name(n.func) == "Thread"]
    assert spawns, "OverloadMonitor spawns no thread — scan broken?"
    for node in spawns:
        names = set(_calls_in(node))
        assert names & {"capture", "bound", "attached"}, \
            f"OverloadMonitor Thread spawn at qos.py:{node.lineno} " \
            "missing the telemetry capture()/bound() wrapping"
