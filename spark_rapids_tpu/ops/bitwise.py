"""Bitwise and shift expressions.

Capability parity with the reference's bitwise.scala: And/Or/Xor/Not/
ShiftLeft/ShiftRight/ShiftRightUnsigned.  Shift distance is masked to the
value's bit width (Java semantics).
"""
from __future__ import annotations

import numpy as np

from .. import types as T
from .expression import BinaryExpression, UnaryExpression


class BitwiseAnd(BinaryExpression):
    def do_cpu(self, l, r):
        return l & r

    def do_tpu(self, l, r):
        return l & r


class BitwiseOr(BinaryExpression):
    def do_cpu(self, l, r):
        return l | r

    def do_tpu(self, l, r):
        return l | r


class BitwiseXor(BinaryExpression):
    def do_cpu(self, l, r):
        return l ^ r

    def do_tpu(self, l, r):
        return l ^ r


class BitwiseNot(UnaryExpression):
    def do_cpu(self, data):
        return ~data

    def do_tpu(self, data):
        return ~data


def _shift_mask(dtype) -> int:
    return 63 if np.dtype(dtype).itemsize == 8 else 31


class _Shift(BinaryExpression):
    def result_dtype(self, lt, rt):
        return lt

    def _cast_inputs_np(self, l, r):
        return l, r.astype(np.int32, copy=False)

    def _cast_inputs_jnp(self, l, r):
        import jax.numpy as jnp

        return l, r.astype(jnp.int32)


class ShiftLeft(_Shift):
    def do_cpu(self, l, r):
        return l << (r & _shift_mask(l.dtype))

    def do_tpu(self, l, r):
        return l << (r & _shift_mask(l.dtype)).astype(l.dtype)


class ShiftRight(_Shift):
    def do_cpu(self, l, r):
        return l >> (r & _shift_mask(l.dtype))

    def do_tpu(self, l, r):
        return l >> (r & _shift_mask(l.dtype)).astype(l.dtype)


class ShiftRightUnsigned(_Shift):
    def do_cpu(self, l, r):
        shift = r & _shift_mask(l.dtype)
        unsigned = l.astype(l.dtype).view(
            np.uint64 if l.dtype.itemsize == 8 else np.uint32)
        return (unsigned >> shift.astype(unsigned.dtype)).view(l.dtype)

    def do_tpu(self, l, r):
        import jax.numpy as jnp

        shift = r & _shift_mask(l.dtype)
        ut = jnp.uint64 if l.dtype.itemsize == 8 else jnp.uint32
        return (l.view(ut) >> shift.astype(ut)).view(l.dtype)
