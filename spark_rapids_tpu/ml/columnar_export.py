"""Zero-copy device export for ML interop.

Reference analogue: ColumnarRdd.scala:41-49 + InternalColumnarRddConverter
(DataFrame -> RDD[cudf.Table] without a device->host round trip, for
XGBoost-style consumers; gated by the exportColumnarRdd conf,
RapidsConf.scala:312).  Here the executed plan's final device stage is
peeled off its DeviceToHost transition and the resident ``DeviceBatch``es
(jax arrays in HBM) are handed to the caller directly.
"""
from __future__ import annotations

from typing import List

from ..data.column import DeviceBatch, HostBatch
from ..exec.base import DevicePartitionedData
from ..exec.transitions import DeviceToHostExec
from ..plan import logical as L


def export_device_batches(session, plan: L.LogicalPlan) -> List[DeviceBatch]:
    """Execute ``plan`` and return the final columnar stage's device
    batches without downloading them (the reference peels
    GpuColumnarToRowExec off the executed plan the same way)."""
    root, ctx = session.prepare_execution(plan)
    try:
        # peel device->host transitions at the root so the final stage
        # stays on the device (reference: detectAndTagFinalColumnarOutput,
        # GpuTransitionOverrides.scala:256-261)
        phys = root
        while isinstance(phys, DeviceToHostExec):
            phys = phys.children[0]
        data = phys.execute_columnar(ctx) \
            if hasattr(phys, "execute_columnar") else phys.execute(ctx)
        out: List[DeviceBatch] = []
        for pid in range(data.n_partitions):
            for b in data.iterator(pid):
                if isinstance(b, HostBatch):  # plan fell back to the host
                    from ..data.column import host_to_device

                    b = host_to_device(b)
                out.append(b)
        return out
    finally:
        # same query-end contract as Session._finalize_metrics: the
        # export path owns its ExecContext, so it must finish the
        # query telemetry (stops the HbmSampler, emits query_end)
        from ..telemetry import finish_query

        finish_query(session, ctx, phys=root)
        root._exec_lock.release()


def to_feature_matrix(batches: List[DeviceBatch], columns=None):
    """Stack numeric columns of the exported batches into one 2-D
    float32 jax array [rows, features] — the XGBoost/NN hand-off shape.
    Padding rows and rows with a NULL in any selected column are dropped
    (device storage zero-fills invalid lanes; exporting them as real 0.0
    features would silently fabricate data)."""
    import jax.numpy as jnp

    if not batches:
        raise ValueError("no batches to export")
    schema = batches[0].schema
    names = columns or [f.name for f in schema
                        if f.dtype.is_numeric or f.dtype.is_bool]
    mats = []
    for b in batches:
        n = int(b.num_rows)
        cols, valid = [], None
        for name in names:
            c = b.column(name)
            cols.append(c.data[:n].astype(jnp.float32))
            v = c.validity[:n]
            valid = v if valid is None else (valid & v)
        m = jnp.stack(cols, axis=1)
        if valid is not None and not bool(valid.all()):
            m = m[valid]
        mats.append(m)
    return jnp.concatenate(mats, axis=0)


def from_device_batches(session, batches: List[DeviceBatch]):
    """Reverse path: device batches -> DataFrame (reference:
    GpuExternalRowToColumnConverter, the RDD[Row] -> batches direction)."""
    from ..data.column import device_to_host

    if not batches:
        raise ValueError("no batches")
    hbs = [device_to_host(b) for b in batches]
    return session.create_dataframe(HostBatch.concat(hbs))
