"""Runtime stage statistics — the AQE input side.

``StageStats`` lives on the ``ExecContext`` and aggregates what the
exchange write drain ALREADY knows once a stage materializes:

* device path — the per-partition count vectors of every packed block,
  pulled to the host in the drain's one gated ``fetch_counts`` batch
  readback (``exec/exchange.py:flush``).  Summing them gives the exact
  per-partition row histogram of the exchange, per-item so a skewed
  partition can later be cut into contiguous sub-slices.
* host path — per-batch row counts from the same gated readback
  (round-robin placement has no per-partition vector; totals only,
  except the trivial single-partition case).
* bytes — the arena-accounting byte sizes the write path tracks per
  block for spill bookkeeping (metadata math, no device touch).

Everything in here is host-side numpy on numbers that were already
host-resident: this module MUST NOT import jax or call any host-sync
primitive — the ``jax-import`` and ``host-sync`` analysis rules
enforce both, which is
how "zero added device syncs on the shuffle write path" stays true as
the code evolves.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: a contiguous chunk of one write-item's rows belonging to one
#: partition: (item_index, row_lo, row_hi) — half-open, item-local
Segment = Tuple[int, int, int]


class ExchangeObservation:
    """What one drained exchange looked like, exactly."""

    __slots__ = ("exchange_id", "n_out", "device_path", "partitioning",
                 "name", "total_bytes", "total_rows", "part_rows",
                 "item_counts")

    def __init__(self, exchange_id: int, *, n_out: int, device_path: bool,
                 partitioning: str, name: str, total_bytes: int,
                 total_rows: int,
                 part_rows: Optional[np.ndarray],
                 item_counts: Optional[List[np.ndarray]]):
        self.exchange_id = exchange_id
        self.n_out = n_out
        self.device_path = device_path
        self.partitioning = partitioning
        self.name = name
        self.total_bytes = int(total_bytes)
        self.total_rows = int(total_rows)
        self.part_rows = part_rows
        self.item_counts = item_counts

    # ------------------------------------------------------------------
    @property
    def has_partition_rows(self) -> bool:
        return self.part_rows is not None and len(self.part_rows) > 0

    def rows_for(self, p: int) -> int:
        assert self.part_rows is not None
        return int(self.part_rows[p])

    def bytes_for(self, p: int) -> int:
        """Per-partition byte estimate: total bytes prorated by rows
        (columns are fixed-width on device, so this is near-exact)."""
        if not self.has_partition_rows or self.total_rows <= 0:
            return 0
        return int(round(self.total_bytes
                         * (int(self.part_rows[p]) / self.total_rows)))

    def histogram(self) -> Optional[Dict[str, int]]:
        """min/p50/max/skew of the partition row counts, all ints so
        they can ride the metrics registry and the Prometheus export."""
        if not self.has_partition_rows:
            return None
        rows = self.part_rows
        med = int(np.median(rows))
        mx = int(rows.max())
        return {
            "partitions": int(len(rows)),
            "min": int(rows.min()),
            "p50": med,
            "max": mx,
            # skew factor as an integer percentage of the median
            "skewPct": int(round(100.0 * mx / max(med, 1))),
        }


class StageStats:
    """Per-query accumulator of :class:`ExchangeObservation`.

    Re-recording an exchange id OVERWRITES the previous observation:
    a stage re-executed from lineage (task retry, corruption recovery)
    re-plans from the fresh drain's numbers, never stale ones.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._obs: Dict[int, ExchangeObservation] = {}

    # ------------------------------------------------------------------
    def allocate_id(self) -> int:
        return next(self._ids)

    def record_exchange(self, exchange_id: int, *, items: Sequence,
                        n_out: int, device_path: bool, total_bytes: int,
                        partitioning: str,
                        name: str = "TpuShuffleExchangeExec"
                        ) -> ExchangeObservation:
        """Aggregate one drained exchange's write items.

        ``items`` is the drain's host-resident store: device path
        ``(buf_id, counts, starts)`` per packed block, host path
        ``(buf_id, rr_start, num_rows)`` per staged batch.  All numbers
        were materialized by the drain's gated readback already — this
        is pure host arithmetic.
        """
        part_rows: Optional[np.ndarray] = None
        item_counts: Optional[List[np.ndarray]] = None
        if device_path:
            item_counts = [np.asarray(it[1], dtype=np.int64)[:n_out]
                           for it in items]
            part_rows = np.zeros(n_out, dtype=np.int64)
            for c in item_counts:
                part_rows += c
            total_rows = int(part_rows.sum())
        else:
            total_rows = int(sum(int(it[2]) for it in items
                                 if len(it) > 2))
            if n_out == 1:
                # single-partition host exchange: the histogram is
                # trivially exact even without per-partition vectors
                part_rows = np.asarray([total_rows], dtype=np.int64)
        obs = ExchangeObservation(
            exchange_id, n_out=n_out, device_path=device_path,
            partitioning=partitioning, name=name,
            total_bytes=int(total_bytes), total_rows=total_rows,
            part_rows=part_rows, item_counts=item_counts)
        with self._lock:
            self._obs[exchange_id] = obs
        return obs

    def record_resumed(self, exchange_id: int, *,
                       n_out: int, part_rows: Sequence[int],
                       total_bytes: int, partitioning: str,
                       name: str) -> ExchangeObservation:
        """A checkpoint-RESUMED exchange (recovery/): per-partition rows
        come exactly from the checkpoint manifest, not a drain.  There
        are no live packed blocks, so ``device_path`` is False and
        ``item_counts`` is None — the skew-split rewrite (which needs
        segment reads over resident device blocks) correctly sees this
        stage as unsplittable, while coalescing, broadcast conversion
        and reservation re-basing get real sizes."""
        rows = np.asarray([int(r) for r in part_rows], dtype=np.int64)
        obs = ExchangeObservation(
            exchange_id, n_out=n_out, device_path=False,
            partitioning=partitioning, name=name,
            total_bytes=int(total_bytes),
            total_rows=int(rows.sum()) if rows.size else 0,
            part_rows=rows if rows.size else None,
            item_counts=None)
        with self._lock:
            self._obs[exchange_id] = obs
        return obs

    # ------------------------------------------------------------------
    def get(self, exchange_id: int) -> Optional[ExchangeObservation]:
        with self._lock:
            return self._obs.get(exchange_id)

    def exchanges(self) -> List[ExchangeObservation]:
        with self._lock:
            return [self._obs[k] for k in sorted(self._obs)]

    def observed_peak_bytes(self) -> int:
        """Largest materialized stage output seen so far — the basis
        for re-basing the scheduler's per-query HBM reservation."""
        with self._lock:
            return max((o.total_bytes for o in self._obs.values()),
                       default=0)

    def metrics(self) -> Dict[str, int]:
        """Flat int metrics merged into ``Session.last_metrics`` (and
        thereby the Prometheus export) — surfaced even with
        ``adaptive.enabled=false`` so skew is always visible."""
        out: Dict[str, int] = {}
        for obs in self.exchanges():
            pfx = f"shuffle.exchange{obs.exchange_id}."
            out[pfx + "partitions"] = obs.n_out
            out[pfx + "rowsTotal"] = obs.total_rows
            out[pfx + "bytesTotal"] = obs.total_bytes
            h = obs.histogram()
            if h is not None:
                out[pfx + "partRowsMin"] = h["min"]
                out[pfx + "partRowsP50"] = h["p50"]
                out[pfx + "partRowsMax"] = h["max"]
                out[pfx + "skewPct"] = h["skewPct"]
        return out


# --------------------------------------------------------------------------
# Pure helpers the AdaptivePlanner computes its rewrites with
# --------------------------------------------------------------------------
def coalesce_groups(part_bytes: Sequence[int],
                    target_bytes: int) -> List[Tuple[int, ...]]:
    """Greedily merge ADJACENT partitions up to ``target_bytes`` —
    Spark's ShufflePartitionsUtil rule.  Adjacency preserves the
    partition order, so downstream concatenation order is exactly the
    non-adaptive order.  A partition already over target stays alone."""
    groups: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_b = 0
    for p, b in enumerate(part_bytes):
        if cur and cur_b + int(b) > target_bytes:
            groups.append(tuple(cur))
            cur, cur_b = [], 0
        cur.append(p)
        cur_b += int(b)
    if cur:
        groups.append(tuple(cur))
    return groups


def split_partition_segments(item_counts: Sequence[np.ndarray], p: int,
                             n_slices: int) -> List[List[Segment]]:
    """Cut partition ``p``'s (item, row) sequence into ``n_slices``
    contiguous row-balanced slices.

    Each slice is a list of ``(item_idx, row_lo, row_hi)`` segments;
    concatenating the slices in order reproduces the partition's exact
    row sequence, which is what keeps a skew split bit-identical to
    reading the whole partition.
    """
    per_item = [int(c[p]) for c in item_counts]
    total = sum(per_item)
    if total <= 0 or n_slices <= 1:
        segs = [(i, 0, n) for i, n in enumerate(per_item) if n > 0]
        return [segs] if segs else []
    cuts = [int(round(j * total / n_slices))
            for j in range(1, n_slices)]
    bounds = [0] + cuts + [total]
    slices: List[List[Segment]] = []
    for j in range(n_slices):
        lo_g, hi_g = bounds[j], bounds[j + 1]
        if hi_g <= lo_g:
            continue  # degenerate cut (tiny partition, many slices)
        segs: List[Segment] = []
        base = 0
        for i, n in enumerate(per_item):
            a, b = max(lo_g, base), min(hi_g, base + n)
            if b > a:
                segs.append((i, a - base, b - base))
            base += n
        if segs:
            slices.append(segs)
    return slices
