"""Plan-template cache: skip planning/fusion for normalized repeats.

``Session.prepare_execution`` consults this cache after its per-object
``_plan_cache`` misses: the submission is normalized to a parameterized
skeleton (serving/prepared.py) and, when the ``(skeleton fingerprint,
literal binding, source identity)`` triple was planned before, the
cached PHYSICAL tree — optimizer, planner, overrides, transitions and
fused segments already applied — is reused without re-planning.  Even an ad-hoc ``submit()``
of a query text the session never saw as a DataFrame object hits, as
long as it normalizes to a seen template.

Handout follows the session's ``_exec_lock`` discipline exactly: exec
instances carry per-execution state, so a cached tree is given to ONE
execution at a time (non-blocking acquire — a busy tree counts as a
miss and the caller plans fresh rather than waiting).

Entries hold planned trees only; compiled kernels live in the process
kernel cache and survive template eviction.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from ..config import SERVING_CACHE_TEMPLATE_MAX_ENTRIES
from ..telemetry.events import emit_event
from .prepared import binding_digest, extract_parameters, \
    skeleton_fingerprint

#: cache key: (skeleton fingerprint, literal-binding digest, source
#: identity digest)
TemplateKey = Tuple[str, str, str]


def _source_digest(plan) -> str:
    """Digest of the plan's scan-leaf DATA identity from a fresh
    discovery stat pass (path+size+mtime_ns per file).  A planned
    physical tree bakes the discovered file list into its scan execs,
    so a template planned before a source directory grew or a file was
    rewritten describes the OLD input — folding the live identity into
    the key makes such a template unreachable instead of stale.
    In-memory relations are immutable and contribute nothing."""
    from ..io.scans import discover_files
    from ..plan import logical as L
    from ..recovery.manager import _digest, file_material

    material: list = []

    def walk(node) -> None:
        if isinstance(node, L.FileScan):
            _files, _values, _keys, fps = discover_files(node.paths)
            material.extend(file_material(fp) for fp in fps)
        for c in getattr(node, "children", ()):
            walk(c)

    walk(plan)
    return _digest("\n".join(material))


class TemplateCache:
    """LRU of planned physical trees keyed by normalized skeleton +
    literal binding (``serving.cache.templates.maxEntries``)."""

    def __init__(self, conf):
        self.conf = conf
        self.max_entries = max(
            1, int(conf.get(SERVING_CACHE_TEMPLATE_MAX_ENTRIES) or 1))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[TemplateKey, object]" = OrderedDict()
        self.counters: Dict[str, int] = {
            "hits": 0, "misses": 0, "busy": 0,
            "stores": 0, "evicted": 0}

    # ----- keying -----------------------------------------------------------
    def key_for(self, plan) -> Optional[TemplateKey]:
        """Normalize ``plan`` to its template key, or None when the
        plan does not normalize (an unknown node shape raising during
        extraction) — then the serving layer simply steps aside."""
        try:
            skeleton, params = extract_parameters(plan)
            skel_fp = skeleton_fingerprint(self.conf, skeleton)
            bind_fp = binding_digest([v for v, _ in params])
            return (skel_fp, bind_fp, _source_digest(plan))
        except Exception:  # noqa: BLE001 - never fail the submit path
            return None

    # ----- lookup / store ---------------------------------------------------
    def acquire(self, key: Optional[TemplateKey]):
        """A cached physical tree for ``key`` with its ``_exec_lock``
        HELD, or None on miss (including the busy-tree case — the
        caller plans fresh, as ``prepare_execution`` does for its own
        cache)."""
        if key is None:
            return None
        with self._lock:
            phys = self._entries.get(key)
            if phys is not None:
                self._entries.move_to_end(key)
        if phys is None:
            with self._lock:
                self.counters["misses"] += 1
            emit_event("cache_miss", tier="template",
                       skeleton=key[0], binding=key[1])
            return None
        if not phys._exec_lock.acquire(blocking=False):
            with self._lock:
                self.counters["busy"] += 1
                self.counters["misses"] += 1
            emit_event("cache_miss", tier="template",
                       skeleton=key[0], binding=key[1], reason="busy")
            return None
        with self._lock:
            self.counters["hits"] += 1
        emit_event("cache_hit", tier="template",
                   skeleton=key[0], binding=key[1])
        return phys

    def store(self, key: Optional[TemplateKey], phys) -> None:
        """Remember a freshly planned tree; evicts LRU entries past
        ``maxEntries`` (dropping only the planned tree — its compiled
        kernels stay in the kernel cache)."""
        if key is None:
            return
        evicted = []
        with self._lock:
            self._entries[key] = phys
            self._entries.move_to_end(key)
            self.counters["stores"] += 1
            while len(self._entries) > self.max_entries:
                old_key, _ = self._entries.popitem(last=False)
                self.counters["evicted"] += 1
                evicted.append(old_key)
        emit_event("cache_store", tier="template", skeleton=key[0],
                   binding=key[1])
        for old_key in evicted:
            emit_event("cache_evict", tier="template",
                       skeleton=old_key[0], reason="maxEntries")

    # ----- surface ----------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def metrics(self) -> Dict[str, int]:
        with self._lock:
            return {f"serving.template.{k}": v
                    for k, v in self.counters.items()}
