"""Projection/filter expression equality tests — CPU oracle vs TPU engine.

Reference analogues: ProjectExprSuite, arithmetic_ops_test.py,
cmp_test.py, conditionals_test.py.
"""
import pytest

from spark_rapids_tpu import f
from spark_rapids_tpu.testing import datagen as dg
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
)


def _num_data(n=200, seed=0):
    return dg.gen_batch({
        "a": dg.IntGen(dg.T.INT32),
        "b": dg.IntGen(dg.T.INT64),
        "c": dg.FloatGen(dg.T.FLOAT64),
        "d": dg.IntGen(dg.T.INT32, min_val=-100, max_val=100),
        "e": dg.FloatGen(dg.T.FLOAT32),
    }, n, seed)


@pytest.mark.parametrize("expr_fn", [
    lambda df: df["a"] + df["d"],
    lambda df: df["a"] - df["d"],
    lambda df: df["a"] * df["d"],
    lambda df: df["c"] / df["d"],
    lambda df: df["a"] % df["d"],
    lambda df: -df["a"],
    lambda df: f.abs(df["d"]),
    lambda df: f.pmod(df["a"], df["d"]),
], ids=["add", "sub", "mul", "div", "mod", "neg", "abs", "pmod"])
def test_arithmetic(expr_fn):
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(expr_fn(df).alias("out")), _num_data())


@pytest.mark.parametrize("expr_fn", [
    lambda df: df["a"] == df["d"],
    lambda df: df["a"] < df["d"],
    lambda df: df["c"] >= df["e"],
    lambda df: (df["a"] > 0) & (df["d"] < 0),
    lambda df: (df["a"] > 0) | (df["d"] < 0),
    lambda df: ~(df["a"] > 0),
    lambda df: df["a"].is_null(),
    lambda df: df["c"].is_not_null(),
    lambda df: f.isnan(df["c"]),
    lambda df: df["d"].isin(1, 2, 3, None),
    lambda df: df["a"].eq_null_safe(df["d"]),
], ids=["eq", "lt", "ge", "and", "or", "not", "isnull", "isnotnull",
        "isnan", "isin", "eqns"])
def test_predicates(expr_fn):
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(expr_fn(df).alias("out")), _num_data())


def test_filter():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.filter((df["a"] > 0) & df["c"].is_not_null())
        .select("a", "c"),
        _num_data(500))


def test_conditional():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            f.when(df["a"] > 0, df["b"]).when(df["a"] < -100, 0)
            .otherwise(-df["b"]).alias("cw"),
            f.if_(df["d"] > 0, df["a"], df["d"]).alias("iff"),
            f.coalesce(df["a"], df["d"], f.lit(7)).alias("co"),
            f.nanvl(df["c"], df["e"]).alias("nv"),
        ), _num_data())


def test_casts():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            df["a"].cast("bigint").alias("i64"),
            df["b"].cast("int").alias("i32"),
            df["c"].cast("int").alias("f2i"),
            df["a"].cast("double").alias("i2d"),
            df["a"].cast("boolean").alias("i2b"),
            df["c"].cast("float").alias("d2f"),
        ), _num_data())


def test_math():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            f.sqrt(f.abs(df["c"])).alias("sqrt"),
            f.floor(df["c"]).alias("floor"),
            f.ceil(df["c"]).alias("ceil"),
            f.exp(df["d"] % 10).alias("exp"),
            f.log(f.abs(df["a"]) + 1).alias("log"),
            f.pow(df["d"], f.lit(2.0)).alias("pow"),
            f.rint(df["c"]).alias("rint"),
        ), _num_data(), approximate_float=1e-12)


def test_bitwise():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            (f.shiftleft(df["a"], f.lit(3))).alias("shl"),
            (f.shiftright(df["b"], f.lit(7))).alias("shr"),
            f.shiftrightunsigned(df["a"], f.lit(2)).alias("sru"),
            f.bitwise_not(df["a"]).alias("bnot"),
        ), _num_data())


def test_strings_device():
    data = dg.gen_batch({
        "s": dg.StringGen(max_len=15),
        "t": dg.StringGen(max_len=6),
    }, 300, 7)
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            f.length(df["s"]).alias("len"),
            df["s"].contains("a").alias("has_a"),
            df["s"].startswith("A").alias("sw"),
            df["s"].endswith("z").alias("ew"),
            f.concat(df["s"], f.lit("-"), df["t"]).alias("cat"),
            f.substring(df["s"], 2, 3).alias("sub"),
            f.locate("b", df["s"]).alias("loc"),
            f.trim(f.concat(f.lit("  "), df["s"], f.lit(" "))).alias("tr"),
            (df["s"] < df["t"]).alias("cmp"),
            (df["s"] == df["t"]).alias("eq"),
        ), data)


def test_string_case_incompat_gate():
    data = {"s": ["MixedCase", "lower", "UPPER", None]}
    # default: Upper/Lower tagged off (incompat) -> runs on host, equal
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(f.upper(df["s"]).alias("u"),
                             f.lower(df["s"]).alias("l")), data)
    # enabled: device ASCII path, still equal for ASCII data
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(f.upper(df["s"]).alias("u")), data,
        conf={"spark.rapids.tpu.sql.incompatibleOps.enabled": True})


def test_datetime():
    data = dg.gen_batch({
        "dt": dg.DateGen(),
        "ts": dg.TimestampGen(),
        "n": dg.IntGen(dg.T.INT32, min_val=-1000, max_val=1000),
    }, 300, 11)
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            f.year(df["dt"]).alias("y"),
            f.month(df["dt"]).alias("m"),
            f.dayofmonth(df["dt"]).alias("d"),
            f.year(df["ts"]).alias("ty"),
            f.hour(df["ts"]).alias("th"),
            f.minute(df["ts"]).alias("tm"),
            f.second(df["ts"]).alias("tsec"),
            f.date_add(df["dt"], df["n"]).alias("da"),
            f.datediff(df["dt"], f.lit(0, dg.T.DATE32)).alias("dd"),
            df["ts"].cast("date").alias("t2d"),
            df["dt"].cast("timestamp").alias("d2t"),
        ), data)


def test_union_limit():
    data = _num_data(100)
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select("a", "b").union(df.select("d", "b")),
        data, ignore_order=True)
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select("a").limit(17), data, ignore_order=True)


def test_explain_and_fallback_report():
    from spark_rapids_tpu import Session

    sess = Session()
    df = sess.create_dataframe(_num_data(50))
    out = df.filter(df["a"] > 0).select((df["a"] + 1).alias("x"))
    report = out.explain()
    assert "*" in report  # something runs on TPU
    assert "TpuProject" not in report  # explain is the tagged host plan


def test_strict_mode_catches_fallback(strict_tpu_session):
    # rlike has no device impl -> strict mode must raise
    df = strict_tpu_session.create_dataframe({"s": ["a", "b"]})
    with pytest.raises(AssertionError):
        df.select(df["s"].rlike("a.*").alias("m")).collect()


@pytest.mark.parametrize("pattern", [
    "MEDIUM POLISHED%",      # prefix (TPC-H q16 shape)
    "%BRASS",                # suffix (q16 NOT LIKE shape)
    "%green%",               # contains (q20 shape)
    "abc",                   # exact
    "",                      # empty pattern: only empty string
    "%",                     # matches everything
    "a%b%c",                 # multi-segment greedy
    "%a%%b%",                # adjacent % (empty segments)
    "50\\%%",                # escaped % then wildcard
], ids=["prefix", "suffix", "contains", "exact", "empty", "any",
        "multi", "adjacent", "escaped"])
def test_like_device(pattern):
    data = {"s": ["MEDIUM POLISHED TIN", "LARGE BRUSHED BRASS",
                  "dark green metallic", "abc", "", "a-b-c", "ab",
                  "aXbYc", "50% off", "50c", None, "abcabc",
                  "MEDIUM POLISHED", "xMEDIUM POLISHED TIN"]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(df["s"].like(pattern).alias("m")), data)


def test_like_simple_pattern_stays_on_device(strict_tpu_session):
    # reference keeps Like on GPU via regex translation
    # (GpuOverrides.scala:326-371); here %-only patterns lower onto the
    # byte-matrix kernels — strict mode proves no host fallback
    df = strict_tpu_session.create_dataframe(
        {"s": ["MEDIUM POLISHED TIN", "SMALL PLATED COPPER", None]})
    out = df.select(df["s"].like("MEDIUM POLISHED%").alias("m")).collect()
    assert [r[0] for r in out] == [True, False, None]


def test_like_underscore_falls_back(strict_tpu_session):
    # `_` is character-based -> host regex; strict mode must raise
    df = strict_tpu_session.create_dataframe({"s": ["ab", "ax"]})
    with pytest.raises(AssertionError):
        df.select(df["s"].like("a_").alias("m")).collect()


@pytest.mark.parametrize("count", [1, 2, 3, -1, -2, 0])
def test_substring_index_device(count):
    data = {"s": ["a.b.c.d", "nodot", ".lead", "trail.", "..", "",
                  "x.y", None]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            f.substring_index(df["s"], ".", count).alias("m")), data)


def test_substring_index_single_byte_stays_on_device(strict_tpu_session):
    df = strict_tpu_session.create_dataframe({"s": ["a.b.c", "q"]})
    out = df.select(f.substring_index(df["s"], ".", 2).alias("m")).collect()
    assert [r[0] for r in out] == ["a.b", "q"]


def test_substring_index_multibyte_falls_back(strict_tpu_session):
    # multi-byte delimiter -> host path; strict mode must raise
    df = strict_tpu_session.create_dataframe({"s": ["a--b--c"]})
    with pytest.raises(AssertionError):
        df.select(f.substring_index(df["s"], "--", 1).alias("m")).collect()


@pytest.mark.parametrize("search,repl", [
    (".", "::"),   # grow
    ("-", ""),     # delete
    ("a", "b"),    # same width
    ("z", "xyz"),  # absent needle
])
def test_string_replace_device(search, repl):
    data = {"s": ["a.b.c", "-a-", "....", "", "no match here",
                  "trail.", None, "aaa"]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            f.replace(df["s"], search, repl).alias("m")), data)


def test_string_replace_multibyte_falls_back(strict_tpu_session):
    df = strict_tpu_session.create_dataframe({"s": ["abab"]})
    with pytest.raises(AssertionError):
        df.select(f.replace(df["s"], "ab", "x").alias("m")).collect()


def test_in_expression_non_literal():
    """value IN (expr, ...) with column members (reference registers In
    beside InSet) incl. Spark's NULL-member semantics."""
    data = {"a": [1, 2, 3, None, 5],
            "b": [1, 0, 3, 4, None],
            "c": [9, 2, 0, 4, 5]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            df["a"].isin(df["b"], df["c"]).alias("m"), df["a"]), data)
    strs = {"s": ["x", "y", None, "zz"], "t": ["x", "q", "w", "zz"],
            "u": ["a", "y", None, "b"]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            df["s"].isin(df["t"], df["u"]).alias("m"), df["s"]), strs)


def test_time_sub():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.ops.datetimeexprs import TimeSub
    from spark_rapids_tpu.plan.functions import Column

    schema = T.Schema([T.Field("ts", T.TIMESTAMP)])
    data = {"ts": [0, 1611700200123456, None, -5]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            Column(TimeSub(df["ts"].expr, 3_600_000_000)).alias("m")),
        data, schema=schema)


def test_new_math_exprs():
    data = {"x": [0.5, 1.0, 2.0, -0.5, None, 10.0]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.select(
            f.asinh(df["x"]).alias("as"), f.acosh(df["x"]).alias("ac"),
            f.atanh(df["x"]).alias("at"), f.cot(df["x"]).alias("ct"),
            f.log_base(2.0, df["x"]).alias("lb")),
        data, approximate_float=1e-12)
