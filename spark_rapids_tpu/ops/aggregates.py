"""Declarative aggregate functions.

Capability parity with the reference's AggregateFunctions.scala: Count,
Sum, Min, Max, Average, First, Last as *declarative* aggregates — each
describes its partial-buffer reductions (``updates``), how partials merge
across batches/partitions (``merges``), and a finalize expression — the
same CudfAggregate-atom design, re-targeted at segment reductions.

The aggregate exec drives these through the sort+segment-reduce kernels
(kernels/segment.py) on either engine.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from .. import types as T
from .arithmetic import Divide
from .expression import BoundReference, Expression


class AggregateFunction:
    """One aggregate call, e.g. sum(x)."""

    #: list of (op, which) where op in {sum,min,max,count,first,last} and
    #: ``which`` selects the input: 0 = the child column
    updates: List[Tuple[str, int]] = []
    #: ops merging each partial buffer across batches (parallel to updates)
    merges: List[str] = []

    def __init__(self, child: Optional[Expression],
                 ignore_nulls: bool = True):
        self.child = child
        self.ignore_nulls = ignore_nulls

    @property
    def children(self):
        return [] if self.child is None else [self.child]

    @property
    def dtype(self) -> T.DType:
        raise NotImplementedError

    @property
    def name(self):
        return type(self).__name__.lower()

    def buffer_dtypes(self) -> List[T.DType]:
        """dtypes of the partial buffers produced by ``updates``."""
        raise NotImplementedError

    def finalize(self, buffer_refs: List[Expression]) -> Expression:
        """Expression over the merged buffers producing the final value."""
        assert len(buffer_refs) == 1
        return buffer_refs[0]

    @property
    def tpu_supported(self) -> bool:
        if self.child is None:
            return True
        if not self.child.tpu_supported:
            return False
        # string inputs: only order/presence aggregates work on device
        if self.child.dtype.is_string:
            return isinstance(self, (Min, Max, First, Last, Count))
        return True

    def sql(self):
        c = self.child.sql() if self.child is not None else "*"
        return f"{self.name}({c})"

    def __repr__(self):  # pragma: no cover
        return self.sql()


class Count(AggregateFunction):
    updates = [("count", 0)]
    merges = ["sum"]

    @property
    def dtype(self):
        return T.INT64

    def buffer_dtypes(self):
        return [T.INT64]

    @property
    def nullable(self):
        return False


class Sum(AggregateFunction):
    updates = [("sum", 0)]
    merges = ["sum"]

    @property
    def dtype(self):
        ct = self.child.dtype
        if ct.is_floating:
            return T.FLOAT64
        return T.INT64

    def buffer_dtypes(self):
        return [self.dtype]


class Min(AggregateFunction):
    updates = [("min", 0)]
    merges = ["min"]

    @property
    def dtype(self):
        return self.child.dtype

    def buffer_dtypes(self):
        return [self.child.dtype]


class Max(AggregateFunction):
    updates = [("max", 0)]
    merges = ["max"]

    @property
    def dtype(self):
        return self.child.dtype

    def buffer_dtypes(self):
        return [self.child.dtype]


class Average(AggregateFunction):
    """sum + count composite (reference: GpuAverage:362 = CudfSum+CudfCount)."""

    updates = [("sum", 0), ("count", 0)]
    merges = ["sum", "sum"]

    @property
    def dtype(self):
        return T.FLOAT64

    def buffer_dtypes(self):
        return [T.FLOAT64 if self.child.dtype.is_floating else T.INT64,
                T.INT64]

    def finalize(self, buffer_refs):
        return Divide(buffer_refs[0], buffer_refs[1])


class First(AggregateFunction):
    """Spark semantics: ignoreNulls=false (the default) returns the first
    ROW's value, null included; true returns the first non-null value."""

    @property
    def updates(self):
        return [("first" if self.ignore_nulls else "first_any", 0)]

    @property
    def merges(self):
        return ["first" if self.ignore_nulls else "first_any"]

    @property
    def dtype(self):
        return self.child.dtype

    def buffer_dtypes(self):
        return [self.child.dtype]


class Last(AggregateFunction):
    @property
    def updates(self):
        return [("last" if self.ignore_nulls else "last_any", 0)]

    @property
    def merges(self):
        return ["last" if self.ignore_nulls else "last_any"]

    @property
    def dtype(self):
        return self.child.dtype

    def buffer_dtypes(self):
        return [self.child.dtype]


class AggregateExpression(Expression):
    """Wrapper carrying (function, mode) through planning, mirroring the
    reference's GpuAggregateExpression.  Not directly evaluable — the
    aggregate exec interprets it."""

    def __init__(self, func: AggregateFunction, mode: str = "complete"):
        super().__init__(list(func.children))
        self.func = func
        self.mode = mode  # complete | partial | final

    def with_children(self, children):
        # keep func.child in sync so expression transforms (notably
        # bind_references) reach through the wrapper into the function
        node = super().with_children(children)
        if node.func.child is not None:
            import copy

            f = copy.copy(node.func)
            f.child = children[0]
            node.func = f
        return node

    @property
    def dtype(self):
        return self.func.dtype

    @property
    def nullable(self):
        return not isinstance(self.func, Count)

    def sql(self):
        return self.func.sql()
