"""Durability rules: atomic-write, crc-verify, no-deserialize,
manifest-fingerprint.

The recovery substrate's correctness story is torn-write-free
persistence (fsio atomic helpers), verify-before-deserialize (CRC
precedes any frame decode), and manifest consumption keyed by the
plan fingerprint so a recovered stage can never feed a different
plan's data.
"""
from __future__ import annotations

from typing import Iterable, List

import ast

from ..engine import AnalysisContext, Rule
from ..findings import Finding
from ..resolver import terminal_name
from . import common

ATOMIC_HELPERS = frozenset({"atomic_write_bytes", "atomic_write_json"})

#: durable-state scope: everything here persists across crashes
DURABLE_PREFIXES = ("recovery/", "streaming/")
DURABLE_FILES = ("memory/spill.py",)

#: minimum atomic-helper call counts per file (the load-bearing
#: persistence points must stay on the atomic path)
ATOMIC_MINIMUMS = (("recovery/store.py", 2), ("memory/spill.py", 1),
                   ("streaming/ledger.py", 1))

WRITE_MODES = set("wax+")


def _is_write_open(call: ast.Call) -> bool:
    if terminal_name(call.func) != "open":
        return False
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return bool(set(mode.value) & WRITE_MODES)
    return False


class AtomicWriteRule(Rule):
    id = "atomic-write"
    title = "durable state is written only through fsio atomic helpers"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rels = common.scoped(ctx, prefixes=DURABLE_PREFIXES,
                             files=DURABLE_FILES)
        calls_checked = 0
        helper_counts = {rel: 0 for rel, _n in ATOMIC_MINIMUMS}
        for fi in ctx.resolver.functions(rels):
            for call in fi.all_calls():
                calls_checked += 1
                name = terminal_name(call.func)
                suffix = next((rel for rel, _n in ATOMIC_MINIMUMS
                               if fi.module.endswith(rel)), None)
                if name in ATOMIC_HELPERS and suffix is not None:
                    helper_counts[suffix] += 1
                if _is_write_open(call):
                    out.append(self.finding(
                        "direct-write", fi.module, call.lineno,
                        f"{fi.qualname}() opens a file for writing "
                        f"directly — durable state goes through "
                        f"{sorted(ATOMIC_HELPERS)} (torn-write-free)",
                        detail=f"{fi.qualname}:open-write"))
                elif name == "tofile":
                    out.append(self.finding(
                        "direct-write", fi.module, call.lineno,
                        f"{fi.qualname}() uses ndarray.tofile() — "
                        f"not atomic; route through fsio",
                        detail=f"{fi.qualname}:tofile"))
        for rel, minimum in ATOMIC_MINIMUMS:
            out.extend(self.health(
                helper_counts[rel] >= minimum, common.PKG + rel,
                f"expected >={minimum} atomic-helper calls in {rel}, "
                f"saw {helper_counts[rel]}"))
        out.extend(self.health(
            calls_checked >= 80, common.PKG + "recovery",
            f"expected >=80 calls scanned in the durable scope, "
            f"saw {calls_checked}"))
        return out


class CrcVerifyRule(Rule):
    id = "crc-verify"
    title = "frame readers verify CRC before deserializing"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rels = common.scoped(ctx, prefixes=("recovery/",),
                             files=("memory/spill.py",))
        readers = 0
        for fi in ctx.resolver.functions(rels):
            if "fromfile" in fi.own_call_names or \
                    "frombuffer" in fi.own_call_names:
                readers += 1
                if "verify_frame" not in fi.own_call_names:
                    out.append(self.finding(
                        "unverified-read", fi.module, fi.lineno,
                        f"{fi.qualname}() reads raw frames without "
                        f"verify_frame — corrupt payloads must be "
                        f"caught before deserialization",
                        detail=f"{fi.qualname}:verify_frame"))
        out.extend(self.health(
            readers >= 1, common.PKG + "recovery",
            f"expected >=1 raw frame reader, saw {readers}"))
        return out


class NoDeserializeRule(Rule):
    id = "no-deserialize"
    title = "recovery/ never decodes payloads itself"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for fi in ctx.resolver.functions(
                common.scoped(ctx, prefixes=("recovery/",))):
            for call in fi.own_calls:
                if terminal_name(call.func) == "deserialize":
                    out.append(self.finding(
                        "decode", fi.module, call.lineno,
                        f"{fi.qualname}() calls deserialize() — "
                        f"recovery hands verified bytes to the "
                        f"native serializer's caller, it never "
                        f"decodes payloads itself",
                        detail=f"{fi.qualname}:deserialize"))
        return out


class ManifestFingerprintRule(Rule):
    id = "manifest-fingerprint"
    title = "manifest consumers key on plan_fingerprint"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rel = common.PKG + "recovery/manager.py"
        mi = ctx.resolver.module(rel)
        if mi is None:
            return [self.finding("health", rel, 0,
                                 "recovery/manager.py missing")]
        consumers = 0
        for fi in mi.functions:
            if "read_manifest" in fi.own_call_names:
                consumers += 1
                if "plan_fingerprint" not in \
                        common.string_literals(fi.node):
                    out.append(self.finding(
                        "unkeyed-consumer", rel, fi.lineno,
                        f"{fi.qualname}() consumes a manifest "
                        f"without checking plan_fingerprint — a "
                        f"recovered stage could feed a different "
                        f"plan's data",
                        detail=f"{fi.qualname}:plan_fingerprint"))
        out.extend(self.health(
            consumers >= 1, rel,
            f"expected >=1 read_manifest consumer, saw {consumers}"))
        return out
