"""Foundation tests: types, columns, hashing, transfers, config."""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import TpuConf, dump_markdown
from spark_rapids_tpu.data.column import (
    HostBatch,
    HostColumn,
    bucket_rows,
    device_to_host,
    host_to_device,
)
from spark_rapids_tpu.utils import hashing


def test_type_gate():
    assert T.is_supported_type(T.INT32)
    assert T.is_supported_type(T.STRING)
    assert T.is_supported_type(T.TIMESTAMP, session_zone_utc=True)
    assert not T.is_supported_type(T.TIMESTAMP, session_zone_utc=False)


def test_promote():
    assert T.promote(T.INT32, T.INT64) == T.INT64
    assert T.promote(T.INT64, T.FLOAT32) == T.FLOAT64
    assert T.promote(T.INT8, T.FLOAT32) == T.FLOAT32


def test_host_column_roundtrip():
    c = HostColumn.from_pylist([1, None, 3], T.INT32)
    assert c.to_pylist() == [1, None, 3]
    assert c.null_count == 1
    s = HostColumn.from_pylist(["a", None, "xyz"], T.STRING)
    assert s.to_pylist() == ["a", None, "xyz"]


def test_bucket_rows():
    assert bucket_rows(0) == 128
    assert bucket_rows(128) == 128
    assert bucket_rows(129) == 256
    assert bucket_rows(5000) == 8192


def test_device_roundtrip():
    batch = HostBatch.from_pydict({
        "i": [1, None, 3, -5],
        "f": [1.5, float("nan"), None, -0.0],
        "s": ["abc", "", None, "Ünïcode"],
        "b": [True, False, None, True],
    }, T.Schema([
        T.Field("i", T.INT64), T.Field("f", T.FLOAT64),
        T.Field("s", T.STRING), T.Field("b", T.BOOL)]))
    db = host_to_device(batch)
    assert db.padded_rows == 128
    back = device_to_host(db)
    assert back.column("i").to_pylist() == [1, None, 3, -5]
    f = back.column("f").to_pylist()
    assert f[0] == 1.5 and np.isnan(f[1]) and f[2] is None and f[3] == 0.0
    assert back.column("s").to_pylist() == ["abc", "", None, "Ünïcode"]
    assert back.column("b").to_pylist() == [True, False, None, True]


def _ref_murmur3_long(v, seed=42):
    """Scalar reference implementation for cross-checking."""
    def mix_k1(k1):
        k1 = (k1 * 0xCC9E2D51) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        return (k1 * 0x1B873593) & 0xFFFFFFFF

    def mix_h1(h1, k1):
        h1 ^= k1
        h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
        return (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF

    def fmix(h1, length):
        h1 ^= length
        h1 ^= h1 >> 16
        h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
        h1 ^= h1 >> 13
        h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
        h1 ^= h1 >> 16
        return h1

    u = v & 0xFFFFFFFFFFFFFFFF
    h = mix_h1(seed, mix_k1(u & 0xFFFFFFFF))
    h = mix_h1(h, mix_k1(u >> 32))
    return fmix(h, 8)


def test_murmur3_long():
    vals = np.asarray([0, 1, -1, 42, 2**40, -(2**40)], dtype=np.int64)
    c = HostColumn(T.INT64, vals)
    h = hashing.hash_batch_np([c]).view(np.uint32)
    for i, v in enumerate(vals):
        assert int(h[i]) == _ref_murmur3_long(int(v)), f"mismatch at {v}"


def test_murmur3_string_matches_known():
    # Spark: SELECT hash('abc') == murmur3(utf8 'abc', seed 42)
    c = HostColumn.from_pylist(["abc", "", "a", "abcd", "hello world"],
                               T.STRING)
    h = hashing.hash_batch_np([c])
    # cross-check against pure-python reference
    def ref_bytes(b, seed=42):
        h1 = seed
        n = len(b)
        def mix_k1(k1):
            k1 = (k1 * 0xCC9E2D51) & 0xFFFFFFFF
            k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
            return (k1 * 0x1B873593) & 0xFFFFFFFF
        def mix_h1(h1, k1):
            h1 ^= k1
            h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
            return (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF
        for blk in range(n // 4):
            word = int.from_bytes(b[blk * 4:blk * 4 + 4], "little")
            h1 = mix_h1(h1, mix_k1(word))
        for i in range((n // 4) * 4, n):
            byte = b[i]
            if byte >= 128:
                byte -= 256
            h1 = mix_h1(h1, mix_k1(byte & 0xFFFFFFFF))
        h1 ^= n
        h1 ^= h1 >> 16
        h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
        h1 ^= h1 >> 13
        h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
        h1 ^= h1 >> 16
        return h1
    for i, s in enumerate(["abc", "", "a", "abcd", "hello world"]):
        assert int(h[i].view(np.uint32)) == ref_bytes(s.encode()), s


def test_murmur3_device_matches_host():
    import jax.numpy as jnp  # noqa: F401

    batch = HostBatch.from_pydict({
        "i": [1, None, 3, -5, 2**40],
        "s": ["abc", None, "", "hello world", "Ünïcode"],
        "d": [1.5, -0.0, None, 3.25, float("nan")],
    }, T.Schema([T.Field("i", T.INT64), T.Field("s", T.STRING),
                 T.Field("d", T.FLOAT64)]))
    host_h = hashing.hash_batch_np(batch.columns)
    db = host_to_device(batch)
    dev_h = np.asarray(hashing.hash_device_batch(db.columns))[:5]
    np.testing.assert_array_equal(host_h, dev_h)


def test_conf_registry_and_docs():
    conf = TpuConf({"spark.rapids.tpu.sql.batchSizeBytes": "1024"})
    assert conf.batch_size_bytes == 1024
    assert conf.is_sql_enabled
    md = dump_markdown()
    assert "spark.rapids.tpu.sql.enabled" in md


def test_packed_upload_roundtrip():
    """host_to_device packs every array into ONE transfer; the unpack
    (slice + bitcast) must be byte-exact for every dtype family."""
    import numpy as np

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.data.column import (HostBatch, device_to_host,
                                              host_to_device,
                                              packed_upload)
    import jax

    probe = [np.asarray([-1, 0, 2**62], dtype=np.int64),
             np.asarray([1.5, -0.0, float("nan")], dtype=np.float64),
             np.asarray([True, False]),
             np.arange(9, dtype=np.uint8).reshape(3, 3),
             np.asarray([7, -7], dtype=np.int32),
             np.asarray([-1, -128, 127], dtype=np.int8)]
    got = jax.device_get(packed_upload(probe))
    for a, o in zip(probe, got):
        np.testing.assert_array_equal(a, np.asarray(o))

    hb = HostBatch.from_pydict({
        "i": [1, None, 3], "f": [0.5, 2.5, None],
        "s": ["ab", None, "xyz"], "b": [True, False, None],
    })
    # force the packed path (auto mode disables it on the CPU backend)
    from spark_rapids_tpu.data import column as dcol

    old = dict(dcol._PACK_STATE)
    dcol._PACK_STATE.update({"mode": "1", "enabled": True,
                             "verified": False})
    try:
        rt = device_to_host(host_to_device(hb))
    finally:
        dcol._PACK_STATE.update(old)
    assert rt.to_rows() == hb.to_rows()


def test_local_scan_upload_cache(monkeypatch):
    """Repeated collects of the same plan reuse the cached device
    upload of an immutable in-memory source; a partially-drained
    partition (limit) is never cached."""
    import spark_rapids_tpu.exec.transitions as tr
    from spark_rapids_tpu import Session, f
    from spark_rapids_tpu.data import column as dc

    calls = {"n": 0}
    orig = dc.host_to_device

    def counting(hb, *a, **k):
        calls["n"] += 1
        return orig(hb, *a, **k)

    monkeypatch.setattr(tr, "host_to_device", counting)
    sess = Session()
    df = sess.create_dataframe(
        {"k": list(range(100)), "v": [float(i) for i in range(100)]})
    # a limit abandons its read early -> partial partitions must NOT
    # be published to the cache
    lim = df.select("k").limit(1).collect()
    assert len(lim) == 1
    q = df.group_by("k").agg(f.sum("v").alias("s"))
    a = sorted(q.collect())
    first = calls["n"]
    assert first > 0
    b = sorted(q.collect())
    assert a == b
    assert calls["n"] == first, \
        "second collect must not re-upload the cached source"
