"""API validation: inventory drift report.

Reference analogue: the ``api_validation`` module
(ApiValidation.scala) — reflection-compares each Spark exec's
constructor signature against its Gpu twin and reports drift.  Here the
host engine and the device engine live in one codebase, so validation
checks three parity surfaces instead:

  1. every host physical exec has a registered TPU conversion rule
     (or is a known host-only node),
  2. every registered expression class implements BOTH backends
     (eval_cpu and eval_tpu overridden — the dual-engine contract of
     ops/expression.py),
  3. every rule's auto-derived enable key exists in the config registry.

Run ``python -m spark_rapids_tpu.testing.api_validation`` for the report;
the test suite asserts the report is clean.
"""
from __future__ import annotations

import inspect
from typing import List

from ..config import lookup
from ..ops import aggregates as agg
from ..ops.expression import Expression
from ..plan import physical as P


# host-only nodes by design: transitions, scans (converted via ScanRule
# analogues in the planner), and the host-side write/coalesce machinery
HOST_ONLY_EXECS = {
    "PhysicalPlan", "LocalScanExec", "HostToDeviceExec", "DeviceToHostExec",
    "DataWritingCommandExec", "CoalescePartitionsExec",
}

# expressions whose device eval intentionally does not exist; their rules
# tag the subtree back to the host engine (reference: the regex-escape
# bail-outs at GpuOverrides.scala:326-371 and the string/TZ gates)
INTENTIONAL_HOST_EXPRS = {
    "UnresolvedAttribute",    # always bound before evaluation
    "RegExpReplace",          # full regex: host fallback by design
    # (Like lowers %-only patterns; SubstringIndex/StringReplace lower
    # single-byte delimiters/needles; the rest fall back per-instance)
    "UnixTimestampParse", "FromUnixTime",  # strftime parse/format on host
    "InputFileName", "InputFileBlockStart",
    "InputFileBlockLength",   # scan-context intrinsics, host metadata
}


def _all_host_execs() -> List[type]:
    out = []
    for name in dir(P):
        obj = getattr(P, name)
        if (inspect.isclass(obj) and issubclass(obj, P.PhysicalPlan)
                and obj.__module__ == P.__name__):
            out.append(obj)
    return out


def _overridden(cls: type, method: str, base: type) -> bool:
    return getattr(cls, method, None) is not getattr(base, method)


def validate() -> List[str]:
    """Returns a list of drift findings (empty = clean)."""
    from ..plan.overrides import EXEC_RULES, EXPR_RULES, _ensure_registry

    _ensure_registry()
    findings = []

    # 1. exec coverage
    for cls in _all_host_execs():
        if cls.__name__ in HOST_ONLY_EXECS:
            continue
        if cls not in EXEC_RULES:
            findings.append(
                f"exec {cls.__name__}: no TPU conversion rule registered")

    # 2. expression dual-backend contract: each backend's entry point
    # (eval_*) or kernel hook (do_*) must be overridden below the
    # abstract template bases, else the device path raises
    # NotImplementedError inside a jit trace at runtime
    from ..ops.expression import (BinaryExpression, TernaryExpression,
                                  UnaryExpression)

    template_bases = {Expression, UnaryExpression, BinaryExpression,
                      TernaryExpression}
    for cls in EXPR_RULES:
        if issubclass(cls, agg.AggregateExpression):
            continue  # interpreted by the aggregate exec, not evaluated
        if cls.__name__ in INTENTIONAL_HOST_EXPRS:
            continue
        for entry, hook in (("eval_cpu", "do_cpu"),
                            ("eval_tpu", "do_tpu")):
            impl = False
            for k in cls.__mro__:
                if k in template_bases:
                    break
                if entry in vars(k) or hook in vars(k):
                    impl = True
                    break
            if not impl:
                findings.append(
                    f"expr {cls.__name__}: neither {entry} nor {hook} "
                    f"overridden below the template bases")

    # 3. enable keys present
    for rule_map, kind in ((EXEC_RULES, "exec"), (EXPR_RULES, "expr")):
        for cls in rule_map:
            key = f"spark.rapids.tpu.sql.{kind}.{cls.__name__}"
            if lookup(key) is None:
                findings.append(f"{kind} {cls.__name__}: enable key "
                                f"{key} missing from config registry")

    # 4. no vapor keys: every registered entry must be read somewhere
    findings.extend(_unread_conf_keys())
    return findings


def _unread_conf_keys() -> List[str]:
    """Registered-but-never-read conf keys are documentation fiction:
    the generated docs promise behavior no code delivers.  An entry
    counts as read when its config.py variable name (or literal key)
    appears in package source outside its own definition."""
    import pathlib
    import re

    from .. import config as C

    src_root = pathlib.Path(C.__file__).parent
    blob = []
    for p in sorted(src_root.rglob("*.py")):
        if p.name == "config.py":
            continue
        blob.append(p.read_text())
    blob = "\n".join(blob)
    config_src = pathlib.Path(C.__file__).read_text()

    # auto-derived per-op enable keys are looked up dynamically by the
    # rule framework (is_operator_enabled) — not scannable by name
    auto = re.compile(
        r"^spark\.rapids\.tpu\.sql\.(exec|expr|scan|part|writecmd)\.")
    names = {e.key: n for n, e in vars(C).items()
             if isinstance(e, C.ConfEntry)}
    out = []
    for key, entry in C._REGISTRY.items():
        if auto.match(key):
            continue
        var = names.get(key)
        used = False
        if var is not None:
            if len(re.findall(rf"\b{var}\b", config_src)) > 1:
                used = True  # read via a TpuConf property/helper
            elif re.search(rf"\b{var}\b", blob):
                used = True
        if not used and key in blob:
            used = True
        if not used:
            out.append(f"conf {key}: registered but never read "
                       "(vapor key — delete it or wire it)")
    return out


# --------------------------------------------------------------------------
# reference expression drift (VERDICT r4 item 8): mechanical diff of the
# registry against the reference's expr[...] rules
# --------------------------------------------------------------------------

#: reference rule name -> this engine's class name, where the concept is
#: identical but the name differs
REFERENCE_EXPR_ALIASES = {
    "AttributeReference": "BoundReference",  # bound column reference
    "Concat": "ConcatStrings",
    "UnixTimestamp": "UnixTimestampParse",
    "AnsiCast": "Cast",  # ansi is a flag on Cast here
}

#: reference rules handled by a SUBSYSTEM rather than an expression
#: registry entry: aggregates via AggMeta (ops/aggregates.py), window
#: pieces lowered by the window exec (ops/windowexprs.py)
REFERENCE_EXPRS_VIA_SUBSYSTEM = {
    "AggregateExpression", "Average", "Count", "First", "Last", "Max",
    "Min", "Sum",                       # AggMeta / ops/aggregates.py
    "RowNumber", "SortOrder", "SpecifiedWindowFrame",
    "WindowExpression", "WindowSpecDefinition",  # exec/window.py
}

#: intentional, documented gaps (must stay under 5)
REFERENCE_EXPR_INTENTIONAL_GAPS = {
    # none currently — the registry covers the reference's table
}


def reference_expression_drift(
        reference_root: str = "/root/reference"):
    """Diff the expression registry against the reference's
    ``expr[...]`` rules (GpuOverrides.scala:395-1449).  Returns None
    when the reference tree is unavailable (end-user installs), else a
    dict with ``covered`` / ``via_subsystem`` / ``missing`` /
    ``extra`` name lists."""
    import pathlib
    import re

    from ..plan.overrides import EXPR_RULES, _ensure_registry

    scala = (pathlib.Path(reference_root) / "sql-plugin" / "src" /
             "main" / "scala" / "com" / "nvidia" / "spark" / "rapids" /
             "GpuOverrides.scala")
    if not scala.exists():
        return None
    ref_names = sorted(set(re.findall(r"expr\[([A-Za-z0-9_]+)\]",
                                      scala.read_text())))
    _ensure_registry()
    ours = {cls.__name__ for cls in EXPR_RULES}
    covered, via_sub, missing = [], [], []
    for name in ref_names:
        local = REFERENCE_EXPR_ALIASES.get(name, name)
        if local in ours:
            covered.append(name)
        elif name in REFERENCE_EXPRS_VIA_SUBSYSTEM:
            via_sub.append(name)
        elif name in REFERENCE_EXPR_INTENTIONAL_GAPS:
            missing.append(name + " (intentional)")
        else:
            missing.append(name)
    aliased = set(REFERENCE_EXPR_ALIASES.values())
    extra = sorted(ours - set(ref_names) - aliased)
    return {"reference_total": len(ref_names), "covered": covered,
            "via_subsystem": via_sub, "missing": missing,
            "extra": extra}


def write_drift_report(path: str,
                       reference_root: str = "/root/reference") -> bool:
    """Render docs/expr_parity.md; returns False when the reference
    tree is absent."""
    drift = reference_expression_drift(reference_root)
    if drift is None:
        return False
    lines = [
        "# Expression parity vs reference GpuOverrides.scala",
        "",
        "Generated by `python -m spark_rapids_tpu.testing."
        "api_validation --drift` — a mechanical diff of this engine's "
        "expression registry against the reference's `expr[...]` rule "
        "table (GpuOverrides.scala:395-1449).",
        "",
        f"- reference rules: **{drift['reference_total']}**",
        f"- covered by the registry: **{len(drift['covered'])}** "
        f"(incl. renames: {', '.join(f'{k}->{v}' for k, v in sorted(REFERENCE_EXPR_ALIASES.items()))})",
        f"- handled by a subsystem instead of a registry entry: "
        f"**{len(drift['via_subsystem'])}** "
        f"({', '.join(drift['via_subsystem'])})",
        f"- missing: **{len(drift['missing'])}**"
        + (f" ({', '.join(drift['missing'])})" if drift['missing']
           else ""),
        f"- registered here beyond the reference's table: "
        f"**{len(drift['extra'])}** ({', '.join(drift['extra'])})",
        "",
        "## Covered",
        "",
        ", ".join(drift["covered"]),
        "",
    ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return True


def main() -> int:  # pragma: no cover - CLI entry
    import sys

    if "--drift" in sys.argv:
        import os

        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "docs",
            "expr_parity.md")
        if write_drift_report(out):
            print(f"wrote {out}")
            return 0
        print("reference tree not available; drift report skipped")
        return 1
    findings = validate()
    if not findings:
        print("API validation: clean "
              "(execs, expressions, and enable keys all in sync)")
        return 0
    print(f"API validation: {len(findings)} finding(s)")
    for f in findings:
        print(f"  - {f}")
    return 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
