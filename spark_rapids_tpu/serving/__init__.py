"""Sub-second serving: prepared statements + plan-template/result caches.

Three cooperating layers take repeated queries from full execution to a
validated disk read (docs/serving_cache.md):

1. **Prepared statements** (prepared.py) — ``Session.prepare(plan)``
   extracts literal parameters into a skeleton; ``execute(params)``
   re-binds them at dispatch without rebuilding the query.
2. **Plan-template cache** (template.py) — skeleton-keyed LRU of fully
   planned physical trees, consulted by ``Session.prepare_execution``
   so even ad-hoc submissions that normalize to a seen template skip
   planning and fusion.
3. **Result cache** (result_cache.py) — completed results persist as
   CRC32C-stamped frames keyed by the recovery query+data fingerprint;
   the scheduler serves a validated hit BEFORE admission (a hit never
   queues and is never shed), and the streaming ledger pushes
   invalidation when source files change.

Everything is gated by ``serving.cache.*`` confs and fails OPEN: any
serving-layer error steps aside and the query executes normally.
"""
from __future__ import annotations

from typing import Dict

from .prepared import (Param, PreparedStatement, bind_parameters,
                       binding_digest, extract_parameters,
                       skeleton_fingerprint)
from .result_cache import (ResultCache, ServingKey, invalidate_for_files,
                           register_stream_result, serving_root)
from .template import TemplateCache

__all__ = [
    "Param", "PreparedStatement", "ResultCache", "ServingCaches",
    "ServingKey", "TemplateCache", "bind_parameters", "binding_digest",
    "extract_parameters", "invalidate_for_files",
    "register_stream_result", "serving_root", "skeleton_fingerprint",
]


class ServingCaches:
    """The session-owned cache pair (``Session.serving``)."""

    def __init__(self, session):
        self.templates = TemplateCache(session.conf)
        self.results = ResultCache(session.conf)

    def metrics(self) -> Dict[str, int]:
        out = dict(self.templates.metrics())
        out.update(self.results.metrics())
        return out
