"""Atomic filesystem helpers shared by checkpointing, spill and the
bench artifacts.

The one durable-write idiom of this engine: serialize into a temp file
in the SAME directory as the target, flush + fsync, then ``os.replace``
over the target.  A crash or SIGKILL mid-write leaves either the old
file or no file — never a truncated artifact a later reader could
mistake for valid data.  Crash-orphaned ``.tmp`` files are invisible to
readers (they never match the target name) and are swept by the
recovery hygiene pass.
"""
from __future__ import annotations

import json
import os
import tempfile

#: prefix of every in-flight temp file this module creates — the
#: recovery sweep removes stale ones; readers never match it
TMP_PREFIX = ".srt-tmp-"


def atomic_write_bytes(path: str, data) -> None:
    """Atomically write ``data`` (bytes / bytearray / a numpy uint8
    array via its buffer) to ``path``: temp file in the same directory,
    fsync, ``os.replace``."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=TMP_PREFIX, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(memoryview(data))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, *, indent: int = 1) -> None:
    """Atomically write ``obj`` as JSON to ``path`` (same temp + fsync
    + replace discipline as :func:`atomic_write_bytes`)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=TMP_PREFIX, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=indent, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def sweep_tmp_files(directory: str) -> int:
    """Remove crash-orphaned temp files under ``directory`` (recursive);
    returns the number removed.  Never raises."""
    removed = 0
    try:
        for root, _dirs, files in os.walk(directory):
            for name in files:
                if name.startswith(TMP_PREFIX) or (
                        name.startswith(".bench-")
                        and name.endswith(".tmp")):
                    try:
                        os.unlink(os.path.join(root, name))
                        removed += 1
                    except OSError:
                        pass
    except OSError:
        pass
    return removed
