"""Device gather/compaction kernels.

Row selection (filter, sort, join output) on TPU is expressed as
stable-sort + gather over static shapes: a boolean keep-mask becomes a
permutation that compacts kept rows to the front, with the logical row
count carried as a traced scalar — no dynamic shapes, no recompiles.
(Reference analogue: cudf Table.filter / gather; SURVEY §7 Hard parts.)
"""
from __future__ import annotations

from typing import Optional

from ...data.column import DeviceBatch, DeviceColumn


def gather_column(col: DeviceColumn, order, valid_mask=None) -> DeviceColumn:
    """Permute one column by ``order`` (int32[n]); optionally AND the
    permuted validity with ``valid_mask`` (already in output order)."""
    data = col.data[order]
    validity = col.validity[order]
    if valid_mask is not None:
        validity = validity & valid_mask
    lengths = col.lengths[order] if col.lengths is not None else None
    return DeviceColumn(col.dtype, data, validity, lengths)


def gather_batch(batch: DeviceBatch, order, num_rows,
                 valid_mask=None) -> DeviceBatch:
    cols = [gather_column(c, order, valid_mask) for c in batch.columns]
    return DeviceBatch(batch.schema, cols, num_rows)


def compact(batch: DeviceBatch, keep) -> DeviceBatch:
    """Compact rows where ``keep`` (bool[padded]) to the front; the new
    logical row count is sum(keep).  Stable."""
    import jax.numpy as jnp

    keep = keep & batch.row_mask()
    # stable argsort of (not keep): kept rows (0) first, original order
    order = jnp.argsort(~keep, stable=True).astype(jnp.int32)
    count = keep.sum().astype(jnp.int32)
    kept_mask = jnp.arange(batch.padded_rows, dtype=jnp.int32) < count
    return gather_batch(batch, order, count, kept_mask)
