"""Typed fault exceptions of the query-level fault-tolerance layer.

Reference analogue: the typed retry OOMs of RmmRapidsRetryIterator
(GpuRetryOOM / GpuSplitAndRetryOOM) extended to the distributed fault
model Theseus-class engines need (PAPERS.md): corrupted payloads,
crashed stages and tripped watchdogs are *recoverable, typed* events —
the runner re-executes from lineage or walks down the degradation
ladder instead of consuming garbage or hanging.

This module must stay import-light (no engine imports): it is imported
by memory/, shuffle/, parallel/ and exec/ alike.
"""
from __future__ import annotations


class TpuFaultError(RuntimeError):
    """Base of every recoverable distributed fault.  The degradation
    ladder (fault/ladder.py, Session.execute) catches exactly this
    family — anything else is a genuine bug and must surface."""

    def __init__(self, *args, site: str = "", injected: bool = False):
        super().__init__(*args)
        #: checkpoint site that raised (e.g. ``spill.write``)
        self.site = site
        #: True when raised by the fault injector (test mode) rather
        #: than by a real corruption/crash/timeout
        self.injected = injected


class TpuPayloadCorruption(TpuFaultError):
    """A spill/shuffle/exchange payload failed its CRC32C verification
    on read.  The producing stage must be re-executed from lineage —
    the corrupted bytes must never reach an operator."""


class TpuStageCrash(TpuFaultError):
    """A stage (or leaf drain) died mid-execution.  Lineage is explicit
    in the stage plan, so the failed stage is re-executed bounded by
    ``fault.maxStageRetries``."""


class TpuStorageExhausted(TpuFaultError):
    """A spill (or other durable) write hit ENOSPC / an OSError: the
    host filesystem under the spill directory is full or failing.  The
    fault is *retryable* — the retry combinators may free space by
    releasing buffers, and the degradation ladder can climb to a rung
    that spills less — so it must surface as a typed fault, never as an
    unhandled crash with a half-written file left behind."""


class TpuStageTimeout(TpuFaultError):
    """A stage watchdog deadline (``fault.stageTimeoutMs``) expired, or
    a bounded producer/consumer queue made no progress past its
    deadline — the hung unit of work is abandoned and re-executed
    instead of blocking the query forever."""


class TpuPeerLost(TpuFaultError):
    """A peer worker process died or stopped heartbeating mid-query, or
    a collective exceeded ``fault.peer.collectiveTimeoutMs``.  Unlike
    the stage-scoped faults above this is NOT stage-retryable (the dead
    peer would wedge the retry in the same collective): the elastic
    layer re-forms the mesh on the surviving devices and re-executes
    from the recovery substrate's checkpoints instead."""
