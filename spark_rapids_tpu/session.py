"""Session — engine entry point and plugin bootstrap.

Reference analogue: SQLPlugin / RapidsDriverPlugin / RapidsExecutorPlugin
(Plugin.scala:145-247) + SparkSession surface.  A Session owns the conf,
initializes the device runtime (device manager + semaphore — the
executor-plugin init path), and drives query execution:

    logical plan -> planner -> host physical plan
      -> TpuOverrides (tag/convert)            [preColumnarTransitions]
      -> TpuTransitionOverrides (transitions)  [postColumnarTransitions]
      -> execute
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

log = logging.getLogger(__name__)

from . import types as T
from .config import EXPORT_COLUMNAR_RDD, TpuConf
from .data.column import HostBatch
from .plan import logical as L
from .plan.logical import DataFrame
from .plan.physical import ExecContext, PhysicalPlan, collect_batches
from .plan.planner import Planner


def _clear_registry_quietly(registry):
    try:
        registry.clear()
    except Exception:  # noqa: BLE001 - interpreter teardown
        pass


class Session:
    """User entry point.

    ``Session()`` enables TPU acceleration; ``Session(tpu_enabled=False)``
    is the pure host engine (the CPU oracle in tests)."""

    _active: Optional["Session"] = None

    def __init__(self, conf: Optional[Dict] = None,
                 tpu_enabled: bool = True):
        settings = dict(conf or {})
        if not tpu_enabled:
            settings.setdefault("spark.rapids.tpu.sql.enabled", False)
        self.conf = TpuConf(settings)
        self._executed_plans: List[PhysicalPlan] = []
        self.capture_plans = False
        self.last_metrics: Dict[str, int] = {}
        self.last_write_stats = None  # WriteStatsTracker of last write
        #: one-line retry/split-retry summary of the last execution
        #: ("" when the query saw no memory pressure) — EXPLAIN/trace
        #: surface for degraded queries
        self.last_retry_summary: str = ""
        #: telemetry.QueryProfile of the most recent execution (None
        #: unless telemetry.enabled); Session.profiles keeps the last
        #: telemetry.maxQueryProfiles of them
        self.last_profile = None
        #: per-kernel profiler delta of the most recent execution
        #: ({fingerprint -> telemetry.profiler.KernelStat}; None unless
        #: telemetry.profiler.enabled) + the observed h2d ceiling
        self.last_kernel_profile = None
        self.last_h2d_ceiling_bps = 0.0
        from collections import deque as _deque

        from .config import TELEMETRY_MAX_QUERY_PROFILES

        self._profiles = _deque(
            maxlen=max(1, self.conf.get(TELEMETRY_MAX_QUERY_PROFILES)))
        #: weakrefs to live StreamHandles (metrics_text/metrics_json
        #: fold their progress + latency histograms into the exports)
        self._streams: List = []
        # logical-plan -> physical-plan cache: repeated collect() of the
        # same DataFrame reuses the exec instances and with them every
        # per-exec jit cache (without this, each collect re-traced and
        # re-compiled ~5 XLA programs — measured ~8s/collect on CPU)
        import weakref

        self._plan_cache = weakref.WeakKeyDictionary()
        # concurrent query scheduler — created lazily on first submit()
        # so plain execute() sessions never pay for its threads
        import threading as _threading

        self._scheduler = None
        self._scheduler_lock = _threading.Lock()
        # serving caches (serving/) — created lazily on first prepare()
        # or first cache-enabled submission
        self._serving = None
        from .config import TRACE_ENABLED
        from .utils import tracing

        if self.conf.get(TRACE_ENABLED):
            tracing.enable(True)
        if self.conf.is_sql_enabled:
            from .memory.device_manager import DeviceManager
            from .memory.spill import install as install_spill

            self.device_manager = DeviceManager.get_or_create(self.conf)
            self.spill_framework = install_spill(self.device_manager,
                                                 self.conf)
            # the shared kernel cache is process-wide (like the device
            # manager); each device session (re)applies its sizing conf
            from .exec.kernel_cache import GLOBAL as _kernel_cache

            _kernel_cache.configure(self.conf)
            # the per-kernel dispatch profiler is process-wide too
            from .telemetry.profiler import PROFILER as _profiler

            _profiler.configure(self.conf)
            # reusable broadcast artifacts (reference:
            # GpuBroadcastExchangeExec's broadcast variable, built once
            # and shared by every consumer)
            from .exec.broadcast import BroadcastRegistry
            from .shuffle.catalog import ShuffleCatalog

            self.broadcast_registry = BroadcastRegistry(
                self.spill_framework)
            weakref.finalize(self, _clear_registry_quietly,
                             self.broadcast_registry)
            # shuffle-id -> map-id -> buffers index with per-shuffle
            # cleanup (reference: ShuffleBufferCatalog.scala)
            self.shuffle_catalog = ShuffleCatalog(self.spill_framework)
            weakref.finalize(self, _clear_registry_quietly,
                             self.shuffle_catalog)
        else:
            self.device_manager = None
            self.spill_framework = None
            self.broadcast_registry = None
            self.shuffle_catalog = None
        Session._active = self

    # ----- data sources ----------------------------------------------------
    def create_dataframe(self, data, schema=None,
                         n_partitions: int = 2) -> DataFrame:
        """From a dict of name->values, a HostBatch, or list of row tuples
        with a Schema.

        Source data is treated as IMMUTABLE once handed in: repeated
        collects may serve cached device uploads (HostToDeviceExec), so
        mutating the source afterwards yields undefined results.  Dict
        and row inputs are copied at creation; a HostBatch hands its
        arrays over — they are frozen (``writeable=False``) so a later
        caller write raises instead of silently serving stale cached
        results.  (A column built over a VIEW can still be mutated
        through the base array; the freeze is a tripwire, not a fence.)"""
        if isinstance(data, HostBatch):
            batch = data
            for c in batch.columns:
                for arr in (c.data, c.validity):
                    if isinstance(arr, np.ndarray):
                        arr.flags.writeable = False
        elif isinstance(data, dict):
            batch = HostBatch.from_pydict(data, schema)
        elif isinstance(data, list):
            assert schema is not None, "row data requires a schema"
            cols = {f.name: [r[i] for r in data]
                    for i, f in enumerate(schema)}
            batch = HostBatch.from_pydict(cols, schema)
        else:
            raise TypeError(f"cannot create dataframe from {type(data)}")
        return DataFrame(self, L.LocalRelation([batch], batch.schema,
                                               n_partitions))

    def read_parquet(self, *paths, schema=None, **options) -> DataFrame:
        return self._read("parquet", list(paths), schema, options)

    def read_orc(self, *paths, schema=None, **options) -> DataFrame:
        return self._read("orc", list(paths), schema, options)

    def read_csv(self, *paths, schema=None, header: bool = True,
                 **options) -> DataFrame:
        options = dict(options, header=header)
        if schema is not None:
            options["schema"] = schema
        return self._read("csv", list(paths), schema, options)

    def _read(self, fmt, paths, schema, options) -> DataFrame:
        from .io import scans

        if schema is None:
            schema = scans.infer_schema(fmt, paths, options)
        return DataFrame(self, L.FileScan(fmt, paths, schema, options))

    # ----- execution -------------------------------------------------------
    def physical_plan(self, plan: L.LogicalPlan) -> PhysicalPlan:
        from .plan.optimizer import optimize

        phys = Planner(self.conf).plan(optimize(plan))
        if self.conf.is_sql_enabled:
            from .plan.overrides import TpuOverrides
            from .plan.transitions import TpuTransitionOverrides

            phys = TpuOverrides(self.conf).apply(phys)
            phys = TpuTransitionOverrides(self.conf).apply(phys)
        return phys

    def prepare_execution(self, plan: L.LogicalPlan, *,
                          scheduled: bool = False, cancel_token=None,
                          force_host_shuffle: bool = False,
                          recovery=None):
        """Plan + capture + context — the shared front half of execute
        paths (incl. the ML columnar export).

        Cached exec instances are handed out to ONE execution at a
        time (``_exec_lock``, non-blocking): execs carry per-execution
        state (metrics registries), so a concurrent collect of the same
        DataFrame gets a freshly planned tree instead of sharing."""
        import threading

        from .exec.kernel_cache import GLOBAL as _kernel_cache

        from .telemetry.profiler import PROFILER as _profiler

        # snapshot BEFORE planning: exec construction is where keyed
        # kernels register (sharedKernels) and misses start compiling,
        # and it belongs to this query's kernelCache.* delta
        kc_mark = _kernel_cache.counters()
        kp_mark = _profiler.mark()
        try:
            phys = self._plan_cache.get(plan)
        except TypeError:  # unhashable/unweakref-able plan
            phys = None
        if phys is not None and not phys._exec_lock.acquire(
                blocking=False):
            phys = None  # cached tree busy in another thread
        serving = self.serving_if_enabled()
        template_key = None
        if phys is None and serving is not None:
            # plan-template cache: a DIFFERENT plan object that
            # normalizes to a seen (skeleton, binding) template reuses
            # its planned tree — acquire() hands it out with the same
            # non-blocking _exec_lock discipline as the cache above
            template_key = serving.templates.key_for(plan)
            phys = serving.templates.acquire(template_key)
            if phys is not None:
                try:
                    self._plan_cache[plan] = phys
                except TypeError:
                    pass
        if phys is None:
            phys = self.physical_plan(plan)
            phys._exec_lock = threading.Lock()
            phys._exec_lock.acquire()
            try:
                self._plan_cache[plan] = phys
            except TypeError:
                pass
            if serving is not None and template_key is not None:
                serving.templates.store(template_key, phys)
        if self.capture_plans:
            self._executed_plans.append(phys)
        if recovery is None:
            # direct callers that bypass the ladder (scheduled queries,
            # the ML columnar export) still checkpoint + auto-resume
            from .config import RECOVERY_ENABLED

            if self.conf.get(RECOVERY_ENABLED):
                from .recovery import RecoveryManager

                recovery = RecoveryManager(self.conf)
                recovery.attach_query(plan)
        ctx = ExecContext(self.conf, self, scheduled=scheduled,
                          cancel_token=cancel_token,
                          force_host_shuffle=force_host_shuffle)
        ctx.kernel_cache_mark = kc_mark
        ctx.kernel_profiler_mark = kp_mark
        if recovery is not None:
            # stamp every exchange with its rung-invariant plan
            # fingerprint (re-stamping a cached tree is idempotent)
            recovery.stamp_plan(phys)
            ctx.recovery = recovery
        return phys, ctx

    def execute(self, plan: L.LogicalPlan) -> HostBatch:
        """Execute with the graceful-degradation ladder: when the
        native (device) execution exhausts its typed fault recovery —
        payload corruption past its task retries, a stage crash, a
        tripped watchdog, a device-semaphore timeout — the query
        re-executes on the CPU-exec plan (bit-identical by the oracle
        contract) instead of raising, and ``fault.degradeLevel``
        records the rung (``fault.degrade.enabled`` gates this)."""
        return self._execute_with_ladder(plan, force_resume=False)

    def resume(self, plan: L.LogicalPlan) -> HostBatch:
        """Crash-recovery entry point: execute ``plan``, resuming from
        any durable stage checkpoints a previous (crashed or killed)
        process left under ``recovery.dir`` — regardless of
        ``recovery.autoResume``.  Requires ``recovery.enabled``;
        checkpoints that fail validation (plan/query fingerprint,
        schema signature, result-affecting conf snapshot, per-frame
        CRC32C) are quarantined with a ``checkpoint_quarantine`` event
        and their stages simply re-execute — a stale or corrupt
        checkpoint can cost time, never correctness."""
        return self._execute_with_ladder(plan, force_resume=True)

    def _execute_with_ladder(self, plan: L.LogicalPlan, *,
                             force_resume: bool) -> HostBatch:
        """The shared body of ``execute``/``resume``: arm the per-query
        attempt budget (``fault.maxTotalAttempts`` — one ceiling across
        task retries, stage retries, shuffle fallbacks and ladder
        rungs), create the ONE RecoveryManager the whole ladder shares
        (checkpoints written on a failed rung are resumed by the next),
        then run the degradation ladder."""
        from .config import FAULT_MAX_TOTAL_ATTEMPTS, RECOVERY_ENABLED
        from .fault.budget import GLOBAL as _budget
        from .fault.errors import TpuFaultError

        recovery = None
        if self.conf.get(RECOVERY_ENABLED):
            from .recovery import RecoveryManager

            recovery = RecoveryManager(self.conf,
                                       force_resume=force_resume)
            recovery.attach_query(plan)
        owned = _budget.begin(self.conf.get(FAULT_MAX_TOTAL_ATTEMPTS))
        try:
            try:
                return self._execute_native(plan, recovery=recovery)
            except TpuFaultError as e:
                from .config import FAULT_DEGRADE_ENABLED, SHUFFLE_MODE

                if self.device_manager is None or \
                        not self.conf.get(FAULT_DEGRADE_ENABLED):
                    raise
                # ladder rung between native and CPU: re-execute with
                # every exchange forced onto the host-staged shuffle
                # path — the recovery for faults confined to the
                # device-resident data path (a device-targeted
                # corruption drill, HBM exhaustion during a packed
                # write).  Skipped when the conf already pins host
                # shuffle (the rung would change nothing).
                if (self.conf.get(SHUFFLE_MODE)
                        or "auto").lower() != "host":
                    try:
                        return self._execute_host_shuffle_rung(
                            plan, e, recovery=recovery)
                    except TpuFaultError as e2:
                        return self._execute_degraded_cpu(
                            plan, e2, recovery=recovery)
                return self._execute_degraded_cpu(
                    plan, e, recovery=recovery)
        finally:
            _budget.end(owned)

    def _finalize_metrics(self, ctx, phys=None,
                          preserve: Optional[Dict] = None) -> None:
        """The ONE place the per-query metric snapshot, the fault/retry
        counters and the telemetry profile are merged into the session
        at query end (previously duplicated — with hand-copied prefix
        filters — between ``_execute_native`` and the CPU-fallback
        path, where drift silently double- or under-counted).

        ``preserve``: already-merged counters from a FAILED earlier
        attempt (the degraded path) that must stay visible next to the
        fresh snapshot.  Counters are never double-counted across
        consecutive queries: the snapshot always starts from this
        query's own registry, and the process-global fault stats are
        reset at every query start by ``ExecContext``."""
        from .fault.stats import GLOBAL as _fault_stats
        from .fault.stats import fault_summary
        from .memory.retry import retry_summary

        merged = ctx.metrics.snapshot()
        # per-exchange partition histograms (adaptive/stats.py) —
        # surfaced regardless of adaptive.enabled, so shuffle skew is
        # visible in last_metrics / profiles / the Prometheus export
        stage_stats = getattr(ctx, "stage_stats", None)
        if stage_stats is not None:
            merged.update(stage_stats.metrics())
        recovery = getattr(ctx, "recovery", None)
        if recovery is not None:
            # recovery.* counters accumulate across ladder rungs (one
            # manager per query), so later rungs report the running sum
            merged.update(recovery.metrics())
        if preserve:
            merged.update(preserve)
        from .fault.budget import GLOBAL as _attempt_budget

        # after ``preserve``: the armed ledger's live count supersedes
        # any stale fault.totalAttempts carried from a failed rung
        if _attempt_budget.armed():
            merged.update(_attempt_budget.snapshot())
        if self.device_manager is not None:
            if not getattr(ctx, "scheduled", False):
                # scheduled queries never reset (or report) the
                # process-global fault counters — a neighbor's fault
                # drill must not leak into this query's metrics
                merged.update(_fault_stats.snapshot())
            from .exec.kernel_cache import GLOBAL as _kernel_cache
            from .shuffle.device_shuffle import GLOBAL as _shuffle_stats

            merged.update(_kernel_cache.metrics_since(
                getattr(ctx, "kernel_cache_mark", None)))
            merged.update(_shuffle_stats.metrics_since(
                getattr(ctx, "shuffle_stats_mark", None)))
            from .telemetry.profiler import PROFILER as _profiler

            if _profiler.enabled:
                # the per-kernel roofline delta of THIS query; the
                # handle/profile read it because last_kernel_profile is
                # last-writer-wins shared state (like last_metrics)
                ctx.kernel_profile = _profiler.since(
                    getattr(ctx, "kernel_profiler_mark", None))
                self.last_kernel_profile = ctx.kernel_profile
                self.last_h2d_ceiling_bps = _profiler.h2d_ceiling_bps()
            fsum = fault_summary(merged)
            if fsum:
                log.warning(
                    "query recovered from faults DEGRADED: %s", fsum)
        self.last_metrics = merged
        self.last_retry_summary = retry_summary(merged)
        if self.last_retry_summary:
            from .config import TRACE_ENABLED

            lvl = logging.WARNING if self.conf.get(TRACE_ENABLED) \
                else logging.INFO
            log.log(lvl, "query completed DEGRADED under memory "
                    "pressure: %s", self.last_retry_summary)
        from .telemetry import finish_query

        # per-query attribution for concurrent callers (QueryHandle):
        # session.last_metrics/last_profile are last-writer-wins shared
        # state, so the handle reads these instead
        ctx.final_metrics = merged
        # an adaptive run profiles its FINAL (rewritten) plan — the
        # "AdaptiveSparkPlan isFinalPlan=true" tree — not the static one
        final_phys = getattr(ctx, "aqe_final_phys", None) or phys
        ctx.profile = finish_query(self, ctx, phys=final_phys,
                                   metrics=merged)
        if ctx.profile is not None:
            kstats = getattr(ctx, "kernel_profile", None)
            if kstats:
                # the profile renders its own roofline section
                ctx.profile.kernel_stats = kstats
                ctx.profile.h2d_ceiling_bps = self.last_h2d_ceiling_bps
            from .config import TELEMETRY_TRACE_DIR

            trace_dir = self.conf.get(TELEMETRY_TRACE_DIR)
            if trace_dir:
                from .telemetry.trace import write_query_trace

                write_query_trace(trace_dir, ctx.profile)
        nodes = getattr(ctx, "aqe_broadcast_nodes", None)
        if nodes:
            # dynamic-conversion build batches are keyed by weakrefs
            # to THIS execution's stage leaves: no future query can
            # reuse them, so free them now (the recorded strong refs
            # keep the keys matchable) instead of leaving them
            # cataloged until the registry's next lazy purge
            if self.broadcast_registry is not None:
                from .exec.broadcast import canonical_key

                for node in nodes:
                    self.broadcast_registry.free_key(canonical_key(node))
            ctx.aqe_broadcast_nodes = None
        if getattr(ctx, "aqe_final_phys", None) is not None:
            # the final plan holds the per-execution stage leaves (and
            # through them the resident shuffle blocks) — drop it now
            # that the profile is rendered
            ctx.aqe_final_phys = None

    def _execute_native(self, plan: L.LogicalPlan, *,
                        scheduled: bool = False, cancel_token=None,
                        ctx_sink: Optional[Dict] = None,
                        force_host_shuffle: bool = False,
                        recovery=None) -> HostBatch:
        phys, ctx = self.prepare_execution(
            plan, scheduled=scheduled, cancel_token=cancel_token,
            force_host_shuffle=force_host_shuffle, recovery=recovery)
        if ctx_sink is not None:
            ctx_sink["phys"] = phys
            ctx_sink["ctx"] = ctx
        try:
            from .adaptive.executor import maybe_execute_adaptive

            # adaptive execution: materialize stages one at a time and
            # re-plan the unexecuted suffix from real sizes; returns
            # None when the plan/conf is ineligible (then the static
            # plan executes unchanged)
            data = maybe_execute_adaptive(phys, ctx)
            if data is None:
                data = phys.execute(ctx)
            schema = phys.schema if len(phys.schema) else plan.schema
            return collect_batches(data, schema, ctx)
        finally:
            # benchmark/debug hook: per-exec metric snapshot of the most
            # recent execution (upload/readback wall decomposition); a
            # degraded query must be VISIBLY degraded (retry/fault
            # counters + summaries, mirroring the reference's retry
            # metrics in the SQL UI)
            self._finalize_metrics(ctx, phys=phys)
            phys._exec_lock.release()
            # per-shuffle cleanup at query end — frees shuffle output
            # even when a reader abandoned early (limit over a join)
            if self.shuffle_catalog is not None:
                for sid in ctx.shuffle_ids:
                    self.shuffle_catalog.unregister_shuffle(sid)

    def _execute_host_shuffle_rung(self, plan: L.LogicalPlan,
                                   cause, recovery=None) -> HostBatch:
        """The device-shuffle → host-shuffle ladder rung: re-execute
        the whole query natively with every exchange forced onto the
        host-staged path.  Injectors stay ARMED (re-armed from conf by
        the new ExecContext) — a drill that also hits the host path
        fails this rung and falls through to the CPU rung.  Fault
        counters from the failed device attempt stay visible in
        ``last_metrics`` whether this rung succeeds or not.  With
        recovery enabled, exchanges the failed attempt checkpointed are
        RESUMED here instead of re-executed (host frames are
        mode-independent), and this rung's own completed exchanges
        checkpoint for the CPU rung below."""
        from .fault.budget import GLOBAL as _budget
        from .fault.errors import TpuFaultError
        from .fault.stats import GLOBAL as _fault_stats
        from .fault.stats import fault_summary
        from .telemetry.events import emit_event

        _budget.charge("ladder_host_shuffle", site="session.ladder")

        # the failed attempt's counters were finalized into
        # last_metrics by _execute_native's finally — carry them
        prior = {k: v for k, v in (self.last_metrics or {}).items()
                 if k.startswith(("fault.", "retry."))}
        prior["fault.numShuffleFallbacks"] = \
            prior.get("fault.numShuffleFallbacks", 0) + 1

        def _emit_rung_events():
            # emitted AFTER the rung's execution: the telemetry binding
            # then points at the rung's own profile (the final
            # last_profile), not the already-finished device attempt's
            emit_event("shuffle_fallback", reason="ladder",
                       cause=type(cause).__name__)
            emit_event("degrade", rung="host-shuffle",
                       cause=type(cause).__name__)

        log.warning(
            "native execution exhausted fault recovery (%s: %s) — "
            "re-executing on the host-staged shuffle rung",
            type(cause).__name__, cause)

        def _merge_prior():
            merged = dict(self.last_metrics)
            for k, v in prior.items():
                if k == "fault.degradeLevel":
                    merged[k] = max(merged.get(k, 0), v)
                else:
                    merged[k] = merged.get(k, 0) + v
            self.last_metrics = merged

        try:
            out = self._execute_native(plan, force_host_shuffle=True,
                                       recovery=recovery)
        except TpuFaultError:
            # keep the device attempt (and this rung's fallback count)
            # visible to the CPU rung: both in last_metrics and in the
            # process-global stats its finalize snapshots (the CPU
            # rung's session-less context never resets them)
            _merge_prior()
            _fault_stats.add("numShuffleFallbacks")
            _emit_rung_events()
            raise
        _merge_prior()
        _fault_stats.add("numShuffleFallbacks")
        _emit_rung_events()
        from .config import TELEMETRY_ENABLED

        if self.last_profile is not None \
                and self.conf.get(TELEMETRY_ENABLED):
            self.last_profile.metrics = dict(self.last_metrics)
        fsum = fault_summary(self.last_metrics)
        if fsum:
            log.warning(
                "query recovered on the host-shuffle rung DEGRADED: %s",
                fsum)
        return out

    def _execute_degraded_cpu(self, plan: L.LogicalPlan,
                              cause, recovery=None) -> HostBatch:
        """The bottom ladder rung: re-execute the WHOLE query on the
        host engine (no TPU overrides), with every injector disarmed —
        the fallback must run clean.  Fault counters from the failed
        native attempt are preserved in ``last_metrics`` so the
        degradation stays visible.  Checkpoints written by the failed
        device/host rungs resume here too: the host plan subtree
        fingerprints are rung-invariant and the frames are plain
        serialized HostBatches."""
        from .fault.budget import GLOBAL as _budget
        from .fault.injector import install_fault_injector
        from .fault.stats import DEGRADE_CPU, GLOBAL as _fault_stats
        from .memory.retry import install_injector
        from .plan.overrides import cpu_exec_plan
        from .telemetry.events import emit_event

        _budget.charge("ladder_cpu", site="session.ladder")
        install_injector(None)
        install_fault_injector(None)
        _fault_stats.set_max("degradeLevel", DEGRADE_CPU)
        emit_event("degrade", level=DEGRADE_CPU, rung="cpu",
                   cause=type(cause).__name__)
        log.warning(
            "native execution exhausted fault recovery (%s: %s) — "
            "DEGRADED to the CPU-exec plan",
            type(cause).__name__, cause)
        # keep the failed attempt's degradation counters visible
        prior = {k: v for k, v in (self.last_metrics or {}).items()
                 if k.startswith(("fault.", "retry."))}
        phys = cpu_exec_plan(self.conf, plan)
        ctx = ExecContext(self.conf, None)
        if recovery is not None:
            recovery.stamp_plan(phys)
            ctx.recovery = recovery
        data = phys.execute(ctx)
        schema = phys.schema if len(phys.schema) else plan.schema
        out = collect_batches(data, schema, ctx)
        self._finalize_metrics(ctx, phys=phys, preserve=prior)
        from .config import TELEMETRY_ENABLED

        if self.last_profile is not None \
                and self.conf.get(TELEMETRY_ENABLED):
            # telemetry was on for THIS query, so last_profile is the
            # native attempt's: refresh it with the final merged
            # counters (degrade event included).  Without the conf
            # guard a stale prior-query profile would be corrupted.
            self.last_profile.metrics = dict(self.last_metrics)
        return out

    # ----- concurrent submission (scheduler/) -------------------------------
    @property
    def scheduler(self):
        """The session's QueryScheduler, created on first access."""
        with self._scheduler_lock:
            if self._scheduler is None:
                from .scheduler.query_scheduler import QueryScheduler

                self._scheduler = QueryScheduler(self)
            return self._scheduler

    # ----- sub-second serving (serving/) ------------------------------------
    @property
    def serving(self):
        """The session's serving caches (prepared statements / plan
        templates / results), created on first access."""
        with self._scheduler_lock:
            if self._serving is None:
                from .serving import ServingCaches

                self._serving = ServingCaches(self)
            return self._serving

    def serving_if_enabled(self):
        """The serving caches when ``serving.cache.enabled`` is on,
        else None — the form the hot paths (prepare_execution, the
        scheduler's admission) consult so disabled sessions never pay
        for normalization or fingerprinting."""
        from .config import SERVING_CACHE_ENABLED

        if not self.conf.get(SERVING_CACHE_ENABLED):
            return None
        return self.serving

    def prepare(self, plan):
        """Prepare ``plan`` (a DataFrame or logical plan) for repeated
        execution: literal values are extracted into positional
        parameters and the returned ``PreparedStatement``'s
        ``execute(params)`` / ``submit(params)`` re-bind them at
        dispatch — planning, fusion and compilation are reused through
        the serving caches instead of redone (docs/serving_cache.md).
        Works regardless of ``serving.cache.enabled`` (that conf gates
        the caching of ad-hoc submissions)."""
        if isinstance(plan, DataFrame):
            plan = plan.plan
        from .serving import PreparedStatement

        return PreparedStatement(self, plan)

    def submit(self, plan, priority: int = 0, tenant: str = "default"):
        """Submit a query (a DataFrame or logical plan) for concurrent
        execution; returns a ``QueryHandle`` with ``result()`` /
        ``cancel()`` / ``status()``.  Queued queries drain by
        per-tenant deficit-weighted fair share with priority aging
        (``scheduler.tenant.<tenant>.*`` confs; see docs/qos.md).
        Admission is bounded (``scheduler.maxConcurrent`` running +
        ``scheduler.maxQueued`` queued); a submit past the bound raises
        ``QueryRejected`` and emits an ``admission_reject`` event, and
        under declared overload a low-tier submit is shed with the
        retryable ``TpuOverloaded`` (its ``retry_after_ms`` is the
        backoff hint)."""
        if isinstance(plan, DataFrame):
            plan = plan.plan
        return self.scheduler.submit(plan, priority=priority,
                                     tenant=tenant)

    # ----- continuous queries (streaming/) ----------------------------------
    def stream(self, plan, trigger=None, priority: int = 0,
               tenant: str = "default"):
        """Start a continuous query over ``plan``'s file sources and
        return a ``StreamHandle`` (``await_batch()`` / ``progress()`` /
        ``stop()``).  Each micro-batch re-discovers the sources, merges
        grown exchanges incrementally through the recovery substrate
        and submits the cumulative plan via the scheduler with the
        per-batch ``streaming.batchDeadlineMs`` deadline — every batch
        result is bit-identical to a cold full recompute of the same
        cumulative input.  ``trigger`` is the tick interval in ms
        (default ``streaming.triggerIntervalMs``); ``trigger=0`` means
        manual ticks via ``handle.process_available()``.  Requires
        ``streaming.enabled``."""
        from .config import STREAMING_ENABLED, STREAMING_TRIGGER_INTERVAL_MS
        from .streaming.stream import StreamHandle

        if not self.conf.get(STREAMING_ENABLED):
            raise RuntimeError(
                "streaming is disabled — set "
                "spark.rapids.tpu.streaming.enabled=true")
        if isinstance(plan, DataFrame):
            plan = plan.plan
        trigger_ms = self.conf.get(STREAMING_TRIGGER_INTERVAL_MS) \
            if trigger is None else int(trigger)
        handle = StreamHandle(self, plan, trigger_ms=trigger_ms,
                              priority=priority, tenant=tenant)
        import weakref

        self._streams = [r for r in self._streams if r() is not None]
        self._streams.append(weakref.ref(handle))
        return handle

    def active_streams(self) -> List:
        """Live StreamHandles started by :meth:`stream`.  Stopped or
        GC'd handles drop out — the scrape surface reflects what is
        running, not what once ran (callers keep the handle if they
        want its final progress)."""
        out = []
        for r in self._streams:
            h = r()
            if h is not None and not getattr(h, "_stopped", False):
                out.append(h)
        return out

    def resume_stream(self, plan, trigger=None, priority: int = 0,
                      tenant: str = "default"):
        """Alias of :meth:`stream` that documents intent after a crash
        or restart: resuming IS starting again — the durable ledger
        (``streaming.stateDir``) carries the exactly-once position and
        the pinned checkpoints carry the aggregate state, so the next
        tick continues from the last COMMITTED batch.  Check
        ``handle.resumed`` to confirm a ledger was found."""
        return self.stream(plan, trigger=trigger, priority=priority,
                           tenant=tenant)

    def shutdown_scheduler(self) -> None:
        """Stop the scheduler (cancelling queued + running queries) and
        join its threads; a later submit() starts a fresh one."""
        with self._scheduler_lock:
            sched, self._scheduler = self._scheduler, None
        if sched is not None:
            sched.shutdown()

    def sweep_storage(self) -> Dict[str, int]:
        """Durable-storage hygiene (shared by :meth:`close` and the
        scheduler's shutdown): remove orphaned spill files a crashed
        process left behind, crash-orphaned checkpoint temp files,
        checkpoint query dirs past ``recovery.ttlSeconds`` and — over
        ``recovery.maxBytes`` — the least-recently-touched checkpoint
        dirs.  Never raises; returns removal counts."""
        out: Dict[str, int] = {}
        try:
            if self.spill_framework is not None:
                out["removedSpillOrphans"] = \
                    self.spill_framework.sweep_orphans()
        except Exception:  # noqa: BLE001 - hygiene must not mask exit
            log.warning("spill orphan sweep failed", exc_info=True)
        try:
            from .recovery.manager import sweep_recovery_dir

            out.update(sweep_recovery_dir(self.conf))
        except Exception:  # noqa: BLE001
            log.warning("recovery sweep failed", exc_info=True)
        return out

    def close(self) -> None:
        """End-of-life hygiene: stop the scheduler (joining its
        threads) and :meth:`sweep_storage`.  Idempotent — the session
        remains usable for further queries afterwards."""
        self.shutdown_scheduler()
        self.sweep_storage()

    def execute_columnar(self, plan: L.LogicalPlan):
        """Zero-copy device export: returns the list of DeviceBatches of
        the final columnar stage (reference analogue: ColumnarRdd /
        InternalColumnarRddConverter, requires exportColumnarRdd)."""
        if not self.conf.get(EXPORT_COLUMNAR_RDD):
            raise RuntimeError(
                "set spark.rapids.tpu.sql.exportColumnarRdd=true")
        from .ml.columnar_export import export_device_batches

        return export_device_batches(self, plan)

    def explain(self, plan: L.LogicalPlan, mode: str = "ALL") -> str:
        phys = Planner(self.conf).plan(plan)
        if not self.conf.is_sql_enabled:
            return phys.tree_string()
        from .plan.overrides import TpuOverrides

        return TpuOverrides(self.conf.set(
            "spark.rapids.tpu.sql.explain", mode)).explain(phys)

    # ----- telemetry surface ------------------------------------------------
    @property
    def profiles(self):
        """Completed query profiles, newest last (bounded by
        ``telemetry.maxQueryProfiles``)."""
        return list(self._profiles)

    def profile_report(self, top_n: int = 5) -> str:
        """EXPLAIN-ANALYZE report of the most recent execution: the
        physical plan annotated with per-exec metrics, the span tree, a
        top-N hot-operator summary and the event digest.  Empty string
        unless ``telemetry.enabled`` was on for the query."""
        if self.last_profile is None:
            return ""
        return self.last_profile.render(top_n=top_n)

    def export_metrics(self) -> Dict:
        """One combined metrics dict for the exporters: the last
        query's snapshot plus the scheduler's ``qos_metrics()`` (when a
        scheduler exists — never created just to export) and every live
        stream's ``streaming.*`` progress."""
        merged = dict(self.last_metrics)
        with self._scheduler_lock:
            sched = self._scheduler
            serving = self._serving
        if sched is not None:
            merged.update(sched.qos_metrics())
        if serving is not None:
            merged.update(serving.metrics())
        for h in self.active_streams():
            merged.update(h.progress())
        return merged

    def metrics_text(self) -> str:
        """Prometheus text exposition of :meth:`export_metrics` plus
        the latency histograms (scheduler queue-wait, per-tenant query
        latency, streaming batch latency) as proper ``# TYPE
        histogram`` families — the process scrape surface."""
        from .telemetry.export import prometheus_text

        with self._scheduler_lock:
            sched = self._scheduler
        hists = list(sched.histograms()) if sched is not None else []
        for h in self.active_streams():
            hists.append(("stream_batch_latency_ms",
                          {"stream": h.stream_id}, h.latency_hist))
        return prometheus_text(self.export_metrics(), histograms=hists)

    def metrics_json(self) -> str:
        """JSON snapshot of :meth:`export_metrics` (byte-stable for
        identical state — exporter stability is what lets a scraper
        diff two snapshots)."""
        from .telemetry.export import json_snapshot

        return json_snapshot(self.export_metrics())

    # ----- test hooks (reference: ExecutionPlanCaptureCallback) ------------
    def start_capture(self):
        self.capture_plans = True
        self._executed_plans = []

    def captured_plans(self) -> List[PhysicalPlan]:
        return list(self._executed_plans)
