"""Hashed priority queue: O(log n) push/pop, O(1) membership, stable
priority updates via lazy invalidation.

Reference analogue: HashedPriorityQueue.java (the spill queue — 300 LoC
of hand-rolled heap + hash map; Python's heapq + dict gives the same
contract).
"""
from __future__ import annotations

import heapq
import itertools
from typing import Any, Dict, List, Optional, Tuple


class HashedPriorityQueue:
    """Min-heap by (priority, insertion order) with O(1) contains and
    remove/update by key."""

    def __init__(self):
        self._heap: List[Tuple[float, int, Any]] = []
        self._entries: Dict[Any, Tuple[float, int, Any]] = {}
        self._counter = itertools.count()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def push(self, key, priority: float) -> None:
        if key in self._entries:
            self.remove(key)
        entry = (priority, next(self._counter), key)
        self._entries[key] = entry
        heapq.heappush(self._heap, entry)

    def remove(self, key) -> bool:
        return self._entries.pop(key, None) is not None

    def update_priority(self, key, priority: float) -> None:
        self.push(key, priority)

    def peek(self) -> Optional[Any]:
        self._prune()
        return self._heap[0][2] if self._heap else None

    def pop(self) -> Optional[Any]:
        self._prune()
        if not self._heap:
            return None
        _, _, key = heapq.heappop(self._heap)
        del self._entries[key]
        return key

    def priority_of(self, key) -> Optional[float]:
        e = self._entries.get(key)
        return e[0] if e else None

    def _prune(self) -> None:
        # drop heap entries whose key was removed or re-pushed
        while self._heap and self._entries.get(
                self._heap[0][2]) is not self._heap[0]:
            heapq.heappop(self._heap)


class NativeHashedPriorityQueue:
    """Same contract backed by the C++ heap (native/src/srt_native.cc,
    srt_hpq_*) for integer keys — the spill queue's hot path."""

    def __init__(self, lib):
        self._lib = lib
        self._h = lib.srt_hpq_create()
        self._pri: Dict[int, float] = {}  # mirror for priority_of

    def __del__(self):  # pragma: no cover - interpreter teardown timing
        try:
            self._lib.srt_hpq_destroy(self._h)
        except Exception:  # noqa: BLE001
            pass

    def __len__(self) -> int:
        return int(self._lib.srt_hpq_size(self._h))

    def __contains__(self, key) -> bool:
        return bool(self._lib.srt_hpq_contains(self._h, int(key)))

    def push(self, key, priority: float) -> None:
        self._lib.srt_hpq_push(self._h, int(key), float(priority))
        self._pri[int(key)] = float(priority)

    def remove(self, key) -> bool:
        self._pri.pop(int(key), None)
        return bool(self._lib.srt_hpq_remove(self._h, int(key)))

    def update_priority(self, key, priority: float) -> None:
        self.push(key, priority)

    def peek(self) -> Optional[int]:
        k = int(self._lib.srt_hpq_peek(self._h))
        return None if k < 0 else k

    def pop(self) -> Optional[int]:
        k = int(self._lib.srt_hpq_pop(self._h))
        if k < 0:
            return None
        self._pri.pop(k, None)
        return k

    def priority_of(self, key) -> Optional[float]:
        return self._pri.get(int(key))


def make_spill_queue():
    """Native-backed queue when the library is available, else Python
    (keys are integer buffer ids either way)."""
    from ..native import get_lib

    lib = get_lib()
    if lib is not None:
        return NativeHashedPriorityQueue(lib)
    return HashedPriorityQueue()
