"""Host-engine window exec.

Reference analogue: the CPU side of GpuWindowExec — the oracle the device
window exec is compared against.  Per-partition-key segment computation in
numpy."""
from __future__ import annotations

from typing import List

import numpy as np

from .. import types as T
from ..data.column import HostBatch, HostColumn
from ..ops.aggregates import AggregateFunction, Average, Count, Sum
from ..ops.expression import as_host_column
from ..ops.kernels import segment as seg
from ..ops.windowexprs import (
    DenseRank,
    Rank,
    RowNumber,
    WindowExpression,
    WindowFunctionBase,
)
from ..plan.physical import PartitionedData, PhysicalPlan


def _frame_bounds(frame, i, seg_lo, seg_hi):
    lo = seg_lo if frame.lower is None else max(seg_lo, i + frame.lower)
    hi = seg_hi if frame.upper is None else min(seg_hi, i + frame.upper + 1)
    return lo, max(hi, lo)


def compute_window_host(batch: HostBatch,
                        wx: WindowExpression) -> HostColumn:
    n = batch.num_rows
    spec = wx.spec
    part_cols = [as_host_column(e.eval_cpu(batch), n)
                 for e in spec.partition_by]
    order_keys = spec.order_by
    order_cols = [as_host_column(k.expr.eval_cpu(batch), n)
                  for k in order_keys]
    # global order: partition keys asc, then order keys
    all_cols = part_cols + order_cols
    desc = [False] * len(part_cols) + [not k.ascending for k in order_keys]
    nf = [True] * len(part_cols) + [k.nulls_first for k in order_keys]
    order = seg.lexsort_np(all_cols, desc, nf) if all_cols else np.arange(n)
    # segments by partition keys over sorted order
    if part_cols:
        sorted_parts = [c.take(order) for c in part_cols]
        _, seg_ids, seg_starts = _segments_presorted(sorted_parts)
    else:
        seg_ids = np.zeros(n, dtype=np.int64)
        seg_starts = np.asarray([0] if n else [], dtype=np.int64)

    func = wx.func
    frame = spec.resolved_frame()
    out_sorted, validity_sorted = _compute_sorted(
        batch, wx, order, seg_ids, seg_starts, n)
    # scatter back to original row order
    inv = np.empty(n, dtype=np.int64)
    inv[order] = np.arange(n)
    data = out_sorted[inv]
    validity = None if validity_sorted is None else validity_sorted[inv]
    return HostColumn(wx.dtype, data, validity)


def _segments_presorted(sorted_cols):
    n = sorted_cols[0].num_rows
    change = np.zeros(n, dtype=np.bool_)
    if n:
        change[0] = True
    for col in sorted_cols:
        data, is_null = seg._null_key_np(col)
        if n > 1:
            if col.dtype.is_string:
                neq = np.asarray(
                    [False] + [data[i] != data[i - 1]
                               or is_null[i] != is_null[i - 1]
                               for i in range(1, n)])
            else:
                neq = np.zeros(n, dtype=np.bool_)
                neq[1:] = (data[1:] != data[:-1]) | \
                    (is_null[1:] != is_null[:-1])
            change |= neq
    seg_ids = np.cumsum(change) - 1 if n else np.zeros(0, np.int64)
    return None, seg_ids.astype(np.int64), np.nonzero(change)[0]


def _compute_sorted(batch, wx, order, seg_ids, seg_starts, n):
    func = wx.func
    frame = wx.spec.resolved_frame()
    seg_start_of_row = seg_starts[seg_ids] if n else np.zeros(0, np.int64)
    idx = np.arange(n)
    if isinstance(func, RowNumber):
        return (idx - seg_start_of_row + 1).astype(np.int32), None
    if isinstance(func, (Rank, DenseRank)):
        order_cols = [as_host_column(k.expr.eval_cpu(batch), n).take(order)
                      for k in wx.spec.order_by]
        _, okey_ids, _ = _segments_presorted(order_cols) if order_cols \
            else (None, idx.copy(), None)
        # ties share a value; okey change points restart counters
        rank = np.zeros(n, dtype=np.int32)
        dense = np.zeros(n, dtype=np.int32)
        last_seg = -1
        last_okey = -1
        cur_rank = cur_dense = 0
        for i in range(n):
            if seg_ids[i] != last_seg:
                last_seg = seg_ids[i]
                last_okey = okey_ids[i]
                cur_rank = 1
                cur_dense = 1
            elif okey_ids[i] != last_okey:
                last_okey = okey_ids[i]
                cur_rank = i - seg_start_of_row[i] + 1
                cur_dense += 1
            rank[i] = cur_rank
            dense[i] = cur_dense
        return (rank if isinstance(func, Rank) else dense), None
    assert isinstance(func, AggregateFunction)
    child = func.child
    if child is None:
        vals = np.ones(n, dtype=np.int64)
        valid = np.ones(n, dtype=np.bool_)
        vdtype = T.INT64
    else:
        c = as_host_column(child.eval_cpu(batch), n).take(order)
        vals, valid, vdtype = c.data, c.is_valid(), c.dtype
    out_dtype = func.dtype
    if out_dtype.id is T.TypeId.STRING:
        out = np.empty(n, dtype=object)
    else:
        out = np.zeros(n, dtype=out_dtype.np_dtype)
    out_valid = np.ones(n, dtype=np.bool_)
    # segment extents
    n_seg = len(seg_starts)
    seg_ends = np.append(seg_starts[1:], n)
    for i in range(n):
        lo, hi = _frame_bounds(frame, i, seg_start_of_row[i],
                               seg_ends[seg_ids[i]])
        v = vals[lo:hi]
        ok = valid[lo:hi]
        vv = v[ok] if vdtype.id is T.TypeId.STRING else v[ok]
        if isinstance(func, Count):
            out[i] = len(vv)
        elif func.name in ("first", "last") and not getattr(
                func, "ignore_nulls", True):
            # Spark default (ignoreNulls=false): the frame-edge ROW's
            # value, null included
            if len(v) == 0:
                out_valid[i] = False
            else:
                j = 0 if func.name == "first" else -1
                if ok[j]:
                    out[i] = v[j]
                else:
                    out_valid[i] = False
        elif len(vv) == 0:
            out_valid[i] = False
        elif isinstance(func, Sum):
            out[i] = vv.sum()
        elif isinstance(func, Average):
            out[i] = float(np.asarray(vv, dtype=np.float64).sum()) / len(vv)
        elif func.name == "min":
            out[i] = vv.min() if vdtype.id is not T.TypeId.STRING \
                else min(vv)
        elif func.name == "max":
            out[i] = vv.max() if vdtype.id is not T.TypeId.STRING \
                else max(vv)
        elif func.name == "first":
            out[i] = vv[0]
        elif func.name == "last":
            out[i] = vv[-1]
        else:
            raise NotImplementedError(func.name)
    return out, (None if out_valid.all() else out_valid)


class WindowExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan,
                 window_exprs: List[WindowExpression], names: List[str]):
        super().__init__([child])
        self.window_exprs = [w.bind(child.schema) for w in window_exprs]
        self.names = names
        fields = list(child.schema.fields)
        for nme, w in zip(names, self.window_exprs):
            fields.append(T.Field(nme, w.dtype, True))
        self._schema = T.Schema(fields)

    @property
    def schema(self):
        return self._schema

    def execute(self, ctx):
        child = self.children[0].execute(ctx)

        def make(pid):
            def it():
                batches = list(child.iterator(pid))
                if not batches:
                    return
                batch = HostBatch.concat(batches) if len(batches) > 1 \
                    else batches[0]
                cols = list(batch.columns)
                for w in self.window_exprs:
                    cols.append(compute_window_host(batch, w))
                yield HostBatch(self._schema, cols)

            return it

        return PartitionedData([make(i) for i in range(child.n_partitions)])

    def describe(self):
        return f"Window[{', '.join(w.sql() for w in self.window_exprs)}]"
