from . import functions  # noqa: F401
