"""TPCx-BB-like queries 1-30 as DataFrame code.

Reference analogue: ``integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala``
(Q1Like..Q30Like at :785-2065) — the ETL/SQL shape of each TPCx-BB query
against the retail schema, expressed through this framework's DataFrame
API.  As in the reference's "Like" suite, the ML/NLP stages of the
original benchmark (clustering, classification, sentiment, NER) are
reduced to their data-preparation SQL, and UDF-based sessionization is
rewritten as join/window plans; magnitude thresholds are scaled for tiny
generated data.

Usage:
    tables = tpcxbb_datagen.dataframes(session, sf=0.001)
    rows = QUERIES[5](tables).collect()
"""
from __future__ import annotations

from ..ops.windowexprs import over, row_number, window
from ..plan import functions as F

col = F.col
lit = F.lit


def _count_distinct(df, group_cols, distinct_col, out_name):
    d = df.select(*(group_cols + [distinct_col])).distinct()
    return d.group_by(*group_cols).agg(F.count(distinct_col).alias(out_name))


def q1(t):
    """Items frequently sold together in the same store basket
    (self-join on ticket), per category pair count."""
    ss = (t["store_sales"].select("ss_ticket_number", "ss_item_sk")
          .join(t["item"].select("i_item_sk",
                                 col("i_category_id").alias("cat_a")),
                on=(["ss_item_sk"], ["i_item_sk"]), how="inner")
          .select(col("ss_ticket_number").alias("tk_a"),
                  col("ss_item_sk").alias("item_a"), "cat_a"))
    ss2 = ss.select(col("tk_a").alias("tk_b"),
                    col("item_a").alias("item_b"),
                    col("cat_a").alias("cat_b"))
    pairs = (ss.join(ss2, on=(["tk_a"], ["tk_b"]), how="inner")
             .filter(col("item_a") < col("item_b")))
    return (pairs.group_by("item_a", "item_b")
            .agg(F.count("*").alias("cnt"))
            .filter(col("cnt") >= lit(2))
            .sort(col("cnt").desc(), col("item_a").asc(),
                  col("item_b").asc())
            .limit(100))


def q2(t):
    """Items clicked in the same session (user+day) as a target item."""
    target = 1
    wcs = t["web_clickstreams"].select("wcs_user_sk", "wcs_click_date_sk",
                                       "wcs_item_sk")
    with_target = (wcs.filter(col("wcs_item_sk") == lit(target))
                   .select(col("wcs_user_sk").alias("u"),
                           col("wcs_click_date_sk").alias("d"))
                   .distinct())
    return (wcs.join(with_target,
                     on=(["wcs_user_sk", "wcs_click_date_sk"], ["u", "d"]),
                     how="semi")
            .filter(col("wcs_item_sk") != lit(target))
            .group_by("wcs_item_sk")
            .agg(F.count("*").alias("cnt"))
            .sort(col("cnt").desc(), col("wcs_item_sk").asc())
            .limit(30))


def q3(t):
    """Views of an item category by users who later purchased in it."""
    buyers = (t["web_sales"]
              .join(t["item"].select("i_item_sk", "i_category_id"),
                    on=(["ws_item_sk"], ["i_item_sk"]), how="inner")
              .select(col("ws_bill_customer_sk").alias("bu"),
                      col("i_category_id").alias("bcat"))
              .distinct())
    views = (t["web_clickstreams"]
             .join(t["item"].select(col("i_item_sk").alias("vi"),
                                    "i_category_id"),
                   on=(["wcs_item_sk"], ["vi"]), how="inner"))
    return (views.join(buyers,
                       on=(["wcs_user_sk", "i_category_id"],
                           ["bu", "bcat"]), how="semi")
            .group_by("i_category_id")
            .agg(F.count("*").alias("views"))
            .sort("i_category_id"))


def q4(t):
    """Sessions with clicks but no converting click (cart abandonment)."""
    per_session = (t["web_clickstreams"]
                   .group_by(col("wcs_user_sk").alias("u"),
                             col("wcs_click_date_sk").alias("d"))
                   .agg(F.count("*").alias("clicks"),
                        F.max("wcs_sales_sk").alias("max_sale")))
    return (per_session.filter(col("max_sale") == lit(0))
            .agg(F.count("*").alias("abandoned_sessions"),
                 F.avg("clicks").alias("avg_clicks")))


def q5(t):
    """Per-user category-click features vs college education (the
    logistic-regression prep)."""
    clicks = (t["web_clickstreams"]
              .join(t["item"].select("i_item_sk", "i_category_id"),
                    on=(["wcs_item_sk"], ["i_item_sk"]), how="inner"))
    feat = (clicks.group_by(col("wcs_user_sk").alias("u"))
            .agg(F.count("*").alias("total_clicks"),
                 F.sum(F.if_(col("i_category_id") == lit(0),
                             lit(1), lit(0))).alias("cat0_clicks")))
    demo = (t["customer"]
            .join(t["customer_demographics"],
                  on=(["c_current_cdemo_sk"], ["cd_demo_sk"]), how="inner")
            .select(col("c_customer_sk").alias("ck"),
                    col("cd_education_status").alias("edu")))
    return (feat.join(demo, on=(["u"], ["ck"]), how="inner")
            .with_column("college",
                         F.if_(col("edu").isin("College",
                                               "Advanced Degree"),
                               lit(1), lit(0)))
            .group_by("college")
            .agg(F.count("*").alias("users"),
                 F.avg("total_clicks").alias("avg_clicks"),
                 F.avg("cat0_clicks").alias("avg_cat0"))
            .sort("college"))


def q6(t):
    """Customers whose web spend grew year-over-year (single-channel
    reduction of the original's web-vs-store comparison)."""
    dd = t["date_dim"].select("d_date_sk", "d_year")
    ws = (t["web_sales"].join(dd, on=(["ws_sold_date_sk"], ["d_date_sk"]),
                              how="inner")
          .filter(col("d_year").isin(2001, 2002))
          .group_by(col("ws_bill_customer_sk").alias("c"),
                    col("d_year").alias("y"))
          .agg(F.sum("ws_net_paid").alias("web_paid")))
    w1 = (ws.filter(col("y") == lit(2001))
          .select(col("c").alias("c1"), col("web_paid").alias("web_2001")))
    w2 = (ws.filter(col("y") == lit(2002))
          .select(col("c").alias("c2"), col("web_paid").alias("web_2002")))
    return (w1.join(w2, on=(["c1"], ["c2"]), how="inner")
            .filter(col("web_2002") > col("web_2001"))
            .select("c1", "web_2001", "web_2002")
            .sort(col("c1").asc())
            .limit(100))


def q7(t):
    """States where >= K customers bought items priced over 1.2x their
    category's average price."""
    avg_cat = (t["item"].group_by(col("i_category_id").alias("cat"))
               .agg(F.avg("i_current_price").alias("avg_price")))
    pricey = (t["item"]
              .join(avg_cat, on=(["i_category_id"], ["cat"]), how="inner")
              .filter(col("i_current_price") > lit(1.2) * col("avg_price"))
              .select(col("i_item_sk").alias("pi")))
    buyers = (t["store_sales"]
              .join(pricey, on=(["ss_item_sk"], ["pi"]), how="semi")
              .select("ss_customer_sk").distinct())
    located = (buyers
               .join(t["customer"].select("c_customer_sk",
                                          "c_current_addr_sk"),
                     on=(["ss_customer_sk"], ["c_customer_sk"]),
                     how="inner")
               .join(t["customer_address"].select("ca_address_sk",
                                                  "ca_state"),
                     on=(["c_current_addr_sk"], ["ca_address_sk"]),
                     how="inner"))
    return (located.group_by("ca_state")
            .agg(F.count("*").alias("cnt"))
            .filter(col("cnt") >= lit(2))
            .sort(col("cnt").desc(), col("ca_state").asc())
            .limit(10))


def q8(t):
    """Web sales by users who previously wrote/read a review."""
    reviewers = t["product_reviews"].select(
        col("pr_user_sk").alias("ru")).distinct()
    ws = t["web_sales"]
    with_rev = ws.join(reviewers, on=(["ws_bill_customer_sk"], ["ru"]),
                       how="semi")
    return (with_rev.agg(F.sum("ws_net_paid").alias("reviewed_sales"),
                         F.count("*").alias("n_rows")))


def q9(t):
    """Store sales aggregated under demographic filter combinations."""
    j = (t["store_sales"]
         .join(t["customer_demographics"],
               on=(["ss_cdemo_sk"], ["cd_demo_sk"]), how="inner"))
    m = ((col("cd_gender") == lit("M"))
         & (col("cd_marital_status") == lit("M"))
         & (col("cd_education_status") == lit("College")))
    f_ = ((col("cd_gender") == lit("F"))
          & (col("cd_marital_status") == lit("S")))
    return (j.filter(m | f_)
            .agg(F.sum("ss_quantity").alias("total_quantity"),
                 F.count("*").alias("n")))


def q10(t):
    """Sentiment-ish: reviews containing positive words per item."""
    pos = (t["product_reviews"]
           .filter(col("pr_review_content").contains("great")
                   | col("pr_review_content").contains("excellent")
                   | col("pr_review_content").contains("love")))
    return (pos.group_by("pr_item_sk")
            .agg(F.count("*").alias("pos_reviews"),
                 F.avg("pr_review_rating").alias("avg_rating"))
            .filter(col("pos_reviews") >= lit(2))
            .sort(col("pos_reviews").desc(), col("pr_item_sk").asc())
            .limit(50))


def q11(t):
    """Per-item review stats joined with web sales (rating/sales corr
    prep)."""
    ratings = (t["product_reviews"]
               .group_by(col("pr_item_sk").alias("ri"))
               .agg(F.avg("pr_review_rating").alias("avg_rating"),
                    F.count("*").alias("n_reviews")))
    sales = (t["web_sales"].group_by(col("ws_item_sk").alias("si"))
             .agg(F.sum("ws_net_paid").alias("sales")))
    return (ratings.join(sales, on=(["ri"], ["si"]), how="inner")
            .select("ri", "avg_rating", "n_reviews", "sales")
            .sort(col("sales").desc(), col("ri").asc())
            .limit(50))


def q12(t):
    """Users who clicked an item category and bought in-store in that
    category within 60 days."""
    clicks = (t["web_clickstreams"]
              .join(t["item"].select("i_item_sk", "i_category_id"),
                    on=(["wcs_item_sk"], ["i_item_sk"]), how="inner")
              .select(col("wcs_user_sk").alias("u"),
                      col("i_category_id").alias("ccat"),
                      col("wcs_click_date_sk").alias("cdate")))
    buys = (t["store_sales"]
            .join(t["item"].select(col("i_item_sk").alias("bi"),
                                   "i_category_id"),
                  on=(["ss_item_sk"], ["bi"]), how="inner")
            .select(col("ss_customer_sk").alias("b_u"),
                    col("i_category_id").alias("bcat"),
                    col("ss_sold_date_sk").alias("bdate")))
    j = (clicks.join(buys, on=(["u", "ccat"], ["b_u", "bcat"]),
                     how="inner")
         .filter((col("bdate") >= col("cdate"))
                 & (col("bdate") <= col("cdate") + lit(60))))
    return _count_distinct(j, ["ccat"], "u", "converting_users") \
        .sort("ccat")


def q13(t):
    """Customer year-over-year web sales ratio."""
    dd = t["date_dim"].select("d_date_sk", "d_year")
    per = (t["web_sales"]
           .join(dd, on=(["ws_sold_date_sk"], ["d_date_sk"]), how="inner")
           .filter(col("d_year").isin(2001, 2002))
           .group_by(col("ws_bill_customer_sk").alias("c"))
           .agg(F.sum(F.if_(col("d_year") == lit(2001),
                            col("ws_net_paid"), lit(0.0))).alias("s1"),
                F.sum(F.if_(col("d_year") == lit(2002),
                            col("ws_net_paid"), lit(0.0))).alias("s2")))
    return (per.filter(col("s1") > lit(0.0))
            .select("c", "s1", "s2", (col("s2") / col("s1")).alias("ratio"))
            .sort(col("ratio").desc(), col("c").asc())
            .limit(100))


def q14(t):
    """Morning vs evening web click traffic ratio."""
    wcs = t["web_clickstreams"]
    morning = F.if_((col("wcs_click_time_sk") >= lit(7 * 3600))
                    & (col("wcs_click_time_sk") < lit(9 * 3600)),
                    lit(1), lit(0))
    evening = F.if_((col("wcs_click_time_sk") >= lit(19 * 3600))
                    & (col("wcs_click_time_sk") < lit(21 * 3600)),
                    lit(1), lit(0))
    return (wcs.agg(F.sum(morning).alias("am"), F.sum(evening).alias("pm"))
            .select((col("am") * lit(1.0)
                     / F.greatest(col("pm"), lit(1))).alias("am_pm_ratio")))


def q15(t):
    """Store category monthly sales slope sign (declining categories):
    first vs second half-year totals."""
    dd = t["date_dim"].select("d_date_sk", "d_year", "d_moy")
    j = (t["store_sales"]
         .join(dd, on=(["ss_sold_date_sk"], ["d_date_sk"]), how="inner")
         .filter(col("d_year") == lit(2002))
         .join(t["item"].select("i_item_sk", "i_category_id"),
               on=(["ss_item_sk"], ["i_item_sk"]), how="inner"))
    per = (j.group_by(col("i_category_id").alias("cat"))
           .agg(F.sum(F.if_(col("d_moy") <= lit(6),
                            col("ss_net_paid"), lit(0.0))).alias("h1"),
                F.sum(F.if_(col("d_moy") > lit(6),
                            col("ss_net_paid"), lit(0.0))).alias("h2")))
    return (per.filter(col("h2") < col("h1"))
            .select("cat", "h1", "h2")
            .sort("cat"))


def q16(t):
    """Web sales net of returns around a pivot date."""
    pivot = 600
    ws = (t["web_sales"]
          .filter((col("ws_sold_date_sk") >= lit(pivot - 30))
                  & (col("ws_sold_date_sk") <= lit(pivot + 30))))
    wr = t["web_returns"].select(
        col("wr_order_number").alias("ro"),
        col("wr_item_sk").alias("ri"),
        col("wr_return_quantity").alias("rq"))
    j = ws.join(wr, on=(["ws_order_number", "ws_item_sk"], ["ro", "ri"]),
                how="left")
    net = (col("ws_quantity") - F.coalesce(col("rq"), lit(0)))
    return (j.agg(F.sum(col("ws_quantity")).alias("sold"),
                  F.sum(net).alias("net_of_returns")))


def q17(t):
    """In-category share of a brand's store sales (promo-ratio shape)."""
    j = (t["store_sales"]
         .join(t["item"].select("i_item_sk", "i_category_id", "i_brand_id"),
               on=(["ss_item_sk"], ["i_item_sk"]), how="inner"))
    per = (j.group_by(col("i_category_id").alias("cat"))
           .agg(F.sum(F.if_(col("i_brand_id") <= lit(10),
                            col("ss_net_paid"), lit(0.0)))
                .alias("brand_sales"),
                F.sum("ss_net_paid").alias("all_sales")))
    return (per.select("cat", (lit(100.0) * col("brand_sales")
                               / col("all_sales")).alias("brand_pct"))
            .sort("cat"))


def q18(t):
    """Stores with declining sales and their review exposure."""
    dd = t["date_dim"].select("d_date_sk", "d_moy", "d_year")
    per_store = (t["store_sales"]
                 .join(dd, on=(["ss_sold_date_sk"], ["d_date_sk"]),
                       how="inner")
                 .filter(col("d_year") == lit(2002))
                 .group_by(col("ss_store_sk").alias("st"))
                 .agg(F.sum(F.if_(col("d_moy") <= lit(6),
                                  col("ss_net_paid"), lit(0.0)))
                      .alias("h1"),
                      F.sum(F.if_(col("d_moy") > lit(6),
                                  col("ss_net_paid"), lit(0.0)))
                      .alias("h2")))
    declining = per_store.filter(col("h2") < col("h1"))
    return (declining.join(t["store"].select("s_store_sk", "s_store_name"),
                           on=(["st"], ["s_store_sk"]), how="inner")
            .select("s_store_name", "h1", "h2")
            .sort("s_store_name"))


def q19(t):
    """Items with high return rates in both channels."""
    sr = (t["store_returns"].group_by(col("sr_item_sk").alias("i1"))
          .agg(F.sum("sr_return_quantity").alias("store_returned")))
    wr = (t["web_returns"].group_by(col("wr_item_sk").alias("i2"))
          .agg(F.sum("wr_return_quantity").alias("web_returned")))
    return (sr.join(wr, on=(["i1"], ["i2"]), how="inner")
            .select(col("i1").alias("item"), "store_returned",
                    "web_returned")
            .sort(col("store_returned").desc(), col("item").asc())
            .limit(50))


def q20(t):
    """Customer return-behavior features (segmentation prep)."""
    sales = (t["store_sales"].group_by(col("ss_customer_sk").alias("c"))
             .agg(F.count("*").alias("orders"),
                  F.sum("ss_net_paid").alias("spend")))
    rets = (t["store_returns"].group_by(col("sr_customer_sk").alias("rc"))
            .agg(F.count("*").alias("returns")))
    j = sales.join(rets, on=(["c"], ["rc"]), how="left")
    return (j.with_column("returns", F.coalesce(col("returns"), lit(0)))
            .with_column("return_ratio",
                         col("returns") * lit(1.0)
                         / F.greatest(col("orders"), lit(1)))
            .filter(col("return_ratio") > lit(0.2))
            .select("c", "orders", "returns", "return_ratio")
            .sort(col("return_ratio").desc(), col("c").asc())
            .limit(100))


def q21(t):
    """Items returned and re-purchased by the same customer within 6
    months (180 day-sks)."""
    sr = t["store_returns"].select(
        col("sr_customer_sk").alias("rc"), col("sr_item_sk").alias("ri"),
        col("sr_returned_date_sk").alias("rd"))
    again = (sr.join(t["store_sales"].select("ss_customer_sk",
                                             "ss_item_sk",
                                             "ss_sold_date_sk"),
                     on=(["rc", "ri"], ["ss_customer_sk", "ss_item_sk"]),
                     how="inner")
             .filter((col("ss_sold_date_sk") > col("rd"))
                     & (col("ss_sold_date_sk") <= col("rd") + lit(180))))
    return _count_distinct(again, ["ri"], "rc", "repurchasers") \
        .sort(col("repurchasers").desc(), col("ri").asc()).limit(50)


def q22(t):
    """Inventory on hand around a pivot date per warehouse."""
    pivot = 900
    inv = t["inventory"].filter(
        (col("inv_date_sk") >= lit(pivot - 30))
        & (col("inv_date_sk") <= lit(pivot + 30)))
    per = (inv.group_by("inv_warehouse_sk")
           .agg(F.sum(F.if_(col("inv_date_sk") < lit(pivot),
                            col("inv_quantity_on_hand"), lit(0)))
                .alias("before"),
                F.sum(F.if_(col("inv_date_sk") >= lit(pivot),
                            col("inv_quantity_on_hand"), lit(0)))
                .alias("after")))
    return (per.join(t["warehouse"].select("w_warehouse_sk",
                                           "w_warehouse_name"),
                     on=(["inv_warehouse_sk"], ["w_warehouse_sk"]),
                     how="inner")
            .select("w_warehouse_name", "before", "after")
            .sort("w_warehouse_name"))


def q23(t):
    """Items whose inventory varies strongly across snapshots
    (coefficient-of-variation shape, via mean/meansq aggregates)."""
    per = (t["inventory"]
           .group_by(col("inv_item_sk").alias("i"))
           .agg(F.avg("inv_quantity_on_hand").alias("mean_q"),
                F.avg(col("inv_quantity_on_hand")
                      * col("inv_quantity_on_hand")).alias("meansq"),
                F.count("*").alias("n")))
    var = col("meansq") - col("mean_q") * col("mean_q")
    return (per.filter(col("mean_q") > lit(0.0))
            .with_column("cv", F.sqrt(F.greatest(var, lit(0.0)))
                         / col("mean_q"))
            .filter(col("cv") > lit(0.4))
            .select("i", "mean_q", "cv")
            .sort(col("cv").desc(), col("i").asc())
            .limit(100))


def q24(t):
    """Sales before/after an item price threshold (elasticity shape)."""
    cheap = t["item"].filter(col("i_current_price") < lit(50.0)) \
        .select(col("i_item_sk").alias("ci"))
    j = t["store_sales"].join(cheap, on=(["ss_item_sk"], ["ci"]),
                              how="semi")
    k = t["store_sales"].join(cheap, on=(["ss_item_sk"], ["ci"]),
                              how="anti")
    a = j.agg(F.sum("ss_quantity").alias("q")).select(
        lit("cheap").alias("bucket"), col("q"))
    b = k.agg(F.sum("ss_quantity").alias("q")).select(
        lit("pricey").alias("bucket"), col("q"))
    return a.union(b).sort("bucket")


def q25(t):
    """Customer RFM features (recency / frequency / monetary)."""
    per = (t["store_sales"]
           .group_by(col("ss_customer_sk").alias("c"))
           .agg(F.max("ss_sold_date_sk").alias("last_day"),
                F.count("*").alias("frequency"),
                F.sum("ss_net_paid").alias("monetary")))
    return (per.with_column("recent",
                            F.if_(col("last_day") >= lit(1460),
                                  lit(1), lit(0)))
            .filter(col("frequency") >= lit(2))
            .select("c", "recent", "frequency", "monetary")
            .sort(col("monetary").desc(), col("c").asc())
            .limit(100))


def q26(t):
    """Per-customer category-spend vector (clustering prep)."""
    j = (t["store_sales"]
         .join(t["item"].select("i_item_sk", "i_category_id"),
               on=(["ss_item_sk"], ["i_item_sk"]), how="inner"))
    catcol = [F.sum(F.if_(col("i_category_id") == lit(c),
                          col("ss_net_paid"), lit(0.0))).alias(f"cat{c}")
              for c in range(5)]
    return (j.group_by(col("ss_customer_sk").alias("c"))
            .agg(F.count("*").alias("n"), *catcol)
            .filter(col("n") >= lit(3))
            .sort(col("n").desc(), col("c").asc())
            .limit(100))


def q27(t):
    """Reviews mentioning a competitor-ish keyword per item (NER
    reduction)."""
    hits = t["product_reviews"].filter(
        col("pr_review_content").contains("refund")
        | col("pr_review_content").contains("broken"))
    return (hits.group_by("pr_item_sk")
            .agg(F.count("*").alias("mentions"))
            .sort(col("mentions").desc(), col("pr_item_sk").asc())
            .limit(50))


def q28(t):
    """Rating-bucket counts per category (naive-bayes prep)."""
    j = (t["product_reviews"]
         .join(t["item"].select("i_item_sk", "i_category_id"),
               on=(["pr_item_sk"], ["i_item_sk"]), how="inner"))
    return (j.with_column("sentiment",
                          F.when(col("pr_review_rating") >= lit(4),
                                 lit("pos"))
                          .when(col("pr_review_rating") == lit(3),
                                lit("neutral"))
                          .otherwise(lit("neg")))
            .group_by("i_category_id", "sentiment")
            .agg(F.count("*").alias("cnt"))
            .sort("i_category_id", "sentiment"))


def q29(t):
    """Category pairs sold together in the same web order."""
    ws = (t["web_sales"].select("ws_order_number", "ws_item_sk")
          .join(t["item"].select("i_item_sk", "i_category_id"),
                on=(["ws_item_sk"], ["i_item_sk"]), how="inner")
          .select(col("ws_order_number").alias("o"),
                  col("i_category_id").alias("cat_a"))
          .distinct())
    ws2 = ws.select(col("o").alias("o2"), col("cat_a").alias("cat_b"))
    pairs = (ws.join(ws2, on=(["o"], ["o2"]), how="inner")
             .filter(col("cat_a") < col("cat_b")))
    return (pairs.group_by("cat_a", "cat_b")
            .agg(F.count("*").alias("cnt"))
            .sort(col("cnt").desc(), col("cat_a").asc(),
                  col("cat_b").asc())
            .limit(50))


def q30(t):
    """Category pairs viewed in the same session, ranked per category by
    affinity (windowed top-N)."""
    v = (t["web_clickstreams"]
         .join(t["item"].select("i_item_sk", "i_category_id"),
               on=(["wcs_item_sk"], ["i_item_sk"]), how="inner")
         .select(col("wcs_user_sk").alias("u"),
                 col("wcs_click_date_sk").alias("d"),
                 col("i_category_id").alias("cat_a"))
         .distinct())
    v2 = v.select(col("u").alias("u2"), col("d").alias("d2"),
                  col("cat_a").alias("cat_b"))
    pairs = (v.join(v2, on=(["u", "d"], ["u2", "d2"]), how="inner")
             .filter(col("cat_a") != col("cat_b"))
             .group_by("cat_a", "cat_b")
             .agg(F.count("*").alias("cnt")))
    ranked = pairs.with_window(
        "rn", over(row_number(),
                   window().partition_by("cat_a")
                   .order_by(col("cnt").desc(), col("cat_b").asc())))
    return (ranked.filter(col("rn") <= lit(3))
            .select("cat_a", "cat_b", "cnt", "rn")
            .sort("cat_a", "rn"))


QUERIES = {i: fn for i, fn in enumerate(
    [q1, q2, q3, q4, q5, q6, q7, q8, q9, q10, q11, q12, q13, q14, q15,
     q16, q17, q18, q19, q20, q21, q22, q23, q24, q25, q26, q27, q28,
     q29, q30], start=1)}
