"""Out-of-core operator tests: partitions larger than the batch-size goal
flow through aggregate/sort/join as multiple batches — the chunked
concat+merge aggregation (reference: aggregate.scala:240-335), the k-way
external tile-merge sort, and the grace-bucketed join — with operator
state registered in the spill catalog so memory pressure can evict it.

These close the "single-batch cliff" SURVEY §5 warns about.
"""
import numpy as np
import pytest

from spark_rapids_tpu import f
from spark_rapids_tpu.memory.spill import SpillFramework
from spark_rapids_tpu.testing import datagen as dg
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect,
)

# force many small batches: host batches split at upload, coalesce target
# tiny so heavy operators see multi-batch partitions
SMALL = {
    "spark.rapids.tpu.sql.reader.batchSizeRows": 256,
    "spark.rapids.tpu.sql.batchSizeBytes": 16 * 1024,
    "spark.rapids.tpu.sql.bucketMinRows": 64,
}


@pytest.fixture(autouse=True)
def fresh_spill_framework():
    SpillFramework.reset()
    yield SpillFramework.get()
    SpillFramework.reset()


def _data(n=4000, seed=0):
    # bounded floats: chunked partial sums re-order float addition (the
    # reference's documented variableFloatAgg incompatibility), so ±max /
    # ±inf specials would make sums order-dependent by design
    return dg.gen_batch({
        "k": dg.IntGen(dg.T.INT32, min_val=-20, max_val=20),
        "v": dg.IntGen(dg.T.INT64, min_val=-1000, max_val=1000),
        "x": dg.FloatGen(dg.T.FLOAT64, special_weight=0.0),
        "s": dg.StringGen(max_len=8),
    }, n, seed)


# --------------------------------------------------------------------------
# chunked aggregation
# --------------------------------------------------------------------------
def test_chunked_groupby_matches_oracle(fresh_spill_framework):
    fw = fresh_spill_framework
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.group_by("k").agg(
            f.sum(df["v"]).alias("sv"),
            f.count("*").alias("c"),
            f.min(df["x"]).alias("mn"),
            f.max(df["v"]).alias("mx"),
            f.avg(df["x"]).alias("av"),
        ), _data(), ignore_order=True, conf=SMALL)
    # the running merge registered state with the spill catalog
    assert fw.catalog._next_id > 0


def test_chunked_groupby_strings():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.group_by("k").agg(
            f.min(df["s"]).alias("mn"),
            f.max(df["s"]).alias("mx"),
            f.count(df["s"]).alias("c"),
        ), _data(3000, 7), ignore_order=True, conf=SMALL)


def test_chunked_final_aggregate_high_cardinality():
    """Near-unique keys make the partial outputs as big as the input,
    so the FINAL aggregate's partitions arrive as multiple batches and
    the chunked merge must finalize (regression: the final-mode merge
    kernel referenced an undefined ``emit`` and NameError'd — no
    low-cardinality test ever reached it)."""
    data = dg.gen_batch({
        "k": dg.IntGen(dg.T.INT64, min_val=0, max_val=1_000_000),
        "v": dg.IntGen(dg.T.INT64, min_val=-1000, max_val=1000),
    }, 4000, 11)
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.group_by("k").agg(
            f.sum(df["v"]).alias("sv"), f.count("*").alias("c")),
        data, ignore_order=True, conf=SMALL)


def test_chunked_global_agg():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.agg(f.sum(df["v"]).alias("sv"),
                          f.count("*").alias("c"),
                          f.avg(df["x"]).alias("av")),
        _data(3000, 3), conf=SMALL)


# --------------------------------------------------------------------------
# external sort
# --------------------------------------------------------------------------
def test_external_sort_matches_oracle():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["v"], df["k"], df["x"], df["s"]),
        _data(3000, 11), conf=SMALL)


def test_external_sort_desc_nulls():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["v"].desc().nulls_first_(), df["k"],
                           df["x"], df["s"]),
        _data(2500, 13), conf=SMALL)


def test_external_sort_strings():
    assert_tpu_and_cpu_are_equal_collect(
        lambda df: df.sort(df["s"], df["v"], df["k"], df["x"]),
        _data(2000, 17), conf=SMALL)


# --------------------------------------------------------------------------
# grace join
# --------------------------------------------------------------------------
@pytest.mark.parametrize("how", ["inner", "left", "full", "semi", "anti"])
def test_grace_join_matches_oracle(how):
    rng = np.random.RandomState(19)
    n_l, n_r = 3000, 2000
    lk = rng.randint(0, 40, n_l).tolist()
    rk = rng.randint(0, 40, n_r).tolist()
    left = {"k": lk, "a": list(range(n_l))}
    right_rows = {"k": rk, "b": [float(i) for i in range(n_r)]}

    import spark_rapids_tpu as srt

    def build(sess):
        l = sess.create_dataframe(left)
        r = sess.create_dataframe(right_rows)
        return l.join(r, on="k", how=how)

    conf = dict(SMALL)
    conf["spark.rapids.tpu.sql.broadcastSizeThreshold"] = 0  # force shuffled
    tpu = srt.Session(dict(conf))
    cpu = srt.Session(dict(conf), tpu_enabled=False)
    got = sorted(map(repr, build(tpu).collect()))
    want = sorted(map(repr, build(cpu).collect()))
    assert got == want


def test_grace_join_recurses_past_bucket_cap(monkeypatch):
    """A partition pair hundreds of times the batch target must recurse
    into sub-buckets (the m<64 cap used to overflow instead — VERDICT
    r3 Weak #7).  Correctness vs the oracle plus evidence the recursion
    actually engaged."""
    from spark_rapids_tpu.exec.joins import TpuHashJoinExec

    levels = []
    orig = TpuHashJoinExec._join_grace

    def spy(self, l, r, total, target, level=0, *args, **kwargs):
        levels.append(level)
        return orig(self, l, r, total, target, level, *args, **kwargs)

    monkeypatch.setattr(TpuHashJoinExec, "_join_grace", spy)

    rng = np.random.RandomState(31)
    n_l, n_r = 6000, 4000
    left = {"k": rng.randint(0, 2000, n_l).tolist(),
            "a": list(range(n_l))}
    right_rows = {"k": rng.randint(0, 2000, n_r).tolist(),
                  "b": [float(i) for i in range(n_r)]}

    import spark_rapids_tpu as srt

    conf = {
        # one shuffle partition => the whole table is one pair,
        # ~150x the 1KB batch target => beyond 64 level-0 buckets
        "spark.rapids.tpu.sql.shuffle.partitions": 1,
        "spark.rapids.tpu.sql.batchSizeBytes": 1024,
        "spark.rapids.tpu.sql.reader.batchSizeRows": 8192,
        "spark.rapids.tpu.sql.bucketMinRows": 64,
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        # keep the shuffled-hash plan: AQE would broadcast-convert this
        # tiny build side and the grace recursion under test would
        # never engage
        "spark.rapids.tpu.sql.adaptive.enabled": False,
    }

    def build(sess):
        l = sess.create_dataframe(left)
        r = sess.create_dataframe(right_rows)
        return l.join(r, on="k", how="inner")

    tpu = srt.Session(dict(conf))
    cpu = srt.Session(dict(conf), tpu_enabled=False)
    got = sorted(map(repr, build(tpu).collect()))
    want = sorted(map(repr, build(cpu).collect()))
    assert got == want
    assert max(levels) >= 1, (
        f"expected recursive grace levels, saw {sorted(set(levels))}")


# --------------------------------------------------------------------------
# spill pressure: a query bigger than the device limit completes, with
# spill events observed (reference: DeviceMemoryEventHandler semantics)
# --------------------------------------------------------------------------
def test_out_of_core_query_spills_and_completes():
    SpillFramework.reset()
    fw = SpillFramework(device_limit_bytes=64 * 1024)
    SpillFramework._instance = fw
    try:
        # external sort registers every sorted-run tile with the catalog;
        # 6000 rows of tiles >> the 64KB device limit, so generation must
        # spill earlier tiles to host while later runs are produced
        assert_tpu_and_cpu_are_equal_collect(
            lambda df: df.sort(df["v"], df["k"], df["x"], df["s"]),
            _data(6000, 23), conf=SMALL)
        assert fw.metrics["spill_to_host"] > 0, (
            "expected device->host spill events under a 64KB device "
            f"limit; metrics={fw.metrics}")
    finally:
        SpillFramework.reset()
