"""Automated bench regression gate (bench.py --compare).

Contract under test (ISSUE 13): diffing two bench summary artifacts
flags >20% regressions on per-query warm/cold times and per-kernel
wall-per-dispatch (matched by kernel fingerprint), exits nonzero when
any are found and zero on self-compare, and REFUSES (exit 2, clear
message) to diff artifacts with different schema_version — a gate
that silently compares re-scoped fields reports garbage.

bench.py's import side effects are env-only (no jax init), so the
compare core is unit-testable in-process; one subprocess test pins the
CLI wiring and exit codes.
"""
import copy
import json
import os
import subprocess
import sys

import pytest

import bench

BENCH_PY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _summary():
    return {
        "metric": "tpch_suite_throughput",
        "schema_version": bench.SCHEMA_VERSION,
        "value": 1.5,
        "per_query": {
            "q1": {"tpu_s": 1.0, "cold_s": 2.0,
                   "kernels": [
                       {"kernel": "agg#abc123", "dispatches": 10,
                        "wall_s": 0.5},
                       {"kernel": "scan#def456", "dispatches": 5,
                        "wall_s": 0.05},
                   ]},
            "q6": {"tpu_s": 0.5, "cold_s": 1.0},
        },
    }


def test_self_compare_is_clean():
    s = _summary()
    assert bench.compare_summaries(s, copy.deepcopy(s)) == []


def test_warm_time_regression_flagged_past_threshold():
    old, new = _summary(), _summary()
    new["per_query"]["q6"]["tpu_s"] = 0.55       # +10%: within noise
    assert bench.compare_summaries(old, new) == []
    new["per_query"]["q6"]["tpu_s"] = 0.65       # +30%: regression
    regs = bench.compare_summaries(old, new)
    assert [r["field"] for r in regs] == ["tpu_s"]
    assert regs[0]["query"] == "q6" and regs[0]["ratio"] == 1.3


def test_cold_time_and_improvements():
    old, new = _summary(), _summary()
    new["per_query"]["q1"]["cold_s"] = 3.0       # +50% compile time
    new["per_query"]["q6"]["tpu_s"] = 0.1        # improvement: not flagged
    regs = bench.compare_summaries(old, new)
    assert [(r["query"], r["field"]) for r in regs] == [("q1", "cold_s")]


def test_synthetic_2x_kernel_slowdown_flagged():
    old, new = _summary(), _summary()
    new["per_query"]["q1"]["kernels"][0]["wall_s"] = 1.0   # 2x per dispatch
    regs = bench.compare_summaries(old, new)
    assert len(regs) == 1
    r = regs[0]
    assert r["kernel"] == "agg#abc123"
    assert r["field"] == "wall_per_dispatch_s"
    assert r["ratio"] == 2.0
    # unmatched fingerprints (recompiled/renamed kernels) are skipped,
    # not treated as regressions
    new["per_query"]["q1"]["kernels"][0]["kernel"] = "agg#zzz999"
    assert bench.compare_summaries(old, new) == []


def test_schema_mismatch_refused_with_clear_message():
    old, new = _summary(), _summary()
    old["schema_version"] = 1
    with pytest.raises(ValueError, match="schema mismatch"):
        bench.compare_summaries(old, new)
    # a baseline predating the version field is also a mismatch
    del old["schema_version"]
    with pytest.raises(ValueError, match="re-run the bench"):
        bench.compare_summaries(old, new)


def test_compare_main_exit_codes(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_summary()))
    slow = _summary()
    slow["per_query"]["q1"]["tpu_s"] = 9.9
    new.write_text(json.dumps(slow))
    assert bench.compare_main(str(old), str(old)) == 0
    assert bench.compare_main(str(old), str(new)) == 1
    skewed = _summary()
    skewed["schema_version"] = 99
    new.write_text(json.dumps(skewed))
    assert bench.compare_main(str(old), str(new)) == 2
    assert bench.compare_main(str(old), str(tmp_path / "absent.json")) == 2
    (tmp_path / "torn.json").write_text('{"truncated": ')
    assert bench.compare_main(str(old), str(tmp_path / "torn.json")) == 2


def test_cli_compare_only_mode_never_runs_the_bench(tmp_path):
    """--compare OLD --new NEW diffs without probing a backend; the
    whole invocation is sub-second and the exit code is the verdict."""
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_summary()))
    slow = _summary()
    slow["per_query"]["q1"]["kernels"][0]["wall_s"] = 1.0
    new.write_text(json.dumps(slow))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, BENCH_PY, "--compare", str(old),
         "--new", str(old)],
        capture_output=True, text=True, timeout=60, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert '"compare": "ok"' in ok.stdout
    bad = subprocess.run(
        [sys.executable, BENCH_PY, "--compare", str(old),
         "--new", str(new)],
        capture_output=True, text=True, timeout=60, env=env)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "wall_per_dispatch_s" in bad.stdout


def _serving(hit_rate=0.95, gold_p50=120.0, bronze_p50=6.0):
    return {
        "metric": "serving_stress",
        "schema_version": bench.SCHEMA_VERSION,
        "rounds": {
            "none": {
                "inject": "none",
                "warm": {
                    "cache_hit_rate": hit_rate,
                    "per_tier": {
                        "gold": {"p50_ms": gold_p50, "p95_ms": 300.0},
                        "bronze": {"p50_ms": bronze_p50, "p95_ms": 9.0},
                    },
                },
            },
            "corrupt": {"inject": "corrupt", "warm": {"skipped": "budget"}},
        },
    }


def test_serving_self_compare_clean_and_warm_p50_regression():
    base = _serving()
    assert bench.compare_summaries(base, copy.deepcopy(base)) == []
    # +30% AND past the absolute floor: flagged
    regs = bench.compare_summaries(base, _serving(gold_p50=200.0))
    assert [(r["query"], r["field"]) for r in regs] == \
        [("serving.none.gold", "warm_p50_ms")]
    # a 2x ratio UNDER the floor is cache-hit jitter, not regression
    assert bench.compare_summaries(base, _serving(bronze_p50=12.0)) == []
    # improvements are never flagged
    assert bench.compare_summaries(base, _serving(gold_p50=40.0)) == []


def test_serving_lost_cache_hit_coverage_flagged():
    base = _serving()
    regs = bench.compare_summaries(base, _serving(hit_rate=0.3))
    assert [(r["query"], r["field"]) for r in regs] == \
        [("serving.none", "cache_hit_rate")]
    # within-threshold wobble is fine
    assert bench.compare_summaries(base, _serving(hit_rate=0.85)) == []
    # artifacts without serving rounds skip the section entirely
    assert bench.compare_summaries(_summary(), _serving()) == []
    assert bench.compare_summaries(_serving(), _summary()) == []


def _multichip(**elastic):
    tail = ("entry ok: ...\n"
            "MULTICHIP_ELASTIC " + json.dumps({
                "degraded_devices": 4, "respeculated_shards": 1,
                "mesh_shrink_count": 1, "stages_resumed": 4,
                **elastic}) + "\n"
            "dryrun ok (virtual 8-device cpu mesh)\n")
    return {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
            "tail": tail}


def test_elastic_fields_parsed_from_multichip_tail():
    got = bench._elastic_summary(_multichip())
    assert got == {"degraded_devices": 4, "respeculated_shards": 1,
                   "mesh_shrink_count": 1}
    assert bench._elastic_summary({"tail": "no marker here"}) is None
    assert bench._elastic_summary(_summary()) is None


def test_elastic_drill_self_compare_clean_and_regressions_flagged():
    base = _multichip()
    assert bench.compare_summaries(base, copy.deepcopy(base)) == []
    # the drill DELIBERATELY kills a peer: detection regressing to
    # zero is the failure mode the gate must catch
    dead = bench.compare_summaries(base, _multichip(mesh_shrink_count=0))
    assert [r["field"] for r in dead] == ["mesh_shrink_count"]
    assert dead[0]["query"] == "elastic_drill"
    nospec = bench.compare_summaries(
        base, _multichip(respeculated_shards=0))
    assert [r["field"] for r in nospec] == ["respeculated_shards"]
    # losing MORE devices than the baseline is also a regression ...
    worse = bench.compare_summaries(base, _multichip(degraded_devices=6))
    assert [r["field"] for r in worse] == ["degraded_devices"]
    # ... but shrinking less / respeculating more is an improvement
    assert bench.compare_summaries(
        base, _multichip(degraded_devices=2,
                         respeculated_shards=3)) == []
