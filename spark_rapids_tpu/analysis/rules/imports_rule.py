"""jax-import — device-independent layers stay device-independent.

``adaptive/`` (host-side planning), ``recovery/`` (must load in a
fresh process before any device exists) and ``streaming/`` (daemon
control plane) must never import jax at module level or lazily — the
exec layer owns every device interaction.
"""
from __future__ import annotations

from typing import Iterable, List

from ..engine import AnalysisContext, Rule
from ..findings import Finding
from . import common

BANNED_PREFIXES = ("adaptive/", "recovery/", "streaming/")


class JaxImportRule(Rule):
    id = "jax-import"
    title = "host-side layers (adaptive/recovery/streaming) never import jax"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rels = common.scoped(ctx, prefixes=BANNED_PREFIXES)
        for rel in rels:
            mi = ctx.resolver.module(rel)
            if mi is None:
                continue
            for mod, lineno in mi.imported_modules():
                if mod == "jax" or mod.startswith("jax."):
                    out.append(self.finding(
                        "device-import", rel, lineno,
                        f"imports {mod} — this layer is host-side by "
                        f"contract; device interaction belongs to "
                        f"exec/",
                        detail=f"import:{mod}"))
        out.extend(self.health(
            len(rels) >= 8, common.PKG + "adaptive",
            f"expected >=8 files in the host-side scope, "
            f"saw {len(rels)}"))
        return out
