"""Contiguous columnar frame serialization.

Reference analogue: JCudfSerialization (the serialized-table format that
rides Spark's shuffle streams, GpuColumnarBatchSerializer.scala:36-246)
plus the TableMeta buffer/sub-buffer metadata (format/ShuffleCommon.fbs).
One ``HostBatch`` becomes ONE contiguous byte frame: header, per-column
meta, then 64-byte-aligned validity and data sections — the unit of host
spill storage and disk spill files.

Framing runs through the native library (srt_frame_*) when available;
the identical layout is produced/parsed by the numpy fallback, so frames
are interchangeable between the two writers.

String columns (object ndarrays) pack as:
    [int64 total_utf8_bytes][int64 offsets (n+1)][utf8 payload]
"""
from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from .. import types as T
from ..data.column import HostBatch, HostColumn
from . import get_lib

_ALIGN = 64
_HEADER = 64
_COLMETA = 24

# TypeId enum values are sql-name strings; frames need stable ints
_TYPE_CODE = {tid: i for i, tid in enumerate(T.TypeId)}


def _align(x: int) -> int:
    return (x + _ALIGN - 1) & ~(_ALIGN - 1)


def _encode_strings(col: HostColumn) -> np.ndarray:
    n = len(col.data)
    valid = col.validity
    payload = []
    offsets = np.zeros(n + 1, dtype=np.int64)
    for i in range(n):
        if valid is not None and not valid[i]:
            b = b""
        else:
            v = col.data[i]
            b = v.encode("utf-8") if isinstance(v, str) else (v or b"")
        payload.append(b)
        offsets[i + 1] = offsets[i] + len(b)
    blob = b"".join(payload)
    out = np.empty(8 + offsets.nbytes + len(blob), dtype=np.uint8)
    out[:8] = np.frombuffer(
        np.int64(len(blob)).tobytes(), dtype=np.uint8)
    out[8:8 + offsets.nbytes] = np.frombuffer(offsets.tobytes(),
                                              dtype=np.uint8)
    if blob:
        out[8 + offsets.nbytes:] = np.frombuffer(blob, dtype=np.uint8)
    return out


def _decode_strings(raw: np.ndarray, n_rows: int,
                    valid: Optional[np.ndarray]) -> np.ndarray:
    offsets = np.frombuffer(raw[8:8 + (n_rows + 1) * 8].tobytes(),
                            dtype=np.int64)
    payload = raw[8 + (n_rows + 1) * 8:].tobytes()
    out = np.empty(n_rows, dtype=object)
    for i in range(n_rows):
        if valid is not None and not valid[i]:
            out[i] = None
        else:
            out[i] = payload[offsets[i]:offsets[i + 1]].decode("utf-8")
    return out


def _column_parts(col: HostColumn):
    """(dtype_id, data_u8, valid_u8_or_None) for one column."""
    if col.dtype.id is T.TypeId.STRING:
        data = _encode_strings(col)
    else:
        data = np.ascontiguousarray(col.data).view(np.uint8).reshape(-1)
    valid = None
    if col.validity is not None:
        valid = np.ascontiguousarray(
            col.validity.astype(np.uint8)).reshape(-1)
    return _TYPE_CODE[col.dtype.id], data, valid


class PreparedFrame:
    """Encoded columns + computed size, so callers can allocate the
    destination (e.g. an arena carve) and write once — no intermediate
    full-frame copy on the spill path."""

    def __init__(self, batch: HostBatch):
        self.parts = [_column_parts(c) for c in batch.columns]
        self.n_rows = batch.num_rows
        self.size = _HEADER + _align(len(self.parts) * _COLMETA) + sum(
            _align(0 if v is None else v.nbytes) + _align(d.nbytes)
            for _, d, v in self.parts)

    def write_into(self, out: np.ndarray) -> None:
        assert out.nbytes >= self.size
        _write(out, self.parts, self.n_rows, self.size)


def frame_size(batch: HostBatch) -> int:
    return PreparedFrame(batch).size


def serialize(batch: HostBatch) -> np.ndarray:
    """HostBatch -> one contiguous uint8 frame."""
    pf = PreparedFrame(batch)
    out = np.zeros(pf.size, dtype=np.uint8)
    pf.write_into(out)
    return out


def _write(out: np.ndarray, parts, n_rows: int, total: int) -> None:
    n_cols = len(parts)
    lib = get_lib()
    if lib is not None:
        u8p = ctypes.POINTER(ctypes.c_uint8)
        datas = (u8p * n_cols)(*[d.ctypes.data_as(u8p) for _, d, _ in parts])
        dlens = (ctypes.c_uint64 * n_cols)(*[d.nbytes for _, d, _ in parts])
        # keep a zero-length placeholder pointer for validity-less columns
        zeros = np.zeros(1, dtype=np.uint8)
        valids = (u8p * n_cols)(*[
            (v if v is not None else zeros).ctypes.data_as(u8p)
            for _, _, v in parts])
        vlens = (ctypes.c_uint64 * n_cols)(*[
            0 if v is None else v.nbytes for _, _, v in parts])
        dts = (ctypes.c_int32 * n_cols)(*[t for t, _, _ in parts])
        n = lib.srt_frame_write(out.ctypes.data_as(u8p), n_cols, n_rows,
                                datas, dlens, valids, vlens, dts)
        assert n == total, (n, total)
        return
    # ----- numpy fallback: identical layout ------------------------------
    out[0:4] = np.frombuffer(np.uint32(0x42545253).tobytes(), np.uint8)
    out[4:8] = np.frombuffer(np.uint32(1).tobytes(), np.uint8)
    out[8:12] = np.frombuffer(np.uint32(n_cols).tobytes(), np.uint8)
    out[12:20] = np.frombuffer(np.uint64(n_rows).tobytes(), np.uint8)
    out[20:28] = np.frombuffer(np.uint64(total).tobytes(), np.uint8)
    # zero padding gaps explicitly: the destination may be a reused arena
    # carve, and frames are spilled to disk verbatim
    out[28:_HEADER] = 0
    out[_HEADER + n_cols * _COLMETA:_HEADER + _align(n_cols * _COLMETA)] = 0
    for i, (t, d, v) in enumerate(parts):
        m = _HEADER + i * _COLMETA
        out[m:m + 4] = np.frombuffer(np.int32(t).tobytes(), np.uint8)
        out[m + 4:m + 8] = np.frombuffer(
            np.int32(0 if v is None else 1).tobytes(), np.uint8)
        out[m + 8:m + 16] = np.frombuffer(
            np.uint64(d.nbytes).tobytes(), np.uint8)
        out[m + 16:m + 24] = np.frombuffer(
            np.uint64(0 if v is None else v.nbytes).tobytes(), np.uint8)
    off = _HEADER + _align(n_cols * _COLMETA)
    for t, d, v in parts:
        if v is not None:
            out[off:off + v.nbytes] = v
            out[off + v.nbytes:off + _align(v.nbytes)] = 0
            off += _align(v.nbytes)
        if d.nbytes:
            out[off:off + d.nbytes] = d
        out[off + d.nbytes:off + _align(d.nbytes)] = 0
        off += _align(d.nbytes)


def deserialize(frame: np.ndarray, schema: T.Schema) -> HostBatch:
    """One contiguous uint8 frame -> HostBatch (schema supplies dtypes;
    the frame's embedded dtype ids are a cross-check)."""
    frame = np.ascontiguousarray(frame, dtype=np.uint8)
    magic = int(np.frombuffer(frame[0:4].tobytes(), np.uint32)[0])
    if magic != 0x42545253:
        raise ValueError("bad frame magic")
    n_cols = int(np.frombuffer(frame[8:12].tobytes(), np.uint32)[0])
    n_rows = int(np.frombuffer(frame[12:20].tobytes(), np.uint64)[0])
    if n_cols != len(schema):
        raise ValueError(f"frame has {n_cols} cols, schema {len(schema)}")
    cols = []
    off = _HEADER + _align(n_cols * _COLMETA)
    for i, f in enumerate(schema):
        m = _HEADER + i * _COLMETA
        dt_id = int(np.frombuffer(frame[m:m + 4].tobytes(), np.int32)[0])
        has_v = int(np.frombuffer(frame[m + 4:m + 8].tobytes(),
                                  np.int32)[0])
        dlen = int(np.frombuffer(frame[m + 8:m + 16].tobytes(),
                                 np.uint64)[0])
        vlen = int(np.frombuffer(frame[m + 16:m + 24].tobytes(),
                                 np.uint64)[0])
        if dt_id != _TYPE_CODE[f.dtype.id]:
            raise ValueError(
                f"column {i}: frame dtype {dt_id} != schema {f.dtype}")
        valid = None
        if has_v:
            valid = frame[off:off + vlen].astype(np.bool_)
            off += _align(vlen)
        raw = frame[off:off + dlen]
        off += _align(dlen)
        if f.dtype.id is T.TypeId.STRING:
            data = _decode_strings(raw, n_rows, valid)
        else:
            data = np.frombuffer(raw.tobytes(), dtype=f.dtype.np_dtype)
        cols.append(HostColumn(f.dtype, data, valid))
    return HostBatch(schema, cols)
