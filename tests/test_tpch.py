"""TPC-H-like q1..q22: CPU-oracle vs TPU-path equality.

Reference analogue: TpchLikeSparkSuite.scala — every query runs on the
small checked-in dataset and the plugin result must match CPU Spark.
Here each query is executed on a Session with tpu_enabled=False (host
numpy engine, the oracle) and tpu_enabled=True (rewrite engine + device
execs), and results are compared with the same sort/float tolerance
semantics as asserts.py.
"""
import pytest

from spark_rapids_tpu.benchmarks import tpch, tpch_datagen
from spark_rapids_tpu.session import Session
from spark_rapids_tpu.testing.asserts import assert_rows_equal

SF = 0.0007
SEED = 7


def _run(qnum: int, tpu: bool):
    sess = Session(tpu_enabled=tpu)
    tables = tpch_datagen.dataframes(sess, sf=SF, seed=SEED)
    df = tpch.QUERIES[qnum](tables)
    return df.collect(), df.columns


# queries whose output has no total order (ties in sort keys / no sort)
_UNORDERED = {2, 5, 6, 10, 11, 13, 14, 16, 17, 18, 19, 21, 22}


@pytest.mark.parametrize("qnum", sorted(tpch.QUERIES))
def test_tpch_query_cpu_vs_tpu(qnum):
    cpu_rows, cols = _run(qnum, tpu=False)
    tpu_rows, _ = _run(qnum, tpu=True)
    assert_rows_equal(cpu_rows, tpu_rows,
                      ignore_order=qnum in _UNORDERED,
                      approximate_float=1e-6)


def test_tpch_q16_like_stays_on_device():
    """q16's `p_type NOT LIKE 'MEDIUM POLISHED%'` must lower onto the
    device byte-matrix kernels (reference keeps Like on GPU via regex
    translation, GpuOverrides.scala:326-371); strict test mode raises
    on any unexpected host fallback."""
    sess = Session({"spark.rapids.tpu.sql.test.enabled": True})
    tables = tpch_datagen.dataframes(sess, sf=SF, seed=SEED)
    rows = tpch.QUERIES[16](tables).collect()
    cpu_rows, _ = _run(16, tpu=False)
    assert_rows_equal(cpu_rows, rows, ignore_order=True,
                      approximate_float=1e-6)


def test_tpch_nonempty_coverage():
    """The generator must feed every query a non-trivial subset (guards
    against the suite silently comparing empty results everywhere)."""
    nonempty = 0
    for qnum in sorted(tpch.QUERIES):
        rows, _ = _run(qnum, tpu=False)
        if rows:
            nonempty += 1
    assert nonempty >= 18, f"only {nonempty}/22 queries returned rows"
