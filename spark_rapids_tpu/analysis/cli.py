"""Command-line entry point: ``python -m spark_rapids_tpu.analysis``.

Exit codes: 0 clean (or only baselined findings), 1 new findings,
2 usage/internal error.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from .baseline import DEFAULT_BASELINE, Baseline
from .engine import AnalysisContext, all_rules, run_rules
from .findings import Finding
from .project import Project

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2


def _render_json(new: List[Finding], suppressed: List[Finding],
                 stale) -> str:
    def enc(f: Finding):
        return {"rule": f.rule, "kind": f.kind, "file": f.file,
                "line": f.line, "severity": f.severity,
                "message": f.message, "detail": f.detail,
                "fingerprint": f.fingerprint}
    return json.dumps({"new": [enc(f) for f in new],
                       "suppressed": [enc(f) for f in suppressed],
                       "stale_baseline_entries": stale}, indent=2)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.analysis",
        description="tpulint: whole-program static analysis "
                    "(see docs/static_analysis.md)")
    ap.add_argument("--rule", action="append", dest="rules",
                    metavar="ID", help="run only this rule "
                    "(repeatable); default: all rules")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline suppression file "
                    "(default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to cover current "
                    "findings (preserves existing justifications)")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: autodetect)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true",
                    help="list rule ids and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in all_rules():
            print(f"{cls.id:<18} {cls.title}")
        return EXIT_CLEAN

    t0 = time.monotonic()
    try:
        ctx = AnalysisContext(Project(args.root))
        findings = run_rules(ctx, args.rules)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return EXIT_ERROR

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.no_baseline:
        baseline = Baseline([])
    else:
        try:
            baseline = Baseline.load(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return EXIT_ERROR

    if args.update_baseline:
        data = baseline.updated(findings)
        Baseline.write(baseline_path, data)
        todo = sum(1 for e in data["entries"]
                   if e["justification"].startswith("TODO"))
        print(f"baseline written: {baseline_path} "
              f"({len(data['entries'])} entries, {todo} need "
              f"justification)")
        return EXIT_CLEAN

    new, suppressed, stale = baseline.split(findings)

    if args.as_json:
        print(_render_json(new, suppressed, stale))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"{e['file']}: [{e['rule']}/{e['kind']}] warning: "
                  f"stale baseline entry (no longer found): "
                  f"{e['detail']}")
        dt = time.monotonic() - t0
        n_rules = len(args.rules) if args.rules else len(all_rules())
        print(f"tpulint: {len(ctx.project.files())} files, "
              f"{n_rules} rules, {len(new)} new finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'} "
              f"[{dt:.2f}s]")
    return EXIT_FINDINGS if new else EXIT_CLEAN
