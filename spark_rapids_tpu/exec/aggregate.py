"""Device hash aggregate.

Reference analogue: GpuHashAggregateExec (aggregate.scala:227-396) — the
mode-aware (partial/final/complete) columnar aggregate.  The reference
lowers to cudf's hash groupBy; hash tables scatter randomly, which is
hostile to the TPU memory model, so this exec is sort-based: lexsort rows
by key, derive segment ids at key-change boundaries, then segment
reductions with a *static* segment count (the row bucket) so shapes stay
XLA-friendly (SURVEY §7 Hard parts: sort + segment-reduce).

The whole aggregate — key eval, sort, segment ids, every buffer reduction,
and the finalize expressions — traces into ONE jitted XLA program per
(schema, row-bucket), so XLA fuses the elementwise work into the sort and
reduction loops.
"""
from __future__ import annotations

from typing import List

from .. import types as T
from ..data.column import DeviceBatch, DeviceColumn
from ..memory import retry as R
from ..ops.aggregates import AggregateFunction
from ..ops.expression import BoundReference, as_device_column
from ..ops.kernels import gather as G
from ..ops.kernels import segment as seg
from ..utils import metrics as M
from ..utils.tracing import trace_range
from .base import DevicePartitionedData, TpuExec


def _string_minmax_device(col: DeviceColumn, valid, seg_ids,
                          n_segments: int, op: str):
    """min/max over a string column per segment via rank encoding:
    lexsort the values once, invert to per-row ranks, reduce ranks per
    segment, then gather the winning rows."""
    import jax.numpy as jnp

    n = col.data.shape[0]
    order = seg.lexsort_device([col], pad_valid=valid)
    rank = jnp.zeros((n,), dtype=jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    big = n + 1
    key = jnp.where(valid, rank, big if op == "min" else -1)
    import jax

    fn = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    picked_rank = fn(key, seg_ids, num_segments=n_segments)
    safe = jnp.clip(picked_rank, 0, n - 1).astype(jnp.int32)
    picked_row = order[safe]
    data = col.data[picked_row]
    lengths = col.lengths[picked_row]
    return data, lengths


class TpuHashAggregateExec(TpuExec):
    """Sort-based group-by on device; wraps the host plan node to reuse its
    bound keys/specs/schema (modes are identical).

    Out-of-core: a partition bigger than the batch-size goal arrives as
    several batches; each is aggregated to its buffer form and merged into
    a running grouped result — the same concat+merge loop the reference
    runs per batch (aggregate.scala:240-335).  The running result is
    registered with the spill catalog between merges so memory pressure
    can evict it."""

    def __init__(self, child, plan):
        super().__init__([child])
        self.plan = plan  # physical.HashAggregateExec (exprs already bound)
        self.mode = plan.mode
        self.keys = plan.keys
        self.specs = plan.specs
        self._schema = plan.schema
        from .kernel_cache import (expr_signature, jit_kernel,
                                   schema_signature)

        sig = ("agg", self.mode, schema_signature(child.schema),
               expr_signature(self.keys),
               tuple(sp.func.sql() for sp in self.specs),
               schema_signature(plan.schema))
        twin = self.kernel_twin()
        self._kernel = jit_kernel(twin.compute_batch,
                                  key=sig + ("batch",))
        # chunked-path kernels (used only when a partition spans batches)
        self._update_kernel = jit_kernel(
            lambda b: twin._compute(b, "update", "buffers"),
            key=sig + ("update",))
        self._merge_kernel = jit_kernel(
            lambda b: twin._compute(b, "merge", "buffers"),
            key=sig + ("merge",))
        # only reached from _agg_chunked when mode is final/complete
        # (partial returns the running buffers before finalize)
        self._merge_final_kernel = jit_kernel(
            lambda b: twin._compute(b, "merge", "final"),
            key=sig + ("merge_final",))

    def compute_batch(self, batch: DeviceBatch) -> DeviceBatch:
        """The mode's full aggregation over one batch (trace-safe; also
        the per-shard form the distributed runner lowers through)."""
        phase = "merge" if self.mode == "final" else "update"
        emit = "buffers" if self.mode == "partial" else "final"
        return self._compute(batch, phase, emit)

    @property
    def schema(self):
        return self._schema

    @property
    def buffer_schema(self) -> T.Schema:
        """Schema of the pre-finalize form: group keys + agg buffers
        (for a partial agg this IS the output schema)."""
        from ..plan.physical import _buffer_fields

        nkeys = len(self.keys)
        if self.mode == "partial":
            return self._schema
        key_fields = [
            T.Field(f.name, k.dtype)
            for f, k in zip(self._schema.fields[:nkeys], self.keys)
        ] if self.mode == "complete" else \
            list(self.children[0].schema.fields[:nkeys])
        return T.Schema(key_fields + _buffer_fields(self.specs))

    @property
    def children_coalesce_goal(self):
        # chunked concat+merge handles multi-batch partitions; the goal is
        # the session batch-size target (reference: aggregate.scala loops
        # concat+merge per batch at the same goal)
        from .base import TargetSize

        return [TargetSize()]

    # ------------------------------------------------------------------
    def _compute(self, batch: DeviceBatch, phase: str,
                 emit: str) -> DeviceBatch:
        """One aggregation pass.  ``phase``: "update" evaluates key/value
        expressions over raw input rows; "merge" treats the batch as
        buffer-form (keys + buffers).  ``emit``: "buffers" outputs the
        grouped buffer form; "final" applies the finalize expressions."""
        import jax
        import jax.numpy as jnp

        nkeys = len(self.keys)
        padded = batch.padded_rows
        rm = batch.row_mask()

        # ----- keys ----------------------------------------------------
        if phase == "merge":
            key_cols = [batch.columns[i] for i in range(nkeys)]
        else:
            key_cols = [as_device_column(k.eval_tpu(batch), padded)
                        for k in self.keys]
        key_cols = [DeviceColumn(c.dtype, c.data, c.validity & rm,
                                 c.lengths) for c in key_cols]

        # ----- sort + segments -----------------------------------------
        if nkeys:
            order = seg.lexsort_device(key_cols, pad_valid=rm)
            sorted_keys = [G.gather_column(c, order) for c in key_cols]
            pad_sorted = rm[order]
            seg_ids = seg.segment_ids_device(sorted_keys,
                                             pad_valid=pad_sorted)
            total = rm.sum().astype(jnp.int32)
            n_real = jnp.where(
                total > 0,
                seg_ids[jnp.clip(total - 1, 0, padded - 1)] + 1, 0)
        else:
            order = jnp.arange(padded, dtype=jnp.int32)
            pad_sorted = rm
            seg_ids = jnp.where(rm, 0,  # padding rows -> own segments
                                jnp.arange(padded, dtype=jnp.int32) + 1
                                ).astype(jnp.int32)
            sorted_keys = []
            n_real = jnp.asarray(1, dtype=jnp.int32)

        out_valid_seg = jnp.arange(padded, dtype=jnp.int32) < n_real

        # output key columns = first row of each segment
        idx = jnp.arange(padded, dtype=jnp.int64)
        seg_starts = jax.ops.segment_min(idx, seg_ids, num_segments=padded)
        safe_starts = jnp.clip(seg_starts, 0, padded - 1).astype(jnp.int32)
        out_keys = []
        for c in sorted_keys:
            g = G.gather_column(c, safe_starts, out_valid_seg)
            out_keys.append(g)

        # ----- reductions ----------------------------------------------
        if phase == "update":
            buffers = self._update_buffers(
                batch, order, pad_sorted, seg_ids, padded, out_valid_seg)
        else:
            buffers = self._merge_buffers(
                batch, order, pad_sorted, seg_ids, padded, out_valid_seg,
                nkeys)

        if emit == "buffers":
            out_cols = out_keys + buffers
            return DeviceBatch(self.buffer_schema, out_cols, n_real)
        return self._finalize(out_keys, buffers, n_real, padded,
                              out_valid_seg)

    # ------------------------------------------------------------------
    def _update_buffers(self, batch, order, pad_sorted, seg_ids, padded,
                        out_valid_seg) -> List[DeviceColumn]:
        import jax.numpy as jnp

        buffers = []
        for sp in self.specs:
            func: AggregateFunction = sp.func
            if func.child is None:  # count(*)
                inputs = [(jnp.ones((padded,), dtype=jnp.int64),
                           pad_sorted, None)]
            else:
                c = as_device_column(func.child.eval_tpu(batch), padded)
                valid = (c.validity & batch.row_mask())[order]
                inputs = [(c.data[order], valid,
                           c.lengths[order] if c.lengths is not None
                           else None)]
            for (op, which), bt in zip(func.updates, func.buffer_dtypes()):
                vals, valid, lens = inputs[which]
                buffers.append(self._reduce_one(
                    vals, valid, lens, seg_ids, padded, op, bt,
                    out_valid_seg, present=pad_sorted))
        return buffers

    def _merge_buffers(self, batch, order, pad_sorted, seg_ids, padded,
                       out_valid_seg, nkeys) -> List[DeviceColumn]:
        buffers = []
        col_idx = nkeys
        for sp in self.specs:
            func: AggregateFunction = sp.func
            for op, bt in zip(func.merges, func.buffer_dtypes()):
                c = batch.columns[col_idx]
                valid = (c.validity & batch.row_mask())[order]
                lens = c.lengths[order] if c.lengths is not None else None
                buffers.append(self._reduce_one(
                    c.data[order], valid, lens, seg_ids, padded, op, bt,
                    out_valid_seg, present=pad_sorted))
                col_idx += 1
        return buffers

    def _reduce_one(self, vals, valid, lens, seg_ids, padded, op,
                    buf_dtype: T.DType, out_valid_seg,
                    present=None) -> DeviceColumn:
        import jax.numpy as jnp

        if buf_dtype.id is T.TypeId.STRING:
            col = DeviceColumn(buf_dtype, vals, valid, lens)
            if op in ("min", "max"):
                data, lengths = _string_minmax_device(
                    col, valid, seg_ids, padded, op)
                import jax

                counts = jax.ops.segment_sum(
                    valid.astype(jnp.int32), seg_ids, num_segments=padded)
                ok = (counts > 0) & out_valid_seg
                return DeviceColumn(buf_dtype, data, ok, lengths)
            # first / last pick a row index; gather bytes+lengths by it
            if op in ("first_any", "last_any"):
                eligible = present if present is not None \
                    else jnp.ones_like(valid)
            else:
                eligible = valid
            safe, has = seg.segment_pick_device(eligible, seg_ids,
                                                padded, op)
            ok = has & out_valid_seg
            if op in ("first_any", "last_any"):
                ok = ok & valid[safe]
            return DeviceColumn(buf_dtype, vals[safe], ok, lens[safe])

        data, ok = seg.segment_reduce_device(vals, valid, seg_ids, padded,
                                             op, present=present)
        if op == "count":
            ok = out_valid_seg
        else:
            ok = ok & out_valid_seg
        if data.dtype != buf_dtype.jnp_dtype:
            data = data.astype(buf_dtype.jnp_dtype)
        return DeviceColumn(buf_dtype, data, ok)

    # ------------------------------------------------------------------
    def _finalize(self, out_keys, buffers, n_real, padded,
                  out_valid_seg) -> DeviceBatch:
        from ..plan.physical import _buffer_fields

        buf_schema = T.Schema(_buffer_fields(self.specs))
        buf_batch = DeviceBatch(buf_schema, buffers, n_real)
        out_cols = list(out_keys)
        bi = 0
        nkeys = len(self.keys)
        for sp, f in zip(self.specs, self._schema.fields[nkeys:]):
            nbuf = len(sp.func.buffer_dtypes())
            refs = [BoundReference(bi + j, buffers[bi + j].dtype, True)
                    for j in range(nbuf)]
            final_expr = sp.func.finalize(refs)
            c = as_device_column(final_expr.eval_tpu(buf_batch), padded)
            if c.dtype != f.dtype and f.dtype.id is not T.TypeId.STRING \
                    and c.dtype.id is not T.TypeId.STRING:
                c = DeviceColumn(f.dtype,
                                 c.data.astype(f.dtype.jnp_dtype),
                                 c.validity, c.lengths)
            c = DeviceColumn(c.dtype, c.data, c.validity & out_valid_seg,
                             c.lengths)
            out_cols.append(c)
            bi += nbuf
        return DeviceBatch(self._schema, out_cols, n_real)

    # ------------------------------------------------------------------
    def _to_buffers_fn(self):
        """Buffer-form transform of one raw input piece (identity for
        ``final`` mode, whose input already IS buffer form), with an
        OOM-injection checkpoint at the attempt boundary."""
        inner = (lambda b: b) if self.mode == "final" \
            else self._update_kernel

        def fn(b):
            R.maybe_inject_oom("TpuHashAggregate.update")
            return inner(b)

        return fn

    def _agg_chunked(self, first: DeviceBatch, rest,
                     rctx) -> DeviceBatch:
        """Out-of-core path: per-batch buffer-form agg + running merge
        (reference: aggregate.scala:240-335 concat+merge loop).  The
        running result sits in the spill catalog between merges so the
        alloc-pressure handler can evict it while the next input batch
        is being produced/aggregated.  Each per-batch pass runs through
        the retry framework: an OOM retries after spill+backoff, a
        split request halves the input batch — buffer forms of the
        pieces merge into the running result exactly like whole
        batches."""
        from itertools import chain

        from ..memory.spill import SpillFramework, SpillPriorities
        from .coalesce import concat_device_batches

        fw = SpillFramework.get()
        to_buffers = self._to_buffers_fn()

        running = None  # merged buffer form so far (device batch)
        rid = None      # spill-catalog id while running is parked

        def park():
            # running sits in the spill catalog while the NEXT piece is
            # being produced/aggregated, so pressure can evict it
            nonlocal rid
            if running is not None and rid is None:
                rid = R.retry_call(
                    lambda: fw.add_batch(
                        running,
                        priority=SpillPriorities.ACTIVE_ON_DECK),
                    rctx)

        def unpark():
            nonlocal rid, running
            if rid is not None:
                running = R.retry_call(
                    lambda: fw.acquire_batch(rid), rctx)
                fw.release_batch(rid)
                fw.remove_batch(rid)
                rid = None

        for nxt in chain([first], rest):
            park()
            for part in R.with_split_retry(nxt, to_buffers, ctx=rctx):
                unpark()
                if running is None:
                    running = part
                else:
                    combined = concat_device_batches([running, part])
                    running = R.retry_call(
                        lambda c=combined: self._merge_kernel(c), rctx)
                park()
        unpark()
        if self.mode == "partial":
            return running
        # re-merging the grouped running result is the identity on every
        # buffer (one row per segment), so this pass just re-groups and
        # applies the finalize expressions
        return self._merge_final_kernel(running)

    def _agg_split(self, batch: DeviceBatch, rctx) -> DeviceBatch:
        """Split-and-retry escalation for the single-batch path: halve
        the input, aggregate each piece to buffer form (recursively
        splittable), then merge — the same composition the chunked
        out-of-core path uses, so results match the unsplit kernel."""
        from .coalesce import concat_device_batches

        to_buffers = self._to_buffers_fn()
        running = None
        for part in R.with_split_retry(batch, to_buffers, ctx=rctx,
                                       initial_split=True):
            running = part if running is None else R.retry_call(
                lambda c=concat_device_batches([running, part]):
                self._merge_kernel(c), rctx)
        if self.mode == "partial":
            return running
        return self._merge_final_kernel(running)

    def execute_columnar(self, ctx):
        child = self.children[0].execute_columnar(ctx)
        self._init_metrics(ctx)
        rctx = R.RetryContext.for_exec(ctx, "TpuHashAggregateExec")

        def make(pid):
            def it():
                batches = child.iterator(pid)
                first = next(batches, None)
                if first is None:
                    if self.keys or self.mode == "partial":
                        return
                    # global agg over empty input still yields one row
                    from ..data.column import host_to_device
                    from ..plan.physical import _empty_batch

                    first = host_to_device(
                        _empty_batch(self.children[0].schema))
                second = next(batches, None)

                def agg_full(b):
                    R.maybe_inject_oom("TpuHashAggregate")
                    return self._kernel(b)

                with trace_range("TpuHashAggregate",
                                 self.metrics[M.TOTAL_TIME]):
                    if second is None:
                        try:
                            # allow_split: a genuine OOM that exhausts
                            # its retries escalates to the split path
                            # below instead of failing the task
                            out = R.retry_call(
                                lambda: agg_full(first), rctx,
                                allow_split=True)
                        except R.TpuSplitAndRetryOOM:
                            if R.can_split(first, rctx):
                                out = self._agg_split(first, rctx)
                            else:
                                # at the floor: plain retries (a split
                                # request degrades inside retry_call)
                                out = R.retry_call(
                                    lambda: agg_full(first), rctx)
                    else:
                        from itertools import chain

                        out = self._agg_chunked(
                            first, chain([second], batches), rctx)
                self.metrics[M.NUM_OUTPUT_BATCHES].add(1)
                yield out

            return it

        return DevicePartitionedData(
            [make(i) for i in range(child.n_partitions)])

    def describe(self):
        return (f"TpuHashAggregate[{self.mode}, keys={len(self.keys)}, "
                f"aggs={[sp.func.sql() for sp in self.specs]}]")


# ==========================================================================
# rule registration
# ==========================================================================
def register(register_exec):
    from ..config import HASH_AGG_REPLACE_MODE
    from ..plan import physical as P

    def tag(meta):
        from ..config import ALLOW_FLOAT_AGG

        if not meta.conf.get(ALLOW_FLOAT_AGG):
            # reference: GpuHashAggregateMeta rejects float aggregation
            # unless variableFloatAgg is enabled (order-dependent sums)
            for sp in meta.plan.specs:
                child = sp.func.child
                if child is not None and child.dtype.is_floating:
                    meta.will_not_work_on_tpu(
                        f"aggregation over floating column "
                        f"({sp.func.sql()}) disabled; enable "
                        "spark.rapids.tpu.sql.variableFloatAgg.enabled")
                    break
        # reference: hashAgg.replaceMode gates which modes convert
        # (aggregate.scala GpuHashAggregateMeta + RapidsConf:483-493)
        allowed = str(meta.conf.get(HASH_AGG_REPLACE_MODE)).lower()
        if allowed != "all":
            modes = {m.strip() for m in allowed.split("|")}
            mode = meta.plan.mode
            if mode == "complete":
                mode = "partial"  # complete ~ single-phase partial+final
            if mode not in modes:
                meta.will_not_work_on_tpu(
                    f"aggregation mode {meta.plan.mode} excluded by "
                    f"hashAgg.replaceMode={allowed}")

    def exprs_of(plan: P.HashAggregateExec):
        out = list(plan.keys)
        for sp in plan.specs:
            out.append(sp.func)
        return out

    register_exec(
        P.HashAggregateExec,
        convert=lambda meta, ch: TpuHashAggregateExec(ch[0], meta.plan),
        desc="sort-based segment-reduce group-by on TPU",
        tag=tag,
        exprs_of=exprs_of)
