"""Tracing & profiling ranges.

Reference analogue: NVTX ranges on the hot path (NvtxRange /
NvtxWithMetrics couple a range with a SQLMetric nanosecond accumulator, see
SURVEY §5).  TPU equivalent: ``jax.profiler.TraceAnnotation`` so ranges show
in xprof, with the same metric coupling so wall time lands in the engine's
metrics too."""
from __future__ import annotations

import time
from contextlib import contextmanager

_ENABLED = False


def enable(flag: bool = True) -> None:
    global _ENABLED
    _ENABLED = flag


@contextmanager
def trace_range(name: str, metric=None):
    """A named profiler range; if ``metric`` is given, elapsed nanoseconds
    are added to it (reference: NvtxWithMetrics.scala:44)."""
    start = time.perf_counter_ns()
    if _ENABLED:
        import jax.profiler

        with jax.profiler.TraceAnnotation(name):
            try:
                yield
            finally:
                if metric is not None:
                    metric.add(time.perf_counter_ns() - start)
    else:
        try:
            yield
        finally:
            if metric is not None:
                metric.add(time.perf_counter_ns() - start)


class DebugRange:
    """Benchmark-facing range wrapper (reference:
    integration_tests/.../DebugRange.scala)."""

    def __init__(self, name: str):
        self._cm = trace_range(name)

    def __enter__(self):
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)
