"""TPU device operators."""


def register_rules(register_exec):
    """Register exec rules for operators implemented in this package.
    Called once by plan.overrides._register_exec_rules; grows as device
    operators land (aggregate, sort, join, exchange, window)."""
    import importlib

    for name in ("aggregate", "sort", "joins", "exchange", "window",
                 "generate", "write"):
        try:
            mod = importlib.import_module(f".{name}", __package__)
        except ModuleNotFoundError as e:
            if e.name != f"{__package__}.{name}":
                raise  # a real import failure inside the module
            continue
        reg = getattr(mod, "register", None)
        if reg is not None:
            reg(register_exec)
