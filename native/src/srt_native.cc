// Native runtime components for the TPU SQL accelerator.
//
// The reference framework leans on three native libraries (SURVEY §2.9):
// RMM (pooled device allocator), libcudf (kernels + JCudfSerialization),
// and UCX (transport).  On TPU the kernels and transport are XLA's job,
// but the *host runtime* around them is native here, as it is there:
//
//  * srt_arena_*  — first-fit address-space sub-allocator over one fixed
//    host staging block (reference: AddressSpaceAllocator.scala, the
//    backing allocator of RapidsHostMemoryStore).
//  * srt_hpq_*    — hashed priority queue: O(log n) push/pop with O(1)
//    membership/removal, the spill-victim queue (reference:
//    HashedPriorityQueue.java).
//  * srt_frame_*  — contiguous columnar batch serialization: one frame =
//    header + per-column meta + validity + data, 64-byte aligned
//    sections (reference: JCudfSerialization + the TableMeta flatbuffers
//    in format/ShuffleCommon.fbs — buffer + per-column sub-buffer meta).
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).
#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

extern "C" {

// ==========================================================================
// Arena: first-fit free-list allocator over [0, size)
// ==========================================================================
struct Arena {
  std::mutex lock;
  uint64_t size = 0;
  uint8_t* base = nullptr;     // optional real backing memory
  // offset -> length, sorted; adjacent blocks coalesced on free
  std::map<uint64_t, uint64_t> free_blocks;
  std::unordered_map<uint64_t, uint64_t> allocated;  // offset -> length
  uint64_t allocated_bytes = 0;
};

void* srt_arena_create(uint64_t size, int with_backing) {
  Arena* a = new Arena();
  a->size = size;
  a->free_blocks[0] = size;
  if (with_backing) {
    a->base = static_cast<uint8_t*>(malloc(size));
    if (a->base == nullptr) {  // caller checks srt_arena_base for NULL
      delete a;
      return nullptr;
    }
  }
  return a;
}

void srt_arena_destroy(void* h) {
  Arena* a = static_cast<Arena*>(h);
  if (a->base) free(a->base);
  delete a;
}

// Returns offset, or -1 if no free block fits.
int64_t srt_arena_alloc(void* h, uint64_t size) {
  if (size == 0) return -1;
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->lock);
  uint64_t want = (size + 63) & ~uint64_t(63);  // 64-byte aligned carve
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= want) {
      uint64_t off = it->first;
      uint64_t rest = it->second - want;
      a->free_blocks.erase(it);
      if (rest) a->free_blocks[off + want] = rest;
      a->allocated[off] = want;
      a->allocated_bytes += want;
      return static_cast<int64_t>(off);
    }
  }
  return -1;
}

int srt_arena_free(void* h, int64_t offset) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->lock);
  auto it = a->allocated.find(static_cast<uint64_t>(offset));
  if (it == a->allocated.end()) return 0;
  uint64_t off = it->first, len = it->second;
  a->allocated.erase(it);
  a->allocated_bytes -= len;
  auto next = a->free_blocks.lower_bound(off);
  // coalesce with next block
  if (next != a->free_blocks.end() && next->first == off + len) {
    len += next->second;
    next = a->free_blocks.erase(next);
  }
  // coalesce with previous block
  if (next != a->free_blocks.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second == off) {
      prev->second += len;
      return 1;
    }
  }
  a->free_blocks[off] = len;
  return 1;
}

uint64_t srt_arena_allocated(void* h) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->lock);
  return a->allocated_bytes;
}

uint64_t srt_arena_available(void* h) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->lock);
  uint64_t total = 0;
  for (auto& kv : a->free_blocks) total += kv.second;
  return total;
}

uint64_t srt_arena_largest_free(void* h) {
  Arena* a = static_cast<Arena*>(h);
  std::lock_guard<std::mutex> g(a->lock);
  uint64_t best = 0;
  for (auto& kv : a->free_blocks) best = std::max(best, kv.second);
  return best;
}

uint8_t* srt_arena_base(void* h) { return static_cast<Arena*>(h)->base; }

// ==========================================================================
// Hashed priority queue: min-heap + id->slot index
// ==========================================================================
struct Hpq {
  std::mutex lock;
  struct Node { int64_t id; double pri; uint64_t seq; };
  std::vector<Node> heap;                     // 0-based binary min-heap
  std::unordered_map<int64_t, size_t> slot;   // id -> heap index
  uint64_t next_seq = 0;                      // FIFO tie-break
};

static bool hpq_less(const Hpq::Node& x, const Hpq::Node& y) {
  if (x.pri != y.pri) return x.pri < y.pri;
  return x.seq < y.seq;
}

static void hpq_swap(Hpq* q, size_t i, size_t j) {
  std::swap(q->heap[i], q->heap[j]);
  q->slot[q->heap[i].id] = i;
  q->slot[q->heap[j].id] = j;
}

static void hpq_up(Hpq* q, size_t i) {
  while (i > 0) {
    size_t p = (i - 1) / 2;
    if (hpq_less(q->heap[i], q->heap[p])) { hpq_swap(q, i, p); i = p; }
    else break;
  }
}

static void hpq_down(Hpq* q, size_t i) {
  size_t n = q->heap.size();
  for (;;) {
    size_t l = 2 * i + 1, r = l + 1, m = i;
    if (l < n && hpq_less(q->heap[l], q->heap[m])) m = l;
    if (r < n && hpq_less(q->heap[r], q->heap[m])) m = r;
    if (m == i) break;
    hpq_swap(q, i, m);
    i = m;
  }
}

void* srt_hpq_create() { return new Hpq(); }
void srt_hpq_destroy(void* h) { delete static_cast<Hpq*>(h); }

// push or update-priority if present
void srt_hpq_push(void* h, int64_t id, double pri) {
  Hpq* q = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> g(q->lock);
  auto it = q->slot.find(id);
  if (it != q->slot.end()) {
    size_t i = it->second;
    q->heap[i].pri = pri;
    q->heap[i].seq = q->next_seq++;
    hpq_up(q, i);
    hpq_down(q, i);
    return;
  }
  q->heap.push_back({id, pri, q->next_seq++});
  size_t i = q->heap.size() - 1;
  q->slot[id] = i;
  hpq_up(q, i);
}

static int64_t hpq_remove_at(Hpq* q, size_t i) {
  int64_t id = q->heap[i].id;
  size_t last = q->heap.size() - 1;
  if (i != last) hpq_swap(q, i, last);
  q->heap.pop_back();
  q->slot.erase(id);
  if (i < q->heap.size()) { hpq_up(q, i); hpq_down(q, i); }
  return id;
}

int64_t srt_hpq_pop(void* h) {
  Hpq* q = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> g(q->lock);
  if (q->heap.empty()) return -1;
  return hpq_remove_at(q, 0);
}

int64_t srt_hpq_peek(void* h) {
  Hpq* q = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> g(q->lock);
  return q->heap.empty() ? -1 : q->heap[0].id;
}

int srt_hpq_remove(void* h, int64_t id) {
  Hpq* q = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> g(q->lock);
  auto it = q->slot.find(id);
  if (it == q->slot.end()) return 0;
  hpq_remove_at(q, it->second);
  return 1;
}

int srt_hpq_contains(void* h, int64_t id) {
  Hpq* q = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> g(q->lock);
  return q->slot.count(id) ? 1 : 0;
}

uint64_t srt_hpq_size(void* h) {
  Hpq* q = static_cast<Hpq*>(h);
  std::lock_guard<std::mutex> g(q->lock);
  return q->heap.size();
}

// ==========================================================================
// Columnar frame serialization
//
// Frame layout (little-endian, all sections 64-byte aligned):
//   [0]  magic  'SRTB' (u32)
//   [4]  version (u32) = 1
//   [8]  n_cols (u32)
//   [12] n_rows (u64)
//   [20] total_size (u64)
//   [28] reserved to 64
//   then per column: meta { dtype(i32), has_validity(i32),
//                           data_len(u64), validity_len(u64) }
//   then per column: validity bytes (aligned), data bytes (aligned)
// ==========================================================================
static const uint32_t kMagic = 0x42545253;  // 'SRTB'

static uint64_t align64(uint64_t x) { return (x + 63) & ~uint64_t(63); }

uint64_t srt_frame_size(uint32_t n_cols, const uint64_t* data_lens,
                        const uint64_t* valid_lens) {
  uint64_t sz = 64 + align64(uint64_t(n_cols) * 24);
  for (uint32_t i = 0; i < n_cols; ++i) {
    sz += align64(valid_lens[i]) + align64(data_lens[i]);
  }
  return sz;
}

// Writes the frame into dst (caller sized via srt_frame_size).
// Returns bytes written.
uint64_t srt_frame_write(uint8_t* dst, uint32_t n_cols, uint64_t n_rows,
                         const uint8_t** datas, const uint64_t* data_lens,
                         const uint8_t** valids, const uint64_t* valid_lens,
                         const int32_t* dtypes) {
  uint64_t total = srt_frame_size(n_cols, data_lens, valid_lens);
  memset(dst, 0, 64);
  // zero the meta-table padding so alignment gaps never leak stale bytes
  // (frames are written into reused arena carves and spilled verbatim)
  memset(dst + 64 + uint64_t(n_cols) * 24, 0,
         align64(uint64_t(n_cols) * 24) - uint64_t(n_cols) * 24);
  memcpy(dst + 0, &kMagic, 4);
  uint32_t ver = 1;
  memcpy(dst + 4, &ver, 4);
  memcpy(dst + 8, &n_cols, 4);
  memcpy(dst + 12, &n_rows, 8);
  memcpy(dst + 20, &total, 8);
  uint64_t meta_off = 64;
  uint64_t payload = 64 + align64(uint64_t(n_cols) * 24);
  for (uint32_t i = 0; i < n_cols; ++i) {
    uint8_t* m = dst + meta_off + uint64_t(i) * 24;
    int32_t has_v = valid_lens[i] ? 1 : 0;
    memcpy(m + 0, &dtypes[i], 4);
    memcpy(m + 4, &has_v, 4);
    memcpy(m + 8, &data_lens[i], 8);
    memcpy(m + 16, &valid_lens[i], 8);
  }
  for (uint32_t i = 0; i < n_cols; ++i) {
    if (valid_lens[i]) {
      memcpy(dst + payload, valids[i], valid_lens[i]);
      memset(dst + payload + valid_lens[i], 0,
             align64(valid_lens[i]) - valid_lens[i]);
      payload += align64(valid_lens[i]);
    }
    if (data_lens[i]) {
      memcpy(dst + payload, datas[i], data_lens[i]);
    }
    memset(dst + payload + data_lens[i], 0,
           align64(data_lens[i]) - data_lens[i]);
    payload += align64(data_lens[i]);
  }
  return total;
}

// Parse header: fills n_cols/n_rows/total; returns 1 if magic/version ok.
int srt_frame_header(const uint8_t* src, uint32_t* n_cols, uint64_t* n_rows,
                     uint64_t* total) {
  uint32_t magic, ver;
  memcpy(&magic, src + 0, 4);
  memcpy(&ver, src + 4, 4);
  if (magic != kMagic || ver != 1) return 0;
  memcpy(n_cols, src + 8, 4);
  memcpy(n_rows, src + 12, 8);
  memcpy(total, src + 20, 8);
  return 1;
}

// Per-column section pointers: writes per-col dtype, validity/data offsets
// (relative to src) and lengths into the out arrays.
void srt_frame_columns(const uint8_t* src, uint32_t n_cols,
                       int32_t* dtypes, uint64_t* valid_offs,
                       uint64_t* valid_lens, uint64_t* data_offs,
                       uint64_t* data_lens) {
  uint64_t payload = 64 + align64(uint64_t(n_cols) * 24);
  for (uint32_t i = 0; i < n_cols; ++i) {
    const uint8_t* m = src + 64 + uint64_t(i) * 24;
    int32_t has_v;
    memcpy(&dtypes[i], m + 0, 4);
    memcpy(&has_v, m + 4, 4);
    memcpy(&data_lens[i], m + 8, 8);
    memcpy(&valid_lens[i], m + 16, 8);
    if (has_v) {
      valid_offs[i] = payload;
      payload += align64(valid_lens[i]);
    } else {
      valid_offs[i] = 0;
    }
    data_offs[i] = payload;
    payload += align64(data_lens[i]);
  }
}

}  // extern "C"
