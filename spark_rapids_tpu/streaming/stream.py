"""Continuous-query surface: micro-batch ticks over growing sources.

``Session.stream(plan, trigger=...)`` returns a :class:`StreamHandle`.
Each tick re-discovers the scan sources (one stat pass — the same
fingerprints feed the ledger AND the recovery leaf material), pins the
discovered files into a concrete cumulative plan, merges grown
exchanges incrementally (streaming/incremental.py), and submits the
cumulative plan through the PR-11 scheduler path with the stream's
:class:`~.incremental.StreamRecoveryManager` and the per-batch deadline
(``streaming.batchDeadlineMs``) attached.  Untouched exchanges resume
from CRC-verified checkpoints; only affected partitions recompute.

Every batch result is bit-identical to a cold full recompute of the
same cumulative input — the stream never serves an "approximately
right" answer, it only saves work.  The ledger commit after the result
materializes is the exactly-once marker; a crash anywhere before it
re-runs an idempotent tick.

Triggers: ``trigger_ms > 0`` runs a daemon tick loop;
``trigger_ms == 0`` means manual ticks via :meth:`StreamHandle
.process_available` (what the deterministic tests use).
"""
from __future__ import annotations

import contextlib
import copy
import logging
import shutil
import threading
import time
from typing import Dict, List, Optional

from ..config import (STREAMING_BATCH_DEADLINE_MS, STREAMING_MAX_BATCH_FILES,
                      TELEMETRY_ENABLED)
from ..io.scans import discover_files
from ..plan import logical as L
from ..recovery.manager import resolve_root
from ..recovery.store import QUARANTINE_PREFIX, CheckpointStore
from ..scheduler import cancel as _cancel
from ..scheduler.cancel import CancelToken, TpuQueryCancelled, check_cancel
from ..telemetry import spans as tspans
from ..telemetry.events import emit_event
from ..telemetry.spans import QueryTelemetry
from ..serving.result_cache import register_stream_result
from .incremental import (StreamRecoveryManager, merge_growing_exchanges,
                          stream_fingerprint)
from .ledger import SourceLedger, split_new_files

log = logging.getLogger(__name__)


def _collect_scans(node, out: List) -> None:
    """Preorder list of the template plan's ``FileScan`` leaves —
    the positions are the ledger's source order."""
    if isinstance(node, L.FileScan):
        out.append(node)
    for c in getattr(node, "children", ()):
        _collect_scans(c, out)


def _pin_sources(node, files_per_scan: List[List[str]], pos: List[int]):
    """Rebuild the template logical plan with each ``FileScan``'s path
    list replaced by concrete discovered files (preorder-matched).
    Pinning makes the tick's plan a closed description of its input —
    a file landing mid-tick joins the NEXT batch, never a torn one."""
    if isinstance(node, L.FileScan):
        i = pos[0]
        pos[0] += 1
        return L.FileScan(node.fmt, list(files_per_scan[i]), node.schema,
                          dict(node.options))
    clone = copy.copy(node)
    clone.children = [_pin_sources(c, files_per_scan, pos)
                      for c in node.children]
    return clone


class StreamHandle:
    """One continuous query: ledger + pinned checkpoint state + ticks.

    Thread model: ticks run either on the daemon trigger thread or on
    the caller's thread via :meth:`process_available`, never both at
    once for correctness-critical state — the ledger and checkpoint
    merges happen inside the tick under ``_tick_lock``.  Consumers wait
    on :meth:`await_batch`."""

    def __init__(self, session, plan, *, trigger_ms: int,
                 priority: int = 0, tenant: str = "default"):
        conf = session.conf
        self.session = session
        self.template = plan
        self.priority = priority
        self.tenant = tenant
        self.trigger_ms = int(trigger_ms)
        self._scans: List[L.FileScan] = []
        _collect_scans(plan, self._scans)
        if not self._scans:
            raise ValueError(
                "streaming requires at least one file source "
                "(in-memory relations cannot grow)")
        for sc in self._scans:
            _files, _values, keys, _fps = discover_files(sc.paths)
            if keys:
                raise ValueError(
                    "streaming over Hive-partitioned sources is not "
                    f"supported (found partition keys {keys!r})")
        self.stream_fp = stream_fingerprint(conf, plan)
        self.stream_id = f"stream-{self.stream_fp[:12]}"
        serving = session.serving_if_enabled()
        self._ledger = SourceLedger(
            conf, self.stream_fp,
            result_cache=serving.results if serving is not None else None)
        #: True when a committed ledger from a previous process/handle
        #: was loaded — the next tick resumes instead of starting over
        self.resumed = self._ledger.load()
        self._store = CheckpointStore(resolve_root(conf))
        # the stream's aggregate state must survive TTL/maxBytes sweeps
        # for as long as this handle lives
        self._store.pin(self.stream_fp)
        self._tele = QueryTelemetry(conf, session=None,
                                    query_id=self.stream_id) \
            if conf.get(TELEMETRY_ENABLED) else None
        self.token = CancelToken()
        self._deadline_ms = int(conf.get(STREAMING_BATCH_DEADLINE_MS) or 0)
        self._max_batch_files = int(
            conf.get(STREAMING_MAX_BATCH_FILES) or 0)
        self._tick_lock = threading.Lock()
        self._cv = threading.Condition()
        self._seq = 0
        self._last = None
        self._progress: List[Dict] = []
        from ..config import TELEMETRY_HISTOGRAM_WINDOW_S
        from ..telemetry.histogram import LatencyHistogram

        #: per-batch commit latency: p50/p95/p99 in progress() and a
        #: histogram family in Session.metrics_text()
        self.latency_hist = LatencyHistogram(
            window_s=max(1, conf.get(TELEMETRY_HISTOGRAM_WINDOW_S)))
        self._stopped = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        with self._bound():
            emit_event("stream_start", stream=self.stream_id,
                       resumed=bool(self.resumed),
                       batch_id=self._ledger.batch_id,
                       trigger_ms=self.trigger_ms,
                       sources=len(self._scans))
        if self.trigger_ms > 0:
            self._thread = threading.Thread(
                target=tspans.bound(tspans.capture(), self._trigger_loop),
                name=self.stream_id, daemon=True)
            self._thread.start()

    # ----- context ---------------------------------------------------------
    @contextlib.contextmanager
    def _bound(self):
        """Bind the stream's telemetry + cancel token to the current
        thread for the duration of a tick (and restore whatever was
        bound before — process_available may run inside a caller that
        has its own query active)."""
        prev_tele = tspans.current()
        prev_token = _cancel.current()
        if self._tele is not None:
            tspans.activate(self._tele)
        _cancel.activate(self.token)
        try:
            yield
        finally:
            if prev_tele is not None:
                tspans.activate(prev_tele)
            else:
                tspans.deactivate()
            _cancel.activate(prev_token)

    # ----- trigger loop ----------------------------------------------------
    def _trigger_loop(self) -> None:
        interval = self.trigger_ms / 1000.0
        while not self._stop_evt.wait(interval):
            if self.token.cancelled():
                break
            with self._bound():
                try:
                    check_cancel("streaming.trigger")
                    self._tick()
                except TpuQueryCancelled:
                    break
                except Exception:  # noqa: BLE001 - loop survives a bad tick
                    log.warning("stream %s: tick failed — next trigger "
                                "retries", self.stream_id, exc_info=True)

    def process_available(self):
        """Run ONE tick synchronously on the caller's thread and return
        its result (None when the tick was skipped — no new files).
        Batch errors propagate to the caller.  The deterministic tests
        and ``trigger=0`` streams drive everything through this."""
        if self._stopped:
            raise RuntimeError(f"stream {self.stream_id} is stopped")
        with self._bound():
            return self._tick()

    # ----- decision helpers (lint-pinned: every skip/shed/cap decision
    # emits its stream_* event from exactly one place) ----------------------
    def _skip_tick(self, reason: str) -> None:
        emit_event("stream_tick_skip", stream=self.stream_id,
                   batch_id=self._ledger.batch_id, reason=reason)
        return None

    def _skip_incremental(self, reason: str) -> None:
        emit_event("stream_incremental_skip", stream=self.stream_id,
                   exchange="*", reason=reason)

    def _cap_batch(self, deferred: int) -> None:
        emit_event("stream_batch_capped", stream=self.stream_id,
                   batch_id=self._ledger.batch_id + 1,
                   max_batch_files=self._max_batch_files,
                   deferred_files=deferred)

    # ----- one tick --------------------------------------------------------
    def _admit(self, prev: List[List[Dict]], new: List[List[Dict]]):
        """Apply ``streaming.maxBatchFiles`` across sources in template
        order; the overflow stays undiscovered until the next tick (a
        growing backlog is drained maxBatchFiles at a time)."""
        if self._max_batch_files <= 0:
            return ([p + n for p, n in zip(prev, new)], 0)
        budget = self._max_batch_files
        admitted, deferred = [], 0
        for p, n in zip(prev, new):
            take = n[:budget] if budget > 0 else []
            budget -= len(take)
            deferred += len(n) - len(take)
            admitted.append(p + take)
        if deferred:
            self._cap_batch(deferred)
        return admitted, deferred

    def _tick(self):
        with self._tick_lock:
            return self._tick_locked()

    def _tick_locked(self):
        t0 = time.monotonic()
        check_cancel("streaming.tick")
        session, conf = self.session, self.session.conf
        cur = [discover_files(sc.paths)[3] for sc in self._scans]
        prev = self._ledger.files
        if len(prev) != len(cur):
            prev = [[] for _ in cur]
        stable, new = True, []
        for p, c in zip(prev, cur):
            ok, suffix = split_new_files(p, c)
            stable = stable and ok
            new.append(suffix if ok else [])
        if not stable:
            # a committed file was rewritten/removed: the incremental
            # contract is broken, but a full-recompute batch over the
            # CURRENT discovery is still exactly right
            self._skip_incremental("source_rewritten")
            admitted, deferred = [list(c) for c in cur], 0
            new = [[] for _ in cur]
            prev = [[] for _ in cur]
        else:
            n_new = sum(len(s) for s in new)
            if n_new == 0:
                if self._ledger.batch_id > 0:
                    return self._skip_tick("no_new_files")
                if sum(len(c) for c in cur) == 0:
                    return self._skip_tick("no_files")
            admitted, deferred = self._admit(prev, new)
        batch_id = self._ledger.batch_id + 1
        paths = [[fp["path"] for fp in fps] for fps in admitted]
        cum_plan = _pin_sources(self.template, paths, [0])
        mgr = StreamRecoveryManager(conf, self.stream_fp)
        mgr.attach_query(cum_plan)
        if mgr.query_fp is None:
            mgr = None
        merged = 0
        if mgr is not None and stable and self._ledger.batch_id > 0 \
                and self._ledger.exchanges:
            # cumulative file tuple -> that source's new-file suffix:
            # how the merge locates each exchange subtree's delta
            new_by_cum = {
                tuple(ps): [fp["path"] for fp in fps[len(p):]]
                for ps, fps, p in zip(paths, admitted, prev)}
            try:
                merged = merge_growing_exchanges(
                    mgr, new_by_cum, self._ledger.exchanges)
            except TpuQueryCancelled:
                raise
            except Exception as e:  # noqa: BLE001 - recompute, never fail
                self._skip_incremental(f"{type(e).__name__}: {e}")
        emit_event("stream_batch_start", stream=self.stream_id,
                   batch_id=batch_id,
                   files_new=sum(len(s) for s in new),
                   files_total=sum(len(a) for a in admitted),
                   merged_exchanges=merged)
        check_cancel("streaming.submit")
        try:
            handle = session.scheduler.submit(
                cum_plan, priority=self.priority, tenant=self.tenant,
                recovery=mgr, deadline_ms=self._deadline_ms or None)
            out = handle.result()
        except BaseException as e:
            # deadline miss / preemption / execution failure: the
            # ledger did NOT advance, so the next tick retries the same
            # cumulative input — committed state is untouched
            emit_event("stream_batch_error", stream=self.stream_id,
                       batch_id=batch_id, error=type(e).__name__,
                       reason=str(e))
            with self._cv:
                self._last = ("err", e)
                self._seq += 1
                self._cv.notify_all()
            raise
        stamped = mgr.stamped_total if mgr is not None else 0
        resumed = int(handle.metrics.get(
            "recovery.numStagesResumed", 0)) if mgr is not None else 0
        fraction = 1.0 if stamped <= 0 \
            else max(0.0, 1.0 - resumed / stamped)
        self._ledger.commit(batch_id, admitted,
                            mgr.exchange_fps if mgr is not None else {})
        # register the committed tick's materialized result with the
        # serving result cache (serving/ owns policy + cache_* events):
        # an ad-hoc submit() of the same cumulative query between ticks
        # fingerprints to this exact (plan, data) identity and hits
        register_stream_result(session, cum_plan, out)
        latency_ms = (time.monotonic() - t0) * 1000.0
        self.latency_hist.observe(latency_ms)
        emit_event("stream_batch_commit", stream=self.stream_id,
                   batch_id=batch_id, latency_ms=round(latency_ms, 3),
                   stages_resumed=resumed, stages_total=stamped,
                   merged_exchanges=merged,
                   recompute_fraction=round(fraction, 4))
        if mgr is not None:
            self._gc_superseded(set(mgr.exchange_fps.values()))
        prog = {
            "streaming.batchId": batch_id,
            "streaming.filesNew": sum(len(s) for s in new),
            "streaming.filesTotal": sum(len(a) for a in admitted),
            "streaming.batchLatencyMs": round(latency_ms, 3),
            "streaming.stagesResumed": resumed,
            "streaming.stagesTotal": stamped,
            "streaming.mergedExchanges": merged,
            "streaming.recomputeFraction": round(fraction, 4),
            "streaming.backlogFiles": deferred,
        }
        for p, v in self.latency_hist.percentiles().items():
            prog[f"streaming.batchLatency{p.capitalize()}Ms"] = round(v, 3)
        with self._cv:
            self._progress.append(prog)
            self._last = ("ok", out)
            self._seq += 1
            self._cv.notify_all()
        return out

    def _gc_superseded(self, keep: set) -> None:
        """Drop checkpoints of exchange fingerprints the latest commit
        superseded (a stream would otherwise accrete one generation per
        tick inside its pinned — unsweepable — query dir).  Quarantined
        dirs are left for the post-mortem sweep.  Never raises."""
        qdir = self._store.query_dir(self.stream_fp)
        try:
            import os

            for name in os.listdir(qdir):
                if name in keep or name.startswith(QUARANTINE_PREFIX):
                    continue
                shutil.rmtree(os.path.join(qdir, name),
                              ignore_errors=True)
        except OSError:
            pass

    # ----- consumer surface ------------------------------------------------
    def await_batch(self, timeout: Optional[float] = None):
        """Block until a tick COMMITS a batch after this call (or one
        errors) and return/raise its outcome."""
        with self._cv:
            seen = self._seq
            ok = self._cv.wait_for(
                lambda: self._seq > seen or self._stopped, timeout)
            if not ok:
                raise TimeoutError(
                    f"stream {self.stream_id}: no batch within "
                    f"{timeout}s")
            if self._seq == seen:
                raise RuntimeError(
                    f"stream {self.stream_id} stopped before a batch")
            kind, payload = self._last
        if kind == "err":
            raise payload
        return payload

    def progress(self) -> Dict:
        """The latest committed batch's progress metrics
        (``streaming.*`` keys; empty before the first commit)."""
        with self._cv:
            return dict(self._progress[-1]) if self._progress else {}

    def progress_history(self) -> List[Dict]:
        with self._cv:
            return [dict(p) for p in self._progress]

    def events(self) -> List[Dict]:
        """Snapshot of the stream's event ring (``stream_*`` lifecycle
        plus checkpoint/merge events emitted inside ticks)."""
        return self._tele.events.snapshot() if self._tele else []

    def stop(self) -> None:
        """Stop the stream: cancel any in-flight tick cooperatively,
        join the trigger thread, unpin the checkpoint state (hygiene
        sweeps may reclaim it afterwards).  Idempotent."""
        if self._stopped:
            return
        self._stopped = True
        self._stop_evt.set()
        self.token.cancel("stream stopped")
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=30)
        self._store.unpin(self.stream_fp)
        prev = tspans.current()
        if self._tele is not None:
            tspans.activate(self._tele)
        emit_event("stream_stop", stream=self.stream_id,
                   batch_id=self._ledger.batch_id)
        if prev is not None:
            tspans.activate(prev)
        else:
            tspans.deactivate()
        with self._cv:
            self._cv.notify_all()
