"""Recovery policy: fingerprints, resume validation, checkpoint writes.

One :class:`RecoveryManager` serves one top-level query END TO END —
``Session.execute``/``Session.resume`` create it before the degradation
ladder and thread it through every rung, so resume counters accumulate
across the device, host-shuffle and CPU rungs (a rung that resumes 2
checkpointed exchanges reports ``recovery.numStagesResumed=2`` even if
the previous rung wrote them).

Fingerprints are derived from the HOST physical plan, which is
rung-invariant by construction: ``Planner(conf).plan(optimize(plan))``
is both the pre-override plan of the native path and exactly what
``cpu_exec_plan`` re-plans on the bottom rung, and the TPU exchange
keeps its originating host exchange node (``TpuShuffleExchangeExec
.plan``).  The query fingerprint additionally folds in leaf DATA
identity (content checksums of in-memory batches, path+size+mtime of
scanned files) — two same-shape plans over different data must never
fingerprint-match, or resume would serve the wrong rows.

Validation is paranoid on purpose: a checkpoint failing ANY check
(plan fingerprint, schema signature, result-affecting conf snapshot,
frame CRC, manifest shape) is quarantined — renamed aside with a
``checkpoint_quarantine`` event — and the exchange re-executes from
scratch.  Wrong answers are not an outcome; at worst, recovery buys
nothing.

No jax in this module (lint-enforced): everything here is host policy
over numpy frames and JSON.
"""
from __future__ import annotations

import hashlib
import logging
import os
import signal
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import (RECOVERY_AUTO_RESUME, RECOVERY_DIR,
                      RECOVERY_ENABLED, RECOVERY_KILL_AFTER_CHECKPOINTS,
                      RECOVERY_MAX_BYTES, RECOVERY_TTL_SECONDS)
from ..telemetry.events import emit_event
from .store import CheckpointStore

log = logging.getLogger(__name__)

#: conf keys whose value changes the RESULT a plan produces — a
#: checkpoint taken under different values must not be resumed (the
#: re-executed suffix would combine data from two semantics)
RESULT_CONF_KEYS = (
    "spark.rapids.tpu.sql.enabled",
    "spark.rapids.tpu.sql.incompatibleOps.enabled",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled",
    "spark.rapids.tpu.sql.castStringToInteger.enabled",
    "spark.rapids.tpu.sql.castStringToFloat.enabled",
    "spark.rapids.tpu.sql.castStringToTimestamp.enabled",
)

#: exchange node types that carry checkpoints (the TPU exec and its
#: host analogue — matched by name so this module imports neither)
_EXCHANGE_TYPE_NAMES = ("TpuShuffleExchangeExec", "ShuffleExchangeExec")


def resolve_root(conf) -> str:
    d = conf.get(RECOVERY_DIR)
    if d:
        return d
    return os.path.join(tempfile.gettempdir(), "srt-recovery")


def schema_signature(schema) -> List[str]:
    """Stable textual signature of an exchange's output schema
    (``name:dtype[ not null]`` per field) — JSON-safe, order-sensitive."""
    return [repr(f) for f in schema.fields]


def _digest(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:24]


def file_material(fp: Dict) -> str:
    """One file-fingerprint record rendered into the fingerprint
    material string (shared between query and streaming fingerprints)."""
    if int(fp.get("size", -1)) < 0:
        return f"file:{fp.get('path')}:?"
    return (f"file:{fp['path']}:{int(fp['size'])}:"
            f"{int(fp['mtime_ns'])}")


def _leaf_material(node, out: List[str]) -> None:
    """Collect leaf DATA identity in preorder: content checksums for
    in-memory relations (``.batches``), path+size+mtime for file scans
    — duck-typed so io/ scan execs need no registration.  Scan execs
    expose ``file_fingerprints`` captured during discovery (a single
    stat pass shared with the streaming ledger); the ``.files`` stat
    fallback remains for exec-like objects without them."""
    batches = getattr(node, "batches", None)
    if batches is not None:
        from ..fault.integrity import checksum_host_batch

        for b in batches:
            out.append(f"batch:{checksum_host_batch(b)}")
    fingerprints = getattr(node, "file_fingerprints", None)
    files = getattr(node, "files", None)
    if isinstance(fingerprints, (list, tuple)) and fingerprints:
        for fp in fingerprints:
            out.append(file_material(fp))
    elif isinstance(files, (list, tuple)):
        for p in files:
            try:
                st = os.stat(p)
                out.append(f"file:{p}:{st.st_size}:{st.st_mtime_ns}")
            except (OSError, TypeError):
                out.append(f"file:{p}:?")
    for c in getattr(node, "children", ()):
        _leaf_material(c, out)


def plan_fingerprints(conf, plan) -> Tuple[Optional[object], Optional[str],
                                           Optional[str], List[str]]:
    """THE query-fingerprint computation, shared by every consumer —
    ``RecoveryManager.attach_query``, the serving plan-template cache
    and the serving result cache (serving/) all call this one helper so
    their fingerprints can never drift apart.

    Returns ``(host_phys, plan_fp, query_fp, material)``:

    * ``host_phys`` — the rung-invariant HOST physical plan
      (``Planner(conf).plan(optimize(plan))``),
    * ``plan_fp`` — digest of the host plan tree alone (data-independent
      — the result-cache manifest records it separately so a stale hit
      can name WHICH identity diverged),
    * ``query_fp`` — digest of the plan tree plus leaf DATA identity
      (content checksums of in-memory batches, path+size+mtime_ns of
      scanned files from the scans.py discovery stat pass),
    * ``material`` — the per-leaf identity strings the data half was
      derived from (the result cache revalidates these against the
      live sources before serving a frame).

    Returns ``(None, None, None, [])`` for nondeterministic plans —
    neither recovery nor any cache may fingerprint a plan whose two
    executions can legitimately disagree.  Raises on planner failure;
    callers that must never fail a query wrap it."""
    from ..adaptive.executor import _has_nondeterministic
    from ..plan.optimizer import optimize
    from ..plan.planner import Planner

    host_phys = Planner(conf).plan(optimize(plan))
    if _has_nondeterministic(host_phys):
        return None, None, None, []
    material: List[str] = []
    _leaf_material(host_phys, material)
    tree = host_phys.tree_string()
    plan_fp = _digest(tree)
    query_fp = _digest(tree + "\n" + "\n".join(material))
    return host_phys, plan_fp, query_fp, material


def _exchange_key(node) -> Optional[str]:
    """The rung-invariant subtree string of an exchange node, or None
    for non-exchange nodes.  The TPU exec fingerprints via its
    ORIGINATING host exchange (``.plan`` — overrides keep the host
    subtree intact underneath), the host exec via itself."""
    name = type(node).__name__
    if name not in _EXCHANGE_TYPE_NAMES:
        return None
    host = getattr(node, "plan", None)
    target = host if host is not None else node
    return target.tree_string()


class RecoveryManager:
    """Per-query checkpoint/resume policy (driver-thread discipline)."""

    def __init__(self, conf, *, force_resume: bool = False):
        self.conf = conf
        enabled = bool(conf.get(RECOVERY_ENABLED))
        #: checkpoint WRITES allowed (dropped on ENOSPC/any write error)
        self.write_enabled = enabled
        #: checkpoint READS allowed (``Session.resume`` forces them on
        #: even when ``recovery.autoResume`` is off)
        self.resume_enabled = enabled and (
            force_resume or bool(conf.get(RECOVERY_AUTO_RESUME)))
        self.store = CheckpointStore(resolve_root(conf))
        self.query_fp: Optional[str] = None
        self._conf_snapshot = {
            k: repr(conf.get_key(k)) for k in RESULT_CONF_KEYS}
        self._kill_after = int(
            conf.get(RECOVERY_KILL_AFTER_CHECKPOINTS) or 0)
        #: exchange fps THIS query checkpointed — a later ladder rung of
        #: the same query may always resume them, independent of
        #: ``recovery.autoResume`` (which governs cross-process resume)
        self._own_checkpoints: set = set()
        self._writes = 0
        self._counters = {"numStagesResumed": 0,
                          "numCheckpointsWritten": 0,
                          "checkpointBytes": 0,
                          "numQuarantined": 0}

    # ----- fingerprints ----------------------------------------------------
    def attach_query(self, plan) -> None:
        """Fingerprint the query from its HOST physical plan + leaf data
        identity and remember it for every later stamp/resume/write.
        Nondeterministic plans decline recovery entirely (a resumed
        prefix and a re-executed suffix would disagree on rand() and
        friends).  Never fails the query."""
        if not (self.write_enabled or self.resume_enabled):
            return
        try:
            _, _, query_fp, _ = plan_fingerprints(self.conf, plan)
            if query_fp is None:
                log.debug("recovery declined: nondeterministic plan")
                self.write_enabled = self.resume_enabled = False
                return
            self.query_fp = query_fp
        except Exception:  # noqa: BLE001 - recovery must never fail a query
            log.warning("recovery disabled: query fingerprint failed",
                        exc_info=True)
            self.write_enabled = self.resume_enabled = False

    def stamp_plan(self, phys) -> int:
        """Preorder walk stamping ``_recovery_fp`` on every exchange
        node: sha256 of the host exchange subtree string plus its
        occurrence index (identical subtrees — self-joins — stay
        distinct, and the preorder position is rung-invariant because
        every rung plans the same host tree shape).  Idempotent; copies
        made by ``with_new_children`` inherit the attribute."""
        if self.query_fp is None:
            return 0
        seen: Dict[str, int] = {}
        stamped = 0

        def visit(node):
            nonlocal stamped
            key = _exchange_key(node)
            if key is not None:
                idx = seen.get(key, 0)
                seen[key] = idx + 1
                node._recovery_fp = _digest(f"{key}#{idx}")
                stamped += 1
            for c in getattr(node, "children", ()):
                visit(c)

        visit(phys)
        return stamped

    # ----- resume ----------------------------------------------------------
    def try_resume(self, exchange_fp: str, *, n_out: Optional[int],
                   schema_sig: List[str]
                   ) -> Optional[Tuple[Dict, List[List[np.ndarray]]]]:
        """Return ``(manifest, frames_per_partition)`` when a VALID
        checkpoint exists for this exchange, else None.  Every frame is
        CRC-verified here, eagerly — after this returns non-None the
        caller skips the exchange's child entirely, so there is no
        later fallback point.  Any invalidity quarantines the
        checkpoint (event + rename aside) and returns None: full
        re-execution, never a wrong answer.

        ``n_out=None`` is the fan-out WILDCARD for elastic resume on a
        different-size mesh (the shrunken-mesh rung): the manifest's
        own partition count is accepted and the caller re-maps the
        checkpointed partitions onto its mesh."""
        if self.query_fp is None:
            return None
        if not self.resume_enabled \
                and exchange_fp not in self._own_checkpoints:
            return None
        d = self.store.exchange_dir(self.query_fp, exchange_fp)
        if not os.path.isfile(os.path.join(d, "manifest.json")):
            return None
        try:
            m = self.store.read_manifest(d)
            if m.get("plan_fingerprint") != exchange_fp:
                raise ValueError(
                    "stale plan fingerprint: manifest "
                    f"{m.get('plan_fingerprint')!r} != {exchange_fp!r}")
            if m.get("query_fingerprint") != self.query_fp:
                raise ValueError("query fingerprint mismatch")
            if m.get("schema") != list(schema_sig):
                raise ValueError("schema signature mismatch")
            load_n = int(m.get("n_out", -1))
            if load_n < 0:
                raise ValueError("manifest missing n_out")
            if n_out is not None and load_n != int(n_out):
                raise ValueError(
                    f"fan-out mismatch: {m.get('n_out')} != {n_out}")
            if m.get("conf") != self._conf_snapshot:
                raise ValueError(
                    "result-affecting conf changed since checkpoint: "
                    f"{m.get('conf')} != {self._conf_snapshot}")
            frames = self.store.load_frames(d, m, load_n)
        except Exception as e:  # noqa: BLE001 - ANY doubt quarantines
            moved = self.store.quarantine(d)
            self._counters["numQuarantined"] += 1
            emit_event("checkpoint_quarantine", exchange=exchange_fp,
                       reason=f"{type(e).__name__}: {e}",
                       quarantined_to=moved or "")
            log.warning(
                "checkpoint for exchange %s quarantined (%s: %s) — "
                "re-executing from scratch", exchange_fp,
                type(e).__name__, e)
            return None
        self._counters["numStagesResumed"] += 1
        emit_event("checkpoint_resume", exchange=exchange_fp,
                   partitions=load_n,
                   rows=int(m.get("total_rows", 0)),
                   bytes=int(m.get("total_bytes", 0)))
        return m, frames

    # ----- checkpoint writes -----------------------------------------------
    def should_checkpoint(self, exchange_fp: str) -> bool:
        return (self.write_enabled and self.query_fp is not None
                and not self.store.has_manifest(self.query_fp,
                                                exchange_fp))

    def checkpoint_exchange(self, exchange_fp: str, *,
                            schema_sig: List[str], n_out: int,
                            part_rows: List[int], total_bytes: int,
                            partitioning: str,
                            frames: List[List[Tuple[np.ndarray, int]]]
                            ) -> int:
        """Persist one completed exchange; returns frame bytes written
        (0 when skipped or failed).  A write failure — ENOSPC, a dying
        disk, anything — disables checkpointing for the rest of the
        query with a ``checkpoint_disabled`` event and lets the query
        run on; checkpointing is an optimization, never a failure
        mode."""
        if not self.should_checkpoint(exchange_fp):
            return 0
        total_rows = int(sum(int(r) for r in part_rows))
        manifest = {
            "query_fingerprint": self.query_fp,
            "plan_fingerprint": exchange_fp,
            "schema": list(schema_sig),
            "n_out": int(n_out),
            "part_rows": [int(r) for r in part_rows],
            "total_rows": total_rows,
            "total_bytes": int(total_bytes),
            "partitioning": partitioning,
            "conf": dict(self._conf_snapshot),
        }
        try:
            written = self.store.write_exchange(
                self.query_fp, exchange_fp, manifest, frames)
        except OSError as e:
            self.disable(f"checkpoint write failed "
                         f"({type(e).__name__}: {e})")
            return 0
        except Exception as e:  # noqa: BLE001 - never fail the query
            self.disable(f"checkpoint write failed "
                         f"({type(e).__name__}: {e})")
            return 0
        self._writes += 1
        self._own_checkpoints.add(exchange_fp)
        self._counters["numCheckpointsWritten"] += 1
        self._counters["checkpointBytes"] += written
        emit_event("checkpoint_write", exchange=exchange_fp,
                   partitions=n_out, rows=total_rows, bytes=written)
        if self._kill_after > 0 and self._writes >= self._kill_after:
            # crash-drill hook (internal conf): die HARD right after
            # the checkpoint committed, like a real power-cut
            log.warning("recovery.killAfterCheckpoints=%d reached — "
                        "SIGKILL", self._kill_after)
            os.kill(os.getpid(), signal.SIGKILL)
        return written

    def disable(self, reason: str) -> None:
        """Turn off checkpoint WRITES for the rest of the query (reads
        stay valid — existing checkpoints are untouched)."""
        if not self.write_enabled:
            return
        self.write_enabled = False
        emit_event("checkpoint_disabled", reason=reason)
        log.warning("checkpointing disabled for this query: %s", reason)

    # ----- surfaces --------------------------------------------------------
    def metrics(self) -> Dict[str, int]:
        return {f"recovery.{k}": v for k, v in self._counters.items()}

    def sweep(self) -> Dict[str, int]:
        return self.store.sweep(
            ttl_seconds=int(self.conf.get(RECOVERY_TTL_SECONDS) or 0),
            max_bytes=int(self.conf.get(RECOVERY_MAX_BYTES) or 0))


def sweep_recovery_dir(conf) -> Dict[str, int]:
    """Hygiene sweep of the recovery root for ``Session.close()`` and
    scheduler shutdown: crash-orphaned temp files, expired query dirs
    (``recovery.ttlSeconds``), LRU eviction over ``recovery.maxBytes``.
    Cheap no-op when the root does not exist; never raises."""
    root = resolve_root(conf)
    if not os.path.isdir(root):
        return {"removedTmpFiles": 0, "removedQueryDirs": 0}
    return CheckpointStore(root).sweep(
        ttl_seconds=int(conf.get(RECOVERY_TTL_SECONDS) or 0),
        max_bytes=int(conf.get(RECOVERY_MAX_BYTES) or 0))
