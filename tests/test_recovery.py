"""Stage-level checkpointing & crash recovery (spark_rapids_tpu/recovery/).

The central invariants:

* a query with ``recovery.enabled`` persists each completed exchange as
  a CRC32C-stamped checkpoint under ``recovery.dir``, and a later
  execution of the SAME query (same plan, same data, same
  result-affecting conf) resumes from it — bit-identical results with
  ``recovery.numStagesResumed`` > 0;
* resume validation is paranoid: a flipped frame byte, a stale plan
  fingerprint, or a changed result-affecting conf each quarantine the
  checkpoint (``checkpoint_quarantine`` event) and the query re-executes
  from scratch — a bad checkpoint can cost time, never correctness;
* a SIGKILLed process (crash drill via ``recovery.killAfterCheckpoints``)
  leaves checkpoints a FRESH process resumes through ``Session.resume``;
* the degradation ladder's rungs reuse the failed rungs' checkpoints;
* ENOSPC on checkpoint writes disables checkpointing gracefully; on
  spill writes it surfaces as typed retryable ``TpuStorageExhausted``;
* ``fault.maxTotalAttempts`` is one attempt ceiling across every retry
  mechanism, exhausted with ONE terminal event carrying the ledger.
"""
import errno
import json
import os
import subprocess
import sys
import textwrap

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.fault.budget import AttemptBudgetExhausted
from spark_rapids_tpu.fault.errors import (TpuFaultError,
                                           TpuStorageExhausted)
from spark_rapids_tpu.plan import functions as F

FAST = {
    "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
    "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
}


def _conf(root, **extra):
    conf = dict(FAST)
    conf.update({
        "spark.rapids.tpu.recovery.enabled": True,
        "spark.rapids.tpu.recovery.dir": str(root),
        "spark.rapids.tpu.telemetry.enabled": True,
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
    })
    conf.update(extra)
    return conf


def _query(sess):
    """A deterministic 2-table join + aggregate: multiple shuffle
    exchanges, so partial-checkpoint scenarios exist."""
    import numpy as np

    rng = np.random.RandomState(11)
    orders = {"o_custkey": rng.randint(0, 40, 300).tolist(),
              "o_total": rng.rand(300).round(6).tolist()}
    cust = {"c_custkey": list(range(40)),
            "c_nation": rng.randint(0, 5, 40).tolist()}
    o = sess.create_dataframe(orders, n_partitions=3)
    c = sess.create_dataframe(cust, n_partitions=2)
    j = o.join(c, on=(["o_custkey"], ["c_custkey"]), how="inner")
    return j.group_by("c_nation").agg(
        F.sum("o_total").alias("rev"), F.count("o_total").alias("n"))


def _norm(rows):
    return sorted(
        (tuple((None if v is None else
                (round(v, 9) if isinstance(v, float) else v))
               for v in r) for r in rows),
        key=repr)


def _batch_rows(hb):
    return _norm(zip(*[c.to_pylist() for c in hb.columns]))


def _events(sess, etype):
    prof = sess.last_profile
    assert prof is not None, "telemetry must be on for event asserts"
    return [e for e in prof.events.snapshot() if e["event"] == etype]


def _exchange_dirs(root):
    out = []
    for q in os.listdir(root):
        qd = os.path.join(root, q)
        if not os.path.isdir(qd):
            continue
        for e in os.listdir(qd):
            if not e.startswith("quarantine-"):
                out.append(os.path.join(qd, e))
    return out


# ==========================================================================
# Checkpoint write + resume
# ==========================================================================
def test_checkpoint_write_then_cross_session_resume(tmp_path):
    sess = srt.Session(_conf(tmp_path))
    want = _norm(_query(sess).collect())
    m = sess.last_metrics
    assert m.get("recovery.numCheckpointsWritten", 0) >= 1, m
    assert m.get("recovery.checkpointBytes", 0) > 0
    assert m.get("shuffle.checkpointBytes", 0) > 0  # delta counter
    assert _events(sess, "checkpoint_write")
    for d in _exchange_dirs(tmp_path):
        assert os.path.isfile(os.path.join(d, "manifest.json"))

    sess2 = srt.Session(_conf(tmp_path))
    got = _batch_rows(sess2.resume(_query(sess2).plan))
    assert got == want
    m2 = sess2.last_metrics
    assert m2.get("recovery.numStagesResumed", 0) >= 1, m2
    assert m2.get("recovery.numQuarantined", 0) == 0
    assert _events(sess2, "checkpoint_resume")
    # a resumed query must be visibly resumed in the profile
    assert "resumedFromStage=" in sess2.profile_report()


def test_auto_resume_on_plain_execute(tmp_path):
    """``recovery.autoResume`` (default on) resumes through plain
    ``execute`` too — ``Session.resume`` is only needed to force it."""
    sess = srt.Session(_conf(tmp_path))
    want = _norm(_query(sess).collect())
    sess2 = srt.Session(_conf(tmp_path))
    got = _norm(_query(sess2).collect())
    assert got == want
    assert sess2.last_metrics.get("recovery.numStagesResumed", 0) >= 1


def test_auto_resume_off_reexecutes(tmp_path):
    sess = srt.Session(_conf(tmp_path))
    want = _norm(_query(sess).collect())
    off = _conf(tmp_path,
                **{"spark.rapids.tpu.recovery.autoResume": False})
    sess2 = srt.Session(off)
    got = _norm(_query(sess2).collect())
    assert got == want
    assert sess2.last_metrics.get("recovery.numStagesResumed", 0) == 0
    # but an explicit resume() overrides autoResume=false
    sess3 = srt.Session(off)
    assert _batch_rows(sess3.resume(_query(sess3).plan)) == want
    assert sess3.last_metrics.get("recovery.numStagesResumed", 0) >= 1


def test_partial_checkpoint_without_manifest_is_ignored(tmp_path):
    """Frames without a manifest (a crash mid-checkpoint) are not a
    checkpoint at all: the manifest is the commit marker.  No resume,
    no quarantine — the fresh run simply writes its own."""
    sess = srt.Session(_conf(tmp_path))
    want = _norm(_query(sess).collect())
    for d in _exchange_dirs(tmp_path):
        os.unlink(os.path.join(d, "manifest.json"))
    sess2 = srt.Session(_conf(tmp_path))
    got = _norm(_query(sess2).collect())
    assert got == want
    m2 = sess2.last_metrics
    assert m2.get("recovery.numStagesResumed", 0) == 0
    assert m2.get("recovery.numQuarantined", 0) == 0
    assert m2.get("recovery.numCheckpointsWritten", 0) >= 1


# ==========================================================================
# Quarantine: corrupt / stale / conf-mismatch checkpoints
# ==========================================================================
def _flip_byte(path, offset=10):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def test_quarantine_on_flipped_frame_byte(tmp_path):
    sess = srt.Session(_conf(tmp_path))
    want = _norm(_query(sess).collect())
    d = _exchange_dirs(tmp_path)[0]
    frame = sorted(f for f in os.listdir(d) if f.endswith(".srtb"))[0]
    _flip_byte(os.path.join(d, frame))

    sess2 = srt.Session(_conf(tmp_path))
    got = _batch_rows(sess2.resume(_query(sess2).plan))
    assert got == want  # never a wrong answer
    m2 = sess2.last_metrics
    assert m2.get("recovery.numQuarantined", 0) >= 1, m2
    ev = _events(sess2, "checkpoint_quarantine")
    assert ev and "TpuPayloadCorruption" in ev[0]["reason"]
    # renamed aside, and the fresh run re-checkpointed in its place
    qd = os.path.dirname(d)
    assert any(n.startswith("quarantine-") for n in os.listdir(qd))


def test_quarantine_on_stale_plan_fingerprint(tmp_path):
    sess = srt.Session(_conf(tmp_path))
    want = _norm(_query(sess).collect())
    d = _exchange_dirs(tmp_path)[0]
    mpath = os.path.join(d, "manifest.json")
    with open(mpath) as f:
        m = json.load(f)
    m["plan_fingerprint"] = "0" * 24  # a different plan's checkpoint
    with open(mpath, "w") as f:
        json.dump(m, f)

    sess2 = srt.Session(_conf(tmp_path))
    got = _batch_rows(sess2.resume(_query(sess2).plan))
    assert got == want
    ev = _events(sess2, "checkpoint_quarantine")
    assert ev and "stale plan fingerprint" in ev[0]["reason"]


def test_quarantine_on_changed_result_conf(tmp_path):
    sess = srt.Session(_conf(tmp_path))
    want_default = _norm(_query(sess).collect())
    assert sess.last_metrics.get("recovery.numCheckpointsWritten", 0)
    # flip a result-affecting key: the checkpoint's conf snapshot no
    # longer matches, so it must NOT be resumed
    from spark_rapids_tpu.config import TpuConf

    default = TpuConf({}).get_key(
        "spark.rapids.tpu.sql.variableFloatAgg.enabled")
    changed = _conf(tmp_path, **{
        "spark.rapids.tpu.sql.variableFloatAgg.enabled": not default})
    sess2 = srt.Session(changed)
    got = _batch_rows(sess2.resume(_query(sess2).plan))
    assert got == want_default  # float agg still matches at 1e-9 here
    m2 = sess2.last_metrics
    assert m2.get("recovery.numStagesResumed", 0) == 0, m2
    assert m2.get("recovery.numQuarantined", 0) >= 1, m2
    ev = _events(sess2, "checkpoint_quarantine")
    assert ev and "conf changed" in ev[0]["reason"]


def test_changed_input_data_changes_query_fingerprint(tmp_path):
    """Same plan SHAPE over different data must not fingerprint-match —
    resume would serve the wrong rows."""
    sess = srt.Session(_conf(tmp_path))
    df = sess.create_dataframe({"k": [1, 2, 1, 2], "v": [1, 2, 3, 4]},
                               n_partitions=2)
    df.group_by("k").agg(F.sum("v").alias("s")).collect()
    fps = set(os.listdir(tmp_path))
    sess2 = srt.Session(_conf(tmp_path))
    df2 = sess2.create_dataframe({"k": [1, 2, 1, 2], "v": [9, 8, 7, 6]},
                                 n_partitions=2)
    rows = _norm(
        df2.group_by("k").agg(F.sum("v").alias("s")).collect())
    assert rows == _norm([(1, 16), (2, 14)])
    assert sess2.last_metrics.get("recovery.numStagesResumed", 0) == 0
    assert set(os.listdir(tmp_path)) - fps  # a NEW query dir appeared


# ==========================================================================
# Ladder rungs + retries reuse checkpoints
# ==========================================================================
@pytest.mark.fault_injection
def test_ladder_rungs_resume_from_checkpoints(tmp_path):
    """``stage_crash`` at exchange.read with no task retries walks the
    ladder; each rung resumes the exchanges the previous rungs already
    checkpointed, and the final result is bit-identical to the CPU
    oracle with ``recovery.numStagesResumed`` > 0."""
    oracle = _norm(_query(srt.Session(tpu_enabled=False)).collect())
    conf = _conf(tmp_path, **{
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "stage_crash",
        "spark.rapids.tpu.fault.injection.site": "exchange.read",
        "spark.rapids.tpu.fault.injection.skipCount": 0,
        "spark.rapids.tpu.sql.taskRetries": 0,
    })
    sess = srt.Session(conf)
    got = _norm(_query(sess).collect())
    assert got == oracle
    m = sess.last_metrics
    assert m.get("recovery.numStagesResumed", 0) >= 1, m
    assert m.get("fault.degradeLevel", 0) >= 1, m


@pytest.mark.fault_injection
@pytest.mark.parametrize("qnum", [1, 3, 5, 6, 16])
def test_tpch_ladder_under_crash_injection_reuses_checkpoints(
        qnum, tmp_path):
    """The acceptance drill on real queries: TPC-H under stage_crash
    injection at the exchange read with no task retries — the ladder
    climbs, later rungs reuse the checkpoints earlier rungs committed,
    and the answer matches the CPU oracle bit-for-bit."""
    from spark_rapids_tpu.benchmarks import tpch, tpch_datagen
    from spark_rapids_tpu.testing.asserts import assert_rows_equal

    def _run(sess):
        tables = tpch_datagen.dataframes(sess, sf=0.0007, seed=7)
        return tpch.QUERIES[qnum](tables).collect()

    oracle = _run(srt.Session(tpu_enabled=False))
    conf = _conf(tmp_path, **{
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "stage_crash",
        "spark.rapids.tpu.fault.injection.site": "exchange.read",
        "spark.rapids.tpu.fault.injection.skipCount": 0,
        "spark.rapids.tpu.sql.taskRetries": 0,
    })
    sess = srt.Session(conf)
    got = _run(sess)
    assert_rows_equal(oracle, got, ignore_order=True,
                      approximate_float=1e-6)
    m = sess.last_metrics
    if m.get("fault.degradeLevel", 0) > 0:
        # the crash fired AFTER an exchange materialized (read side),
        # so a checkpoint existed — the next rung must have used it
        assert m.get("recovery.numStagesResumed", 0) >= 1, (qnum, m)
    if qnum in (3, 5, 16):  # join queries: the read crash must fire
        assert m.get("fault.degradeLevel", 0) > 0, (qnum, m)


@pytest.mark.fault_injection
def test_corrupt_injection_with_recovery_stays_bit_identical(tmp_path):
    """A corruption drill on the exchange write path composes with
    checkpointing: lineage recompute + ladder still produce the
    injection-free answer."""
    clean = _norm(_query(srt.Session(dict(
        FAST, **{"spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
                 "spark.rapids.tpu.sql.taskRetries": 3}))).collect())
    conf = _conf(tmp_path, **{
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "corrupt",
        "spark.rapids.tpu.fault.injection.site": "exchange.write",
        "spark.rapids.tpu.sql.taskRetries": 3,
    })
    sess = srt.Session(conf)
    got = _norm(_query(sess).collect())
    assert got == clean
    oracle = _norm(_query(srt.Session(tpu_enabled=False)).collect())
    assert got == oracle


# ==========================================================================
# Disk-exhaustion robustness
# ==========================================================================
def test_enospc_on_checkpoint_write_disables_gracefully(
        tmp_path, monkeypatch):
    from spark_rapids_tpu.utils import fsio

    def _boom(path, data):
        raise OSError(errno.ENOSPC, "No space left on device", path)

    monkeypatch.setattr(fsio, "atomic_write_bytes", _boom)
    sess = srt.Session(_conf(tmp_path))
    want = _norm(_query(srt.Session(tpu_enabled=False)).collect())
    got = _norm(_query(sess).collect())  # query must still succeed
    assert got == want
    m = sess.last_metrics
    assert m.get("recovery.numCheckpointsWritten", 0) == 0, m
    ev = _events(sess, "checkpoint_disabled")
    assert ev, "checkpoint_disabled event missing"
    assert "space" in ev[0]["reason"] or "OSError" in ev[0]["reason"]
    # nothing half-written became a valid checkpoint
    for d in _exchange_dirs(tmp_path):
        assert not os.path.isfile(os.path.join(d, "manifest.json"))


def test_enospc_on_spill_write_is_typed_retryable_fault(monkeypatch):
    from spark_rapids_tpu.data.column import HostBatch, host_to_device
    from spark_rapids_tpu.memory.spill import SpillFramework, StorageTier
    from spark_rapids_tpu.utils import fsio

    fw = SpillFramework(host_limit_bytes=1)  # host tier always over

    def _boom(path, data):
        raise OSError(errno.ENOSPC, "No space left on device", path)

    monkeypatch.setattr(fsio, "atomic_write_bytes", _boom)
    bid = fw.add_batch(host_to_device(HostBatch.from_pydict(
        {"x": list(range(64))})))
    with pytest.raises(TpuStorageExhausted) as ei:
        fw.spill_device_to_target(0)
    assert isinstance(ei.value, TpuFaultError)  # the ladder can catch
    assert ei.value.site == "spill.write.disk"
    # the victim survived intact on the host tier and is re-queued
    buf = fw.catalog.get(bid)
    assert buf.tier == StorageTier.HOST
    monkeypatch.undo()
    hb = fw.acquire_batch(bid)
    assert hb is not None
    fw.release_batch(bid)
    fw.remove_batch(bid)


def test_spill_to_disk_is_atomic_no_partial_file(tmp_path, monkeypatch):
    """A failure at the rename step of the atomic write must leave NO
    ``.srtb`` (and no orphan temp) behind — a partial frame must never
    be readable later."""
    from spark_rapids_tpu.data.column import HostBatch, host_to_device
    from spark_rapids_tpu.memory.spill import SpillFramework

    fw = SpillFramework(host_limit_bytes=1, spill_dir=str(tmp_path))

    def _boom_replace(src, dst):
        raise OSError(errno.ENOSPC, "No space left on device", dst)

    bid = fw.add_batch(host_to_device(HostBatch.from_pydict(
        {"x": list(range(64))})))
    monkeypatch.setattr(os, "replace", _boom_replace)
    try:
        with pytest.raises(TpuStorageExhausted):
            fw.spill_device_to_target(0)
    finally:
        monkeypatch.undo()
    left = os.listdir(tmp_path)
    assert not [f for f in left if f.endswith(".srtb")], left
    assert not [f for f in left if f.startswith(".srt-tmp-")], left
    fw.remove_batch(bid)


# ==========================================================================
# Unified attempt budget
# ==========================================================================
@pytest.mark.fault_injection
def test_attempt_budget_exhausted_one_terminal_event():
    conf = dict(FAST, **{
        "spark.rapids.tpu.fault.injection.mode": "always",
        "spark.rapids.tpu.fault.injection.type": "stage_crash",
        "spark.rapids.tpu.fault.injection.site": "exchange.read",
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        "spark.rapids.tpu.sql.taskRetries": 6,
        "spark.rapids.tpu.fault.maxTotalAttempts": 2,
        "spark.rapids.tpu.telemetry.enabled": True,
    })
    sess = srt.Session(conf)
    with pytest.raises(AttemptBudgetExhausted) as ei:
        _query(sess).collect()
    assert len(ei.value.ledger) == 3  # charges 1,2 ok; 3 crossed
    assert all(a["kind"] for a in ei.value.ledger)
    ev = _events(sess, "attempt_budget_exhausted")
    assert len(ev) == 1, ev  # ONE terminal event, full ledger attached
    assert ev[0]["limit"] == 2
    assert len(ev[0]["ledger"]) == 3
    # the budget disarmed on the way out (try/finally at query entry)
    from spark_rapids_tpu.fault.budget import GLOBAL as _g
    assert not _g.armed()


@pytest.mark.fault_injection
def test_budget_not_exhausted_within_limit():
    conf = dict(FAST, **{
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "stage_crash",
        "spark.rapids.tpu.fault.injection.site": "exchange.read",
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        "spark.rapids.tpu.sql.taskRetries": 3,
        "spark.rapids.tpu.fault.maxTotalAttempts": 64,
    })
    sess = srt.Session(conf)
    got = _norm(_query(sess).collect())
    oracle = _norm(_query(srt.Session(tpu_enabled=False)).collect())
    assert got == oracle
    assert sess.last_metrics.get("fault.totalAttempts", 0) >= 1


def test_budget_disabled_with_zero_limit():
    from spark_rapids_tpu.fault.budget import AttemptBudget

    b = AttemptBudget()
    owned = b.begin(0)
    assert owned
    for _ in range(100):
        b.charge("task_retry", site="x")  # never raises at limit 0
    assert b.count() == 0
    b.end(owned)


def test_budget_nested_begin_is_not_owner():
    from spark_rapids_tpu.fault.budget import AttemptBudget

    b = AttemptBudget()
    outer = b.begin(5)
    inner = b.begin(99)
    assert outer and not inner
    b.charge("stage_retry", site="nested")
    b.end(inner)  # non-owner end is a no-op
    assert b.armed() and b.count() == 1
    b.end(outer)
    assert not b.armed()


# ==========================================================================
# Hygiene: close(), sweeps, TTL, LRU cap
# ==========================================================================
def test_session_close_sweeps_orphans_and_expired_checkpoints(tmp_path):
    root = tmp_path / "rec"
    sess = srt.Session(_conf(
        root, **{"spark.rapids.tpu.recovery.ttlSeconds": 3600}))
    _query(sess).collect()
    live = _exchange_dirs(root)
    assert live
    # plant crash debris: orphan temp files + an expired query dir
    stale = root / "deadbeefdeadbeefdeadbeef" / "ex"
    os.makedirs(stale)
    (stale / "p0-b0.srtb").write_bytes(b"x" * 8)
    os.utime(stale.parent, (1, 1))  # ancient
    tmp_file = root / ".srt-tmp-orphan.tmp"
    tmp_file.write_bytes(b"partial")
    spill_dir = sess.spill_framework.spill_dir
    orphan = os.path.join(spill_dir, "buffer-999999.srtb")
    with open(orphan, "wb") as f:
        f.write(b"o" * 16)
    sess.close()
    assert not tmp_file.exists()
    assert not stale.parent.exists()
    assert not os.path.exists(orphan)
    # live (non-expired) checkpoints survive close
    assert all(os.path.isdir(d) for d in live)
    # close is idempotent and the session stays usable
    sess.close()
    assert _query(sess).collect()


def test_max_bytes_lru_cap_evicts_oldest(tmp_path):
    from spark_rapids_tpu.recovery import CheckpointStore

    store = CheckpointStore(str(tmp_path))
    for i, mtime in [(0, 10), (1, 50), (2, 100)]:
        d = tmp_path / f"q{i}" / "ex"
        os.makedirs(d)
        (d / "p0-b0.srtb").write_bytes(b"x" * 1000)
        os.utime(tmp_path / f"q{i}", (mtime, mtime))
    removed = store.sweep(ttl_seconds=0, max_bytes=1500)
    assert removed["removedQueryDirs"] == 2
    assert not (tmp_path / "q0").exists()  # oldest evicted first
    assert not (tmp_path / "q1").exists()
    assert (tmp_path / "q2").exists()


def test_scheduler_shutdown_sweeps_storage(tmp_path):
    root = tmp_path / "rec"
    sess = srt.Session(_conf(root))
    h = sess.submit(_query(sess))
    h.result()
    tmp_file = root / ".srt-tmp-orphan.tmp"
    os.makedirs(root, exist_ok=True)
    tmp_file.write_bytes(b"partial")
    sess.shutdown_scheduler()
    assert not tmp_file.exists()


# ==========================================================================
# SIGKILL crash drill: checkpoint, die, resume in a fresh process
# ==========================================================================
_CHILD = textwrap.dedent("""\
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    sys.path.insert(0, {repo!r})
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.benchmarks import tpch, tpch_datagen

    mode = sys.argv[1]       # "crash" | "resume" | "baseline"
    qnum = int(sys.argv[2])
    root = sys.argv[3]
    conf = {{
        "spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
        "spark.rapids.tpu.recovery.enabled": mode != "baseline",
        "spark.rapids.tpu.recovery.dir": root,
        "spark.rapids.tpu.telemetry.enabled": True,
    }}
    if mode == "crash":
        conf["spark.rapids.tpu.recovery.killAfterCheckpoints"] = 1
    sess = srt.Session(conf)
    tables = tpch_datagen.dataframes(sess, sf=0.0007, seed=7)
    df = tpch.QUERIES[qnum](tables)
    if mode == "resume":
        hb = sess.resume(df.plan)
        rows = list(zip(*[c.to_pylist() for c in hb.columns]))
    else:
        rows = df.collect()
    norm = sorted((tuple(round(v, 9) if isinstance(v, float) else v
                         for v in r) for r in rows), key=repr)
    out = {{"rows": repr(norm),
            "resumed": sess.last_metrics.get(
                "recovery.numStagesResumed", 0)}}
    print("RESULT:" + json.dumps(out))
""")


def _run_child(mode, qnum, root):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=repo),
         mode, str(qnum), str(root)],
        capture_output=True, text=True, timeout=300)


def _child_result(proc):
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT:"):
            return json.loads(line[len("RESULT:"):])
    raise AssertionError(
        f"child produced no result:\n{proc.stdout}\n{proc.stderr}")


@pytest.mark.parametrize("qnum", [
    3, pytest.param(5, marks=pytest.mark.slow)])
def test_sigkill_after_checkpoint_then_resume_fresh_process(
        qnum, tmp_path):
    """The crash drill of the issue: run TPC-H q3/q5 with
    ``recovery.killAfterCheckpoints=1`` (SIGKILL right after the first
    checkpoint commits), then resume in a FRESH process — bit-identical
    rows with at least one stage served from checkpoints."""
    baseline = _run_child("baseline", qnum, tmp_path)
    assert baseline.returncode == 0, baseline.stderr
    want = _child_result(baseline)["rows"]

    crashed = _run_child("crash", qnum, tmp_path)
    assert crashed.returncode == -9, (  # died by SIGKILL, mid-query
        crashed.returncode, crashed.stdout, crashed.stderr)
    assert _exchange_dirs(tmp_path), "no checkpoint survived the kill"

    resumed = _run_child("resume", qnum, tmp_path)
    assert resumed.returncode == 0, resumed.stderr
    got = _child_result(resumed)
    assert got["rows"] == want
    assert got["resumed"] >= 1, got
