"""Query-scoped observability: hierarchical spans, the structured
event log, EXPLAIN-ANALYZE profiles and metrics exporters.

See docs/observability.md for the span model, event schema and
exporter formats.  Everything is gated by the ``telemetry.*`` confs
(config.py) so the disabled path stays near-zero-cost: one
thread-local ``getattr`` per emitter call.

Public surface:

* :func:`~.events.emit_event` — the exception-safe event emitter every
  call site outside this package must use;
* :mod:`~.spans` — ``capture()`` / ``attached()`` / ``bound()`` for
  worker-thread context propagation, ``span()`` for scoped spans;
* :func:`~.profile.explain_analyze` and
  :class:`~.profile.QueryProfile` — the EXPLAIN-ANALYZE surface
  (``Session.profile_report()``);
* :mod:`~.export` — Prometheus-text / JSON exporters and the
  HBM-watermark sampler.
"""
from __future__ import annotations

from .events import (EventLog, emit_event, read_event_log,  # noqa: F401
                     replay_summary)
from .export import json_snapshot, prometheus_text  # noqa: F401
from .profile import QueryProfile, explain_analyze  # noqa: F401
from .spans import QueryTelemetry, Span  # noqa: F401


def finish_query(session, ctx, phys=None, metrics=None):
    """The ONE finish path every execution driver calls at query end
    (Session._finalize_metrics, run_distributed, run_distributed_mp):
    finishes ``ctx``'s QueryTelemetry — if any, exactly once — into
    ``session.last_profile`` / ``session.profiles`` and returns the
    profile.

    ``metrics``: the final merged snapshot for exec-span back-fill;
    defaults to THIS query's ``ctx.metrics.snapshot()`` plus the
    per-query fault counters (never a previous query's
    ``session.last_metrics``)."""
    tele = getattr(ctx, "telemetry", None)
    if tele is None:
        # a telemetry-disabled query must not leave a stale "most
        # recent execution" profile behind (history stays available in
        # session.profiles); the CPU-degraded rung's inner context
        # keeps conf-enabled, so the native attempt's profile survives
        from ..config import TELEMETRY_ENABLED

        conf = getattr(ctx, "conf", None)
        if session is not None and conf is not None \
                and not conf.get(TELEMETRY_ENABLED):
            session.last_profile = None
        return None
    if tele.finished:
        return None
    if metrics is None:
        from ..fault.stats import GLOBAL as _fault_stats

        metrics = dict(ctx.metrics.snapshot())
        metrics.update(_fault_stats.snapshot())
    profile = tele.finish(metrics=metrics, plan=phys)
    if profile is not None:
        session.last_profile = profile
        session._profiles.append(profile)
    return profile
