"""Window expression IR.

Capability parity with the reference's GpuWindowExpression.scala (722 LoC):
WindowSpecDefinition (partition-by + order-by), SpecifiedWindowFrame
(row-based frames), RowNumber, rank family, and aggregates-over-window.
The exec layer computes these via segmented scans (device) / per-segment
numpy (host)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Union

from .. import types as T
from .aggregates import AggregateFunction
from .expression import Expression, bind_references

UNBOUNDED = None  # frame boundary sentinel
CURRENT_ROW = 0


@dataclass
class WindowFrame:
    """Row-based frame [lower, upper] relative to the current row;
    None = unbounded (reference: SpecifiedWindowFrame, rows only — range
    frames beyond unbounded/current are tagged off, same as the
    reference)."""

    lower: Optional[int] = UNBOUNDED     # e.g. None (unbounded preceding)
    upper: Optional[int] = CURRENT_ROW   # e.g. 0 (current row)

    @property
    def is_unbounded_to_current(self):
        return self.lower is UNBOUNDED and self.upper == 0

    @property
    def is_unbounded_both(self):
        return self.lower is UNBOUNDED and self.upper is UNBOUNDED


@dataclass
class WindowSpec:
    """Reference: WindowSpecDefinition."""

    partition_by: List[Expression] = field(default_factory=list)
    order_by: List = field(default_factory=list)  # List[functions.SortKey]
    frame: Optional[WindowFrame] = None

    def resolved_frame(self) -> WindowFrame:
        if self.frame is not None:
            return self.frame
        # Spark default: unbounded..current with order, whole partition
        # without
        if self.order_by:
            return WindowFrame(UNBOUNDED, CURRENT_ROW)
        return WindowFrame(UNBOUNDED, UNBOUNDED)


class WindowFunctionBase:
    pass


class RowNumber(WindowFunctionBase):
    dtype = T.INT32
    name = "row_number"


class Rank(WindowFunctionBase):
    dtype = T.INT32
    name = "rank"


class DenseRank(WindowFunctionBase):
    dtype = T.INT32
    name = "dense_rank"


@dataclass
class WindowExpression:
    """One windowed computation: function OVER spec
    (reference: GpuWindowExpression)."""

    func: Union[WindowFunctionBase, AggregateFunction]
    spec: WindowSpec

    @property
    def dtype(self) -> T.DType:
        return self.func.dtype

    def bind(self, schema: T.Schema) -> "WindowExpression":
        from ..plan import functions as F

        func = self.func
        if isinstance(func, AggregateFunction) and func.child is not None:
            import copy

            func = copy.copy(func)
            func.child = bind_references(func.child, schema)
        spec = WindowSpec(
            [bind_references(e, schema) for e in self.spec.partition_by],
            [F.SortKey(bind_references(k.expr, schema), k.ascending,
                       k.nulls_first) for k in self.spec.order_by],
            self.spec.frame)
        return WindowExpression(func, spec)

    def sql(self) -> str:
        fname = self.func.name if isinstance(self.func, WindowFunctionBase) \
            else self.func.sql()
        return f"{fname} OVER (...)"


# --------------------------------------------------------------------------
# user-facing builders (pyspark-like)
# --------------------------------------------------------------------------
class WindowBuilder:
    def __init__(self):
        self._partition = []
        self._order = []
        self._frame = None

    def partition_by(self, *cols) -> "WindowBuilder":
        from ..plan.logical import _to_expr

        self._partition = [_to_expr(c) for c in cols]
        return self

    def order_by(self, *keys) -> "WindowBuilder":
        from ..plan import functions as F
        from ..plan.logical import _to_expr

        self._order = [k if isinstance(k, F.SortKey)
                       else F.SortKey(_to_expr(k)) for k in keys]
        return self

    def rows_between(self, lower, upper) -> "WindowBuilder":
        self._frame = WindowFrame(lower, upper)
        return self

    def spec(self) -> WindowSpec:
        return WindowSpec(self._partition, self._order, self._frame)


def window() -> WindowBuilder:
    return WindowBuilder()


def over(func_col, spec_builder: Union[WindowBuilder, WindowSpec]
         ) -> WindowExpression:
    """``over(f.sum("x"), window().partition_by("k").order_by("t"))``"""
    from ..plan import functions as F

    spec = spec_builder.spec() if isinstance(spec_builder, WindowBuilder) \
        else spec_builder
    if isinstance(func_col, WindowFunctionBase):
        return WindowExpression(func_col, spec)
    if isinstance(func_col, F.AggColumn):
        return WindowExpression(func_col.func, spec)
    raise TypeError(f"cannot window over {func_col!r}")


def row_number() -> RowNumber:
    return RowNumber()


def rank() -> Rank:
    return Rank()


def dense_rank() -> DenseRank:
    return DenseRank()
