"""Seeded random data generators.

Capability parity with the reference's fuzzing layer (FuzzerUtils.scala +
integration_tests data_gen.py 645 LoC): composable per-type generators
with special values (NaN, +/-0.0, min/max, nulls), seeded for
reproducibility."""
from __future__ import annotations

import string as pystring
from typing import List, Optional, Sequence

import numpy as np

from .. import types as T
from ..data.column import HostBatch, HostColumn


class DataGen:
    def __init__(self, dtype: T.DType, nullable: bool = True,
                 null_prob: float = 0.1):
        self.dtype = dtype
        self.nullable = nullable
        self.null_prob = null_prob if nullable else 0.0

    def generate(self, n: int, rng: np.random.Generator) -> HostColumn:
        data = self._values(n, rng)
        validity = None
        if self.null_prob > 0:
            validity = rng.random(n) >= self.null_prob
            if self.dtype.id is T.TypeId.STRING:
                for i in range(n):
                    if not validity[i]:
                        data[i] = None
        return HostColumn(self.dtype, data, validity)

    def _values(self, n, rng) -> np.ndarray:
        raise NotImplementedError


class IntGen(DataGen):
    def __init__(self, dtype: T.DType = T.INT32, nullable=True,
                 min_val: Optional[int] = None,
                 max_val: Optional[int] = None,
                 special_weight: float = 0.05, **kw):
        super().__init__(dtype, nullable, **kw)
        info = np.iinfo(dtype.np_dtype)
        self.min_val = info.min if min_val is None else min_val
        self.max_val = info.max if max_val is None else max_val
        self.special_weight = special_weight

    def _values(self, n, rng):
        vals = rng.integers(self.min_val, self.max_val, size=n,
                            endpoint=True, dtype=self.dtype.np_dtype)
        specials = np.asarray([self.min_val, self.max_val, 0, 1, -1],
                              dtype=self.dtype.np_dtype)
        mask = rng.random(n) < self.special_weight
        vals[mask] = rng.choice(specials, size=int(mask.sum()))
        return vals


class BooleanGen(DataGen):
    def __init__(self, nullable=True, **kw):
        super().__init__(T.BOOL, nullable, **kw)

    def _values(self, n, rng):
        return rng.random(n) < 0.5


class FloatGen(DataGen):
    """Floats with NaN/inf/-0.0 specials (reference data_gen.py special
    values)."""

    def __init__(self, dtype: T.DType = T.FLOAT64, nullable=True,
                 no_nans: bool = False, special_weight: float = 0.05, **kw):
        super().__init__(dtype, nullable, **kw)
        self.no_nans = no_nans
        self.special_weight = special_weight

    def _values(self, n, rng):
        vals = (rng.standard_normal(n) * 1e6).astype(self.dtype.np_dtype)
        specials = [0.0, -0.0, 1.0, -1.0, np.finfo(
            self.dtype.np_dtype).max, np.finfo(self.dtype.np_dtype).min]
        if not self.no_nans:
            specials += [np.nan, np.inf, -np.inf]
        mask = rng.random(n) < self.special_weight
        vals[mask] = rng.choice(
            np.asarray(specials, dtype=self.dtype.np_dtype),
            size=int(mask.sum()))
        return vals


class StringGen(DataGen):
    def __init__(self, nullable=True, max_len: int = 12,
                 charset: str = pystring.ascii_letters + pystring.digits,
                 **kw):
        super().__init__(T.STRING, nullable, **kw)
        self.max_len = max_len
        self.charset = np.asarray(list(charset))

    def _values(self, n, rng):
        out = np.empty(n, dtype=object)
        lens = rng.integers(0, self.max_len, size=n, endpoint=True)
        for i in range(n):
            out[i] = "".join(rng.choice(self.charset, size=lens[i]))
        return out


class DateGen(DataGen):
    def __init__(self, nullable=True, **kw):
        super().__init__(T.DATE32, nullable, **kw)

    def _values(self, n, rng):
        # ~1940..2070
        return rng.integers(-11000, 37000, size=n).astype(np.int32)


class TimestampGen(DataGen):
    def __init__(self, nullable=True, **kw):
        super().__init__(T.TIMESTAMP, nullable, **kw)

    def _values(self, n, rng):
        return rng.integers(-10**15, 4 * 10**15, size=n).astype(np.int64)


class RepeatSeqGen(DataGen):
    """Low-cardinality keys for group-by/join tests (reference:
    RepeatSeqGen)."""

    def __init__(self, values: Sequence, dtype: T.DType):
        super().__init__(dtype, nullable=any(v is None for v in values),
                         null_prob=0.0)
        self.values = list(values)

    def generate(self, n, rng):
        reps = [self.values[i % len(self.values)] for i in range(n)]
        perm = rng.permutation(n)
        vals = [reps[p] for p in perm]
        return HostColumn.from_pylist(vals, self.dtype)


byte_gen = IntGen(T.INT8)
short_gen = IntGen(T.INT16)
int_gen = IntGen(T.INT32)
long_gen = IntGen(T.INT64)
float_gen = FloatGen(T.FLOAT32)
double_gen = FloatGen(T.FLOAT64)
no_nans_double_gen = FloatGen(T.FLOAT64, no_nans=True)
boolean_gen = BooleanGen()
string_gen = StringGen()
date_gen = DateGen()
timestamp_gen = TimestampGen()

numeric_gens: List[DataGen] = [byte_gen, short_gen, int_gen, long_gen,
                               float_gen, double_gen]
all_basic_gens: List[DataGen] = numeric_gens + [boolean_gen, string_gen,
                                                date_gen, timestamp_gen]


def gen_batch(gens: dict, n: int, seed: int = 0) -> HostBatch:
    """dict of name -> DataGen."""
    rng = np.random.default_rng(seed)
    cols, fields = [], []
    for name, g in gens.items():
        c = g.generate(n, rng)
        cols.append(c)
        fields.append(T.Field(name, g.dtype, g.nullable))
    return HostBatch(T.Schema(fields), cols)


def gen_pydict(gens: dict, n: int, seed: int = 0) -> dict:
    return gen_batch(gens, n, seed).to_pydict()


def gen_df(session, gens: dict, n: int, seed: int = 0, n_partitions=2):
    from ..plan import logical as L
    from ..plan.logical import DataFrame

    batch = gen_batch(gens, n, seed)
    return DataFrame(session, L.LocalRelation([batch], batch.schema,
                                              n_partitions))
