"""Execution metrics.

Reference analogue: Spark SQLMetrics per exec (GpuExec.scala:45-60 standard
set: numOutputRows, numOutputBatches, totalTime, peakDevMemory; per-op
extras like sortTime/joinTime/spillSize)."""
from __future__ import annotations

import threading
from typing import Dict


class Metric:
    __slots__ = ("name", "unit", "_value", "_lock")

    def __init__(self, name: str, unit: str = "sum"):
        self.name = name
        self.unit = unit
        self._value = 0
        self._lock = threading.Lock()

    def add(self, v) -> None:
        with self._lock:
            self._value += v

    def set_max(self, v) -> None:
        with self._lock:
            self._value = max(self._value, v)

    @property
    def value(self):
        return self._value

    def __repr__(self):  # pragma: no cover
        return f"{self.name}={self._value}"


# Standard metric names (reference: GpuMetricNames)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
TOTAL_TIME = "totalTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
#: host wall spent blocked on device transfer/sync (transitions) —
#: registered per exec only while telemetry is enabled, so the default
#: metrics snapshot stays byte-identical to the un-instrumented engine
DEVICE_SYNC_TIME = "deviceSyncTime"
#: compile-inclusive wall of first-shape kernel dispatches, attributed
#: to the dispatching exec by the KernelCache (exec/kernel_cache.py)
COMPILE_TIME = "compileTime"

# OOM retry framework (memory/retry.py; registered as "retry.<name>")
NUM_RETRIES = "numRetries"
NUM_SPLIT_RETRIES = "numSplitRetries"
RETRY_BLOCK_TIME = "retryBlockTimeMs"
SPILL_BYTES_ON_RETRY = "spillBytesOnRetry"


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}

    def metric(self, name: str, unit: str = "sum") -> Metric:
        m = self._metrics.get(name)
        if m is None:
            m = Metric(name, unit)
            self._metrics[name] = m
        return m

    def snapshot(self) -> Dict[str, int]:
        return {k: m.value for k, m in self._metrics.items()}

    def __getitem__(self, name: str) -> Metric:
        return self.metric(name)
