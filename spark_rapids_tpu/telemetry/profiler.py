"""Per-kernel dispatch profiler with roofline attribution.

Every ``jit_kernel`` dispatch in ``exec/kernel_cache.py`` reports to the
process-global :data:`PROFILER` (mirroring the KernelCache GLOBAL): per
kernel *fingerprint* it accumulates dispatch count, dispatch wall, input
and output rows/bytes, and the padding waste from power-of-two shape
bucketing.  ``HostToDeviceExec`` reports each upload so the observed
h2d ceiling (peak bytes/s) anchors the roofline: a kernel far below the
ceiling on bytes/s is compute-bound, not transfer-bound — which is the
question ROADMAP item 2 needs answered per kernel, not per query.

Hot-path discipline (enforced by the ``profiler-guard`` and
``host-sync`` analysis rules):

* the disabled cost is ONE attribute read (``PROFILER.enabled``) per
  dispatch — no allocation, no locking;
* the enabled path reads only shape-derived metadata (``padded_rows``,
  ``device_bytes()``, ``nbytes``) — never ``block_until_ready`` /
  ``np.asarray`` or anything else that would force a host sync.  A
  batch's logical ``num_rows`` is counted only when it is a plain
  Python int (kernel *outputs* can carry traced/device scalars there).

Wall times are dispatch wall: on asynchronous backends this measures
enqueue + any blocking the dispatch itself does (first-shape dispatches
include compile), which is exactly what the per-query ``compute_s``
wall is made of.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict, List, Optional, Tuple

_H2D_MIN_BYTES = 1 << 16   # ignore tiny transfers when taking the peak


def kernel_fingerprint(key, fn: Callable) -> str:
    """Stable human-readable fingerprint for a kernel-cache entry.

    ``<head>#<md5-6>`` where head is the operator kind from the cache
    key (or the function's qualname for anonymous kernels) and the
    suffix is a deterministic content hash of the full key — stable
    across processes (unlike ``hash()``) so bench artifacts from
    different runs can be diffed kernel-by-kernel.
    """
    if key is None:
        head = fn.__qualname__.replace("<locals>.", "")
        return f"{head}#anon"
    head = key[0] if (isinstance(key, tuple) and key
                      and isinstance(key[0], str)) else \
        fn.__qualname__.replace("<locals>.", "")
    digest = hashlib.md5(repr(key).encode()).hexdigest()[:6]
    return f"{head}#{digest}"


class KernelStat:
    """Accumulated counters for one kernel fingerprint."""

    __slots__ = ("dispatches", "wall_ns", "in_rows", "in_padded",
                 "in_padded_known", "in_bytes", "out_padded", "out_bytes")

    def __init__(self):
        self.dispatches = 0
        self.wall_ns = 0
        self.in_rows = 0          # logical rows (only when known host-side)
        self.in_padded = 0        # padded rows over ALL dispatches
        self.in_padded_known = 0  # padded rows over rows-known dispatches
        self.in_bytes = 0
        self.out_padded = 0
        self.out_bytes = 0

    def as_tuple(self) -> Tuple[int, ...]:
        return (self.dispatches, self.wall_ns, self.in_rows,
                self.in_padded, self.in_padded_known, self.in_bytes,
                self.out_padded, self.out_bytes)

    @classmethod
    def from_delta(cls, cur: Tuple[int, ...],
                   base: Optional[Tuple[int, ...]]) -> "KernelStat":
        st = cls()
        vals = (cur if base is None
                else tuple(c - b for c, b in zip(cur, base)))
        (st.dispatches, st.wall_ns, st.in_rows, st.in_padded,
         st.in_padded_known, st.in_bytes, st.out_padded,
         st.out_bytes) = vals
        return st

    @property
    def padding_waste(self) -> float:
        """Fraction of padded input rows that carry no logical row
        (over dispatches whose logical row count was known)."""
        if self.in_padded_known <= 0:
            return 0.0
        return max(0.0, 1.0 - self.in_rows / float(self.in_padded_known))


def _measure(values) -> Tuple[int, int, int, int]:
    """(logical_rows, padded_rows, padded_rows_known, bytes) over a
    flat sequence of kernel args/outputs.  Shape-metadata only."""
    rows = padded = padded_known = nbytes = 0
    for v in values:
        pr = getattr(v, "padded_rows", None)
        if pr is not None:                       # DeviceBatch-like
            db = v.device_bytes()
            nbytes += int(db)
            padded += int(pr)
            nr = v.num_rows
            if type(nr) is int:                  # traced scalars excluded
                rows += nr
                padded_known += int(pr)
            continue
        nb = getattr(v, "nbytes", None)
        if nb is not None and not isinstance(v, (int, float, bool)):
            try:
                nbytes += int(nb)
                shape = v.shape
                if shape:
                    padded += int(shape[0])
            except Exception:  # noqa: BLE001 - abstract/deleted arrays
                pass
    return rows, padded, padded_known, nbytes


class KernelProfiler:
    """Process-global per-kernel dispatch accumulator (see module doc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, KernelStat] = {}
        self.enabled = False
        self._h2d_bytes = 0
        self._h2d_ns = 0
        self._h2d_peak_bps = 0.0

    # ---------------- configuration / lifecycle -----------------------
    def configure(self, conf) -> None:
        from ..config import TELEMETRY_PROFILER_ENABLED

        self.enabled = bool(conf.get(TELEMETRY_PROFILER_ENABLED))

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self.enabled = False
            self._h2d_bytes = 0
            self._h2d_ns = 0
            self._h2d_peak_bps = 0.0

    # ---------------- hot-path recorders ------------------------------
    def record_dispatch(self, fingerprint: str, wall_ns: int,
                        args, out) -> None:
        """Account one jit dispatch.  Exception-safe; shape-metadata
        reads only (never forces a host sync)."""
        try:
            in_rows, in_padded, in_known, in_bytes = _measure(args)
            out_vals = out if isinstance(out, (tuple, list)) else (out,)
            _, out_padded, _, out_bytes = _measure(out_vals)
            with self._lock:
                st = self._stats.get(fingerprint)
                if st is None:
                    st = self._stats[fingerprint] = KernelStat()
                st.dispatches += 1
                st.wall_ns += wall_ns
                st.in_rows += in_rows
                st.in_padded += in_padded
                st.in_padded_known += in_known
                st.in_bytes += in_bytes
                st.out_padded += out_padded
                st.out_bytes += out_bytes
        except Exception:  # noqa: BLE001 - profiling must never fail a query
            pass

    def record_h2d(self, nbytes: int, elapsed_ns: int) -> None:
        """Account one host->device upload (the roofline ceiling)."""
        try:
            with self._lock:
                self._h2d_bytes += int(nbytes)
                self._h2d_ns += int(elapsed_ns)
                if nbytes >= _H2D_MIN_BYTES and elapsed_ns > 0:
                    bps = nbytes / (elapsed_ns / 1e9)
                    if bps > self._h2d_peak_bps:
                        self._h2d_peak_bps = bps
        except Exception:  # noqa: BLE001
            pass

    # ---------------- snapshots / per-query deltas ---------------------
    def mark(self) -> Dict[str, Tuple[int, ...]]:
        """Counter snapshot for a later :meth:`since` delta (taken at
        query start, like KernelCache.counters())."""
        if not self.enabled:
            return {}
        with self._lock:
            return {fp: st.as_tuple() for fp, st in self._stats.items()}

    def since(self, mark: Optional[Dict[str, Tuple[int, ...]]]
              ) -> Dict[str, KernelStat]:
        """Per-kernel deltas since ``mark`` (kernels with no new
        dispatches are dropped)."""
        with self._lock:
            cur = {fp: st.as_tuple() for fp, st in self._stats.items()}
        out: Dict[str, KernelStat] = {}
        for fp, tup in cur.items():
            st = KernelStat.from_delta(tup, (mark or {}).get(fp))
            if st.dispatches > 0:
                out[fp] = st
        return out

    def snapshot(self) -> Dict[str, KernelStat]:
        return self.since(None)

    def h2d_ceiling_bps(self) -> float:
        """Observed h2d ceiling, bytes/s: peak single-transfer rate,
        falling back to the aggregate rate when no transfer cleared the
        size floor."""
        with self._lock:
            if self._h2d_peak_bps > 0:
                return self._h2d_peak_bps
            if self._h2d_ns > 0:
                return self._h2d_bytes / (self._h2d_ns / 1e9)
            return 0.0


def roofline_rows(stats: Dict[str, KernelStat],
                  h2d_ceiling_bps: float = 0.0,
                  top_n: Optional[int] = None) -> List[dict]:
    """Derive the roofline table from a stats snapshot: one dict per
    kernel, sorted by wall descending — the JSON form consumed by the
    BENCH ``kernels`` section and ``bench.py --compare``."""
    rows = []
    for fp, st in sorted(stats.items(), key=lambda kv: -kv[1].wall_ns):
        wall_s = st.wall_ns / 1e9
        nbytes = st.in_bytes + st.out_bytes
        row = {
            "kernel": fp,
            "dispatches": st.dispatches,
            "wall_s": round(wall_s, 6),
            "rows": st.in_rows,
            "padded_rows": st.in_padded,
            "bytes": nbytes,
            "padding_waste": round(st.padding_waste, 4),
            "bytes_per_s": round(nbytes / wall_s, 1) if wall_s > 0 else 0.0,
            "rows_per_s": round(st.in_padded / wall_s, 1)
            if wall_s > 0 else 0.0,
        }
        if h2d_ceiling_bps > 0 and wall_s > 0:
            row["pct_of_h2d_ceiling"] = round(
                100.0 * row["bytes_per_s"] / h2d_ceiling_bps, 2)
        rows.append(row)
    return rows[:top_n] if top_n else rows


def _fmt_rate(v: float) -> str:
    if v >= 1e9:
        return f"{v / 1e9:.2f}G"
    if v >= 1e6:
        return f"{v / 1e6:.2f}M"
    if v >= 1e3:
        return f"{v / 1e3:.2f}K"
    return f"{v:.1f}"


def render_roofline(stats: Dict[str, KernelStat],
                    h2d_ceiling_bps: float = 0.0,
                    top_n: int = 10) -> List[str]:
    """Text roofline table for Session.profile_report()."""
    rows = roofline_rows(stats, h2d_ceiling_bps, top_n=top_n)
    ceiling = (f"{_fmt_rate(h2d_ceiling_bps)}B/s"
               if h2d_ceiling_bps > 0 else "unmeasured")
    lines = [f"-- Kernel roofline (h2d ceiling={ceiling}) --"]
    if not rows:
        lines.append("  (no kernel dispatches recorded)")
        return lines
    hdr = (f"  {'kernel':<34} {'disp':>5} {'wall':>9} {'rows/s':>9} "
           f"{'bytes/s':>9} {'%ceil':>6} {'waste':>6}")
    lines.append(hdr)
    for r in rows:
        pct = r.get("pct_of_h2d_ceiling")
        lines.append(
            f"  {r['kernel'][:34]:<34} {r['dispatches']:>5} "
            f"{r['wall_s'] * 1e3:>7.1f}ms {_fmt_rate(r['rows_per_s']):>9} "
            f"{_fmt_rate(r['bytes_per_s']):>8}B "
            f"{(f'{pct:.0f}%' if pct is not None else '-'):>6} "
            f"{r['padding_waste'] * 100:>5.1f}%")
    return lines


#: THE process-wide profiler instance (analogue: KernelCache.GLOBAL)
PROFILER = KernelProfiler()
