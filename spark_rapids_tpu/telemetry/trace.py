"""Chrome-trace / Perfetto timeline export.

Converts finished :class:`~.profile.QueryProfile` objects — span tree,
HBM sampler timeline, and the structured event ring (scheduler
admission/preempt/overload instants, streaming batch commits, retry
events, ...) — into the Chrome Trace Event JSON format that Perfetto
(ui.perfetto.dev) and chrome://tracing load directly.

Track layout: each query is one *process* (pid) whose name is the query
id; within it, tid 0 carries the root query span and every direct child
subtree of the root (a stage, a worker-pool drain, an exec group) gets
its own *thread* track, so concurrent stages render side by side
instead of stacking into one incoherent lane.  The HBM watermark
renders as a counter track; ring events render as instants.

Clock mapping: spans are stamped with ``perf_counter_ns`` while events
and HBM samples carry wall-clock ``time.time()``.  The exporter anchors
both to the query's ``query_begin`` event (emitted at the same instant
the root span starts), yielding one µs timeline that is clamped
non-negative — Perfetto rejects negative timestamps.

Writing goes through the fsio atomic helpers (crash mid-write leaves a
sweepable temp file, never a torn trace); per-query auto-export is
gated by the ``telemetry.trace.dir`` conf.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..utils import fsio


def _span_events(root_dict: Dict, pid: int, anchor_ns: int,
                 out: List[Dict]) -> None:
    """Emit one complete ("X") event per span node, assigning each
    direct child subtree of the root its own tid track."""
    tid_names: Dict[int, str] = {0: "query"}
    next_tid = [0]

    def emit(sp: Dict, tid: int, depth: int) -> None:
        if depth == 1:
            next_tid[0] += 1
            tid = next_tid[0]
            tid_names.setdefault(tid, f"{sp['kind']}:{sp['name']}")
        args = {"kind": sp["kind"]}
        for k in ("rows", "batches", "bytes"):
            if sp.get(k):
                args[k] = sp[k]
        if sp.get("device_sync_ns"):
            args["device_sync_us"] = round(sp["device_sync_ns"] / 1e3, 1)
        if sp.get("attrs"):
            args.update({f"attr.{k}": v for k, v in sp["attrs"].items()})
        out.append({
            "ph": "X",
            "name": f"{sp['kind']}:{sp['name']}",
            "pid": pid,
            "tid": tid,
            "ts": max(0.0, round((sp["start_ns"] - anchor_ns) / 1e3, 3)),
            "dur": max(0.0, round(sp["wall_ns"] / 1e3, 3)),
            "args": args,
        })
        for c in sp["children"]:
            emit(c, tid, depth + 1)

    emit(root_dict, 0, 0)
    for tid, name in tid_names.items():
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "ts": 0,
                    "args": {"name": name}})


def _annotated_span_tree(span) -> Dict:
    """Span.to_dict() plus the absolute start_ns each node needs for
    timeline placement (to_dict() itself only keeps wall)."""
    d = span.to_dict()
    d["start_ns"] = span.start_ns
    d["children"] = [_annotated_span_tree(c) for c in span.children]
    return d


def chrome_trace(profiles, include_events: bool = True) -> Dict:
    """Build one Chrome-trace document from one or more finished
    QueryProfiles (one pid track per query).  Pure function — callers
    decide where the JSON goes."""
    if not isinstance(profiles, (list, tuple)):
        profiles = [profiles]
    events: List[Dict] = []
    for pid, prof in enumerate(profiles, start=1):
        if prof is None:
            continue
        anchor_ns = prof.root.start_ns
        ring = prof.events.snapshot() if prof.events is not None else []
        # wall-clock anchor: the query_begin event fires at root-span
        # start; fall back to the earliest stamped thing we have
        anchor_epoch = None
        for ev in ring:
            if ev.get("event") == "query_begin":
                anchor_epoch = ev["ts"]
                break
        if anchor_epoch is None:
            candidates = [ev["ts"] for ev in ring if "ts" in ev]
            candidates += [t[0] for t in prof.hbm_timeline]
            anchor_epoch = min(candidates) if candidates else 0.0

        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "ts": 0,
                       "args": {"name": f"query {prof.query_id}"}})
        _span_events(_annotated_span_tree(prof.root), pid, anchor_ns,
                     events)
        for ts, allocated, peak in prof.hbm_timeline:
            events.append({
                "ph": "C", "name": "HBM", "pid": pid, "tid": 0,
                "ts": max(0.0, round((ts - anchor_epoch) * 1e6, 3)),
                "args": {"allocated": allocated, "peak": peak},
            })
        if include_events:
            for ev in ring:
                etype = ev.get("event", "event")
                if etype in ("query_begin", "query_end"):
                    continue  # already delimited by the root span
                args = {k: v for k, v in ev.items()
                        if k not in ("ts", "event", "query")
                        and isinstance(v, (str, int, float, bool))}
                events.append({
                    "ph": "i", "s": "t", "name": etype,
                    "pid": pid, "tid": 0,
                    "ts": max(0.0,
                              round((ev.get("ts", anchor_epoch)
                                     - anchor_epoch) * 1e6, 3)),
                    "args": args,
                })
    # metadata (ts 0) first, then strictly non-decreasing timestamps —
    # not required by the format, but it makes the artifact diffable
    # and lets tests assert monotonicity directly
    events.sort(key=lambda e: (0 if e["ph"] == "M" else 1,
                               e["pid"], e["ts"]))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_trace(path: str, profiles, include_events: bool = True) -> str:
    """Atomically write a combined trace for ``profiles`` to ``path``."""
    doc = chrome_trace(profiles, include_events=include_events)
    fsio.atomic_write_json(path, doc)
    return path


def write_query_trace(trace_dir: str, profile) -> Optional[str]:
    """Per-query auto-export used by Session._finalize_metrics when
    ``telemetry.trace.dir`` is set: ``<dir>/trace-<queryId>.json``.
    Exception-safe — trace export must never fail a query."""
    if not trace_dir or profile is None:
        return None
    try:
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(trace_dir, f"trace-{profile.query_id}.json")
        return write_trace(path, profile)
    except Exception:  # noqa: BLE001
        return None
