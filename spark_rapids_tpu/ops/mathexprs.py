"""Math expressions.

Capability parity with the reference's mathExpressions.scala: trig, log,
exp, sqrt, cbrt, rint, signum, floor, ceil, pow and friends.  Most Spark
math functions operate in double; floor/ceil of integrals stay integral.
"""
from __future__ import annotations

import numpy as np

from .. import types as T
from .expression import BinaryExpression, UnaryExpression


def _jnp():
    import jax.numpy as jnp

    return jnp


class _DoubleUnary(UnaryExpression):
    """Unary math op computing in double (Spark semantics)."""

    np_fn = None
    jnp_name = None

    def result_dtype(self, ct):
        return T.FLOAT64

    def do_cpu(self, data):
        return type(self).np_fn(data.astype(np.float64))

    def do_tpu(self, data):
        jnp = _jnp()
        fn = getattr(jnp, self.jnp_name)
        return fn(data.astype(jnp.float64))


def _double_unary(name, np_fn, jnp_name):
    cls = type(name, (_DoubleUnary,), {"np_fn": staticmethod(np_fn),
                                       "jnp_name": jnp_name})
    globals()[name] = cls
    return cls


Acos = _double_unary("Acos", np.arccos, "arccos")
Asin = _double_unary("Asin", np.arcsin, "arcsin")
Atan = _double_unary("Atan", np.arctan, "arctan")
Cos = _double_unary("Cos", np.cos, "cos")
Sin = _double_unary("Sin", np.sin, "sin")
Tan = _double_unary("Tan", np.tan, "tan")
Cosh = _double_unary("Cosh", np.cosh, "cosh")
Sinh = _double_unary("Sinh", np.sinh, "sinh")
Tanh = _double_unary("Tanh", np.tanh, "tanh")
Exp = _double_unary("Exp", np.exp, "exp")
Expm1 = _double_unary("Expm1", np.expm1, "expm1")
Log = _double_unary("Log", np.log, "log")
Log1p = _double_unary("Log1p", np.log1p, "log1p")
Log2 = _double_unary("Log2", np.log2, "log2")
Log10 = _double_unary("Log10", np.log10, "log10")
Sqrt = _double_unary("Sqrt", np.sqrt, "sqrt")
Cbrt = _double_unary("Cbrt", np.cbrt, "cbrt")
Rint = _double_unary("Rint", np.rint, "rint")
Acosh = _double_unary("Acosh", np.arccosh, "arccosh")
Asinh = _double_unary("Asinh", np.arcsinh, "arcsinh")
Atanh = _double_unary("Atanh", np.arctanh, "arctanh")


class Cot(_DoubleUnary):
    """cot(x) = 1/tan(x) (reference registers Cot beside the trig set)."""

    np_fn = staticmethod(lambda d: 1.0 / np.tan(d))
    jnp_name = "tan"

    def do_tpu(self, data):
        jnp = _jnp()
        return 1.0 / jnp.tan(data.astype(jnp.float64))


class Logarithm(BinaryExpression):
    """log(base, x) — Spark's two-argument Logarithm."""

    def result_dtype(self, lt, rt):
        return T.FLOAT64

    def do_cpu(self, base, x):
        return np.log(x.astype(np.float64)) / np.log(
            base.astype(np.float64))

    def do_tpu(self, base, x):
        jnp = _jnp()
        return jnp.log(x.astype(jnp.float64)) / jnp.log(
            base.astype(jnp.float64))


class Signum(UnaryExpression):
    def result_dtype(self, ct):
        return T.FLOAT64

    def do_cpu(self, data):
        return np.sign(data.astype(np.float64))

    def do_tpu(self, data):
        jnp = _jnp()
        return jnp.sign(data.astype(jnp.float64))


_LONG_HI_F = float(np.nextafter(float(2 ** 63 - 1), 0.0))


def _sat_to_long_np(d):
    """Saturating double->long (Java (long) cast semantics).  The clamp
    upper bound must be float-representable BELOW 2**63."""
    d = np.where(np.isnan(d), 0.0, d)
    d = np.clip(d, float(-2 ** 63), _LONG_HI_F)
    out = d.astype(np.int64)
    # values clamped to the float bound still mean Long.MAX_VALUE
    return np.where(d >= _LONG_HI_F, np.int64(2 ** 63 - 1), out)


class Floor(UnaryExpression):
    """Spark floor returns LONG for fractional input (saturating cast)."""

    def result_dtype(self, ct):
        return T.INT64 if ct.is_floating else ct

    def do_cpu(self, data):
        if np.issubdtype(data.dtype, np.integer):
            return data
        return _sat_to_long_np(np.floor(data))

    def do_tpu(self, data):
        jnp = _jnp()
        if jnp.issubdtype(data.dtype, jnp.integer):
            return data
        d = jnp.floor(data)
        d = jnp.where(jnp.isnan(d), 0.0, d)
        d = jnp.clip(d, float(-2 ** 63), float(2 ** 63 - 1))
        return d.astype(jnp.int64)


class Ceil(UnaryExpression):
    def result_dtype(self, ct):
        return T.INT64 if ct.is_floating else ct

    def do_cpu(self, data):
        if np.issubdtype(data.dtype, np.integer):
            return data
        return _sat_to_long_np(np.ceil(data))

    def do_tpu(self, data):
        jnp = _jnp()
        if jnp.issubdtype(data.dtype, jnp.integer):
            return data
        d = jnp.ceil(data)
        d = jnp.where(jnp.isnan(d), 0.0, d)
        d = jnp.clip(d, float(-2 ** 63), float(2 ** 63 - 1))
        return d.astype(jnp.int64)


class Pow(BinaryExpression):
    def result_dtype(self, lt, rt):
        return T.FLOAT64

    def do_cpu(self, l, r):
        return np.power(l.astype(np.float64), r.astype(np.float64))

    def do_tpu(self, l, r):
        jnp = _jnp()
        return jnp.power(l.astype(jnp.float64), r.astype(jnp.float64))


class Atan2(BinaryExpression):
    def result_dtype(self, lt, rt):
        return T.FLOAT64

    def do_cpu(self, l, r):
        return np.arctan2(l.astype(np.float64), r.astype(np.float64))

    def do_tpu(self, l, r):
        jnp = _jnp()
        return jnp.arctan2(l.astype(jnp.float64), r.astype(jnp.float64))


class ToDegrees(_DoubleUnary):
    np_fn = staticmethod(np.degrees)
    jnp_name = "degrees"


class ToRadians(_DoubleUnary):
    np_fn = staticmethod(np.radians)
    jnp_name = "radians"
