"""Device string encoding.

XLA needs static shapes, so variable-width strings are hostile to the device
path (SURVEY §7 "Strings on TPU").  The device representation here is a
fixed-width padded byte matrix:

    bytes:   uint8[rows, max_len]   (UTF-8 payload, zero padded)
    lengths: int32[rows]            (byte length per row)

This supports vectorized upper/lower/substring/length/contains/starts/ends/
concat/compare on the VPU.  Regex-class ops fall back to the host engine,
mirroring the reference's regex bail-outs (GpuOverrides.scala:326-371).

Host-side strings are ``object`` ndarrays of python ``str``.
"""
from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

# pyarrow's object-ndarray converters are not reliably thread-safe in
# this environment when task threads convert while XLA's own thread pool
# is busy (observed hard SIGSEGV in pa.array under the concurrent
# collect path); one lock serializes the C conversion — still ~40x the
# python loop — and costs nothing in the single-thread case
_PA_LOCK = threading.Lock()


def _pa():
    """pyarrow with its memory pool forced to the system allocator —
    arrow's bundled mimalloc pool segfaults under this image's
    concurrent XLA-CPU + task-thread workload (observed repeatedly in
    pa.array during multithreaded collects; system pool is stable)."""
    import pyarrow as pa

    if not getattr(_pa, "_pool_set", False):
        try:
            pa.set_memory_pool(pa.system_memory_pool())
        except Exception:  # noqa: BLE001
            pass
        _pa._pool_set = True
    return pa


import os as _os

_FORCE_SLOW_ENCODE = _os.environ.get("SRT_SLOW_ENCODE") == "1"
_FORCE_SLOW_DECODE = _os.environ.get("SRT_SLOW_DECODE") == "1"


def _encode_slow(values, validity, max_len):
    n = len(values)
    encoded = []
    for i in range(n):
        if validity is not None and not validity[i]:
            encoded.append(b"")
        else:
            v = values[i]
            encoded.append(v.encode("utf-8") if isinstance(v, str)
                           else (v if isinstance(v, bytes) else b""))
    lengths = np.fromiter((len(b) for b in encoded), dtype=np.int32, count=n)
    ml = int(lengths.max()) if n else 0
    if max_len is None:
        max_len = max(1, ml)
    elif ml > max_len:
        raise ValueError(f"string of {ml} bytes exceeds max_len {max_len}")
    out = np.zeros((n, max_len), dtype=np.uint8)
    for i, b in enumerate(encoded):
        if b:
            out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out, lengths


def encode(values: np.ndarray, validity: Optional[np.ndarray],
           max_len: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Encode an object ndarray of str into (bytes[rows,max_len], lengths).

    Vectorized via arrow's C encoder (offsets+data buffers) — the
    per-row python loop was the single hottest host-path line in the
    r3 bench (≈40% of a q1 collect).  Falls back to the python loop for
    mixed/bytes inputs."""
    n = len(values)
    if n == 0 or _FORCE_SLOW_ENCODE:
        return _encode_slow(values, validity, max_len)
    try:
        pa = _pa()
    except ImportError:
        return _encode_slow(values, validity, max_len)
    try:
        vals = np.asarray(values, dtype=object)
        if validity is not None:
            vals = np.where(np.asarray(validity, dtype=bool), vals, None)
        with _PA_LOCK:
            arr = pa.array(vals, type=pa.string())
            bufs = arr.buffers()
            offsets = np.array(
                np.frombuffer(bufs[1], dtype=np.int32, count=n + 1))
            nbytes = int(offsets[-1])
            data = (np.array(np.frombuffer(bufs[2], dtype=np.uint8,
                                           count=nbytes))
                    if bufs[2] is not None and nbytes else
                    np.empty(0, dtype=np.uint8))
        # null rows have equal offsets, so their lengths are already 0
        lengths = np.diff(offsets).astype(np.int32)
    except Exception:  # noqa: BLE001 — any arrow failure: exact slow path
        return _encode_slow(values, validity, max_len)
    ml = int(lengths.max()) if n else 0
    if max_len is None:
        max_len = max(1, ml)
    elif ml > max_len:
        raise ValueError(f"string of {ml} bytes exceeds max_len {max_len}")
    out = np.zeros((n, max_len), dtype=np.uint8)
    # row-major boolean scatter: the True cells enumerate in exactly
    # concatenated-row order, which is the arrow data buffer's layout
    mask = np.arange(max_len, dtype=np.int32) < lengths[:, None]
    out[mask] = data
    return out, lengths


def decode(byte_mat: np.ndarray, lengths: np.ndarray,
           validity: Optional[np.ndarray] = None) -> np.ndarray:
    """Decode (bytes, lengths) back to an object ndarray of str."""
    n = byte_mat.shape[0]
    lengths = np.asarray(lengths)
    try:
        if _FORCE_SLOW_DECODE:
            raise RuntimeError("forced slow decode")
        pa = _pa()

        w = byte_mat.shape[1] if byte_mat.ndim == 2 else 0
        # clamp HARD: invalid/padding lanes carry arbitrary gathered
        # lengths (negative or > width); unclamped they make the cumsum
        # offsets non-monotonic and from_buffers then reads out of
        # bounds — corrupt str objects that crash far away (observed
        # SIGSEGV in a later pa.array over re-encoded output)
        ln = np.clip(lengths.astype(np.int64), 0, w)
        if validity is not None:
            ln = np.where(np.asarray(validity, dtype=bool), ln, 0)
        mask = np.arange(w, dtype=np.int64) < ln[:, None]
        flat = np.ascontiguousarray(byte_mat[mask])
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(ln, out=offsets[1:])
        with _PA_LOCK:
            arr = pa.StringArray.from_buffers(
                n, pa.py_buffer(offsets.tobytes()),
                pa.py_buffer(flat.tobytes()))
            out = arr.to_numpy(zero_copy_only=False)
        if out.dtype != object:
            out = out.astype(object)
    except Exception:  # noqa: BLE001 — e.g. invalid utf-8: exact slow path
        w = byte_mat.shape[1] if byte_mat.ndim == 2 else 0
        out = np.empty(n, dtype=object)
        for i in range(n):
            k = max(0, min(int(lengths[i]), w))
            out[i] = bytes(byte_mat[i, :k]).decode("utf-8",
                                                   errors="replace")
    if validity is not None:
        out[~np.asarray(validity, dtype=bool)] = None
    return out


def pad_rows(byte_mat: np.ndarray, lengths: np.ndarray,
             target_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    n, w = byte_mat.shape
    if target_rows == n:
        return byte_mat, lengths
    bm = np.zeros((target_rows, w), dtype=np.uint8)
    bm[:n] = byte_mat
    ln = np.zeros(target_rows, dtype=np.int32)
    ln[:n] = lengths
    return bm, ln
