"""Resource management helpers.

Reference analogue: ``Arm.scala`` (withResource loan pattern) and
``implicits.scala`` safeClose.  Python's GC covers most cases, but device
buffers tracked by the spill framework need deterministic release, so the
same loan-pattern API is kept."""
from __future__ import annotations

from contextlib import contextmanager
from typing import Iterable


@contextmanager
def with_resource(resource):
    """``with with_resource(r) as r: ...`` — closes r on exit."""
    try:
        yield resource
    finally:
        close = getattr(resource, "close", None)
        if close is not None:
            close()


def safe_close(resources: Iterable) -> None:
    """Close all, raising the first error after attempting every close."""
    first_err = None
    for r in resources:
        try:
            close = getattr(r, "close", None)
            if close is not None:
                close()
        except Exception as e:  # noqa: BLE001
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err
