"""Device-resident shuffle: packed partition blocks + shuffle stats.

Reference analogue: the UCX shuffle plugin's device-to-device data path
(RapidsShuffleClient/Server) — map output never round-trips through
host memory on the happy path.  The TPU form: a shuffle write runs ONE
jitted partition-build kernel per input batch that groups rows by
destination partition inside a single flat HBM block (stable sort by
partition id), and records per-partition ``counts``/``starts`` vectors.
Readers slice their partition out of the resident block with a shared
gather kernel — no d2h, no host CRC, no h2d.  CRC32C stamping moves to
the spill/host boundary: it happens exactly when a block is demoted off
the device tier (``SpillableBuffer.to_host``), which is also where the
``shuffle.hostBytes`` metric accrues.

Layout note: the LOCAL block is the sorted-flat ragged form (block
padded size == input padded size).  The padded ``[n_parts, max_rows]``
tile form lives in ``parallel/exchange.py`` (``bucket_rows`` /
``collective_exchange``) where the fused ``lax.all_to_all`` collective
needs equal-capacity lanes per destination; a local exchange with
``n_out`` readers over one process would pay ``n_out×`` HBM for the
same information the flat block carries in ``1×``.

Both kernels register in the process-wide kernel cache keyed by schema
signature, so every exchange of the same layout shares one compiled
build and one compiled slice program.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict

from ..data.column import DeviceBatch
from ..ops.kernels.gather import gather_batch, gather_column


# ==========================================================================
# shuffle counters (process-wide, delta-reported per query like the
# kernel cache: ExecContext marks at query start, the session merges
# ``metrics_since(mark)`` into last_metrics under ``shuffle.*``)
# ==========================================================================
class ShuffleStats:
    _KEYS = ("deviceBytes", "hostBytes", "collectiveTimeNs",
             "numFallbacks", "checkpointBytes")

    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[str, int] = {k: 0 for k in self._KEYS}

    def reset(self) -> None:
        with self._lock:
            for k in self._KEYS:
                self._values[k] = 0

    def add(self, name: str, v: int = 1) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0) + v

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._values)

    def metrics_since(self, mark) -> Dict[str, int]:
        """Per-query ``shuffle.*`` metric section: counter deltas since
        ``mark`` (a :meth:`counters` snapshot from ExecContext)."""
        cur = self.counters()
        out = {}
        for k, v in cur.items():
            base = mark.get(k, 0) if mark else 0
            out[f"shuffle.{k}"] = v - base
        return out


#: THE process-wide instance (like kernel_cache.GLOBAL)
GLOBAL = ShuffleStats()


@contextmanager
def collective_timer():
    """Wall-clock a Python-level collective dispatch into
    ``shuffle.collectiveTime`` (trace-time collective calls inside
    shard_map cost nothing per se — the dispatch that launches them is
    what this measures)."""
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        GLOBAL.add("collectiveTimeNs", time.perf_counter_ns() - t0)


# ==========================================================================
# packed partition block: build + slice kernel bodies (module level so
# the kernel-cache key — not a per-exec closure — owns the compilation)
# ==========================================================================
def packed_build(batch: DeviceBatch, pids, n_out: int):
    """Group rows by destination partition inside ONE flat device block.

    Stable sort by partition id (padding rows get the sentinel id
    ``n_out`` so every real row lands in front — the spill serializer
    trims to ``num_rows`` and must lose only padding); returns
    ``(block, counts, starts)`` where ``counts[p]``/``starts[p]``
    delimit partition ``p``'s contiguous row range in the block.  The
    contiguousSplit analogue of the reference (Plugin.scala:54-83):
    one sort yields every split at once."""
    import jax.numpy as jnp

    pids = jnp.where(batch.row_mask(), pids, n_out)
    order = jnp.argsort(pids, stable=True).astype(jnp.int32)
    sorted_pids = pids[order]
    bounds = jnp.searchsorted(
        sorted_pids, jnp.arange(n_out + 1, dtype=sorted_pids.dtype))
    counts = (bounds[1:] - bounds[:-1]).astype(jnp.int32)
    starts = bounds[:-1].astype(jnp.int32)
    return gather_batch(batch, order, batch.num_rows), counts, starts


def packed_slice(block: DeviceBatch, start, count) -> DeviceBatch:
    """Slice one partition's contiguous row range out of a packed
    block: a clipped-index gather to the front plus a lane mask.
    ``start``/``count`` are traced scalars, so ONE compiled program
    serves every (partition, block) pair of the same layout."""
    import jax.numpy as jnp

    padded = block.padded_rows
    lane = jnp.arange(padded, dtype=jnp.int32)
    idx = jnp.clip(start + lane, 0, max(padded - 1, 0))
    mask = lane < count
    cols = [gather_column(c, idx, mask) for c in block.columns]
    return DeviceBatch(block.schema, cols,
                       jnp.asarray(count, dtype=jnp.int32))


def packed_build_kernel(schema, n_out: int):
    """The jitted build kernel, shared across execs via the kernel
    cache (key: schema layout + fan-out; ``n_out`` is static — it
    shapes the counts/starts vectors)."""
    from ..exec.kernel_cache import jit_kernel, schema_signature

    return jit_kernel(
        packed_build,
        key=("shuffle.packedBuild", int(n_out), schema_signature(schema)),
        static_argnums=(2,))


def packed_slice_kernel(schema):
    from ..exec.kernel_cache import jit_kernel, schema_signature

    return jit_kernel(
        packed_slice,
        key=("shuffle.packedSlice", schema_signature(schema)))


def fetch_counts(handles):
    """The ONE gated host readback of the device exchange write path:
    a single batched ``jax.device_get`` of the flush chunk's
    counts/starts vectors (tiny int32[n_out] pairs — per-block syncs
    would be a device RTT each).  Named so the host-sync analysis
    rule can gate exactly this function as the device path's host
    materialization point."""
    import jax

    return jax.device_get(list(handles))


def resolve_mode(conf_mode: str, *, force_host: bool = False,
                 headroom: int = 1) -> str:
    """Effective exchange data path for one shuffle write.

    ``device``/``host`` obey the conf; ``auto`` picks device while the
    HBM arena has headroom; a ladder-forced re-execution
    (``force_host``) always stages.  An unknown conf value raises at
    the write, not mid-drain.  Note range partitioning never takes the
    PACKED path even under ``device`` (its placement needs sampled
    bounds that only exist after the full write drain) — it keeps the
    legacy device-resident path, staging only when this returns
    ``host``."""
    mode = (conf_mode or "auto").lower()
    if mode not in ("device", "host", "auto"):
        raise ValueError(
            f"shuffle.mode must be device|host|auto, got {conf_mode!r}")
    if force_host:
        return "host"
    if mode == "auto":
        return "device" if headroom > 0 else "host"
    return mode
