"""Device joins: shuffled hash join + broadcast hash join.

Reference analogue: GpuShuffledHashJoinExec.scala:59 (build one side
into a single table, stream the other), GpuBroadcastHashJoinExec
(org/apache/spark/sql/rapids/execution/...:83), shared core
GpuHashJoin.scala:25-140, and GpuSortMergeJoinMeta (SMJ replaced by the
shuffled join, GpuSortMergeJoinExec.scala:23).  Capability superset:
the reference supports inner/left/semi/anti with conditions only on
inner; this exec adds right/full outer (still condition-on-inner-only,
matching GpuHashJoin.tagJoin's gate).

The kernel is the sort-merge pipeline in ops/kernels/join.py; both
sides require a single batch per partition (the reference's
RequireSingleBatch on the build side, extended to both because the
merge sorts both sides together).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .. import types as T
from ..data.column import DeviceBatch, bucket_rows
from ..memory import retry as R
from ..ops.cast import Cast
from ..ops.expression import Expression, as_device_column
from ..ops.kernels import join as J
from ..ops.kernels.gather import compact
from ..utils import metrics as M
from ..utils.tracing import trace_range
from .base import DevicePartitionedData, RequireSingleBatch, TpuExec
from .coalesce import concat_device_batches


def _max_string_widths(batches) -> dict:
    """col index -> max string byte-matrix width across ``batches`` (an
    upper bound for any key-hash bucket of their rows)."""
    widths: dict = {}
    for b in batches:
        for ci, c in enumerate(b.columns):
            if c.lengths is not None:
                widths[ci] = max(widths.get(ci, 1), c.data.shape[1])
    return widths


def _common_key_exprs(l_keys: List[Expression],
                      r_keys: List[Expression]):
    """Cast key pairs to a common dtype so device comparison is exact
    (the host oracle compares python values, where 1 == 1.0)."""
    lo, ro = [], []
    for lk, rk in zip(l_keys, r_keys):
        if lk.dtype.np_dtype == rk.dtype.np_dtype \
                or lk.dtype.is_string or rk.dtype.is_string:
            lo.append(lk)
            ro.append(rk)
            continue
        common = T.from_numpy(np.promote_types(lk.dtype.np_dtype,
                                               rk.dtype.np_dtype))
        lo.append(lk if lk.dtype == common else Cast(lk, common))
        ro.append(rk if rk.dtype == common else Cast(rk, common))
    return lo, ro


class TpuHashJoinExec(TpuExec):
    """Shared device join core (reference: GpuHashJoin trait)."""

    def __init__(self, left, right, plan):
        super().__init__([left, right])
        self.plan = plan  # physical.HashJoinExec (exprs already bound)
        self.how = plan.how
        self.left_keys, self.right_keys = _common_key_exprs(
            plan.left_keys, plan.right_keys)
        self.condition = plan.condition
        self._schema = plan.schema
        from .kernel_cache import (expr_signature, jit_kernel,
                                   schema_signature)

        sig = ("join", type(self).__name__, self.how,
               expr_signature(self.left_keys),
               expr_signature(self.right_keys),
               self.condition.sql() if self.condition is not None
               else None,
               schema_signature(left.schema),
               schema_signature(right.schema),
               schema_signature(plan.schema))
        twin = self.kernel_twin()
        self._count_kernel = jit_kernel(twin._count,
                                        key=sig + ("count",))
        self._expand_kernel = jit_kernel(twin._expand, static_argnums=(0,),
                                         key=sig + ("expand",))
        self._semi_kernel = jit_kernel(twin._semi_anti,
                                       key=sig + ("semi",))

    @property
    def schema(self):
        return self._schema

    @property
    def children_coalesce_goal(self):
        return [RequireSingleBatch(), RequireSingleBatch()]

    # ------------------------------------------------------------------
    # out-of-core: grace partitioning (both sides split by key hash into
    # sub-buckets that fit the batch target; equal keys colocate, so each
    # bucket pair joins independently for every join type)
    # ------------------------------------------------------------------
    def _bucket_side(self, batches, key_exprs, m: int, fw,
                     seed: int) -> List[List[int]]:
        """Split each batch into ``m`` key-hash buckets, registering every
        sub-batch with the spill catalog.  Returns per-bucket buf-id
        lists.

        ``seed`` must differ from the exchange's partitioning seed (42):
        rows inside one shuffle partition already satisfy h42 % P == p,
        so re-bucketing them with the same hash is degenerate whenever
        ``m`` shares factors with P (everything lands in one bucket).
        Each recursion level gets its own seed for the same reason."""
        import jax
        import jax.numpy as jnp

        from ..data.column import slice_device_batch
        from ..memory.spill import SpillPriorities
        from ..utils import hashing

        buckets: List[List[int]] = [[] for _ in range(m)]
        totals = [0] * m  # per-bucket row totals (for shape unification)
        for b in batches:
            padded = b.padded_rows
            keys = [as_device_column(k.eval_tpu(b), padded)
                    for k in key_exprs]
            h = hashing.hash_device_batch(keys, seed=seed)
            pids = hashing.pmod(h, m).astype(jnp.int32)
            # ONE readback of all m bucket counts (a per-bucket
            # int(sub.num_rows) is a device RTT each — m<=64 of them
            # per batch dominated grace joins on a remote-TPU link)
            seg = jnp.where(b.row_mask(), pids, m)
            counts = np.asarray(jax.ops.segment_sum(
                jnp.ones_like(seg, dtype=jnp.int32), seg,
                num_segments=m + 1))[:m]
            for i in range(m):
                cnt = int(counts[i])
                if cnt == 0:
                    continue
                sub = slice_device_batch(compact(b, pids == i), 0, cnt)
                buckets[i].append(fw.add_batch(
                    sub, priority=SpillPriorities.output_for_read()))
                totals[i] += cnt
        return buckets, totals

    def _take_bucket(self, buf_ids: List[int], side: int, fw) -> DeviceBatch:
        from ..data.column import host_to_device
        from ..plan.physical import _empty_batch

        if not buf_ids:
            return host_to_device(_empty_batch(self.children[side].schema))
        parts = []
        for bid in buf_ids:
            parts.append(fw.acquire_batch(bid))
            fw.release_batch(bid)
            fw.remove_batch(bid)
        return concat_device_batches(parts) if len(parts) > 1 else parts[0]

    #: recursion bound for grace bucketing: 64 buckets/level ^ 6 levels
    #: is far past any realistic skew; a hit means pathological input
    _GRACE_MAX_LEVEL = 6

    def _join_grace(self, l_batches, r_batches, total_bytes: int,
                    target: int, level: int = 0, rctx=None):
        """Join sides too big for one batch pair: hash both into the same
        bucket space and join bucket-wise (the spill-aware analogue of the
        reference's RequireSingleBatch build side — which documents
        no-spill as a TODO, aggregate.scala pipeline comment; this
        extends it).  Buckets still larger than the target RECURSE with
        a fresh hash seed instead of overflowing (r3 Weak #7 lifted the
        m<64 cap).

        Every directly-joined bucket pair at a level is padded to ONE
        (row-capacity, string-width) shape per side — computed from the
        bucket row counts and the parent batches' widths — so the join
        kernels trace/compile ONCE per level instead of once per pair
        shape (r4: q3 spent ~200s tracing per-pair grace programs,
        VERDICT r4 next-round #2).  Capacities snap to the engine's
        power-of-two row grid, so repeats across levels, partitions and
        queries collapse onto cached executables."""
        from ..data.column import bucket_rows as _brows
        from ..data.column import pad_device_batch
        from ..memory.spill import SpillFramework

        fw = SpillFramework.get()
        m = 2
        while m * target < total_bytes and m < 64:
            m <<= 1
        seed = 0x5D1E_995 + 1_000_003 * level  # != exchange seed 42
        l_bytes = sum(b.device_bytes() for b in l_batches)
        r_bytes = total_bytes - l_bytes
        l_buckets, l_counts = self._bucket_side(
            l_batches, self.left_keys, m, fw, seed)
        r_buckets, r_counts = self._bucket_side(
            r_batches, self.right_keys, m, fw, seed)
        l_rows = sum(l_counts)
        r_rows = sum(r_counts)
        l_bpr = l_bytes / max(l_rows, 1)
        r_bpr = r_bytes / max(r_rows, 1)
        # decide recursion from the bucket COUNTS (known before any
        # take), so the pad capacity can exclude recursing buckets: a
        # skewed hot bucket must not inflate every small pair's shape
        est = [l_counts[i] * l_bpr + r_counts[i] * r_bpr
               for i in range(m)]
        recurse = [est[i] > 2 * target
                   and level < self._GRACE_MAX_LEVEL
                   and est[i] < total_bytes
                   for i in range(m)]
        direct_l = [l_counts[i] for i in range(m) if not recurse[i]]
        direct_r = [r_counts[i] for i in range(m) if not recurse[i]]
        cap_l = _brows(max(direct_l) if any(direct_l) else 1)
        cap_r = _brows(max(direct_r) if any(direct_r) else 1)
        l_widths = _max_string_widths(l_batches)
        r_widths = _max_string_widths(r_batches)
        for i in range(m):
            if not l_buckets[i] and not r_buckets[i]:
                continue
            lb = self._take_bucket(l_buckets[i], 0, fw)
            rb = self._take_bucket(r_buckets[i], 1, fw)
            if recurse[i]:
                # still oversized but shrinking: split this bucket again
                # (est == total_bytes would mean one dominant key —
                # rehashing cannot split equal keys, join directly)
                pair_bytes = lb.device_bytes() + rb.device_bytes()
                yield from self._join_grace([lb], [rb], pair_bytes,
                                            target, level + 1, rctx)
            else:
                lbp = pad_device_batch(lb, cap_l, l_widths)
                rbp = pad_device_batch(rb, cap_r, r_widths)
                yield R.retry_call(
                    lambda lbp=lbp, rbp=rbp: self._metrics_wrap(
                        lambda: self._join(lbp, rbp)), rctx)

    # ------------------------------------------------------------------
    def _keys_of(self, batch: DeviceBatch, exprs):
        return [as_device_column(k.eval_tpu(batch), batch.padded_rows)
                for k in exprs]

    def _count(self, lb: DeviceBatch, rb: DeviceBatch):
        pr = J.probe(self._keys_of(lb, self.left_keys),
                     self._keys_of(rb, self.right_keys),
                     lb.row_mask(), rb.row_mask())
        emit, r_extra, total = J.emit_counts(pr, self.how,
                                             lb.row_mask(), rb.row_mask())
        return pr, emit, r_extra, total

    def _expand(self, c_out: int, lb: DeviceBatch, rb: DeviceBatch,
                pr: J.Probe, emit, r_extra) -> DeviceBatch:
        import jax.numpy as jnp

        lidx, ridx, slot_valid = J.expand_pairs(pr, emit, r_extra, c_out)
        cols = (J.gather_side(lb.columns, lidx, slot_valid)
                + J.gather_side(rb.columns, ridx, slot_valid))
        num_rows = slot_valid.sum().astype(jnp.int32)
        out = DeviceBatch(self._schema, cols, num_rows)
        if self.condition is not None:
            c = as_device_column(self.condition.eval_tpu(out), c_out)
            keep = c.data.astype(jnp.bool_) & c.validity & slot_valid
            out = compact(out, keep)
        return out

    def _semi_anti(self, lb: DeviceBatch, rb: DeviceBatch) -> DeviceBatch:
        pr = J.probe(self._keys_of(lb, self.left_keys),
                     self._keys_of(rb, self.right_keys),
                     lb.row_mask(), rb.row_mask())
        has = pr.cnt > 0
        keep = has if self.how == "semi" else ~has
        return compact(lb, keep)

    def _join(self, lb: DeviceBatch, rb: DeviceBatch) -> DeviceBatch:
        # OOM-injection checkpoint: the join's working set is the pair
        R.maybe_inject_oom(type(self).__name__ + ".join")
        if self.how in ("semi", "anti"):
            return self._semi_kernel(lb, rb)
        pr, emit, r_extra, total = self._count_kernel(lb, rb)
        c_out = bucket_rows(int(total))  # host sync: output sizing
        return self._expand_kernel(c_out, lb, rb, pr, emit, r_extra)

    #: join types whose stream (left) side is row-local — every output
    #: row depends on one left row plus the whole build side — so the
    #: stream batch can be split by rows under memory pressure and the
    #: piece results concatenated (right/full track build-side match
    #: state across ALL stream rows and must not be split)
    _STREAM_SPLITTABLE = ("inner", "left", "semi", "anti")

    def _join_stream_retry(self, lb: DeviceBatch, rb: DeviceBatch, rctx):
        """Join one stream batch against the (held) build batch through
        the retry framework, splitting the stream side when allowed."""
        fn = lambda l: self._metrics_wrap(lambda: self._join(l, rb))  # noqa: E731
        if self.how in self._STREAM_SPLITTABLE:
            yield from R.with_split_retry(lb, fn, ctx=rctx)
        else:
            yield R.retry_call(lambda: fn(lb), rctx)

    def join_static(self, lb: DeviceBatch, rb: DeviceBatch, c_out: int):
        """Trace-safe join with a fixed output capacity (no host sync) —
        the SPMD form used under shard_map by the distributed runner.
        Returns ``(out_batch, total)``: ``total`` is the true match
        count so the caller can detect capacity overflow and retry with
        a larger ``c_out``."""
        import jax.numpy as jnp

        if self.how in ("semi", "anti"):
            out = self._semi_anti(lb, rb)
            return out, jnp.asarray(0, dtype=jnp.int64)
        pr, emit, r_extra, total = self._count(lb, rb)
        return self._expand(c_out, lb, rb, pr, emit, r_extra), total

    # ------------------------------------------------------------------
    def execute_columnar(self, ctx):
        raise NotImplementedError

    def _metrics_wrap(self, fn):
        with trace_range(type(self).__name__,
                         self.metrics[M.TOTAL_TIME]):
            out = fn()
        self.metrics[M.NUM_OUTPUT_BATCHES].add(1)
        return out


class TpuShuffledHashJoinExec(TpuHashJoinExec):
    """Both sides co-partitioned by the exchange; joins partition-wise
    (reference: GpuShuffledHashJoinExec.doExecuteColumnar:88).  A
    partition pair that exceeds the batch target joins out-of-core via
    grace hash bucketing instead of demanding a single batch."""

    @property
    def children_coalesce_goal(self):
        from .base import TargetSize

        return [TargetSize(), TargetSize()]

    def execute_columnar(self, ctx):
        left = self.children[0].execute_columnar(ctx)
        right = self.children[1].execute_columnar(ctx)
        self._init_metrics(ctx)
        assert left.n_partitions == right.n_partitions, \
            "shuffled join requires co-partitioned children"
        target = ctx.conf.batch_size_bytes
        rctx = R.RetryContext.for_exec(ctx, type(self).__name__)

        def make(pid):
            def it():
                l_batches = list(left.iterator(pid))
                r_batches = list(right.iterator(pid))
                total = sum(b.device_bytes()
                            for b in l_batches + r_batches)
                if len(l_batches) <= 1 and len(r_batches) <= 1:
                    lb = self._of(l_batches, 0)
                    rb = self._of(r_batches, 1)
                    yield from self._join_stream_retry(lb, rb, rctx)
                    return
                yield from self._join_grace(l_batches, r_batches,
                                            total, target, rctx=rctx)

            return it

        return DevicePartitionedData(
            [make(i) for i in range(left.n_partitions)])

    def _of(self, batches, side: int) -> DeviceBatch:
        from ..data.column import host_to_device
        from ..plan.physical import _empty_batch

        if not batches:
            return host_to_device(_empty_batch(self.children[side].schema))
        return concat_device_batches(batches) \
            if len(batches) > 1 else batches[0]

    def describe(self):
        return f"TpuShuffledHashJoin[{self.how}]"


def _is_adaptive_build(node) -> bool:
    """True when the broadcast build subtree contains a materialized
    stage leaf — i.e. the join was converted by adaptive execution and
    its broadcast artifact is scoped to this one query."""
    from ..adaptive.executor import MaterializedStageExec

    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, MaterializedStageExec):
            return True
        stack.extend(n.children)
    return False


class TpuBroadcastHashJoinExec(TpuHashJoinExec):
    """Build (right) side gathered across partitions once and joined
    against every stream partition (reference:
    GpuBroadcastHashJoinExec.doExecuteColumnar:115 — the broadcast
    re-upload becomes a device concat; on a mesh the build side is
    replicated, the XLA analogue of the broadcast exchange).  The stream
    side is NOT coalesced to one batch: every join type this exec allows
    (inner/left/semi/anti, planner gate) is row-local on the stream side,
    so batches stream through independently."""

    @property
    def children_coalesce_goal(self):
        from .base import TargetSize

        # build side keeps the single-batch demand, as the reference does
        return [TargetSize(), RequireSingleBatch()]

    def execute_columnar(self, ctx):
        from .broadcast import canonical_key

        left = self.children[0].execute_columnar(ctx)
        self._init_metrics(ctx)
        reg = sem = None
        if ctx is not None and getattr(ctx, "session", None) is not None:
            reg = getattr(ctx.session, "broadcast_registry", None)
            dm = ctx.session.device_manager
            sem = dm.semaphore if dm is not None else None
        assert reg is not None, \
            "broadcast join requires the device session's registry"
        key = canonical_key(self.children[1])
        if ctx is not None and _is_adaptive_build(self.children[1]):
            # dynamic (AQE-converted) build side: the artifact's key
            # weakly references a per-execution stage leaf, so no
            # future query can ever hit it.  Record a strong ref so
            # the session frees the build at query end — otherwise it
            # stays cataloged until the registry's next lazy purge.
            nodes = getattr(ctx, "aqe_broadcast_nodes", None)
            if nodes is None:
                nodes = ctx.aqe_broadcast_nodes = []
            nodes.append(self.children[1])

        def build_batch() -> DeviceBatch:
            # the build child executes ONLY when the artifact is not
            # cached yet (reference: the broadcast relation future runs
            # once, GpuBroadcastExchangeExec.scala:247)
            right = self.children[1].execute_columnar(ctx)
            batches = []
            for pid in range(right.n_partitions):
                batches.extend(right.iterator(pid))
            if batches:
                return (concat_device_batches(batches)
                        if len(batches) > 1 else batches[0])
            from ..data.column import host_to_device
            from ..plan.physical import _empty_batch

            return host_to_device(_empty_batch(self.children[1].schema))

        rctx = R.RetryContext.for_exec(ctx, type(self).__name__)

        def make(pid):
            def it():
                art = reg.get_or_build(key, build_batch,
                                       self.children[1].schema, sem=sem)
                streamed = False
                for lb in left.iterator(pid):
                    streamed = True
                    # lazy re-upload if spilled — a promotion is an
                    # allocation, so it recovers via spill+backoff
                    rb = R.retry_call(art.acquire, rctx)
                    try:
                        yield from self._join_stream_retry(lb, rb, rctx)
                    finally:
                        art.release()
                if not streamed:
                    lb = self._one_batch_empty(0)
                    rb = R.retry_call(art.acquire, rctx)
                    try:
                        yield R.retry_call(
                            lambda: self._metrics_wrap(
                                lambda: self._join(lb, rb)), rctx)
                    finally:
                        art.release()

            return it

        return DevicePartitionedData(
            [make(i) for i in range(left.n_partitions)])

    def _one_batch_empty(self, side: int) -> DeviceBatch:
        from ..data.column import host_to_device
        from ..plan.physical import _empty_batch

        return host_to_device(_empty_batch(self.children[side].schema))

    def describe(self):
        return f"TpuBroadcastHashJoin[{self.how}]"


# ==========================================================================
# rule registration
# ==========================================================================
def register(register_exec):
    from ..plan import physical as P

    def tag(meta):
        plan = meta.plan
        if plan.condition is not None and plan.how != "inner":
            # reference: GpuHashJoin.tagJoin — conditions only on inner
            meta.will_not_work_on_tpu(
                f"join condition on {plan.how} join is not supported "
                f"on TPU (inner only)")

    def exprs_of(plan: P.HashJoinExec):
        out = list(plan.left_keys) + list(plan.right_keys)
        if plan.condition is not None:
            out.append(plan.condition)
        return out

    def convert(meta, ch):
        cls = TpuBroadcastHashJoinExec if meta.plan.broadcast \
            else TpuShuffledHashJoinExec
        return cls(ch[0], ch[1], meta.plan)

    register_exec(
        P.HashJoinExec,
        convert=convert,
        desc="sort-merge equi-join on TPU",
        tag=tag,
        exprs_of=exprs_of)
