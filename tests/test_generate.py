"""Device Generate (explode) vs CPU oracle.

Reference analogue: GpuGenerateExec tests — explode of per-row literal
element patterns, the statically-shaped case.
"""
import numpy as np

import spark_rapids_tpu as srt
from spark_rapids_tpu import f


def _sessions():
    return srt.Session(), srt.Session(tpu_enabled=False)


def _df(sess, n=50):
    rng = np.random.default_rng(4)
    return sess.create_dataframe({
        "a": np.arange(n, dtype=np.int64),
        "b": rng.random(n),
        "s": np.array([f"x{i%7}" for i in range(n)], dtype=object),
    }, n_partitions=2)


def _check(build, expect_tpu=True):
    tpu, cpu = _sessions()
    qs = [build(_df(s)) for s in (tpu, cpu)]
    if expect_tpu:
        ex = qs[0].explain()
        assert "GenerateExec -> will run on TPU" in ex, ex
    assert qs[0].collect() == qs[1].collect()


def test_explode_numeric_expressions():
    _check(lambda df: df.explode(
        [f.col("a"), f.col("a") * f.lit(10), f.lit(-1)], name="e"))


def test_explode_preserves_row_major_order():
    tpu, cpu = _sessions()
    rows = tpu.create_dataframe({"a": np.array([7, 8])}) \
        .explode([f.lit(1), f.lit(2), f.lit(3)], name="e").collect()
    assert rows == [(7, 1), (7, 2), (7, 3), (8, 1), (8, 2), (8, 3)]


def test_explode_strings():
    _check(lambda df: df.explode(
        [f.col("s"), f.lit("fixed"), f.concat(f.col("s"), f.lit("!"))],
        name="e"))


def test_explode_with_nulls():
    _check(lambda df: df.explode(
        [f.col("a"), f.lit(None, None), f.col("a") + f.lit(1)], name="e"))


def test_explode_then_aggregate():
    _check(lambda df: df.explode([f.col("a"), f.col("a") * f.lit(2)],
                                 name="e")
           .group_by("s").agg(f.sum("e").alias("t"))
           .sort("s"))
