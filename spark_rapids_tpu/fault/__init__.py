"""Query-level fault tolerance.

The distributed path must survive what PR-1's OOM framework survives
locally: a corrupted spill/shuffle payload, a hung collective, a
crashed stage.  This package holds the pieces:

* :mod:`.errors`    — typed recoverable faults (corruption, crash,
  watchdog timeout) under one :class:`~.errors.TpuFaultError` base
* :mod:`.injector`  — the generalized deterministic
  :class:`~.injector.FaultInjector` (``oom|corrupt|delay|stage_crash``)
  every recovery path runs through in CI on CPU-only JAX
* :mod:`.integrity` — CRC32C checksums over spill frames and exchange
  host round-trips, verified on read
* :mod:`.stats`     — the per-query ``fault.*`` counters surfaced in
  ``Session.last_metrics``
* :mod:`.ladder`    — the graceful-degradation ladder: distributed ->
  single-process -> CPU-exec plan
"""
from .errors import (TpuFaultError, TpuPayloadCorruption, TpuStageCrash,
                     TpuStageTimeout)
from .injector import (FaultInjector, get_fault_injector,
                       install_fault_injector, maybe_corrupt,
                       maybe_inject_fault, recovery_in_flight)
from .stats import GLOBAL as fault_stats
from .stats import fault_summary

__all__ = [
    "TpuFaultError", "TpuPayloadCorruption", "TpuStageCrash",
    "TpuStageTimeout", "FaultInjector", "get_fault_injector",
    "install_fault_injector", "maybe_corrupt", "maybe_inject_fault",
    "recovery_in_flight", "fault_stats", "fault_summary",
]
