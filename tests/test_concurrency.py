"""Concurrent partition execution (reference: GpuSemaphore.scala:58-98 —
2-4 concurrent tasks per device; docs/tuning-guide.md:85-100).

Partitions are drained by a task thread pool under device-semaphore
admission; results must be identical to sequential execution and the
semaphore must bound concurrent holders.
"""
import threading

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import f
from spark_rapids_tpu.memory.semaphore import DeviceSemaphore


def _norm(rows):
    return sorted(rows, key=repr)


def _assert_rows_close(got, exp):
    """Sorted row equality with float tolerance (device partial sums
    reduce in a different order than the host oracle)."""
    got, exp = _norm(got), _norm(exp)
    assert len(got) == len(exp), (len(got), len(exp))
    for g, e in zip(got, exp):
        assert len(g) == len(e)
        for a, b in zip(g, e):
            if isinstance(a, float) and b is not None:
                assert a == pytest.approx(b, rel=1e-9, abs=1e-9), (g, e)
            else:
                assert a == b, (g, e)


@pytest.mark.parametrize("threads", [1, 4])
def test_concurrent_collect_matches_sequential(threads):
    rng = np.random.RandomState(5)
    data = {"k": rng.randint(0, 30, 2000).tolist(),
            "v": rng.randint(-100, 100, 2000).tolist()}

    sess = srt.Session({"spark.rapids.tpu.sql.taskThreads": threads})
    df = sess.create_dataframe(data, n_partitions=8)
    got = _norm(df.group_by("k").agg(f.sum(df["v"]).alias("s"),
                                     f.count("*").alias("c")).collect())

    ref = srt.Session({"spark.rapids.tpu.sql.taskThreads": 1})
    rdf = ref.create_dataframe(data, n_partitions=8)
    want = _norm(rdf.group_by("k").agg(f.sum(rdf["v"]).alias("s"),
                                       f.count("*").alias("c")).collect())
    assert got == want


def test_concurrent_join_matches_sequential():
    rng = np.random.RandomState(7)
    left = {"k": rng.randint(0, 50, 1500).tolist(),
            "a": list(range(1500))}
    right = {"k": rng.randint(0, 50, 1000).tolist(),
             "b": list(range(1000))}

    def run(threads):
        s = srt.Session({"spark.rapids.tpu.sql.taskThreads": threads})
        l = s.create_dataframe(left, n_partitions=6)
        r = s.create_dataframe(right, n_partitions=6)
        return _norm(l.join(r, on="k", how="left").collect())

    assert run(4) == run(1)


def test_semaphore_bounds_concurrency():
    sem = DeviceSemaphore(2)
    active = []
    peak = []
    lock = threading.Lock()
    barrier = threading.Barrier(6, timeout=10)

    def task():
        barrier.wait()  # all threads contend at once
        sem.acquire_if_necessary()
        sem.acquire_if_necessary()  # reentrant: still one permit
        try:
            with lock:
                active.append(1)
                peak.append(len(active))
            import time

            time.sleep(0.02)
            with lock:
                active.pop()
        finally:
            sem.release_all()

    threads = [threading.Thread(target=task) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert max(peak) <= 2
    assert len(peak) == 6  # every task eventually admitted


def test_acquire_watchdog_raises_instead_of_hanging():
    from spark_rapids_tpu.memory.semaphore import DeviceSemaphoreTimeout

    sem = DeviceSemaphore(1, acquire_timeout=0.2)
    sem.acquire_if_necessary()

    err = []

    def starved():
        try:
            sem.acquire_if_necessary()
        except DeviceSemaphoreTimeout as e:
            err.append(e)

    t = threading.Thread(target=starved)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    assert err, "blocked acquire must raise after the watchdog deadline"
    sem.release_all()


def _two_leaf_join_query(sess, orders, cust):
    from spark_rapids_tpu.plan import functions as F

    o = sess.create_dataframe(dict(orders))
    c = sess.create_dataframe(dict(cust))
    j = o.join(c, on=(["o_custkey"], ["c_custkey"]), how="inner")
    return j.group_by("c_nation").agg(F.sum("o_total").alias("rev"),
                                      F.count("o_total").alias("n"))


def _deadlock_conf():
    # the r3 deadlock shape: more task threads than device permits
    return {"spark.rapids.tpu.sql.taskThreads": 8,
            "spark.rapids.tpu.sql.concurrentTpuTasks": 2,
            "spark.rapids.tpu.sql.broadcastSizeThreshold": 0}


def _join_inputs():
    rng = np.random.RandomState(11)
    orders = {"o_custkey": rng.randint(0, 50, 400),
              "o_total": rng.rand(400) * 1000}
    cust = {"c_custkey": np.arange(50),
            "c_nation": rng.randint(0, 5, 50)}
    return orders, cust


def test_distributed_two_leaf_join_does_not_leak_permits():
    """r3 deadlock #1 regression: a >=2-leaf distributed plan with
    taskThreads > concurrentTpuTasks — the drain workers of the first
    leaf used to consume every permit forever (runner._run_leaf had no
    task-completion release)."""
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.runner import run_distributed

    orders, cust = _join_inputs()
    sess = srt.Session(_deadlock_conf())
    got = run_distributed(sess, _two_leaf_join_query(
        sess, orders, cust), mesh=make_mesh(8)).to_rows()

    ref = srt.Session(tpu_enabled=False)
    want = _two_leaf_join_query(ref, orders, cust).collect()
    _assert_rows_close(got, want)


def test_two_consecutive_distributed_runs_same_process():
    """r3 deadlock #1 regression (second shape): the DeviceManager is a
    process singleton, so permits leaked by run #1 used to wedge run #2
    even for single-leaf plans."""
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.runner import run_distributed
    from spark_rapids_tpu.plan import functions as F

    rng = np.random.RandomState(3)
    data = {"k": rng.randint(0, 20, 300), "v": rng.rand(300) * 100}

    def q(sess):
        df = sess.create_dataframe(dict(data), n_partitions=6)
        return df.group_by("k").agg(F.sum("v").alias("s"))

    sess = srt.Session(_deadlock_conf())
    mesh = make_mesh(8)
    first = _norm(run_distributed(sess, q(sess), mesh=mesh).to_rows())
    second = _norm(run_distributed(sess, q(sess), mesh=mesh).to_rows())
    assert first == second

    ref = srt.Session(tpu_enabled=False)
    want = _norm(q(ref).collect())
    assert first == want


def test_local_shuffled_join_under_permit_starvation():
    """r3 deadlock #2 regression: exchange materialization used to hold
    its write lock across the child drain (which blocks on a permit)
    while permit-holding reader tasks blocked on the lock.  8 task
    threads over 2 permits through a two-exchange shuffled join is
    exactly the bench q3/q5/q16 shape that timed out."""
    orders, cust = _join_inputs()
    sess = srt.Session(_deadlock_conf())
    got = _two_leaf_join_query(sess, orders, cust).collect()

    ref = srt.Session(tpu_enabled=False)
    want = _two_leaf_join_query(ref, orders, cust).collect()
    _assert_rows_close(got, want)


def test_release_all_drops_reentrant_hold():
    sem = DeviceSemaphore(1)
    sem.acquire_if_necessary()
    sem.acquire_if_necessary()
    sem.acquire_if_necessary()
    sem.release_all()
    # permit must be back: a fresh acquire succeeds without blocking
    ok = sem._sem.acquire(timeout=1)
    assert ok
    sem._sem.release()


@pytest.mark.parametrize("threads", [1, 4])
def test_task_retry_reexecutes_failed_partition(threads):
    """A transiently failing partition task is re-run from its lineage
    instead of failing the query (reference: Spark task rescheduling;
    FetchRetry in RapidsShuffleClient.scala:378).  VERDICT r3 row 61."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.data.column import HostBatch
    from spark_rapids_tpu.plan.physical import (ExecContext,
                                                PartitionedData,
                                                collect_batches)
    from spark_rapids_tpu.session import Session

    sess = Session({"spark.rapids.tpu.sql.taskThreads": threads})
    schema = T.Schema([T.Field("x", T.INT64)])
    fails = {"left": 1}

    def good(pid):
        def it():
            yield HostBatch.from_pydict({"x": [pid * 10, pid * 10 + 1]},
                                        schema)
        return it

    def flaky(pid):
        def it():
            if fails["left"] > 0:
                fails["left"] -= 1
                raise RuntimeError("transient task failure")
            yield HostBatch.from_pydict({"x": [99]}, schema)
        return it

    data = PartitionedData([good(0), flaky(1), good(2)])
    out = collect_batches(data, schema,
                          ExecContext(sess.conf, sess))
    assert sorted(out.column("x").to_pylist()) == [0, 1, 20, 21, 99]

    # retries exhausted -> the failure propagates
    fails["left"] = 10
    with pytest.raises(RuntimeError):
        collect_batches(PartitionedData([good(0), flaky(1)]), schema,
                        ExecContext(sess.conf, sess))


def test_task_retry_through_exchange(monkeypatch):
    """A transient failure during the shuffle WRITE must be retryable:
    the failed write re-arms its election so the task-level retry
    re-executes the exchange from lineage (reference: FetchRetry +
    Spark task rescheduling)."""
    import spark_rapids_tpu.exec.transitions as tr
    from spark_rapids_tpu import Session, f
    from spark_rapids_tpu.data import column as dc

    orig = dc.host_to_device
    state = {"fails": 1}

    def flaky(hb, *a, **k):
        if state["fails"]:
            state["fails"] -= 1
            raise RuntimeError("transient upload failure")
        return orig(hb, *a, **k)

    monkeypatch.setattr(tr, "host_to_device", flaky)
    sess = Session()
    df = sess.create_dataframe({"k": [1, 1, 2, 2, 3],
                                "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    got = sorted(df.group_by("k").agg(f.sum("v").alias("s")).collect())
    assert got == [(1, 3.0), (2, 7.0), (3, 5.0)]
    assert state["fails"] == 0, "the injected failure never fired"
