"""Multi-process distributed execution: 2 OS processes, 8 global
devices, one shuffled TPC-H-shaped join+agg, oracle-equal on every
controller.

Reference analogue: the multi-executor UCX shuffle deployment the
reference only ever exercised on real clusters (SURVEY §4 "Multi-node
without a real cluster: they don't simulate it") — this closes that gap
with a hermetic 2-process CPU fixture over jax.distributed + gloo.
"""
import os
import socket
import subprocess
import sys

import pytest


def _cpu_collectives_unavailable() -> str:
    """Multi-process jax.distributed on the CPU backend needs the gloo
    TCP collectives; some jaxlib builds ship without them, and every
    worker then dies in ``jax.distributed.initialize``.  Detect that
    at collection time instead of burning a subprocess timeout on the
    known-doomed drill (ROADMAP "Known environment caveats")."""
    if os.environ.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"):
        return ""
    try:
        from jax.lib import xla_extension
    except Exception as e:  # noqa: BLE001 — no jaxlib = no drill either
        return f"jax.lib.xla_extension unavailable: {e!r}"
    if not hasattr(xla_extension, "make_gloo_tcp_collectives"):
        return ("this jaxlib build has no gloo TCP collectives "
                "(xla_extension.make_gloo_tcp_collectives missing) — "
                "multi-process CPU collectives cannot initialize")
    return ""


_SKIP_REASON = _cpu_collectives_unavailable()
if _SKIP_REASON:
    pytest.skip(_SKIP_REASON, allow_module_level=True)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_shuffled_join_oracle_equal(tmp_path):
    # pre-create a multi-file parquet dataset (>= 8 files so every
    # global shard owns at least one split) for the ownership check
    import numpy as np

    import spark_rapids_tpu as srt

    rng = np.random.RandomState(7)
    scan_dir = os.path.join(str(tmp_path), "scan")
    srt.Session(tpu_enabled=False).create_dataframe(
        {"g": rng.randint(0, 5, 4000),
         "v": (rng.rand(4000) * 100).round(6)},
        n_partitions=8).write_parquet(scan_dir)

    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    script = os.path.join(os.path.dirname(__file__),
                          "mp_worker_script.py")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    procs = [subprocess.Popen(
        [sys.executable, script, coordinator, "2", str(pid), scan_dir],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    opened = {}
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"worker {pid} rc={p.returncode}:\n{out[-4000:]}"
        assert f"MP RESULT OK pid={pid}" in out, out[-4000:]
        for line in out.splitlines():
            if line.startswith(f"MP OPENED pid={pid} "):
                opened[pid] = set(
                    line.split("files=", 1)[1].split(","))
    # per-process split ownership: disjoint file-open sets covering
    # the dataset (reference: GpuParquetScan.scala:174)
    assert set(opened) == {0, 1}, opened
    assert opened[0] and opened[1]
    assert not (opened[0] & opened[1]), opened
    all_files = {f for f in os.listdir(scan_dir)
                 if f.startswith("part-")}
    assert opened[0] | opened[1] == all_files, (opened, all_files)
