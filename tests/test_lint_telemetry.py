"""AST lint: telemetry emitter and thread-spawn discipline.

Two invariants the telemetry subsystem's correctness rests on, enforced
mechanically so refactors cannot silently regress them:

1. **Exception-safe emitters** — outside ``telemetry/``, event
   emission may ONLY go through ``telemetry.events.emit_event`` (which
   never raises and is a no-op when inactive).  A bare ``.emit(...)``
   call in an engine module could throw from inside a recovery path.
2. **Worker threads capture the span/query context** — thread-locals
   do not cross thread spawns, so every ``Thread``/
   ``ThreadPoolExecutor`` spawn site in the package must capture the
   telemetry binding (``spans.capture``/``bound``/``attached``) in the
   same enclosing function.  A missed capture silently drops every
   span/event the worker would have produced.
"""
import ast
import os

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "spark_rapids_tpu")

SPAWN_NAMES = {"Thread", "ThreadPoolExecutor", "Timer",
               "ProcessPoolExecutor"}
CAPTURE_NAMES = {"capture", "bound", "attached"}


def _package_files():
    for root, _dirs, files in os.walk(PKG):
        for fn in files:
            if fn.endswith(".py"):
                yield os.path.join(root, fn)


def _terminal_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_telemetry_module(path: str) -> bool:
    return os.sep + "telemetry" + os.sep in path


def test_no_bare_emit_outside_telemetry():
    offenders = []
    for path in _package_files():
        if _is_telemetry_module(path):
            continue
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _terminal_name(node.func) == "emit":
                offenders.append(f"{path}:{node.lineno}")
    assert not offenders, \
        "bare .emit() outside telemetry/ — use the exception-safe " \
        f"telemetry.events.emit_event instead: {offenders}"


def test_emit_event_is_exception_safe_by_construction():
    """The one emitter engine code is allowed to call must wrap its
    body in a swallow-all try/except (it sits inside recovery paths)."""
    path = os.path.join(PKG, "telemetry", "events.py")
    tree = ast.parse(open(path).read(), filename=path)
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef)
              and n.name == "emit_event")
    tries = [n for n in fn.body if isinstance(n, ast.Try)]
    assert tries, "emit_event must wrap its body in try/except"
    handlers = [h for t in tries for h in t.handlers]
    assert any(
        h.type is None
        or (isinstance(h.type, ast.Name)
            and h.type.id in ("Exception", "BaseException"))
        for h in handlers), \
        "emit_event must swallow Exception — telemetry must never " \
        "break a recovery path"


class _SpawnVisitor(ast.NodeVisitor):
    """Records, for every thread-spawn call, the call node itself and
    its innermost enclosing function (module level counts as None)."""

    def __init__(self):
        self.stack = []
        self.spawns = []  # (call node, enclosing function node or None)

    def _visit_fn(self, node):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node):
        if _terminal_name(node.func) in SPAWN_NAMES:
            self.spawns.append(
                (node, self.stack[-1] if self.stack else None))
        self.generic_visit(node)


def _has_capture(node) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) \
                and _terminal_name(n.func) in CAPTURE_NAMES:
            return True
    return False


def test_every_thread_spawn_site_captures_telemetry_context():
    offenders = []
    found_spawns = 0
    for path in _package_files():
        tree = ast.parse(open(path).read(), filename=path)
        v = _SpawnVisitor()
        v.visit(tree)
        for call, fn in v.spawns:
            found_spawns += 1
            name = _terminal_name(call.func)
            if name == "Thread":
                # per-SITE check: the Thread(...) call itself must
                # wrap its target with bound()/attached()/capture() —
                # a second unwrapped Thread in an already-compliant
                # function must not ride the first one's capture
                ok = _has_capture(call)
            else:
                # pool executors: the map/submit wrapping happens next
                # to the constructor, so check the enclosing function
                ok = fn is not None and _has_capture(fn)
            if not ok:
                offenders.append(f"{path}:{call.lineno}")
    # the engine definitely spawns workers — an empty scan means the
    # lint itself broke, not that the invariant holds
    assert found_spawns >= 5, \
        f"spawn-site scan found only {found_spawns} sites — lint broken?"
    assert not offenders, \
        "thread-spawn sites missing a telemetry-context capture " \
        "(wrap the Thread target with spans.bound(spans.capture(), " \
        f"fn), or capture in the pool's enclosing function): {offenders}"
