"""AST lint: cooperative-cancellation and scheduler-thread discipline.

The scheduler's cancellation model is COOPERATIVE: a cancelled query
stops because its operator loops poll the cancel token, not because
anything preempts them.  That property is only as strong as the least
compliant loop, so it is enforced mechanically:

1. **Drain loops poll** — in the operator layers a cancelled query
   flows through (``exec/``, ``parallel/runner.py``,
   ``parallel/multiprocess.py``), every infinite loop (``while True``)
   and every queue-draining loop (a ``while`` whose body blocks on
   ``.get(...)``/``.put(...)``) must call one of the cancellation/
   injection checkpoints (``check_cancel`` / ``maybe_inject_fault`` /
   ``maybe_inject_oom``) each iteration, or appear in the explicit
   allowlist below with a reason.
2. **Scheduler threads capture context** — every ``Thread`` spawned in
   ``scheduler/`` must wrap its target with the telemetry ``capture``/
   ``bound`` binding (thread-locals do not cross spawns), and the
   worker body must both bind AND unbind the per-query cancel token
   (an activate without a deactivate leaks the token onto a pooled
   thread's next query).
"""
import ast
import os

PKG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "spark_rapids_tpu")

#: the operator layers a running query's control flow lives in
SCOPE_DIRS = ("exec",)
SCOPE_FILES = (os.path.join("parallel", "runner.py"),
               os.path.join("parallel", "multiprocess.py"))

POLL_NAMES = {"check_cancel", "maybe_inject_fault", "maybe_inject_oom"}
CAPTURE_NAMES = {"capture", "bound", "attached"}

#: "<relpath>:<lineno>" -> reason.  Keep this SHORT — an entry here is
#: a loop a cancelled query can wedge in.
ALLOWLIST = {}


def _scope_files():
    for d in SCOPE_DIRS:
        base = os.path.join(PKG, d)
        for root, _dirs, files in os.walk(base):
            for fn in files:
                if fn.endswith(".py"):
                    yield os.path.join(root, fn)
    for rel in SCOPE_FILES:
        yield os.path.join(PKG, rel)


def _terminal_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _calls_in(node):
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield _terminal_name(n.func)


def _is_drain_loop(loop: ast.While) -> bool:
    """Infinite, or blocking on queue traffic in the body."""
    if isinstance(loop.test, ast.Constant) and loop.test.value is True:
        return True
    return any(name in ("get", "put") for name in _calls_in(loop))


def test_every_drain_loop_polls_a_cancellation_checkpoint():
    offenders, checked = [], 0
    for path in _scope_files():
        rel = os.path.relpath(path, PKG)
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.While) \
                    or not _is_drain_loop(node):
                continue
            checked += 1
            if f"{rel}:{node.lineno}" in ALLOWLIST:
                continue
            if not any(n in POLL_NAMES for n in _calls_in(node)):
                offenders.append(f"{rel}:{node.lineno}")
    # transitions.py's prefetch loops alone guarantee a non-empty scan
    assert checked >= 3, \
        f"drain-loop scan found only {checked} loops — lint broken?"
    assert not offenders, \
        "drain loops without a cancellation checkpoint (add " \
        "check_cancel(site) per iteration, or allowlist with a " \
        f"reason): {offenders}"


def _scheduler_tree(name="query_scheduler.py"):
    path = os.path.join(PKG, "scheduler", name)
    return path, ast.parse(open(path).read(), filename=path)


def test_scheduler_thread_spawns_capture_telemetry_binding():
    offenders, spawns = [], 0
    for fn in os.listdir(os.path.join(PKG, "scheduler")):
        if not fn.endswith(".py"):
            continue
        path, tree = _scheduler_tree(fn)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _terminal_name(node.func) == "Thread":
                spawns += 1
                names = set(_calls_in(node))
                if not names & CAPTURE_NAMES:
                    offenders.append(f"{fn}:{node.lineno}")
    assert spawns >= 2, \
        "scheduler spawns dispatcher + worker threads — scan broken?"
    assert not offenders, \
        "scheduler Thread spawns missing the telemetry capture()/" \
        f"bound() wrapping: {offenders}"


def test_worker_binds_and_unbinds_the_cancel_token():
    """``_worker_main`` must activate the query's cancel token (and
    scoped injectors) before executing, and deactivate/unbind them in a
    ``finally`` — a leaked binding would cancel or fault-inject the
    NEXT query that runs on the thread."""
    _path, tree = _scheduler_tree()
    worker = next(n for n in ast.walk(tree)
                  if isinstance(n, ast.FunctionDef)
                  and n.name == "_worker_main")
    calls = set(_calls_in(worker))
    assert "activate" in calls, \
        "_worker_main must bind the cancel token via cancel.activate"
    finals = [n for t in ast.walk(worker) if isinstance(t, ast.Try)
              for n in t.finalbody]
    final_calls = {name for f in finals for name in _calls_in(f)}
    assert "deactivate" in final_calls, \
        "_worker_main must deactivate the cancel token in a finally"
    assert "bind_scoped_injector" in final_calls \
        and "bind_scoped_fault_injector" in final_calls, \
        "_worker_main must unbind the scoped injectors in a finally"
