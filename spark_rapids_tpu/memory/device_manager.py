"""Device manager — device acquisition and memory arena sizing.

Reference analogue: GpuDeviceManager.scala (one-GPU-per-executor
acquisition, RMM pool init as fraction of device memory, pinned pool) and
the executor-plugin init path (Plugin.scala:219-247).

On TPU the runtime owns physical HBM; the manager tracks a *logical*
arena — ``allocFraction`` × device memory — that the spill framework and
admission control budget against, and installs the alloc-failure -> spill
hook (reference: DeviceMemoryEventHandler)."""
from __future__ import annotations

import logging
import threading
from typing import Optional

from ..config import (
    CONCURRENT_TPU_TASKS,
    DEVICE_MEMORY_DEBUG,
    DEVICE_MEMORY_FRACTION,
    FAULT_SEMAPHORE_TIMEOUT_MS,
    TpuConf,
)
from .semaphore import DeviceSemaphore

log = logging.getLogger(__name__)

_DEFAULT_HBM_BYTES = 16 * 1024 ** 3  # v5e chip, used when query fails


class DeviceManager:
    """Process singleton (reference: one GPU per executor —
    GpuDeviceManager.scala:98-112 throws on more; here one process drives
    one local device set)."""

    _instance: Optional["DeviceManager"] = None
    _lock = threading.Lock()

    def __init__(self, conf: TpuConf):
        import jax

        self.conf = conf
        self.devices = jax.devices()
        self.device = self.devices[0]
        self.platform = self.device.platform
        if self.platform != "cpu":
            # CPU AOT cache entries are machine-feature sensitive
            # (XLA warns about SIGILL on mismatch), and the CPU warm
            # path is already covered by the session's plan cache
            self._enable_persistent_compile_cache(jax)
        total = self._query_memory()
        self.arena_bytes = int(total * conf.get(DEVICE_MEMORY_FRACTION))
        self.debug = conf.get(DEVICE_MEMORY_DEBUG)
        # acquire watchdog: fault.semaphoreTimeoutMs (0 = the class's
        # built-in default) — its DeviceSemaphoreTimeout is a retryable
        # fault the degradation ladder recovers on
        sem_timeout_ms = conf.get(FAULT_SEMAPHORE_TIMEOUT_MS)
        self.semaphore = DeviceSemaphore(
            conf.get(CONCURRENT_TPU_TASKS),
            acquire_timeout=(sem_timeout_ms / 1000.0
                             if sem_timeout_ms and sem_timeout_ms > 0
                             else None))
        self._allocated = 0
        self._alloc_lock = threading.Lock()
        self._peak = 0
        self._reserved = 0
        self.event_handler = None  # installed by spill framework
        if self.debug:
            log.info("DeviceManager: %s, arena=%d bytes",
                     self.device, self.arena_bytes)

    @staticmethod
    def _enable_persistent_compile_cache(jax) -> None:
        """Cross-process XLA compile cache (reference intent: cuDF JNI
        ships precompiled kernels; here compiles are runtime, so cache
        them on disk — first collect in a fresh process reuses prior
        compiles of the same program+shape)."""
        import os
        import tempfile

        try:
            if jax.config.jax_compilation_cache_dir:
                return
            cache = os.environ.get(
                "SRT_XLA_CACHE_DIR",
                os.path.join(tempfile.gettempdir(), "srt_xla_cache"))
            os.makedirs(cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.3)
        except Exception:  # noqa: BLE001 — cache is best-effort
            pass

    @classmethod
    def get_or_create(cls, conf: TpuConf) -> "DeviceManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceManager(conf)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._instance = None

    def _query_memory(self) -> int:
        try:
            stats = self.device.memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:  # noqa: BLE001
            pass
        return _DEFAULT_HBM_BYTES

    # ----- logical arena accounting (RMM-pool analogue) -------------------
    def track_alloc(self, nbytes: int) -> None:
        """Record a device allocation; fires the event handler (spill) when
        the logical arena would overflow (reference:
        DeviceMemoryEventHandler.onAllocFailure).

        Raises :class:`~.retry.TpuRetryOOM` — the typed signal the retry
        framework recovers from — when the arena is over budget and the
        spill handler could not free anything (everything pinned); the
        allocation is rolled back so a retried attempt re-tracks it.
        Also an OOM-injection checkpoint (fires BEFORE any accounting)."""
        from .retry import TpuRetryOOM, maybe_inject_oom

        maybe_inject_oom("DeviceManager.track_alloc", nbytes)
        with self._alloc_lock:
            self._allocated += nbytes
            self._peak = max(self._peak, self._allocated)
            over = self._allocated - self.arena_bytes
        if over > 0 and self.event_handler is not None:
            freed = self.event_handler.on_alloc_threshold(over)
            with self._alloc_lock:
                still_over = self._allocated - self.arena_bytes
            if still_over > 0 and not freed:
                with self._alloc_lock:
                    self._allocated = max(0, self._allocated - nbytes)
                from ..telemetry.events import emit_event

                emit_event("admission_reject", requested=nbytes,
                           over_bytes=still_over,
                           arena_bytes=self.arena_bytes)
                raise TpuRetryOOM(
                    f"device arena exhausted: allocation of {nbytes} "
                    f"bytes leaves usage {still_over} bytes over the "
                    f"{self.arena_bytes}-byte arena and nothing could "
                    "be spilled (all device buffers pinned)")
        if self.debug:
            log.info("alloc %d (total %d)", nbytes, self._allocated)

    def track_free(self, nbytes: int) -> None:
        with self._alloc_lock:
            self._allocated = max(0, self._allocated - nbytes)

    # ----- admission-side reservations (scheduler) ------------------------
    # A lifetime HBM reservation per *running* query: the scheduler only
    # dispatches a query when its reservation fits, so the sum of
    # running reservations never exceeds the arena.  Reservations are a
    # dispatch gate, not an allocation — running queries' real
    # allocations still flow through track_alloc against the full
    # arena (the retry/spill machinery arbitrates inside the budget).
    def try_reserve(self, nbytes: int) -> bool:
        """Atomically reserve admission budget; False when it does not
        fit (the caller keeps the query queued)."""
        if nbytes <= 0:
            return True
        with self._alloc_lock:
            if self._reserved + nbytes > self.arena_bytes:
                return False
            self._reserved += nbytes
            return True

    def release_reservation(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._alloc_lock:
            self._reserved = max(0, self._reserved - nbytes)

    def headroom(self) -> int:
        """Unallocated logical-arena bytes (may be negative while the
        spiller catches up) — the ``shuffle.mode=auto`` admission
        signal: a device-resident shuffle write only starts while the
        arena has room, otherwise it degrades to the host-staged
        path up front instead of thrashing the spiller."""
        with self._alloc_lock:
            return self.arena_bytes - self._allocated

    @property
    def reserved_bytes(self) -> int:
        return self._reserved

    @property
    def allocated_bytes(self) -> int:
        return self._allocated

    @property
    def peak_bytes(self) -> int:
        return self._peak
