"""Per-module symbol/import resolver and call/attribute index.

The shared infrastructure every rule used to rebuild privately: for
each module, the function table (with *own-body* call lists — nested
defs own their bodies, the discipline the old lints converged on), the
import alias map, module-level assignments, class table, thread-spawn
sites and ``with``-acquired locks.  Cross-module call resolution is
*name-based and conservative*: a call resolves to the functions of the
same terminal name, preferring same-module definitions — precise
enough for reachability/lock analysis over this codebase's idiom,
cheap enough to run on every tier-1 invocation.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .project import Project

#: call names never worth resolving cross-module (builtins/collection
#: traffic) — keeps the name-based call graph from inventing edges
UNRESOLVED_NAMES = frozenset({
    "get", "put", "pop", "append", "add", "discard", "remove", "clear",
    "extend", "update", "items", "keys", "values", "setdefault", "set",
    "join", "start", "wait", "notify", "notify_all", "cancel", "close",
    "len", "int", "float", "str", "bool", "list", "dict", "tuple",
    "isinstance", "getattr", "setattr", "hasattr", "print", "range",
    "sorted", "min", "max", "sum", "abs", "round", "repr", "open",
    "copy", "format", "split", "strip", "encode", "decode", "read",
    "write", "snapshot", "info", "warning", "error", "debug",
})


def terminal_name(func: ast.AST) -> str:
    """The rightmost name of a call target: ``a.b.c(...)`` -> ``c``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain
    (``self._dm.semaphore`` -> ``"self._dm.semaphore"``); empty string
    for anything not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def own_body_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body with nested function defs excluded — a
    nested def owns its body (gated inner functions must not taint
    their parent, and vice versa)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class FuncInfo:
    """One function/method definition and its own-body call index."""

    __slots__ = ("module", "qualname", "name", "node", "lineno",
                 "own_calls", "own_call_names", "class_name")

    def __init__(self, module: str, qualname: str, node,
                 class_name: Optional[str]):
        self.module = module
        self.qualname = qualname
        self.name = node.name
        self.node = node
        self.lineno = node.lineno
        self.class_name = class_name
        self.own_calls: List[ast.Call] = [
            n for n in own_body_nodes(node) if isinstance(n, ast.Call)]
        self.own_call_names: Set[str] = {
            terminal_name(c.func) for c in self.own_calls}

    def all_calls(self) -> List[ast.Call]:
        """Every call under the def, nested functions included."""
        return [n for n in ast.walk(self.node)
                if isinstance(n, ast.Call)]


class ModuleIndex:
    """Function/class/import/global index of one parsed module."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.functions: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        #: module import names: alias -> imported module/symbol path
        self.imports: Dict[str, str] = {}
        #: names assigned at module (or class) level -> the value node
        self.module_assigns: Dict[str, ast.AST] = {}
        self._index()

    def _index(self) -> None:
        def visit(node, qual: str, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    fi = FuncInfo(self.rel, q, child, cls)
                    self.functions.append(fi)
                    self.by_name.setdefault(child.name, []).append(fi)
                    visit(child, q, cls)
                elif isinstance(child, ast.ClassDef):
                    self.classes[child.name] = child
                    q = f"{qual}.{child.name}" if qual \
                        else child.name
                    visit(child, q, child.name)
                else:
                    visit(child, qual, cls)

        visit(self.tree, "", None)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{node.module or ''}.{a.name}"
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.module_assigns[t.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    self.module_assigns[stmt.target.id] = stmt.value

    def imported_modules(self) -> Iterable[Tuple[str, int]]:
        """Yield (module-path, lineno) for every import statement."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    yield a.name, node.lineno
            elif isinstance(node, ast.ImportFrom):
                yield node.module or "", node.lineno


class Resolver:
    """Cached :class:`ModuleIndex` per file plus conservative
    cross-module call resolution."""

    def __init__(self, project: Project):
        self.project = project
        self._modules: Dict[str, Optional[ModuleIndex]] = {}

    def module(self, rel: str) -> Optional[ModuleIndex]:
        if rel not in self._modules:
            tree = self.project.tree(rel)
            self._modules[rel] = \
                ModuleIndex(rel, tree) if tree is not None else None
        return self._modules[rel]

    def modules(self, rels: Iterable[str]) -> List[ModuleIndex]:
        out = []
        for rel in rels:
            mi = self.module(rel)
            if mi is not None:
                out.append(mi)
        return out

    def functions(self, rels: Iterable[str]) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        for mi in self.modules(rels):
            out.extend(mi.functions)
        return out

    def resolve_call(self, caller: FuncInfo, call: ast.Call,
                     scope: List[ModuleIndex]) -> List[FuncInfo]:
        """Candidate callees of ``call`` within ``scope``: same-module
        definitions of the terminal name win; otherwise cross-module
        definitions, but only when the name is not a generic
        collection/builtin name and is defined somewhere in scope."""
        name = terminal_name(call.func)
        if not name or name in UNRESOLVED_NAMES:
            return []
        own = self.module(caller.module)
        if own is not None and name in own.by_name:
            return own.by_name[name]
        out: List[FuncInfo] = []
        for mi in scope:
            out.extend(mi.by_name.get(name, ()))
        return out
