"""Data type system for the TPU columnar engine.

Capability parity with the reference's Spark<->cudf DType mapping
(reference: sql-plugin/.../GpuColumnVector.java:134-206) and the plan-rewrite
type gate (reference: GpuOverrides.scala:375-387).  Here the mapping is
SQL type <-> numpy dtype (host columns) <-> jnp dtype (device columns).

TPU-first notes:
  * TIMESTAMP is int64 microseconds since epoch, UTC only — same gate as the
    reference (timestamps allowed only when the session zone is UTC).
  * STRING columns are variable-width on the host (object ndarray of ``str``)
    and fixed-width padded uint8 matrices on the device (see data/strings.py);
    XLA needs static shapes, so the device encoding carries (bytes, lengths).
  * FLOAT64/INT64 require jax x64 mode, enabled at package import.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class TypeId(enum.Enum):
    BOOL = "boolean"
    INT8 = "tinyint"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "float"
    FLOAT64 = "double"
    DATE32 = "date"          # int32 days since unix epoch
    TIMESTAMP = "timestamp"  # int64 microseconds since unix epoch, UTC
    STRING = "string"
    NULL = "void"            # untyped null literal


@dataclass(frozen=True)
class DType:
    """An engine data type.  Hashable; use the singletons below."""

    id: TypeId

    # ----- classification -------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self.id in _NUMERIC

    @property
    def is_integral(self) -> bool:
        return self.id in _INTEGRAL

    @property
    def is_floating(self) -> bool:
        return self.id in (TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_datetime(self) -> bool:
        return self.id in (TypeId.DATE32, TypeId.TIMESTAMP)

    @property
    def is_string(self) -> bool:
        return self.id is TypeId.STRING

    @property
    def is_bool(self) -> bool:
        return self.id is TypeId.BOOL

    # ----- physical representation ---------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        """numpy dtype of the physical host representation.

        STRING host columns are ``object`` ndarrays of python str; the
        physical dtype here refers to the non-string payload.
        """
        return _NP[self.id]

    @property
    def jnp_dtype(self):
        import jax.numpy as jnp  # local import: keep module importable pre-jax

        return _JNP(jnp)[self.id]

    @property
    def byte_width(self) -> int:
        if self.id is TypeId.STRING:
            return 8  # estimate, matches reference GpuBatchUtils default-ish
        return _NP[self.id].itemsize

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.id.value

    @property
    def sql_name(self) -> str:
        return self.id.value


_NUMERIC = {
    TypeId.INT8,
    TypeId.INT16,
    TypeId.INT32,
    TypeId.INT64,
    TypeId.FLOAT32,
    TypeId.FLOAT64,
}
_INTEGRAL = {TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64}

_NP = {
    TypeId.BOOL: np.dtype(np.bool_),
    TypeId.INT8: np.dtype(np.int8),
    TypeId.INT16: np.dtype(np.int16),
    TypeId.INT32: np.dtype(np.int32),
    TypeId.INT64: np.dtype(np.int64),
    TypeId.FLOAT32: np.dtype(np.float32),
    TypeId.FLOAT64: np.dtype(np.float64),
    TypeId.DATE32: np.dtype(np.int32),
    TypeId.TIMESTAMP: np.dtype(np.int64),
    TypeId.STRING: np.dtype(object),
    TypeId.NULL: np.dtype(np.bool_),
}


def _JNP(jnp):
    return {
        TypeId.BOOL: jnp.bool_,
        TypeId.INT8: jnp.int8,
        TypeId.INT16: jnp.int16,
        TypeId.INT32: jnp.int32,
        TypeId.INT64: jnp.int64,
        TypeId.FLOAT32: jnp.float32,
        TypeId.FLOAT64: jnp.float64,
        TypeId.DATE32: jnp.int32,
        TypeId.TIMESTAMP: jnp.int64,
        TypeId.STRING: jnp.uint8,
        TypeId.NULL: jnp.bool_,
    }


BOOL = DType(TypeId.BOOL)
INT8 = DType(TypeId.INT8)
INT16 = DType(TypeId.INT16)
INT32 = DType(TypeId.INT32)
INT64 = DType(TypeId.INT64)
FLOAT32 = DType(TypeId.FLOAT32)
FLOAT64 = DType(TypeId.FLOAT64)
DATE32 = DType(TypeId.DATE32)
TIMESTAMP = DType(TypeId.TIMESTAMP)
STRING = DType(TypeId.STRING)
NULL = DType(TypeId.NULL)

ALL_TYPES = (BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, DATE32,
             TIMESTAMP, STRING)

_BY_NAME = {t.sql_name: t for t in ALL_TYPES}
_BY_NAME.update({
    "long": INT64, "integer": INT32, "short": INT16, "byte": INT8,
    "bool": BOOL, "real": FLOAT32, "str": STRING, "void": NULL,
})


def from_name(name: str) -> DType:
    return _BY_NAME[name.lower()]


def from_numpy(dt) -> DType:
    dt = np.dtype(dt)
    for tid, nd in _NP.items():
        if tid in (TypeId.DATE32, TypeId.TIMESTAMP, TypeId.NULL):
            continue
        if nd == dt:
            return DType(tid)
    if dt == np.dtype(object) or dt.kind in ("U", "S"):
        return STRING
    raise TypeError(f"unsupported numpy dtype {dt}")


# --------------------------------------------------------------------------
# Type gate — which types the device engine handles at all.
# Reference: GpuOverrides.isSupportedType (GpuOverrides.scala:375-387):
# primitives + Date + String always; Timestamp only under UTC; no
# decimal/array/map/struct/binary/interval.  Same surface here.
# --------------------------------------------------------------------------
def is_supported_type(t: DType, *, session_zone_utc: bool = True) -> bool:
    if t.id is TypeId.TIMESTAMP:
        return session_zone_utc
    return t.id in (
        TypeId.BOOL, TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
        TypeId.FLOAT32, TypeId.FLOAT64, TypeId.DATE32, TypeId.STRING,
        TypeId.NULL,
    )


# numeric promotion table used by binary arithmetic (Spark semantics:
# result type of an arithmetic op between integrals widens to the larger,
# mixing with floating promotes to floating; division is always double).
_RANK = {
    TypeId.INT8: 0, TypeId.INT16: 1, TypeId.INT32: 2, TypeId.INT64: 3,
    TypeId.FLOAT32: 4, TypeId.FLOAT64: 5,
}


def promote(a: DType, b: DType) -> DType:
    if not (a.is_numeric and b.is_numeric):
        raise TypeError(f"cannot promote {a} and {b}")
    ra, rb = _RANK[a.id], _RANK[b.id]
    winner = a if ra >= rb else b
    # int64 + float32 -> float64 divergence-avoidance (Spark promotes to
    # double when a float meets a >32-bit integral)
    loser = b if ra >= rb else a
    if winner.id is TypeId.FLOAT32 and loser.id in (TypeId.INT64,):
        return FLOAT64
    return winner


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True

    def __repr__(self) -> str:  # pragma: no cover
        n = "" if self.nullable else " not null"
        return f"{self.name}:{self.dtype}{n}"


class Schema:
    """Ordered collection of fields with name lookup."""

    def __init__(self, fields):
        self.fields = list(fields)
        self._index = {}
        for i, f in enumerate(self.fields):
            # last wins for duplicate names (matches positional binding use)
            self._index[f.name] = i

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, key):
        if isinstance(key, int):
            return self.fields[key]
        return self.fields[self._index[key]]

    def __contains__(self, name):
        return name in self._index

    def index_of(self, name: str) -> int:
        return self._index[name]

    @property
    def names(self):
        return [f.name for f in self.fields]

    @property
    def dtypes(self):
        return [f.dtype for f in self.fields]

    def __eq__(self, other):
        return isinstance(other, Schema) and self.fields == other.fields

    def __repr__(self) -> str:  # pragma: no cover
        return "Schema(" + ", ".join(map(repr, self.fields)) + ")"
