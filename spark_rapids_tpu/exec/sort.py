"""Device sort.

Reference analogue: GpuSortExec.scala — per-partition sort via cudf
``Table.orderBy`` with nulls-first/last handling, requiring a single batch
per partition (coalesceGoal=RequireSingleBatch).  Here the sort is the
device lexsort (order-preserving uint64 key passes + stable argsort —
XLA's sort lowers onto the TPU's sorting network), followed by a gather.

Global sorts get a range exchange below them from the planner, exactly as
Spark's EnsureRequirements provides for the reference.
"""
from __future__ import annotations

from ..ops.expression import as_device_column
from ..ops.kernels import gather as G
from ..ops.kernels import segment as seg
from ..utils import metrics as M
from ..utils.tracing import trace_range
from .base import DevicePartitionedData, RequireSingleBatch, TpuExec


class TpuSortExec(TpuExec):
    def __init__(self, child, keys):
        super().__init__([child])
        self.keys = keys  # List[functions.SortKey], exprs already bound
        import jax

        self._kernel = jax.jit(self._compute)

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def children_coalesce_goal(self):
        return [RequireSingleBatch()]

    def _compute(self, batch):
        padded = batch.padded_rows
        rm = batch.row_mask()
        key_cols = [as_device_column(k.expr.eval_tpu(batch), padded)
                    for k in self.keys]
        # mask computed keys so padding rows can't influence ordering
        key_cols = [type(c)(c.dtype, c.data, c.validity & rm, c.lengths)
                    for c in key_cols]
        order = seg.lexsort_device(
            key_cols,
            descending=[not k.ascending for k in self.keys],
            nulls_first=[k.nulls_first for k in self.keys],
            pad_valid=rm)
        return G.gather_batch(batch, order, batch.num_rows)

    def execute_columnar(self, ctx):
        child = self.children[0].execute_columnar(ctx)
        self._init_metrics(ctx)

        def make(pid):
            def it():
                for db in child.iterator(pid):
                    with trace_range("TpuSort",
                                     self.metrics[M.TOTAL_TIME]):
                        out = self._kernel(db)
                    self.metrics[M.NUM_OUTPUT_BATCHES].add(1)
                    yield out

            return it

        return DevicePartitionedData(
            [make(i) for i in range(child.n_partitions)])

    def describe(self):
        ks = ", ".join(
            f"{k.expr.sql()} {'ASC' if k.ascending else 'DESC'}"
            for k in self.keys)
        return f"TpuSort[{ks}]"


def register(register_exec):
    from ..plan import physical as P

    register_exec(
        P.SortExec,
        convert=lambda meta, ch: TpuSortExec(ch[0], meta.plan.keys),
        desc="device lexsort (stable multi-key radix passes)",
        exprs_of=lambda plan: [k.expr for k in plan.keys])
