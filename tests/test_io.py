"""Writer/reader round trips — parquet, ORC, CSV, dynamic partitions.

Reference analogues: ParquetWriterSuite / OrcScanSuite / CsvScanSuite +
the write pipeline (GpuParquetFileFormat.scala:88,
GpuFileFormatDataWriter.scala dynamic partitions,
ColumnarOutputWriter.scala).  Each format round-trips through the
device engine and must match the host oracle reading the same files.
"""
import os

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import f
from spark_rapids_tpu import types as T
from spark_rapids_tpu.testing.asserts import assert_rows_equal


@pytest.fixture()
def mixed_df_data():
    rng = np.random.RandomState(17)
    n = 500
    return {
        "k": rng.randint(0, 4, n),
        "v": (rng.rand(n) * 100).round(6),
        "s": [None if i % 29 == 0 else f"name-{i % 37}"
              for i in range(n)],
        "d": rng.randint(0, 20000, n).astype("int32"),
    }


def _schema():
    return T.Schema([
        T.Field("k", T.INT64), T.Field("v", T.FLOAT64),
        T.Field("s", T.STRING), T.Field("d", T.DATE32)])


@pytest.mark.parametrize("fmt", ["parquet", "orc"])
def test_write_read_roundtrip(tmp_path, mixed_df_data, fmt):
    sess = srt.Session()
    df = sess.create_dataframe(mixed_df_data, _schema(), n_partitions=3)
    out = os.path.join(str(tmp_path), fmt)
    getattr(df, f"write_{fmt}")(out)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    parts = [p for p in os.listdir(out) if p.startswith("part-")]
    assert len(parts) == 3, parts

    back = getattr(sess, f"read_{fmt}")(out)
    got = back.collect()
    cpu = srt.Session(tpu_enabled=False)
    exp = getattr(cpu, f"read_{fmt}")(out).collect()
    assert_rows_equal(exp, got, ignore_order=True,
                      approximate_float=1e-9)
    orig = cpu.create_dataframe(mixed_df_data, _schema()).collect()
    assert_rows_equal(orig, got, ignore_order=True,
                      approximate_float=1e-9)


def test_dynamic_partition_write(tmp_path, mixed_df_data):
    """partition_by produces hive-style k=<value> directories whose
    union reads back to the full dataset (reference:
    GpuFileFormatDataWriter dynamic partitioning)."""
    sess = srt.Session()
    df = sess.create_dataframe(mixed_df_data, _schema())
    out = os.path.join(str(tmp_path), "hive")
    df.write_parquet(out, partition_by=["k"])
    dirs = sorted(d for d in os.listdir(out) if d.startswith("k="))
    assert dirs == ["k=0", "k=1", "k=2", "k=3"], dirs

    back = sess.read_parquet(os.path.join(out, "k=1"))
    got = back.collect()
    cpu = srt.Session(tpu_enabled=False)
    exp = [r for r in cpu.create_dataframe(mixed_df_data, _schema())
           .collect() if r[0] == 1]
    # partition column is materialized in the directory, not the files
    exp_nok = [r[1:] for r in exp]
    assert_rows_equal(exp_nok, got, ignore_order=True,
                      approximate_float=1e-9)


def test_csv_read_options(tmp_path):
    path = os.path.join(str(tmp_path), "t.csv")
    with open(path, "w") as fh:
        fh.write("a;b;s\n1;1.5;x\n2;2.5;y\n3;;z\n")
    sess = srt.Session()
    df = sess.read_csv(path, header=True, sep=";")
    got = df.filter(df["a"] > 1).select("a", "b", "s").collect()
    cpu = srt.Session(tpu_enabled=False)
    cdf = cpu.read_csv(path, header=True, sep=";")
    exp = cdf.filter(cdf["a"] > 1).select("a", "b", "s").collect()
    assert_rows_equal(exp, got, ignore_order=True)
    assert len(got) == 2


def test_write_then_query_pipeline(tmp_path, mixed_df_data):
    """Write -> scan -> filter+agg end-to-end on the device engine vs
    the oracle over the same files."""
    sess = srt.Session()
    out = os.path.join(str(tmp_path), "pq")
    sess.create_dataframe(mixed_df_data, _schema(),
                          n_partitions=2).write_parquet(out)

    def q(s):
        df = getattr(s, "read_parquet")(out)
        return (df.filter(df["v"] > 50)
                  .group_by("k")
                  .agg(f.sum("v").alias("sv"), f.count("v").alias("c")))

    got = q(sess).collect()
    exp = q(srt.Session(tpu_enabled=False)).collect()
    assert_rows_equal(exp, got, ignore_order=True,
                      approximate_float=1e-9)
