"""Basic device operators: Project, Filter, Union, Limit, Expand, Coalesce.

Reference analogue: basicPhysicalOperators.scala (GpuProjectExec:65,
GpuFilterExec:126, GpuUnionExec:179, GpuCoalesceExec:202), limit.scala,
GpuExpandExec.scala.
"""
from __future__ import annotations

from typing import List

from .. import types as T
from ..data.column import DeviceBatch
from ..ops.expression import Expression, as_device_column, bind_references, \
    output_name
from ..ops.kernels.gather import compact
from ..utils import metrics as M
from ..utils.tracing import trace_range
from .base import DevicePartitionedData, TpuExec
from .kernel_cache import expr_signature, jit_kernel, schema_signature


class TpuProjectExec(TpuExec):
    def __init__(self, child, exprs: List[Expression],
                 schema: T.Schema = None):
        super().__init__([child])
        self.exprs = [bind_references(e, child.schema) for e in exprs]
        if schema is None:
            schema = T.Schema([
                T.Field(output_name(raw, i), b.dtype, b.nullable)
                for i, (raw, b) in enumerate(zip(exprs, self.exprs))])
        self._schema = schema
        self._kernel = jit_kernel(
            self.kernel_twin()._compute,
            key=("project", schema_signature(child.schema),
                 expr_signature(self.exprs), schema_signature(schema)))

    @property
    def schema(self):
        return self._schema

    def _compute(self, batch: DeviceBatch) -> DeviceBatch:
        cols = [as_device_column(e.eval_tpu(batch), batch.padded_rows)
                for e in self.exprs]
        # padding rows must stay invalid
        mask = batch.row_mask()
        cols = [type(c)(c.dtype, c.data, c.validity & mask, c.lengths)
                for c in cols]
        return DeviceBatch(self._schema, cols, batch.num_rows)

    def execute_columnar(self, ctx):
        child = self.children[0].execute_columnar(ctx)
        self._init_metrics(ctx)

        def make(pid):
            def it():
                for db in child.iterator(pid):
                    with trace_range("TpuProject",
                                     self.metrics[M.TOTAL_TIME]):
                        out = self._kernel(db, metrics=self.metrics)
                    self.metrics[M.NUM_OUTPUT_BATCHES].add(1)
                    yield out

            return it

        return DevicePartitionedData(
            [make(i) for i in range(child.n_partitions)])

    def describe(self):
        return f"TpuProject[{', '.join(e.sql() for e in self.exprs)}]"


class TpuFilterExec(TpuExec):
    def __init__(self, child, condition: Expression):
        super().__init__([child])
        self.condition = bind_references(condition, child.schema)
        self._kernel = jit_kernel(
            self.kernel_twin()._compute,
            key=("filter", schema_signature(child.schema),
                 expr_signature([self.condition])))

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def coalesce_after(self):
        return True

    def _keep(self, batch: DeviceBatch):
        """The keep mask of ``condition`` over ``batch`` — shared with
        the fused-segment kernel, which threads the mask through the
        segment instead of compacting per filter."""
        c = as_device_column(self.condition.eval_tpu(batch),
                             batch.padded_rows)
        return c.data & c.validity

    def _compute(self, batch: DeviceBatch) -> DeviceBatch:
        return compact(batch, self._keep(batch))

    def execute_columnar(self, ctx):
        child = self.children[0].execute_columnar(ctx)
        self._init_metrics(ctx)

        def make(pid):
            def it():
                for db in child.iterator(pid):
                    with trace_range("TpuFilter",
                                     self.metrics[M.TOTAL_TIME]):
                        out = self._kernel(db, metrics=self.metrics)
                    self.metrics[M.NUM_OUTPUT_BATCHES].add(1)
                    yield out

            return it

        return DevicePartitionedData(
            [make(i) for i in range(child.n_partitions)])

    def describe(self):
        return f"TpuFilter[{self.condition.sql()}]"


class TpuUnionExec(TpuExec):
    def __init__(self, children):
        super().__init__(children)

    @property
    def schema(self):
        return self.children[0].schema

    def execute_columnar(self, ctx):
        parts = []
        for ch in self.children:
            data = ch.execute_columnar(ctx)
            parts.extend(data.parts)
        return DevicePartitionedData(parts)

    def describe(self):
        return "TpuUnion"


class TpuLocalLimitExec(TpuExec):
    def __init__(self, child, n: int):
        super().__init__([child])
        self.n = n

    @property
    def schema(self):
        return self.children[0].schema

    def execute_columnar(self, ctx):
        import jax.numpy as jnp

        child = self.children[0].execute_columnar(ctx)
        self._init_metrics(ctx)

        def make(pid):
            def it():
                remaining = self.n
                for db in child.iterator(pid):
                    if remaining <= 0:
                        break
                    n_rows = int(db.num_rows)
                    if n_rows <= remaining:
                        remaining -= n_rows
                        yield db
                    else:
                        # shrink logical count; padded arrays unchanged,
                        # but rows past the limit must become padding
                        mask = jnp.arange(db.padded_rows,
                                          dtype=jnp.int32) < remaining
                        cols = [type(c)(c.dtype, c.data,
                                        c.validity & mask, c.lengths)
                                for c in db.columns]
                        yield DeviceBatch(db.schema, cols, remaining)
                        remaining = 0

            return it

        return DevicePartitionedData(
            [make(i) for i in range(child.n_partitions)])

    def describe(self):
        return f"TpuLocalLimit[{self.n}]"


class TpuGlobalLimitExec(TpuLocalLimitExec):
    def describe(self):
        return f"TpuGlobalLimit[{self.n}]"


class TpuExpandExec(TpuExec):
    """Reference analogue: GpuExpandExec — one projected batch per
    projection list per input batch."""

    def __init__(self, child, projections: List[List[Expression]],
                 output_names: List[str]):
        super().__init__([child])
        self.projections = [[bind_references(e, child.schema) for e in ps]
                            for ps in projections]
        first = self.projections[0]
        self._schema = T.Schema([T.Field(n, b.dtype, True)
                                 for n, b in zip(output_names, first)])
        # raw bodies kept for the fused-segment / distributed lowering;
        # built on the kernel twin so neither the registered kernels nor
        # a fused segment holding _kernel_fns pins this exec's subtree
        twin = self.kernel_twin()
        self._kernel_fns = [twin._mk_kernel(ps) for ps in self.projections]
        self._kernels = [
            jit_kernel(fn, key=("expand",
                                schema_signature(child.schema),
                                expr_signature(ps),
                                schema_signature(self._schema)))
            for fn, ps in zip(self._kernel_fns, self.projections)]

    @property
    def schema(self):
        return self._schema

    @property
    def coalesce_after(self):
        return True

    def _mk_kernel(self, ps):
        def compute(batch: DeviceBatch) -> DeviceBatch:
            mask = batch.row_mask()
            cols = []
            for f, e in zip(self._schema, ps):
                c = as_device_column(e.eval_tpu(batch), batch.padded_rows)
                if c.dtype != f.dtype and not f.dtype.is_string \
                        and not c.dtype.is_string:
                    c = type(c)(f.dtype, c.data.astype(f.dtype.jnp_dtype),
                                c.validity, c.lengths)
                cols.append(type(c)(c.dtype, c.data, c.validity & mask,
                                    c.lengths))
            return DeviceBatch(self._schema, cols, batch.num_rows)

        return compute

    def execute_columnar(self, ctx):
        child = self.children[0].execute_columnar(ctx)
        self._init_metrics(ctx)

        def make(pid):
            def it():
                for db in child.iterator(pid):
                    for k in self._kernels:
                        with trace_range("TpuExpand",
                                         self.metrics[M.TOTAL_TIME]):
                            yield k(db, metrics=self.metrics)

            return it

        return DevicePartitionedData(
            [make(i) for i in range(child.n_partitions)])

    def describe(self):
        return f"TpuExpand[{len(self.projections)} projections]"
