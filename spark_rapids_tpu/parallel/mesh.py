"""Device mesh management for distributed execution.

Reference analogue: the topology side of the RAPIDS shuffle
(RapidsShuffleInternalManager.scala:147-157 publishes `rapids=<port>`
topology strings via MapStatus; UCX.scala owns the peer endpoints).  On
TPU the topology is owned by XLA: we only need to pick a
`jax.sharding.Mesh` and express the exchange as compiled collectives
riding ICI (SURVEY §2.8 / §5 "Distributed communication backend").

The parallelism model of this workload is data-parallel partitions plus
repartitioning exchanges (SURVEY §2.8: no tensor/pipeline/sequence axes
exist in a SQL engine) — so the canonical mesh is 1-D over the ``dp``
axis, one shard = one partition group.  Multi-host meshes work the same
way: `jax.devices()` spans hosts and XLA routes intra-slice traffic over
ICI, cross-slice over DCN, replacing the reference's
NVLink/IB-vs-TCP split (UCXShuffleTransport.scala).
"""
from __future__ import annotations

from typing import Optional, Sequence

DATA_AXIS = "dp"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = DATA_AXIS):
    """A 1-D mesh over the first ``n_devices`` devices."""
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                f"for CPU simulation)")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def surviving_devices(mesh) -> list:
    """The devices a shrunken mesh re-forms on after a peer loss.

    Multi-controller: the surviving process can only compile against
    devices it can address, so the shrunken mesh is exactly this
    process's addressable slice of the old mesh (the dead peer's
    devices are unreachable by definition).  Single-controller (the CI
    drill, where every "peer" is a simulated process on one host): the
    first half of the old mesh stands in for the survivors.
    """
    import jax

    devs = list(mesh.devices.flat)
    try:
        nprocs = jax.process_count()
    except Exception:
        nprocs = 1
    if nprocs > 1:
        local = set(d.id for d in jax.local_devices())
        mine = [d for d in devs if d.id in local]
        if mine:
            return mine
    return devs[:max(1, len(devs) // 2)]


def make_shrunken_mesh(mesh, axis_name: str = DATA_AXIS):
    """Re-form a 1-D mesh on the surviving devices after a peer loss
    (the elastic layer's shrink planner).  The shrunken mesh keeps the
    same data axis, so plans re-execute unchanged with fewer shards."""
    from jax.sharding import Mesh
    import numpy as np

    devs = surviving_devices(mesh)
    return Mesh(np.array(devs), (axis_name,))


def shard_batch_arrays(mesh, *arrays, axis_name: str = DATA_AXIS):
    """Place stacked per-partition arrays [n_parts, ...] so the leading
    axis is split across the mesh.  n_parts must equal mesh size."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis_name))
    return tuple(jax.device_put(a, sharding) for a in arrays)


def replicate(mesh, *arrays):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    return tuple(jax.device_put(a, sharding) for a in arrays)
