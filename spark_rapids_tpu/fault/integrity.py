"""Payload integrity: CRC32C checksums over spill frames and exchange
host round-trips.

Reference analogue: the UCX shuffle's per-transfer metadata validation
(TableMeta riding every buffer) — here strengthened to a content
checksum, because a TPU spill frame crosses host RAM and disk where
bit-rot and torn writes are real.  Checksums are computed ONCE on the
write side (spill-frame serialization, exchange host staging) and
verified on the read side; a mismatch raises
:class:`~.errors.TpuPayloadCorruption`, which triggers
recompute-from-lineage of the producing stage instead of consuming
garbage.

CRC32C (Castagnoli) is used when a native implementation is available
(``crc32c`` / ``google_crc32c``); otherwise the zlib CRC32 fallback
keeps the identical detect-and-recompute semantics (the polynomial only
matters for cross-system interchange, which spill frames never do —
they are written and read by the same process family).
"""
from __future__ import annotations

import zlib
from typing import Iterable, List

import numpy as np

from ..telemetry.events import emit_event
from .errors import TpuPayloadCorruption
from .stats import GLOBAL as _stats

try:  # native Castagnoli CRC when the wheel is present
    import crc32c as _crc32c_mod

    def _crc(data, value: int = 0) -> int:
        return _crc32c_mod.crc32c(data, value)
except Exception:  # noqa: BLE001 — no new deps: zlib fallback
    try:
        import google_crc32c as _gcrc

        def _crc(data, value: int = 0) -> int:
            return _gcrc.extend(value, bytes(data))
    except Exception:  # noqa: BLE001
        def _crc(data, value: int = 0) -> int:
            return zlib.crc32(data, value)


def crc32c(data, value: int = 0) -> int:
    """Checksum of a bytes-like or uint8 ndarray (accumulating form)."""
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
    return _crc(data, value) & 0xFFFFFFFF


def checksum_frame(frame: np.ndarray) -> int:
    """Checksum of one contiguous serialized spill frame."""
    return crc32c(frame)


def verify_frame(frame: np.ndarray, expected: int, site: str,
                 detail: str = "") -> None:
    got = checksum_frame(frame)
    if got != expected:
        _stats.add("numChecksumFailures", 1)
        emit_event("checksum_failure", site=site,
                   got=f"0x{got:08x}", expected=f"0x{expected:08x}")
        raise TpuPayloadCorruption(
            f"payload checksum mismatch at {site}: "
            f"crc32c=0x{got:08x} expected=0x{expected:08x}"
            + (f" ({detail})" if detail else ""), site=site)


# ----- host-batch checksums (exchange host round-trips) -------------------
def _column_crc(col, value: int) -> int:
    data = col.data
    if isinstance(data, np.ndarray) and data.dtype == object:
        # string columns: hash the encoded values (None-safe)
        for v in data:
            b = b"\x00" if v is None else (
                v.encode("utf-8") if isinstance(v, str) else bytes(v))
            value = _crc(b, value)
    else:
        value = crc32c(np.asarray(data), value)
    if col.validity is not None:
        value = crc32c(
            np.ascontiguousarray(col.validity).astype(np.uint8), value)
    return value


def checksum_host_batch(hb) -> int:
    """Content checksum of one HostBatch (column data + validity)."""
    value = crc32c(np.asarray([hb.num_rows], dtype=np.int64))
    for col in hb.columns:
        value = _column_crc(col, value)
    return value & 0xFFFFFFFF


def stamp_host_batches(batches: Iterable) -> List[int]:
    """Write-side stamps for a host round-trip (one crc per batch)."""
    return [checksum_host_batch(b) for b in batches]


def verify_host_batches(batches, stamps: List[int], site: str) -> None:
    """Read-side verification of a stamped host round-trip."""
    for i, (b, expected) in enumerate(zip(batches, stamps)):
        got = checksum_host_batch(b)
        if got != expected:
            _stats.add("numChecksumFailures", 1)
            emit_event("checksum_failure", site=site, batch=i,
                       got=f"0x{got:08x}", expected=f"0x{expected:08x}")
            raise TpuPayloadCorruption(
                f"host round-trip checksum mismatch at {site} "
                f"(batch {i}): crc32c=0x{got:08x} "
                f"expected=0x{expected:08x}", site=site)


def corrupted_copy(hb):
    """Injection helper: a DEEP copy of ``hb`` with one byte flipped in
    its first non-empty numeric column.  A copy (never in-place) so the
    damage cannot alias cached uploads or user-owned source arrays —
    the clean retry must see clean data."""
    from ..data.column import HostBatch, HostColumn

    cols = []
    flipped = False
    for col in hb.columns:
        data = col.data.copy() if isinstance(col.data, np.ndarray) \
            else col.data
        if not flipped and isinstance(data, np.ndarray) \
                and data.dtype != object and data.nbytes:
            flat = data.view(np.uint8).reshape(-1)
            flat[flat.shape[0] // 2] ^= 0xFF
            flipped = True
        validity = col.validity.copy() if col.validity is not None \
            else None
        cols.append(HostColumn(col.dtype, data, validity))
    return HostBatch(hb.schema, cols)


def corrupt_host_batch(hb) -> None:
    """Injection helper: flip one byte of the first non-empty numeric
    column IN PLACE (the read-side verify must catch it).  Host batches
    from device downloads own their arrays, so the flip never aliases
    user data."""
    for col in hb.columns:
        data = col.data
        if isinstance(data, np.ndarray) and data.dtype != object \
                and data.nbytes:
            flat = data.view(np.uint8).reshape(-1)
            if not flat.flags.writeable:
                continue
            flat[flat.shape[0] // 2] ^= 0xFF
            return
