"""Device-resident shuffle (shuffle/device_shuffle.py + exchange).

The central invariant: results are BIT-IDENTICAL between
``shuffle.mode=device`` (packed blocks resident in HBM, one jitted
partition-build kernel per input batch, readers slice on device) and
``shuffle.mode=host`` (every block staged + CRC32C-stamped immediately
— the pre-device behavior), including under fault injection, OOM
pressure, and concurrent submission.  The ``shuffle.*`` metrics and
``shuffle_fallback``/``degrade`` events make every degradation of the
device path visible.
"""
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.plan import functions as F
from spark_rapids_tpu.shuffle import device_shuffle as DS

FAST = {
    "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
    "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
}

#: force real exchanges (no broadcast shortcut) like the fault suite
SHUFFLED = {"spark.rapids.tpu.sql.broadcastSizeThreshold": 0,
            "spark.rapids.tpu.sql.taskRetries": 3}

TEL = {"spark.rapids.tpu.telemetry.enabled": True}


def _inject(mode, fault_type, site="", skip=0, **extra):
    conf = dict(FAST)
    conf.update({
        "spark.rapids.tpu.fault.injection.mode": mode,
        "spark.rapids.tpu.fault.injection.type": fault_type,
        "spark.rapids.tpu.fault.injection.site": site,
        "spark.rapids.tpu.fault.injection.skipCount": skip,
    })
    conf.update(extra)
    return conf


def _norm(rows):
    return sorted(
        (tuple((None if v is None else
                (round(v, 9) if isinstance(v, float) else v))
               for v in r) for r in rows),
        key=repr)


def _join_agg_query(sess):
    rng = np.random.RandomState(11)
    orders = {"o_custkey": rng.randint(0, 40, 300).tolist(),
              "o_total": [round(float(v), 6)
                          for v in rng.rand(300) * 1000]}
    cust = {"c_custkey": list(range(40)),
            "c_nation": rng.randint(0, 5, 40).tolist()}
    o = sess.create_dataframe(orders)
    c = sess.create_dataframe(cust)
    j = o.join(c, on=(["o_custkey"], ["c_custkey"]), how="inner")
    return j.group_by("c_nation").agg(
        F.sum("o_total").alias("rev"), F.count("o_total").alias("n"))


def _mode_conf(mode, **extra):
    conf = dict(SHUFFLED, **FAST)
    conf["spark.rapids.tpu.shuffle.mode"] = mode
    conf.update(extra)
    return conf


# ==========================================================================
# resolve_mode policy
# ==========================================================================
def test_resolve_mode_policy():
    assert DS.resolve_mode("device") == "device"
    assert DS.resolve_mode("host") == "host"
    assert DS.resolve_mode(None) == "device"          # auto + headroom
    assert DS.resolve_mode("auto", headroom=0) == "host"
    assert DS.resolve_mode("auto", headroom=-5) == "host"
    # the ladder's forced re-execution wins over everything
    assert DS.resolve_mode("device", force_host=True) == "host"
    with pytest.raises(ValueError):
        DS.resolve_mode("bogus")


# ==========================================================================
# packed build/slice kernel round trip
# ==========================================================================
def test_packed_build_slice_roundtrip():
    """One build + n_out slices must reproduce exactly the rows the
    direct per-partition compaction produces, partition by partition."""
    import jax.numpy as jnp

    from spark_rapids_tpu.data.column import (HostBatch, device_to_host,
                                              host_to_device)

    rng = np.random.RandomState(5)
    hb = HostBatch.from_pydict({
        "k": rng.randint(0, 1000, 200).tolist(),
        "s": [f"row{i}" for i in range(200)]})
    b = host_to_device(hb)
    n_out = 4
    pids = jnp.asarray(rng.randint(0, n_out, b.padded_rows),
                       dtype=jnp.int32)
    block, counts, starts = DS.packed_build(b, pids, n_out)
    counts = np.asarray(counts)
    starts = np.asarray(starts)
    pids_np = np.asarray(pids)
    assert counts.sum() == 200
    # real rows sorted to the front: the spill serializer (which trims
    # to num_rows) must lose only padding
    assert int(np.asarray(block.num_rows)) == 200
    got_all = []
    for p in range(n_out):
        n = int(counts[p])
        if n == 0:
            continue
        out = DS.packed_slice(block, jnp.int32(int(starts[p])),
                              jnp.int32(n))
        hp = device_to_host(out)
        rows = list(zip(hp.column("k").to_pylist(),
                        hp.column("s").to_pylist()))
        # every row of partition p carries pid p
        want = [(k, s) for i, (k, s) in enumerate(
            zip(hb.column("k").to_pylist(), hb.column("s").to_pylist()))
            if int(pids_np[i]) == p]
        assert sorted(rows) == sorted(want), p
        got_all.extend(rows)
    assert sorted(got_all) == sorted(
        zip(hb.column("k").to_pylist(), hb.column("s").to_pylist()))


def test_shuffle_stats_delta_reporting():
    DS.GLOBAL.reset()
    mark = DS.GLOBAL.counters()
    DS.GLOBAL.add("deviceBytes", 100)
    DS.GLOBAL.add("numFallbacks")
    got = DS.GLOBAL.metrics_since(mark)
    assert got["shuffle.deviceBytes"] == 100
    assert got["shuffle.numFallbacks"] == 1
    assert got["shuffle.hostBytes"] == 0


# ==========================================================================
# device/host mode bit-identity + metrics
# ==========================================================================
def test_mode_bit_identity_and_metrics():
    s_dev = srt.Session(_mode_conf("device"))
    dev = _join_agg_query(s_dev).collect()
    m_dev = s_dev.last_metrics
    assert m_dev.get("shuffle.deviceBytes", 0) > 0, m_dev
    assert m_dev.get("shuffle.hostBytes", 0) == 0, m_dev

    s_host = srt.Session(_mode_conf("host"))
    host = _join_agg_query(s_host).collect()
    m_host = s_host.last_metrics
    assert m_host.get("shuffle.hostBytes", 0) > 0, m_host
    assert m_host.get("shuffle.deviceBytes", 0) == 0, m_host

    assert _norm(dev) == _norm(host)


def test_auto_mode_prefers_device_with_headroom():
    sess = srt.Session(_mode_conf("auto"))
    _join_agg_query(sess).collect()
    m = sess.last_metrics
    assert m.get("shuffle.deviceBytes", 0) > 0, m
    assert m.get("shuffle.hostBytes", 0) == 0, m


@pytest.mark.parametrize("qnum", [1, 3, 5, 6, 16])
def test_tpch_mode_bit_identity(qnum):
    """q1/q3/q5/q6/q16 return identical rows under device and host
    shuffle (the oracle-vs-tpu comparison lives in test_tpch; this
    pins the two DATA PATHS against each other exactly)."""
    from spark_rapids_tpu.benchmarks import tpch, tpch_datagen

    def run(mode):
        sess = srt.Session(_mode_conf(mode))
        tables = tpch_datagen.dataframes(sess, sf=0.0007, seed=7)
        return tpch.QUERIES[qnum](tables).collect()

    assert _norm(run("device")) == _norm(run("host"))


# ==========================================================================
# fault injection on the device path
# ==========================================================================
@pytest.mark.fault_injection
def test_device_corrupt_recomputes_from_lineage():
    """A corrupted device-resident block (demoted + bit-flipped by the
    injector at the device write site) must be caught by the CRC on
    promote, recomputed from lineage, and end bit-identical."""
    clean = _join_agg_query(srt.Session(_mode_conf("device"))).collect()
    sess = srt.Session(_mode_conf(
        "device", **_inject("nth", "corrupt",
                            site="exchange.write.device")))
    got = _join_agg_query(sess).collect()
    assert _norm(got) == _norm(clean)
    m = sess.last_metrics
    assert m.get("fault.numChecksumFailures", 0) >= 1, m


@pytest.mark.fault_injection
def test_device_oom_spills_blocks_and_completes():
    """An injected OOM at a device write checkpoint makes the retry
    framework spill the already-resident packed blocks; the spill is
    the per-buffer degradation (hostBytes + numFallbacks accrue, a
    shuffle_fallback event fires) and the query still completes
    bit-identical with readers promoting from host."""
    from spark_rapids_tpu.memory.spill import SpillFramework

    clean = _join_agg_query(srt.Session(_mode_conf("device"))).collect()
    # fresh framework: the clean run's cached uploads would otherwise
    # absorb the spill-to-half target before any shuffle block
    SpillFramework._instance = SpillFramework()
    # many small writes (tiny reader batches, coalescing off), OOM at
    # the 4th device write: three packed blocks are already resident
    # and spillable when the recovery runs
    sess = srt.Session(_mode_conf(
        "device",
        **_inject("nth", "oom", site="exchange.write.device", skip=3),
        **dict(TEL, **{
            "spark.rapids.tpu.sql.reader.batchSizeRows": 64,
            "spark.rapids.tpu.shuffle.targetBatchRows": 0,
        })))
    got = _join_agg_query(sess).collect()
    assert _norm(got) == _norm(clean)
    m = sess.last_metrics
    assert m.get("shuffle.hostBytes", 0) > 0, m
    assert m.get("shuffle.numFallbacks", 0) >= 1, m
    events = [e for e in sess.last_profile.events.snapshot()
              if e["event"] == "shuffle_fallback"]
    assert events and events[0]["reason"] == "spill", events


# ==========================================================================
# degradation ladder: device-shuffle -> host-shuffle -> CPU
# ==========================================================================
@pytest.mark.fault_injection
def test_ladder_device_to_host_shuffle_rung():
    """An always-corrupt drill scoped to the DEVICE write site exhausts
    the device attempt; the ladder's host-shuffle rung re-executes with
    exchanges staged (the drill's site no longer matches) and the query
    completes there — below the CPU rung, with the fallback visible."""
    conf = _mode_conf("device", **_inject(
        "always", "corrupt", site="exchange.write.device"))
    conf.update(TEL)
    conf["spark.rapids.tpu.sql.taskRetries"] = 0
    sess = srt.Session(conf)
    got = _join_agg_query(sess).collect()
    oracle = _join_agg_query(srt.Session(tpu_enabled=False)).collect()
    assert _norm(got) == _norm(oracle)
    m = sess.last_metrics
    assert m.get("fault.numShuffleFallbacks", 0) >= 1, m
    # recovered ABOVE the CPU rung: degradeLevel untouched
    assert m.get("fault.degradeLevel", 0) == 0, m
    events = sess.last_profile.events.snapshot()
    kinds = {e["event"] for e in events}
    assert "shuffle_fallback" in kinds, kinds
    rungs = [e.get("rung") for e in events if e["event"] == "degrade"]
    assert "host-shuffle" in rungs, events


@pytest.mark.fault_injection
def test_ladder_walks_host_rung_then_cpu():
    """An always-crash drill matching BOTH write sites fails the device
    attempt AND the host-shuffle rung; the query must still return
    correct rows via the CPU rung, with each rung's event emitted."""
    conf = _mode_conf("device", **_inject(
        "always", "stage_crash", site="exchange.write"))
    conf.update(TEL)
    conf["spark.rapids.tpu.sql.taskRetries"] = 0
    sess = srt.Session(conf)
    got = _join_agg_query(sess).collect()
    oracle = _join_agg_query(srt.Session(tpu_enabled=False)).collect()
    assert _norm(got) == _norm(oracle)
    m = sess.last_metrics
    assert m.get("fault.numShuffleFallbacks", 0) >= 1, m
    assert m.get("fault.degradeLevel") == 2, m
    events = sess.last_profile.events.snapshot()
    rungs = [e.get("rung") for e in events if e["event"] == "degrade"]
    assert "host-shuffle" in rungs and "cpu" in rungs, rungs


# ==========================================================================
# coalesce-before-exchange (shuffle.targetBatchRows)
# ==========================================================================
def test_coalesce_cuts_build_dispatches():
    """With tiny reader batches, coalescing to targetBatchRows must cut
    the kernel dispatches of the exchange write (one build per merged
    batch instead of one per scan batch) — measured through the
    kernel-cache telemetry, not timing."""
    # static shuffled plan on purpose: AQE's dynamic broadcast
    # conversion would bypass the exchange write whose dispatch
    # economics this measures
    small = {"spark.rapids.tpu.sql.reader.batchSizeRows": 32,
             "spark.rapids.tpu.sql.adaptive.enabled": False}

    sess_off = srt.Session(_mode_conf(
        "device", **dict(small, **{
            "spark.rapids.tpu.shuffle.targetBatchRows": 0})))
    off_rows = _join_agg_query(sess_off).collect()
    off = sess_off.last_metrics.get("kernelCache.dispatches", 0)

    sess_on = srt.Session(_mode_conf("device", **small))
    on_rows = _join_agg_query(sess_on).collect()
    on = sess_on.last_metrics.get("kernelCache.dispatches", 0)

    assert _norm(off_rows) == _norm(on_rows)
    assert 0 < on < off, (on, off)


def test_exchange_declares_target_rows_goal():
    from spark_rapids_tpu.exec.base import TargetRows
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec

    class _Plan:
        from spark_rapids_tpu.shuffle.partitioning import \
            SinglePartitioning
        partitioning = SinglePartitioning()
        n_out = 1

    class _Child:
        from spark_rapids_tpu import types as T
        schema = T.Schema([T.Field("x", T.INT64)])
        children = ()

    ex = TpuShuffleExchangeExec(_Child(), _Plan())
    goals = ex.children_coalesce_goal
    assert len(goals) == 1 and isinstance(goals[0], TargetRows)
    assert goals[0].rows is None  # conf-resolved at execute time


def test_target_rows_goal_lattice():
    from spark_rapids_tpu.exec.base import (RequireSingleBatch,
                                            TargetRows)

    assert TargetRows(10).max_with(TargetRows(20)).rows == 20
    assert TargetRows(None).max_with(TargetRows(20)).rows is None
    assert isinstance(TargetRows(10).max_with(RequireSingleBatch()),
                      RequireSingleBatch)


# ==========================================================================
# concurrent submission
# ==========================================================================
def test_concurrent_submit_device_mode_bit_identity():
    """Concurrent device-mode queries through the scheduler return the
    same rows as the serial host-mode run — the shared device arena and
    spill framework must not let neighbors corrupt each other's packed
    blocks."""
    serial = _norm(_join_agg_query(
        srt.Session(_mode_conf("host"))).collect())
    sess = srt.Session(_mode_conf("device"))
    try:
        handles = [sess.submit(_join_agg_query(sess).plan)
                   for _ in range(3)]
        for h in handles:
            assert _norm(h.result(timeout=120).to_rows()) == serial
    finally:
        sess.shutdown_scheduler()


# ==========================================================================
# host-staging + spill interplay
# ==========================================================================
def test_host_mode_blocks_are_crc_stamped_immediately():
    """mode=host serializes + CRC-stamps every block at write time —
    the stamp exists BEFORE any spill pressure, which is the point of
    the staged path (integrity over latency)."""
    from spark_rapids_tpu.memory.spill import SpillFramework

    sess = srt.Session(_mode_conf("host"))
    out = _join_agg_query(sess).collect()
    assert out
    # stage_to_host of an unknown / non-device buffer is a 0-byte no-op
    fw = SpillFramework.get()
    assert fw.stage_to_host(999999999) == 0


# ==========================================================================
# 2-process collective shuffle bit-identity (slow tier)
# ==========================================================================
@pytest.mark.slow
def test_two_process_collective_shuffle_bit_identity():
    """A 2-process multi-controller run of the join+agg plan returns
    oracle-equal rows under BOTH shuffle modes, with the collective
    dispatch wall accrued to ``shuffle.collectiveTime`` on every
    controller (tests/mp_shuffle_worker.py does the in-process
    asserts; this harness checks every worker reached them)."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    coordinator = f"127.0.0.1:{port}"
    script = os.path.join(os.path.dirname(__file__),
                          "mp_shuffle_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    procs = [subprocess.Popen(
        [sys.executable, script, coordinator, "2", str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process shuffle workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    if any("Multiprocess computations aren't implemented" in (o or "")
           for o in outs):
        pytest.skip("this jax build's CPU backend lacks multi-process "
                    "collectives (same limitation as "
                    "test_multiprocess) — nothing to exchange over")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"worker {pid} rc={p.returncode}:\n{out[-4000:]}"
        for mode in ("device", "host"):
            assert f"MPS MODE OK pid={pid} mode={mode}" in out, \
                out[-4000:]
        assert f"MPS RESULT OK pid={pid}" in out, out[-4000:]
