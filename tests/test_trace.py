"""Chrome-trace / Perfetto timeline export (telemetry/trace.py).

Contract under test (ISSUE 13): a finished query round-trips through
``chrome_trace``/``write_trace`` into a document Perfetto loads —
valid JSON, non-negative monotonic µs timestamps, every span of the
profile present exactly once as a complete ("X") event, the HBM
sampler surfaced as a counter ("C") track — and a concurrent 3-query
scheduler run renders as three distinct process tracks.  Per-query
auto-export is gated by ``telemetry.trace.dir`` and goes through the
atomic fsio writer.
"""
import glob
import json
import os

import numpy as np

import spark_rapids_tpu as srt
from spark_rapids_tpu.plan import functions as F
from spark_rapids_tpu.telemetry.trace import (chrome_trace, write_trace,
                                              write_query_trace)

TEL = {"spark.rapids.tpu.telemetry.enabled": True,
       "spark.rapids.tpu.telemetry.sampleHbmMs": 5}


def _agg_df(sess, n=4096):
    rng = np.random.RandomState(7)
    df = sess.create_dataframe({
        "g": rng.randint(0, 16, n),
        "v": (rng.rand(n) * 10).round(6)})
    return df.group_by("g").agg(F.sum("v").alias("s"),
                                F.count("v").alias("n"))


def _span_count(sp):
    return 1 + sum(_span_count(c) for c in sp.children)


def test_trace_roundtrip_valid_monotonic_and_complete(tmp_path):
    sess = srt.Session(dict(TEL))
    _agg_df(sess).collect()
    prof = sess.last_profile
    path = write_trace(str(tmp_path / "t.json"), prof)
    doc = json.loads(open(path).read())      # valid JSON on disk
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert evs
    # timestamps/durations non-negative; ordering is metadata first,
    # then non-decreasing ts (the exporter's documented sort)
    for e in evs:
        assert e["ts"] >= 0
        assert e.get("dur", 0) >= 0
    keys = [(0 if e["ph"] == "M" else 1, e["pid"], e["ts"]) for e in evs]
    assert keys == sorted(keys)
    # every span of the profile appears exactly once as an X event
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == _span_count(prof.root)
    names = [e["name"] for e in xs]
    assert f"query:{prof.query_id}" in names
    assert any(n.startswith("exec:HostToDeviceExec") for n in names)
    # the HBM sampler renders as a counter track
    cs = [e for e in evs if e["ph"] == "C"]
    assert cs and all(e["name"] == "HBM" for e in cs)
    assert all(e["args"]["peak"] >= e["args"]["allocated"] >= 0
               for e in cs)
    # ring events render as instants; the begin/end pair is already
    # delimited by the root span and must not double-render
    inames = {e["name"] for e in evs if e["ph"] == "i"}
    assert not inames & {"query_begin", "query_end"}
    # process/thread naming metadata for Perfetto's track labels
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in evs)
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in evs)


def test_concurrent_scheduler_queries_get_distinct_tracks():
    sess = srt.Session(dict(TEL))
    handles = [sess.submit(_agg_df(sess)) for _ in range(3)]
    for h in handles:
        h.result(timeout=180)
    profs = [h.profile for h in handles]
    assert all(p is not None for p in profs)
    doc = chrome_trace(profs)
    evs = doc["traceEvents"]
    # one pid per query, each with its own process_name metadata
    pids = {e["pid"] for e in evs}
    assert pids == {1, 2, 3}
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert len(pnames) == 3
    assert len(set(pnames.values())) == 3      # distinct query ids
    # every pid carries its spans and an HBM counter track
    for pid in (1, 2, 3):
        assert any(e["ph"] == "X" and e["pid"] == pid for e in evs)
        assert any(e["ph"] == "C" and e["pid"] == pid for e in evs)
    # document is serializable as-is (what write_trace persists)
    json.loads(json.dumps(doc))


def test_trace_dir_conf_auto_exports_per_query(tmp_path):
    td = str(tmp_path / "traces")
    sess = srt.Session(dict(TEL, **{
        "spark.rapids.tpu.telemetry.trace.dir": td}))
    _agg_df(sess, n=256).collect()
    _agg_df(sess, n=256).collect()
    files = sorted(glob.glob(os.path.join(td, "trace-*.json")))
    assert len(files) == 2
    for f in files:
        doc = json.load(open(f))
        assert doc["traceEvents"]
    # atomic writer: no temp files left behind
    assert not glob.glob(os.path.join(td, ".srt-tmp-*"))
    # exception-safety contract: no profile -> no file, no raise
    assert write_query_trace(td, None) is None
    assert write_query_trace("", sess.last_profile) is None


def test_trace_export_off_by_default(tmp_path):
    sess = srt.Session(dict(TEL))
    _agg_df(sess, n=256).collect()
    # no trace.dir conf -> nothing written anywhere under cwd/tmp
    from spark_rapids_tpu.config import TELEMETRY_TRACE_DIR
    assert sess.conf.get(TELEMETRY_TRACE_DIR) == ""
    assert not glob.glob(str(tmp_path / "trace-*.json"))
