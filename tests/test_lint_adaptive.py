"""AST lint: adaptive query execution stays off the device.

Two contracts, enforced at the source level so a refactor cannot
silently regress them:

* **Zero added device syncs.**  AQE feeds exclusively on statistics the
  shuffle write path ALREADY pulled to host (the gated count fetch in
  ``exec/exchange.py``): nothing under ``spark_rapids_tpu/adaptive/``
  may import jax or call a host-sync primitive, and the exchange
  function that records stats must stay free of ungated syncs of its
  own.
* **Every rewrite announces itself.**  Each decision site in
  ``adaptive/planner.py`` (anything bumping an ``aqe.*`` metric) must
  emit the matching structured ``aqe_*`` event — the events are the
  acceptance surface for "which rewrite fired", so a silent rewrite is
  a lint failure, not a style nit.
"""
import ast
import os

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "spark_rapids_tpu")
ADAPTIVE = os.path.join(PKG, "adaptive")

#: functions in exchange.py whose host syncs are the DESIGNED, gated
#: count fetches (mirrors tests/test_lint_shuffle.py) — stats recording
#: rides these, it must not add its own
GATED_FUNCS = {"fetch_counts", "flush", "drain_outs"}
HOST_SYNC_NAMES = {"device_get", "tolist", "item", "device_to_host",
                   "to_host"}


def _parse(path):
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def _adaptive_modules():
    for fn in sorted(os.listdir(ADAPTIVE)):
        if fn.endswith(".py"):
            yield fn, _parse(os.path.join(ADAPTIVE, fn))


def _terminal_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _calls_in(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _is_host_sync(call) -> bool:
    name = _terminal_name(call.func)
    if name in HOST_SYNC_NAMES:
        return True
    if (name == "asarray" and isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "np"):
        return True
    return False


def _functions_with_calls(tree):
    """Yield (funcdef, calls-in-OWN-body) — nested defs own their
    bodies, so a gated inner function doesn't taint its parent."""
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        own = []
        stack = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # the nested def owns its body
            if isinstance(n, ast.Call):
                own.append(n)
            stack.extend(ast.iter_child_nodes(n))
        yield fn, own


# ==========================================================================
# Host-only statistics
# ==========================================================================
def test_adaptive_package_never_imports_jax():
    offenders = []
    for fn, tree in _adaptive_modules():
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for name in names:
                if name == "jax" or name.startswith("jax."):
                    offenders.append(f"{fn}:{node.lineno} imports {name}")
    assert not offenders, (
        "adaptive/ must stay device-free (stats are host math over "
        f"already-fetched counts): {offenders}")


def test_adaptive_package_has_no_host_sync_calls():
    offenders = []
    checked = 0
    for fn, tree in _adaptive_modules():
        for call in _calls_in(tree):
            checked += 1
            name = _terminal_name(call.func)
            if name in HOST_SYNC_NAMES:
                offenders.append(f"{fn}:{call.lineno} calls {name}()")
    assert checked >= 50, "lint saw suspiciously little code"
    assert not offenders, (
        f"host-sync primitives in adaptive/: {offenders}")


def test_planner_and_executor_never_touch_device_arrays():
    """np.asarray on the rewrite/driver hot path would be a device
    readback in disguise (DevicePartitionedData flows through here);
    only stats.py may coerce — its inputs are host-resident by the
    record_exchange contract."""
    offenders = []
    for fn, tree in _adaptive_modules():
        if fn not in ("planner.py", "executor.py"):
            continue
        for call in _calls_in(tree):
            if _is_host_sync(call):
                offenders.append(
                    f"{fn}:{call.lineno} {_terminal_name(call.func)}()")
    assert not offenders, offenders


def test_exchange_stats_recording_adds_no_syncs():
    """The function in exec/exchange.py that calls record_exchange must
    not perform host syncs of its own — it records numbers the gated
    fetch already pulled.  (The gated functions themselves are nested
    defs and own their bodies.)"""
    tree = _parse(os.path.join(PKG, "exec", "exchange.py"))
    recorders = 0
    offenders = []
    for fn, own_calls in _functions_with_calls(tree):
        names = {_terminal_name(c.func) for c in own_calls}
        if "record_exchange" not in names:
            continue
        recorders += 1
        for call in own_calls:
            if _is_host_sync(call):
                offenders.append(
                    f"{fn.name}:{call.lineno} "
                    f"{_terminal_name(call.func)}()")
    assert recorders >= 1, \
        "exchange.py no longer records stage stats — AQE is blind"
    assert not offenders, (
        "stats recording added device syncs to the shuffle write "
        f"path: {offenders}")


# ==========================================================================
# Every rewrite emits its decision
# ==========================================================================
def _emitted_literals(own_calls):
    out = set()
    for call in own_calls:
        if _terminal_name(call.func) == "emit_event" and call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.add(arg.value)
    return out


def test_every_rewrite_decision_site_emits_event():
    tree = _parse(os.path.join(ADAPTIVE, "planner.py"))
    decision_sites = 0
    offenders = []
    for fn, own_calls in _functions_with_calls(tree):
        bumps = [c for c in own_calls
                 if _terminal_name(c.func) == "_bump"]
        if not bumps:
            continue
        decision_sites += 1
        emitted = _emitted_literals(own_calls)
        if not any(e.startswith("aqe_") for e in emitted):
            offenders.append(
                f"{fn.name} bumps an aqe.* metric but emits no "
                "aqe_* event")
    assert decision_sites >= 3, (
        "expected at least broadcast/skew/coalesce decision sites, "
        f"found {decision_sites}")
    assert not offenders, offenders


def test_all_three_rewrite_events_exist():
    tree = _parse(os.path.join(ADAPTIVE, "planner.py"))
    emitted = set()
    for fn, own_calls in _functions_with_calls(tree):
        emitted |= _emitted_literals(own_calls)
    for required in ("aqe_broadcast_join", "aqe_skew_split",
                     "aqe_coalesce_partitions"):
        assert required in emitted, (
            f"planner.py lost the {required} decision event "
            f"(has {sorted(emitted)})")


def test_executor_emits_stage_stats_and_final_plan():
    tree = _parse(os.path.join(ADAPTIVE, "executor.py"))
    emitted = set()
    for fn, own_calls in _functions_with_calls(tree):
        emitted |= _emitted_literals(own_calls)
    assert "aqe_stage_stats" in emitted
    assert "aqe_final_plan" in emitted
