"""Per-query fault-tolerance counters.

Reference analogue: the retry metrics of the RMM retry framework
surfaced in the SQL UI — a degraded query must be VISIBLY degraded.
The counters here are process-global (the spill framework and the
distributed runner have no per-exec metrics registry) and are reset at
query start by ``ExecContext`` exactly like the fault injector; the
session merges the snapshot into ``Session.last_metrics`` under
``fault.*`` keys at query end.

Counters:

* ``fault.numStageRetries``     — stage/leaf re-executions from lineage
* ``fault.numChecksumFailures`` — CRC32C mismatches detected on read
* ``fault.numWatchdogTrips``    — stage/queue watchdog deadlines hit
* ``fault.numShuffleFallbacks`` — device-shuffle queries re-executed on
  the host-staged shuffle rung (the ladder's device-shuffle →
  host-shuffle step; orthogonal to ``degradeLevel``, whose numbering
  is stable)
* ``fault.degradeLevel``        — final ladder rung (0 = native plan,
  1 = single-process fallback, 2 = CPU-exec plan)
* ``fault.numPeerLost``         — peer processes declared dead (missed
  heartbeats or a collective deadline)
* ``fault.numMeshShrinks``      — mesh re-formations on the surviving
  devices after a peer loss
* ``fault.numSpeculativeWins``  — straggler shards whose speculative
  duplicate attempt finished first
"""
from __future__ import annotations

import threading
from typing import Dict

#: degradation-ladder rungs (fault/ladder.py walks these in order)
DEGRADE_NONE = 0
DEGRADE_SINGLE_PROCESS = 1
DEGRADE_CPU = 2

_COUNTERS = ("numStageRetries", "numChecksumFailures",
             "numWatchdogTrips", "numShuffleFallbacks", "degradeLevel",
             "numPeerLost", "numMeshShrinks", "numSpeculativeWins")


class FaultStats:
    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[str, int] = {k: 0 for k in _COUNTERS}

    def reset(self) -> None:
        with self._lock:
            for k in _COUNTERS:
                self._values[k] = 0

    def add(self, name: str, v: int = 1) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0) + v

    def set_max(self, name: str, v: int) -> None:
        with self._lock:
            self._values[name] = max(self._values.get(name, 0), v)

    def get(self, name: str) -> int:
        return self._values.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        """``fault.*``-prefixed snapshot for ``Session.last_metrics``."""
        with self._lock:
            return {f"fault.{k}": v for k, v in self._values.items()}


#: the process-wide instance (reset per query by ExecContext)
GLOBAL = FaultStats()


def fault_summary(metric_snapshot) -> str:
    """One-line summary of the fault counters in a metrics snapshot;
    empty string when the query saw no faults (mirrors
    ``memory.retry.retry_summary``)."""
    keys = tuple(f"fault.{k}" for k in _COUNTERS)
    vals = {k: metric_snapshot.get(k, 0) for k in keys}
    if not any(vals.values()):
        return ""
    return " ".join(f"{k}={vals[f'fault.{k}']}" for k in _COUNTERS)
