"""Sliding-window latency histograms with fixed log-scale buckets.

``LatencyHistogram`` records latencies in milliseconds into a fixed set
of power-of-two buckets (reference: the log-linear layout of HdrHistogram
and the prometheus client's exponential buckets).  Two views coexist:

* **Cumulative** totals (never reset) feed the prometheus exposition —
  a proper ``# TYPE <family> histogram`` with ``_bucket{le=...}``,
  ``_sum`` and ``_count`` series, which must be monotonic.
* A **sliding window** (``window_s`` seconds, rotated in fixed slices)
  feeds the p50/p95/p99 readouts so dashboards and the scheduler's
  OverloadMonitor react to *recent* latency, not the whole process
  lifetime.

Percentiles interpolate within the winning bucket between its lower and
upper bound; samples beyond the last finite bound saturate the overflow
bucket and report the last finite bound (a deliberate floor — the
histogram cannot resolve beyond its range).

All methods are thread-safe; ``observe`` is O(log n buckets) and never
allocates on the hot path.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Upper bounds in milliseconds: 0.25ms .. ~2097s, factor 2 per bucket.
_DEFAULT_BOUNDS_MS: Tuple[float, ...] = tuple(0.25 * (2 ** i)
                                              for i in range(24))

# Number of rotating slices the sliding window is divided into.  More
# slices -> smoother expiry at the cost of memory (n_buckets ints each).
_WINDOW_SLICES = 6


class LatencyHistogram:
    """Fixed-bucket log-scale histogram of latencies in milliseconds."""

    __slots__ = ("bounds", "window_s", "_slice_s", "_lock",
                 "_total", "_total_count", "_total_sum",
                 "_slices", "_slice_epoch")

    def __init__(self, window_s: float = 300.0,
                 bounds_ms: Sequence[float] = _DEFAULT_BOUNDS_MS):
        self.bounds = tuple(float(b) for b in bounds_ms)
        self.window_s = float(window_s)
        self._slice_s = max(self.window_s / _WINDOW_SLICES, 1e-3)
        self._lock = threading.Lock()
        n = len(self.bounds) + 1          # +1 overflow (+Inf) bucket
        self._total = [0] * n
        self._total_count = 0
        self._total_sum = 0.0
        self._slices = [[0] * n for _ in range(_WINDOW_SLICES)]
        self._slice_epoch = 0

    # -- recording ---------------------------------------------------------

    def observe(self, latency_ms: float, now: Optional[float] = None) -> None:
        if latency_ms != latency_ms or latency_ms < 0:   # NaN / negative
            latency_ms = 0.0
        idx = bisect.bisect_left(self.bounds, latency_ms)
        if now is None:
            now = time.monotonic()
        epoch = int(now / self._slice_s)
        with self._lock:
            self._rotate_locked(epoch)
            self._total[idx] += 1
            self._total_count += 1
            self._total_sum += latency_ms
            self._slices[epoch % _WINDOW_SLICES][idx] += 1

    def _rotate_locked(self, epoch: int) -> None:
        gap = epoch - self._slice_epoch
        if gap <= 0:
            return
        for i in range(min(gap, _WINDOW_SLICES)):
            sl = self._slices[(self._slice_epoch + 1 + i) % _WINDOW_SLICES]
            for j in range(len(sl)):
                sl[j] = 0
        self._slice_epoch = epoch

    # -- windowed percentile readout --------------------------------------

    def _window_counts(self, now: Optional[float] = None) -> List[int]:
        if now is None:
            now = time.monotonic()
        epoch = int(now / self._slice_s)
        with self._lock:
            self._rotate_locked(epoch)
            counts = [0] * (len(self.bounds) + 1)
            for sl in self._slices:
                for j, c in enumerate(sl):
                    counts[j] += c
            return counts

    def percentile(self, q: float, now: Optional[float] = None) -> float:
        """q-th percentile (0..100) over the sliding window; 0.0 if empty."""
        counts = self._window_counts(now)
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = max(1, int(math.ceil(total * (q / 100.0))))
        cum = 0
        for j, c in enumerate(counts):
            cum += c
            if cum >= rank:
                if j >= len(self.bounds):        # overflow bucket
                    return self.bounds[-1]
                lo = self.bounds[j - 1] if j > 0 else 0.0
                hi = self.bounds[j]
                # linear interpolation of the rank within the bucket
                frac = (rank - (cum - c)) / float(c)
                return lo + (hi - lo) * frac
        return self.bounds[-1]

    def percentiles(self, now: Optional[float] = None
                    ) -> Dict[str, float]:
        """{"p50": ..., "p95": ..., "p99": ...} over the sliding window."""
        counts = self._window_counts(now)
        total = sum(counts)
        out = {}
        for label, q in (("p50", 50.0), ("p95", 95.0), ("p99", 99.0)):
            out[label] = self._percentile_from_counts(counts, total, q)
        return out

    def _percentile_from_counts(self, counts: List[int], total: int,
                                q: float) -> float:
        if total == 0:
            return 0.0
        rank = max(1, int(math.ceil(total * (q / 100.0))))
        cum = 0
        for j, c in enumerate(counts):
            cum += c
            if cum >= rank:
                if j >= len(self.bounds):
                    return self.bounds[-1]
                lo = self.bounds[j - 1] if j > 0 else 0.0
                frac = (rank - (cum - c)) / float(c)
                return lo + (self.bounds[j] - lo) * frac
        return self.bounds[-1]

    def window_count(self, now: Optional[float] = None) -> int:
        return sum(self._window_counts(now))

    # -- cumulative view (prometheus) --------------------------------------

    @property
    def count(self) -> int:
        return self._total_count

    @property
    def sum_ms(self) -> float:
        return self._total_sum

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """[(le_bound_ms, cumulative_count), ...] ending with +Inf."""
        with self._lock:
            out = []
            cum = 0
            for j, b in enumerate(self.bounds):
                cum += self._total[j]
                out.append((b, cum))
            cum += self._total[-1]
            out.append((math.inf, cum))
            return out

    def reset(self) -> None:
        with self._lock:
            n = len(self.bounds) + 1
            self._total = [0] * n
            self._total_count = 0
            self._total_sum = 0.0
            self._slices = [[0] * n for _ in range(_WINDOW_SLICES)]


def _fmt_le(bound: float) -> str:
    if math.isinf(bound):
        return "+Inf"
    if bound == int(bound):
        return str(int(bound))
    return repr(bound)


def prometheus_histogram_lines(family: str,
                               labeled: Sequence[Tuple[Dict[str, str],
                                                       LatencyHistogram]]
                               ) -> List[str]:
    """Render one ``# TYPE <family> histogram`` exposition block.

    ``labeled`` pairs a label dict (may be empty) with a histogram; all
    pairs share the family.  Label values are escaped per the prometheus
    text format (backslash, double-quote, newline).
    """
    lines = [f"# TYPE {family} histogram"]
    for labels, hist in labeled:
        base = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items()))
        for bound, cum in hist.cumulative_buckets():
            sep = "," if base else ""
            lines.append(
                f'{family}_bucket{{{base}{sep}le="{_fmt_le(bound)}"}} {cum}')
        lab = f"{{{base}}}" if base else ""
        lines.append(f"{family}_sum{lab} {hist.sum_ms:.6g}")
        lines.append(f"{family}_count{lab} {hist.count}")
    return lines


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))
