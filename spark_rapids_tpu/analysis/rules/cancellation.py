"""Cancellation rules: cancel-poll, collective-cancel.

Long-running drain loops must hit a cancellation/fault checkpoint per
iteration (``check_cancel`` raises on a cancelled token; the injector
checkpoints double as poll points), streaming daemon loops must watch
their stop signal, and the collective exchange must poll before every
blocking collective so one cancelled participant cannot wedge the
mesh.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..engine import AnalysisContext, Rule
from ..findings import Finding
from ..resolver import own_body_nodes, terminal_name
from . import common

#: any of these in a loop body counts as a poll point
POLL_NAMES = frozenset({"check_cancel", "maybe_inject_fault",
                        "maybe_inject_oom"})

#: names a streaming daemon loop may watch instead (stop-signal idiom)
STREAM_POLL_NAMES = frozenset({"check_cancel", "cancelled", "wait"})

DRAIN_SCOPE_PREFIXES = ("exec/",)
DRAIN_SCOPE_FILES = ("parallel/runner.py", "parallel/multiprocess.py")


def _is_drain_loop(loop: ast.While) -> bool:
    if isinstance(loop.test, ast.Constant) and loop.test.value is True:
        return True
    for n in ast.walk(loop):
        if isinstance(n, ast.Call) and \
                terminal_name(n.func) in ("get", "put"):
            return True
    return False


def _loop_polls(loop: ast.AST, names: frozenset) -> bool:
    for n in ast.walk(loop):
        if isinstance(n, ast.Call) and terminal_name(n.func) in names:
            return True
        if isinstance(n, ast.Attribute) and n.attr in names:
            return True
        if isinstance(n, ast.Name) and (
                n.id in names or n.id.startswith("_stop")):
            return True
    return False


class CancelPollRule(Rule):
    id = "cancel-poll"
    title = "drain/daemon loops poll a cancellation checkpoint"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        rels = common.scoped(ctx, prefixes=DRAIN_SCOPE_PREFIXES,
                             files=DRAIN_SCOPE_FILES)
        checked = 0
        for fi in ctx.resolver.functions(rels):
            for n in own_body_nodes(fi.node):
                if isinstance(n, ast.While) and _is_drain_loop(n):
                    checked += 1
                    if not _loop_polls(n, POLL_NAMES):
                        out.append(self.finding(
                            "drain-loop", fi.module, n.lineno,
                            f"drain loop in {fi.qualname}() never "
                            f"polls {sorted(POLL_NAMES)} — a "
                            f"cancelled query cannot unwind it",
                            detail=f"{fi.qualname}:drain-loop"))
        out.extend(self.health(
            checked >= 3, common.PKG + "exec",
            f"expected >=3 drain loops in scope, saw {checked}"))

        # streaming daemons: every while loop watches its stop signal
        stream_loops = 0
        for fi in ctx.resolver.functions(
                common.scoped(ctx, prefixes=("streaming/",))):
            for n in own_body_nodes(fi.node):
                if isinstance(n, ast.While):
                    stream_loops += 1
                    if not _loop_polls(n, STREAM_POLL_NAMES):
                        out.append(self.finding(
                            "stream-loop", fi.module, n.lineno,
                            f"streaming loop in {fi.qualname}() "
                            f"never consults its stop signal "
                            f"(check_cancel/cancelled/wait/_stop*)",
                            detail=f"{fi.qualname}:stream-loop"))
        out.extend(self.health(
            stream_loops >= 2, common.PKG + "streaming",
            f"expected >=2 streaming daemon loops, saw {stream_loops}"))
        return out


#: the ONE module allowed to dispatch cross-controller collectives
#: directly — everything else must route through its guarded funnels
ELASTIC_MODULE = common.PKG + "parallel/elastic.py"


class CollectiveCancelRule(Rule):
    id = "collective-cancel"
    title = "collectives route through the guarded elastic funnel"

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        out: List[Finding] = []
        # whole-program: a direct process_allgather anywhere outside
        # parallel/elastic.py bypasses the cancellation poll, the
        # collective wall-clock accounting AND the peer-loss deadline —
        # a dead peer would wedge that call site forever
        dispatchers = 0
        for fi in ctx.resolver.functions(ctx.project.files()):
            if "process_allgather" not in fi.own_call_names:
                continue
            if fi.module != ELASTIC_MODULE:
                out.append(self.finding(
                    "allgather", fi.module, fi.lineno,
                    f"{fi.qualname}() dispatches process_allgather "
                    f"directly — route it through "
                    f"elastic.guarded_allgather (cancellation poll + "
                    f"fault.peer.collectiveTimeoutMs guard)",
                    detail=f"{fi.qualname}:allgather"))
            else:
                dispatchers += 1
        out.extend(self.health(
            dispatchers == 1, ELASTIC_MODULE,
            f"expected exactly one process_allgather dispatcher in "
            f"the elastic funnel, saw {dispatchers}"))
        # the funnel itself must poll: guarded_call is the one place
        # cancellation is checked before joining a mesh-wide
        # collective, so every routed site inherits it
        guards = [fi for fi in ctx.resolver.functions([ELASTIC_MODULE])
                  if fi.name == "guarded_call"]
        for fi in guards:
            if not any(terminal_name(c.func) == "check_cancel"
                       for c in fi.all_calls()):
                out.append(self.finding(
                    "guard-poll", fi.module, fi.lineno,
                    "guarded_call() never polls check_cancel — one "
                    "cancelled participant would wedge every peer",
                    detail="guarded_call:check_cancel"))
        out.extend(self.health(
            len(guards) == 1, ELASTIC_MODULE,
            f"expected exactly one guarded_call funnel, "
            f"saw {len(guards)}"))
        # the exchange step dispatches THROUGH the funnel
        rels = common.scoped(ctx, prefixes=("parallel/",))
        steps = [fi for fi in ctx.resolver.functions(rels)
                 if fi.name == "exchange_step"]
        for fi in steps:
            # the routing lives in the returned dispatch closure —
            # check the whole subtree, nested defs included
            if not any(terminal_name(c.func) == "guarded_call"
                       for c in fi.all_calls()):
                out.append(self.finding(
                    "exchange-step", fi.module, fi.lineno,
                    "exchange_step() must dispatch through "
                    "elastic.guarded_call — a direct collective has "
                    "no cancellation poll or peer-loss guard",
                    detail="exchange_step:guarded_call"))
        out.extend(self.health(
            len(steps) == 1, common.PKG + "parallel/exchange.py",
            f"expected exactly one exchange_step, saw {len(steps)}"))
        return out
