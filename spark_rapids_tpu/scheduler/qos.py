"""Multi-tenant QoS: tenant registry, deficit-weighted fair admission,
priority aging, overload detection and load shedding.

Reference analogue: the admission tiers + fair resource arbitration
multi-tenant GPU SQL serving needs ("Accelerating Presto with GPUs",
PAPERS.md), layered onto the PR 7 scheduler:

* **Tenants** — every submission names a tenant (``default`` unless
  given).  Tenants need no pre-registration: the first submission
  creates the :class:`TenantState` from the dynamic conf keys
  ``scheduler.tenant.<name>.{weight,maxConcurrent,hbmFraction}``,
  falling back to the registered ``scheduler.tenant.default.*``
  entries.
* **Deficit-weighted fair share** — each tenant carries a virtual-time
  deficit clock advanced by ``1/weight`` per dispatch; the dispatcher
  always drains the eligible tenant with the smallest clock, so under
  contention service converges to the weight ratio regardless of
  arrival order (start-time fair queuing).  An idle tenant re-joining
  is floored to the current minimum active clock so it cannot hoard a
  burst out of banked idle time.
* **Priority aging** — within a tenant the highest *effective*
  priority dispatches first: ``priority + queue_wait_ms /
  scheduler.priorityAgingMs``.  Aging is what turns fixed priorities
  from a starvation hazard into an ordering hint — a steady
  high-priority stream delays, but can never indefinitely starve, an
  already-queued low-priority query.
* **Overload detection** — :class:`OverloadMonitor` tracks the p95
  queue wait (recent dispatches plus queries still waiting) and arena
  pressure against ``scheduler.overload.{queueWaitMs,hbmFraction}``.
  While overloaded, the scheduler sheds new low-tier submissions with
  :class:`TpuOverloaded` — a *typed retryable* rejection carrying a
  ``retry_after_ms`` backoff hint — and emits ``overload_enter`` /
  ``overload_exit`` / ``overload_shed`` events.

All ``*_locked`` methods must be called with the owning scheduler's
condition (``_cv``) held — the registry has no lock of its own.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..telemetry.histogram import LatencyHistogram

DEFAULT_TENANT = "default"

#: counters every TenantState tracks (surfaced as
#: ``scheduler.tenant.<name>.<counter>`` by ``qos_metrics``)
_COUNTERS = ("submitted", "dispatched", "finished", "failed",
             "cancelled", "shed", "preempted", "cacheHits",
             "queueWaitMsTotal")


class QueryRejected(RuntimeError):
    """The scheduler shed this query (queue full, queue timeout, or —
    as the :class:`TpuOverloaded` subtype — load shedding)."""


class TpuOverloaded(QueryRejected):
    """Typed retryable shed: the scheduler is overloaded and refused a
    low-tier submission.  ``retry_after_ms`` is the backoff hint — the
    client should resubmit no sooner (and ideally with jitter)."""

    def __init__(self, msg: str, *, retry_after_ms: int):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


def effective_priority(handle, now: float, aging_ms: int) -> float:
    """A queued query's aged priority: the static priority plus one
    per ``aging_ms`` of queue wait (0 disables aging).  Aging accrues
    from the FIRST enqueue — a preemption victim keeps its credit
    across the requeue."""
    if aging_ms <= 0:
        return float(handle.priority)
    waited_ms = (now - handle._first_queued_at) * 1000.0
    return handle.priority + waited_ms / float(aging_ms)


def tenant_conf(conf, name: str, field: str, conv, default):
    """Read a dynamic per-tenant conf key, falling back to the
    registered ``scheduler.tenant.default.*`` entry (``conf.get_key``
    resolves registered keys through the registry and unknown keys
    from the raw settings dict)."""
    from ..config import (SCHEDULER_TENANT_DEFAULT_HBM_FRACTION,
                          SCHEDULER_TENANT_DEFAULT_MAX_CONCURRENT,
                          SCHEDULER_TENANT_DEFAULT_WEIGHT)

    registered = {"weight": SCHEDULER_TENANT_DEFAULT_WEIGHT,
                  "maxConcurrent": SCHEDULER_TENANT_DEFAULT_MAX_CONCURRENT,
                  "hbmFraction": SCHEDULER_TENANT_DEFAULT_HBM_FRACTION}
    raw = None
    if name != DEFAULT_TENANT:
        raw = conf.get_key(
            f"spark.rapids.tpu.scheduler.tenant.{name}.{field}")
    if raw is None:
        raw = conf.get(registered[field])
    if raw is None:
        return default
    try:
        return conv(raw)
    except (TypeError, ValueError):
        return default


class TenantState:
    """One tenant's queue, fair-share clock and counters."""

    def __init__(self, name: str, weight: float, max_concurrent: int,
                 hbm_fraction: float, hist_window_s: float = 300.0):
        self.name = name
        self.weight = max(1e-6, float(weight))
        self.max_concurrent = int(max_concurrent)
        self.hbm_fraction = float(hbm_fraction)
        #: virtual-time deficit clock: +1/weight per dispatch; the
        #: smallest eligible clock dispatches next
        self.vtime = 0.0
        self.queue: List = []  # FIFO of queued QueryHandles
        self.running = 0
        self.counters: Dict[str, float] = {c: 0 for c in _COUNTERS}
        #: end-to-end (submit -> terminal) latency, sliding-window
        #: p50/p95/p99 in qos_metrics + histogram prometheus exposition
        self.latency_hist = LatencyHistogram(window_s=hist_window_s)


class TenantRegistry:
    """Per-tenant queues drained by deficit-weighted fair share.
    Owned by one QueryScheduler; every ``*_locked`` method runs under
    the scheduler's ``_cv``."""

    def __init__(self, conf):
        from ..config import TELEMETRY_HISTOGRAM_WINDOW_S

        self._conf = conf
        self._hist_window_s = max(1, conf.get(TELEMETRY_HISTOGRAM_WINDOW_S))
        self.tenants: Dict[str, TenantState] = {}
        #: dispatch order, (tenant, query_id) — test/bench-visible
        #: evidence of the fair-share interleave
        self.dispatch_log: List = []

    # ----- tenant lookup ---------------------------------------------------
    def get_locked(self, name: str) -> TenantState:
        t = self.tenants.get(name)
        if t is None:
            t = TenantState(
                name,
                tenant_conf(self._conf, name, "weight", float, 1.0),
                tenant_conf(self._conf, name, "maxConcurrent", int, 0),
                tenant_conf(self._conf, name, "hbmFraction", float, 0.0),
                hist_window_s=self._hist_window_s)
            self.tenants[name] = t
        return t

    def _min_active_vtime_locked(self) -> float:
        active = [t.vtime for t in self.tenants.values()
                  if t.queue or t.running > 0]
        return min(active) if active else 0.0

    # ----- queue operations ------------------------------------------------
    def enqueue_locked(self, handle) -> TenantState:
        t = self.get_locked(handle.tenant)
        # SFQ idle-tenant floor: re-joining after idle must not spend
        # banked virtual time as a burst against busy tenants
        t.vtime = max(t.vtime, self._min_active_vtime_locked())
        t.queue.append(handle)
        t.counters["submitted"] += 1
        return t

    def requeue_front_locked(self, handle) -> None:
        """Put a handle back at its tenant's queue head (reservation
        retry, or a preemption victim keeping its FIFO position)."""
        self.get_locked(handle.tenant).queue.insert(0, handle)

    def _eligible_locked(self, global_slots_free: bool):
        for t in self.tenants.values():
            t.queue = [h for h in t.queue if not h._done.is_set()]
            if not t.queue:
                continue
            if global_slots_free and t.max_concurrent > 0 \
                    and t.running >= t.max_concurrent:
                continue
            yield t

    def _best_locked(self, now: float, aging_ms: int,
                     respect_tenant_caps: bool = True):
        best = None
        for t in self._eligible_locked(respect_tenant_caps):
            if best is None or t.vtime < best.vtime \
                    or (t.vtime == best.vtime and t.name < best.name):
                best = t
        if best is None:
            return None, None
        # max() keeps the FIRST of equals, and the queue is FIFO — so
        # equal effective priorities dispatch in arrival order
        h = max(best.queue,
                key=lambda h: effective_priority(h, now, aging_ms))
        return best, h

    def pick_locked(self, now: float, aging_ms: int):
        """Remove and return the next handle to dispatch (smallest
        tenant clock, then highest effective priority), or None.  The
        fair-share charge happens at ``note_dispatch_locked`` so a
        failed reservation can requeue without skewing the clock."""
        t, h = self._best_locked(now, aging_ms)
        if h is None:
            return None
        t.queue.remove(h)
        return h

    def peek_locked(self, now: float, aging_ms: int):
        """The handle ``pick_locked`` would return, without removing
        it — the preemption check runs while every slot is busy, where
        per-tenant run caps must not hide a higher-tier candidate."""
        _t, h = self._best_locked(now, aging_ms,
                                  respect_tenant_caps=False)
        return h

    def remove_locked(self, handle) -> bool:
        t = self.tenants.get(handle.tenant)
        if t is None or handle not in t.queue:
            return False
        t.queue.remove(handle)
        return True

    def drain_all_locked(self) -> List:
        out: List = []
        for t in self.tenants.values():
            out.extend(t.queue)
            t.queue = []
        return out

    # ----- accounting ------------------------------------------------------
    def note_dispatch_locked(self, handle, now: float) -> float:
        """Charge the fair-share clock and queue-wait accounting for a
        dispatch; returns the wait in milliseconds."""
        t = self.get_locked(handle.tenant)
        t.vtime += 1.0 / t.weight
        t.running += 1
        wait_ms = max(0.0, (now - handle._queued_at) * 1000.0)
        t.counters["dispatched"] += 1
        t.counters["queueWaitMsTotal"] += wait_ms
        self.dispatch_log.append((handle.tenant, handle.query_id))
        return wait_ms

    def note_done_locked(self, handle, counter: Optional[str]) -> None:
        t = self.get_locked(handle.tenant)
        t.running = max(0, t.running - 1)
        if counter is not None:
            t.counters[counter] += 1
            if counter in ("finished", "failed", "cancelled"):
                # end-to-end latency from the FIRST enqueue: a
                # preemption victim's requeue wait stays inside its
                # measured latency, exactly as its submitter saw it
                first = getattr(handle, "_first_queued_at", None)
                if first is not None:
                    t.latency_hist.observe(
                        max(0.0, (time.monotonic() - first) * 1000.0))

    def count_shed_locked(self, tenant: str) -> None:
        self.get_locked(tenant).counters["shed"] += 1

    def count_cache_hit_locked(self, tenant: str) -> None:
        """A serving result-cache hit completed before admission: it
        counts as submitted AND finished for the tenant (the caller got
        a FINISHED handle) but never dispatches, so its near-zero
        latency goes straight into the tenant histogram — the warm-path
        p50 the serving bench asserts on is this population."""
        t = self.get_locked(tenant)
        t.counters["submitted"] += 1
        t.counters["finished"] += 1
        t.counters["cacheHits"] += 1
        t.latency_hist.observe(0.0)

    # ----- queue introspection --------------------------------------------
    def queued_count_locked(self) -> int:
        return sum(len(t.queue) for t in self.tenants.values())

    def queue_waits_ms_locked(self, now: float) -> List[float]:
        return [(now - h._queued_at) * 1000.0
                for t in self.tenants.values() for h in t.queue]

    def earliest_queued_at_locked(self) -> Optional[float]:
        stamps = [h._queued_at for t in self.tenants.values()
                  for h in t.queue]
        return min(stamps) if stamps else None

    def all_queued_locked(self) -> List:
        return [h for t in self.tenants.values() for h in t.queue]

    def metrics_locked(self) -> Dict[str, float]:
        """``scheduler.tenant.<name>.<counter>`` snapshot plus live
        queue/running depths."""
        out: Dict[str, float] = {}
        for name, t in self.tenants.items():
            pfx = f"scheduler.tenant.{name}."
            for c, v in t.counters.items():
                out[pfx + c] = v
            out[pfx + "queued"] = len(t.queue)
            out[pfx + "running"] = t.running
            out[pfx + "weight"] = t.weight
            for p, v in t.latency_hist.percentiles().items():
                out[pfx + f"latency{p.capitalize()}Ms"] = round(v, 3)
        return out

    def histograms_locked(self) -> List:
        """``(family_suffix, labels, hist)`` triples for
        ``prometheus_text(histograms=...)``."""
        return [("query_latency_ms", {"tenant": name}, t.latency_hist)
                for name, t in sorted(self.tenants.items())]


def _p95(samples: List[float]) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.95 * len(s)))]


class OverloadMonitor:
    """Tracks queue-wait p95 and arena pressure against the
    ``scheduler.overload.*`` thresholds and holds the overload state
    the scheduler sheds against.

    The state is re-evaluated inline at every submit AND by a sampler
    thread (so overload *exit* is detected even when no submissions
    arrive).  Transitions emit ``overload_enter`` / ``overload_exit``
    events and are recorded in :attr:`history` (the monitor thread
    usually has no query-telemetry binding, so the history is the
    test- and bench-visible record).  Hysteresis: overload exits only
    once every enabled signal drops below half its threshold."""

    def __init__(self, conf, queued_waits_ms: Callable[[], List[float]],
                 arena_pressure: Callable[[], float]):
        from ..config import (SCHEDULER_OVERLOAD_HBM_FRACTION,
                              SCHEDULER_OVERLOAD_QUEUE_WAIT_MS,
                              SCHEDULER_OVERLOAD_RETRY_AFTER_MS,
                              SCHEDULER_OVERLOAD_SAMPLE_MS)

        self.queue_wait_ms = conf.get(SCHEDULER_OVERLOAD_QUEUE_WAIT_MS)
        self.hbm_fraction = conf.get(SCHEDULER_OVERLOAD_HBM_FRACTION)
        self.retry_after_base_ms = conf.get(
            SCHEDULER_OVERLOAD_RETRY_AFTER_MS)
        self.sample_ms = max(10, conf.get(SCHEDULER_OVERLOAD_SAMPLE_MS))
        self._queued_waits_ms = queued_waits_ms
        self._arena_pressure = arena_pressure
        self._lock = threading.Lock()
        #: queue-wait latency histogram (30s sliding window for the
        #: overload p95 — the pre-PR-13 deque recency — while its
        #: cumulative buckets feed the prometheus histogram exposition)
        self.wait_hist = LatencyHistogram(window_s=30.0)
        self._overloaded = False
        #: enter/exit transition records (test/bench-visible)
        self.history: List[Dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.queue_wait_ms > 0 or self.hbm_fraction > 0

    @property
    def overloaded(self) -> bool:
        return self._overloaded

    # ----- inputs ----------------------------------------------------------
    def record_wait(self, wait_ms: float) -> None:
        self.wait_hist.observe(float(wait_ms))

    def wait_p95(self, now: Optional[float] = None) -> float:
        """p95 over the histogram's sliding window (recent recorded
        waits) PLUS the live waits of still-queued queries — a wedged
        queue must register as overload even before anything
        dispatches."""
        try:
            live = list(self._queued_waits_ms())
        except Exception:  # noqa: BLE001 — monitor must never throw
            live = []
        # the live waits are exact values; merging them as raw samples
        # next to the bucketed window keeps the wedged-queue signal
        # unquantized (a single long-stuck query must cross the
        # threshold at the threshold, not at the next bucket bound)
        return max(self.wait_hist.percentile(95.0, now), _p95(live))

    def arena_pressure(self) -> float:
        try:
            return float(self._arena_pressure())
        except Exception:  # noqa: BLE001 — monitor must never throw
            return 0.0

    # ----- state machine ---------------------------------------------------
    def evaluate(self) -> bool:
        """Recompute the overload state; emits the transition events.
        Returns the (possibly new) state."""
        from ..telemetry.events import emit_event

        if not self.enabled:
            return False
        p95 = self.wait_p95()
        pressure = self.arena_pressure()
        wait_hot = self.queue_wait_ms > 0 and p95 >= self.queue_wait_ms
        hbm_hot = self.hbm_fraction > 0 and pressure >= self.hbm_fraction
        with self._lock:
            prev = self._overloaded
            if not prev and (wait_hot or hbm_hot):
                self._overloaded = True
            elif prev:
                wait_cool = self.queue_wait_ms <= 0 \
                    or p95 < 0.5 * self.queue_wait_ms
                hbm_cool = self.hbm_fraction <= 0 \
                    or pressure < 0.5 * self.hbm_fraction
                if wait_cool and hbm_cool:
                    self._overloaded = False
            cur = self._overloaded
            if cur != prev:
                self.history.append({
                    "event": "overload_enter" if cur else "overload_exit",
                    "ts": time.time(),
                    "queue_wait_p95_ms": round(p95, 1),
                    "arena_pressure": round(pressure, 4)})
        if cur != prev:
            emit_event("overload_enter" if cur else "overload_exit",
                       queue_wait_p95_ms=round(p95, 1),
                       arena_pressure=round(pressure, 4),
                       queue_wait_threshold_ms=self.queue_wait_ms,
                       hbm_threshold=self.hbm_fraction)
        return cur

    def retry_after_ms(self, queue_depth: int, max_queued: int) -> int:
        """Backoff hint for a shed submission: the base, scaled up
        with how full the queue is — deeper congestion, later
        retry."""
        base = max(1, self.retry_after_base_ms)
        return int(base * (1.0 + queue_depth / float(max(1, max_queued))))

    # ----- sampler thread --------------------------------------------------
    def start(self) -> None:
        """Spawn the sampler thread (no-op when both thresholds are 0:
        the monitor is inert and submit-side evaluation suffices)."""
        from ..telemetry import spans as tspans

        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=tspans.bound(tspans.capture(), self._sample_loop),
            daemon=True, name="query-scheduler-overload")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def _sample_loop(self) -> None:
        while not self._stop.wait(self.sample_ms / 1000.0):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 — monitor must never die
                pass
