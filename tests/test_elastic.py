"""Elastic multi-host execution (spark_rapids_tpu/parallel/elastic.py).

The elastic invariant: a peer process that dies or stalls mid-query
must never wedge the surviving mesh — heartbeat staleness or a tripped
``fault.peer.collectiveTimeoutMs`` surfaces as ``TpuPeerLost``, the
mesh re-forms on the survivors, completed stages resume from recovery
checkpoints, and the answer stays bit-identical to a fault-free run.
Straggling shards get ONE speculative duplicate: first result wins,
the loser is cancelled and unwinds with the zero-leak discipline.
"""
import os
import threading
import time

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.fault import fault_stats
from spark_rapids_tpu.fault.errors import TpuPeerLost
from spark_rapids_tpu.parallel import elastic
from spark_rapids_tpu.plan import functions as F

FAST = {
    "spark.rapids.tpu.memory.retry.backoffBaseMs": 0.1,
    "spark.rapids.tpu.memory.retry.backoffMaxMs": 2.0,
}


def _norm(rows):
    return sorted(
        (tuple((None if v is None else
                (round(v, 9) if isinstance(v, float) else v))
               for v in r) for r in rows),
        key=repr)


# ==========================================================================
# Heartbeat ledger
# ==========================================================================
def test_heartbeat_ledger_detects_stale_and_missing_peers(tmp_path):
    led = elastic.HeartbeatLedger(str(tmp_path), 0, 2,
                                  heartbeat_ms=50, missed_limit=3)
    # before start() the ledger must stay silent: a worker that has
    # not begun heartbeating has no business declaring peers dead
    assert led.lost_peers() == ()
    led.start()
    try:
        # missing peer file inside the startup grace: not lost yet
        assert led.lost_peers() == ()
        peer = os.path.join(str(tmp_path), "hb-1")
        with open(peer, "a"):
            pass
        assert led.lost_peers() == ()
        # stale mtime past heartbeat_ms * missed_limit: lost
        past = time.time() - 10.0
        os.utime(peer, (past, past))
        assert led.lost_peers() == (1,)
        # file vanished AND the startup grace expired: lost
        os.remove(peer)
        led._start_wall -= 10.0
        assert led.lost_peers() == (1,)
        # our own heartbeat file is kept fresh by the beat thread
        own = os.path.join(str(tmp_path), "hb-0")
        time.sleep(0.2)
        assert time.time() - os.stat(own).st_mtime < 5.0
    finally:
        led.stop()


def test_make_shrunken_mesh_halves_single_controller_mesh():
    from spark_rapids_tpu.parallel.mesh import (make_mesh,
                                                make_shrunken_mesh)

    mesh = make_mesh(8)
    small = make_shrunken_mesh(mesh)
    assert small.axis_names == mesh.axis_names
    devs, sdevs = list(mesh.devices.flat), list(small.devices.flat)
    assert len(sdevs) == 4
    assert [d.id for d in sdevs] == [d.id for d in devs[:4]]


# ==========================================================================
# Deadline-guarded collective dispatch
# ==========================================================================
def test_guarded_call_is_direct_when_nothing_armed():
    prev = elastic.install_collective_deadline(0)
    try:
        assert elastic.installed_heartbeat_ledger() is None
        assert elastic.guarded_call(lambda: 42) == 42
    finally:
        elastic.install_collective_deadline(prev)


def test_guarded_call_deadline_aborts_with_peer_lost(monkeypatch):
    events = []
    monkeypatch.setattr(
        elastic, "emit_event",
        lambda name, **kw: events.append((name, kw)))
    release = threading.Event()
    epoch0 = elastic.collective_epoch()
    lost0 = fault_stats.get("numPeerLost")
    t0 = time.monotonic()
    try:
        with pytest.raises(TpuPeerLost) as ei:
            elastic.guarded_call(lambda: release.wait(30),
                                 site="test.collective",
                                 timeout_ms=300)
    finally:
        release.set()
    assert time.monotonic() - t0 < 10.0, "must abandon, not wait out"
    assert "collectiveTimeoutMs" in str(ei.value)
    # the loss is counted, announced and aborts sibling dispatches
    assert fault_stats.get("numPeerLost") == lost0 + 1
    assert elastic.collective_epoch() == epoch0 + 1
    lost_events = [kw for name, kw in events if name == "peer_lost"]
    assert len(lost_events) == 1
    assert "collectiveTimeoutMs" in lost_events[0]["reason"]


def test_guarded_call_ledger_staleness_aborts(monkeypatch, tmp_path):
    monkeypatch.setattr(elastic, "emit_event", lambda *a, **k: None)
    led = elastic.HeartbeatLedger(str(tmp_path), 0, 2,
                                  heartbeat_ms=20, missed_limit=2)
    led.start()
    led._start_wall -= 60.0  # peer 1 never wrote: grace long expired
    prev = elastic.install_heartbeat_ledger(led)
    release = threading.Event()
    try:
        with pytest.raises(TpuPeerLost, match="stopped heartbeating"):
            elastic.guarded_call(lambda: release.wait(30),
                                 site="test.collective")
    finally:
        release.set()
        elastic.install_heartbeat_ledger(prev)
        led.stop()


def test_abort_collectives_unwinds_in_flight_dispatch():
    release = threading.Event()
    caught = []

    def call():
        try:
            elastic.guarded_call(lambda: release.wait(30),
                                 site="test.collective",
                                 timeout_ms=60000)
        except BaseException as e:  # noqa: BLE001
            caught.append(e)

    t = threading.Thread(target=call, daemon=True)
    t.start()
    time.sleep(0.4)  # let the dispatch enter its collector loop
    try:
        elastic.abort_collectives("test epoch bump")
        t.join(timeout=30)
        assert not t.is_alive()
        assert len(caught) == 1
        assert isinstance(caught[0], TpuPeerLost)
        assert "epoch bump" in str(caught[0])
    finally:
        release.set()


# ==========================================================================
# Straggler speculation
# ==========================================================================
def test_speculation_first_result_wins_and_loser_unwinds():
    from spark_rapids_tpu.scheduler.cancel import (TpuQueryCancelled,
                                                   check_cancel)

    mon = elastic.SpeculationMonitor(multiplier=1.0, quantile=50.0,
                                     min_samples=2, min_latency_ms=1.0)
    mon.observe(5.0)
    mon.observe(5.0)
    calls = {}
    unwound = []

    def drain(pid):
        n = calls.get(pid, 0)
        calls[pid] = n + 1
        if pid == 0:
            return "ok0"
        if n == 0:
            # primary straggler: spin at the cancellation checkpoint
            # until the speculative sibling wins and cancels us
            try:
                while True:
                    time.sleep(0.005)
                    check_cancel("test.drain")
            except TpuQueryCancelled:
                unwound.append("primary")
                raise
        return "fast"

    wins0 = fault_stats.get("numSpeculativeWins")
    got = elastic.drain_with_speculation(
        [0, 1], drain, max_threads=2, site="test.drain", monitor=mon)
    assert got == {0: "ok0", 1: "fast"}
    assert fault_stats.get("numSpeculativeWins") == wins0 + 1
    # zero-leak: the cancelled primary unwinds through its own except
    deadline = time.monotonic() + 5.0
    while not unwound and time.monotonic() < deadline:
        time.sleep(0.01)
    assert unwound == ["primary"]


def test_speculation_emits_attempt_and_win_events(monkeypatch):
    events = []
    monkeypatch.setattr(
        elastic, "emit_event",
        lambda name, **kw: events.append((name, kw)))
    mon = elastic.SpeculationMonitor(multiplier=1.0, quantile=50.0,
                                     min_samples=2, min_latency_ms=1.0)
    mon.observe(5.0)
    mon.observe(5.0)
    calls = {}
    release = threading.Event()

    def drain(pid):
        n = calls.get(pid, 0)
        calls[pid] = n + 1
        if n == 0:
            release.wait(30)  # primary blocks until the test ends
            return "slow"
        return "fast"

    try:
        got = elastic.drain_with_speculation(
            [7], drain, max_threads=1, site="test.drain", monitor=mon)
    finally:
        release.set()
    assert got == {7: "fast"}
    names = [name for name, _ in events]
    assert names.count("speculative_attempt") == 1
    assert names.count("speculative_win") == 1
    att = [kw for n, kw in events if n == "speculative_attempt"][0]
    assert att["shard"] == 7 and att["elapsed_ms"] > att["baseline_ms"]


def test_speculation_monitor_gates_on_samples_and_floor():
    mon = elastic.SpeculationMonitor(multiplier=2.0, quantile=95.0,
                                     min_samples=4, min_latency_ms=50.0)
    assert not mon.should_speculate(10000.0), "no samples yet"
    for _ in range(4):
        mon.observe(10.0)
    assert not mon.should_speculate(45.0), "under the floor"
    assert mon.should_speculate(55.0)


# ==========================================================================
# The shrunken-mesh rung: peer crash -> mesh shrink -> checkpoint resume
# ==========================================================================
def _elastic_query(sess):
    rng = np.random.RandomState(11)
    facts = sess.create_dataframe({
        "k": rng.randint(0, 16, 240).tolist(),
        "v": [round(float(x), 6) for x in rng.rand(240) * 50]},
        n_partitions=8)
    dims = sess.create_dataframe({
        "dk": list(range(16)),
        "w": [round(float(x), 6) for x in rng.rand(16) * 10]},
        n_partitions=8)
    j = facts.join(dims, on=(["k"], ["dk"]), how="inner")
    return j.group_by("k").agg(F.sum("v").alias("s"),
                               F.count("w").alias("c"))


def _elastic_conf(extra=None):
    conf = dict(FAST)
    conf["spark.rapids.tpu.sql.broadcastSizeThreshold"] = 0
    conf.update(extra or {})
    return conf


def _count_stage_runs():
    """How many ``stage.run`` checkpoints one clean execution of the
    drill query polls (site-filtered counting on a never-firing nth
    injector) — the deterministic knob for crashing the LAST stage."""
    from spark_rapids_tpu.fault.injector import get_fault_injector
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.runner import run_distributed

    sess = srt.Session(_elastic_conf({
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "peer_crash",
        "spark.rapids.tpu.fault.injection.site": "stage.run",
        "spark.rapids.tpu.fault.injection.skipCount": 10 ** 6,
    }))
    out = run_distributed(sess, _elastic_query(sess), mesh=make_mesh(8))
    return get_fault_injector().checkpoints_seen, _norm(out.to_rows())


@pytest.mark.fault_injection
def test_peer_crash_shrinks_mesh_and_resumes_from_checkpoints(tmp_path):
    """An injected peer crash on the LAST stage re-forms the mesh on
    the surviving half, resumes every completed stage from recovery
    checkpoints (numStagesResumed > 0), and the answer is bit-identical
    — without ever touching the single-process degradation rung."""
    from spark_rapids_tpu.fault.ladder import run_with_fault_tolerance

    n_runs, clean = _count_stage_runs()
    assert n_runs >= 2, "drill query must be multi-stage"
    sess = srt.Session(_elastic_conf({
        "spark.rapids.tpu.recovery.enabled": True,
        "spark.rapids.tpu.recovery.dir": str(tmp_path),
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "peer_crash",
        "spark.rapids.tpu.fault.injection.site": "stage.run",
        "spark.rapids.tpu.fault.injection.skipCount": n_runs - 1,
    }))
    out = run_with_fault_tolerance(sess, _elastic_query(sess),
                                   n_devices=8)
    assert _norm(out.to_rows()) == clean
    m = sess.last_metrics
    assert m.get("fault.numMeshShrinks", 0) >= 1, m
    assert m.get("recovery.numStagesResumed", 0) >= 1, m
    assert m.get("recovery.numCheckpointsWritten", 0) >= 1, m
    # the shrunken rung finished the query: no single-process degrade
    assert m.get("fault.degradeLevel", 0) == 0, m
    # the extra rung is charged to the unified attempt budget
    assert m.get("fault.totalAttempts", 0) >= 1, m


@pytest.mark.fault_injection
def test_peer_crash_without_degrade_enabled_raises(tmp_path):
    from spark_rapids_tpu.fault.ladder import run_with_fault_tolerance

    sess = srt.Session(_elastic_conf({
        "spark.rapids.tpu.fault.degrade.enabled": False,
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "peer_crash",
        "spark.rapids.tpu.fault.injection.site": "stage.run",
        "spark.rapids.tpu.fault.injection.skipCount": 0,
    }))
    with pytest.raises(TpuPeerLost):
        run_with_fault_tolerance(sess, _elastic_query(sess), n_devices=8)


@pytest.mark.fault_injection
def test_peer_stall_speculation_wins_in_distributed_drain():
    """A ``peer_stall`` straggler injected at the leaf drain arms one
    speculative duplicate whose result wins (speculative_win >= 1) and
    the query completes bit-identical without any mesh shrink."""
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.runner import run_distributed

    def run(extra):
        sess = srt.Session(_elastic_conf(extra))
        out = run_distributed(sess, _elastic_query(sess),
                              mesh=make_mesh(8))
        return sess, _norm(out.to_rows())

    _, clean = run({})
    sess, got = run({
        "spark.rapids.tpu.speculation.enabled": True,
        "spark.rapids.tpu.speculation.minSamples": 3,
        "spark.rapids.tpu.speculation.multiplier": 2.0,
        "spark.rapids.tpu.speculation.minLatencyMs": 200.0,
        "spark.rapids.tpu.fault.injection.mode": "nth",
        "spark.rapids.tpu.fault.injection.type": "peer_stall",
        "spark.rapids.tpu.fault.injection.site": "leaf.drain",
        "spark.rapids.tpu.fault.injection.skipCount": 6,
        "spark.rapids.tpu.fault.injection.delayMs": 30000.0,
    })
    assert got == clean
    m = sess.last_metrics
    assert m.get("fault.numSpeculativeWins", 0) >= 1, m
    assert m.get("fault.numMeshShrinks", 0) == 0, m
